"""Background compaction & retention plane.

Reference: the dedicated compactor node (src/storage/compactor/ and the
`fast_compactor_runner`) — compaction is a SUBSYSTEM, not a side effect
of committing. The shape kept here:

- `BackgroundCompactor` is barrier-paced: the coordinator pulses it in
  the same synchronous between-epochs window the scrubber uses. Each
  pulse does O(1) loop work — harvest a finished merge (one manifest
  swap, deletes strictly after), refresh gauges, and maybe START a new
  merge on a worker thread (`asyncio.to_thread`, the PR 2 uploader
  discipline). The commit path itself never merges: attaching the
  compactor flips `HummockStateStore.inline_compaction` off.
- Merges are bounded and tiered: the oldest contiguous tail of L0,
  capped by a byte budget that accrues per barrier (pacing — bytes
  rewritten per interval is bounded) and a max run count. Only when a
  merge covers all of L0 does L1 join and tombstones drop (nothing
  lives below the bottom level).
- `PinRegistry` aggregates the minimum pinned epoch across every reader
  that could look below the committed tip: serving snapshot pins,
  durable subscription cursors + live pumps (LogStoreHub), explicit
  scan/backup pins. No run newer than that floor is ever rewritten, so
  no version or tombstone a pinned reader could need is collapsed.
- Fail-safety: a merge-thread crash or an abandoned install leaves at
  worst an orphan output object — `compaction_inflight` keeps live
  outputs out of the scrubber's sweep, and everything else is exactly
  the orphan shape the PR 12 scrubber already collects.
- `BrokerRetentionManager` rides the same pulse: the earliest DURABLE
  offset per broker partition (min over committed source offsets — the
  connector's in-memory offset runs ahead of the checkpoint and must
  not gate deletion) is pushed to the broker, which drops whole sealed
  segments below it and key-compacts changelog topics.
"""

from __future__ import annotations

import asyncio
import time
from typing import Callable, Optional

from ..utils.faults import FAULTS
from ..utils.metrics import (COMPACTION_BYTES_REWRITTEN, COMPACTION_RUNS,
                             COMPACTION_SECONDS, LSM_L0_RUNS, LSM_READ_AMP,
                             RETENTION_SEGMENTS_DROPPED,
                             retention_floor_gauge)
from .hummock import CompactionTask, HummockStateStore


class PinRegistry:
    """Aggregates the minimum pinned epoch across every source that can
    read below the committed tip. `floor()` returns the epoch below
    which versions/tombstones may be collapsed: +inf (no constraint)
    when nothing pins. Explicit pins (backfill scans, backups) use
    pin()/unpin() tokens; serving and logstore sources are polled."""

    def __init__(self):
        self.serving = None          # ServingManager, attached by the coord
        self.logstore = None         # LogStoreHub, attached by the coord
        self._explicit: dict[int, tuple[str, int]] = {}  # token -> (src, ep)
        self._next_token = 1

    # ------------------------------------------------------ explicit pins
    def pin(self, epoch: int, source: str = "scan") -> int:
        token = self._next_token
        self._next_token += 1
        self._explicit[token] = (source, int(epoch))
        return token

    def unpin(self, token: int) -> None:
        self._explicit.pop(token, None)

    # ----------------------------------------------------------- the floor
    def floors(self) -> dict[str, Optional[int]]:
        """Per-source minimum pinned epoch (None = source holds nothing)."""
        out: dict[str, Optional[int]] = {
            "serving": None, "subscriptions": None,
            "scan": None, "backup": None,
        }
        if self.serving is not None:
            pinned = [ent.cache.snapshot.epoch
                      for ent in self.serving._mvs.values()
                      if ent.cache is not None
                      and ent.cache.snapshot is not None
                      and ent.cache.snapshot.pins > 0]
            if pinned:
                out["serving"] = min(pinned)
        if self.logstore is not None:
            cursors: list[int] = []
            for name, log in self.logstore.mv_logs.items():
                cursors.extend(
                    self.logstore.pinning_sub_cursors(name, log).values())
            cursors.extend(p.cursor_epoch
                           for p in self.logstore.subscriptions)
            if cursors:
                out["subscriptions"] = min(cursors)
        for source, epoch in self._explicit.values():
            if out.get(source) is None or epoch < out[source]:
                out[source] = epoch
        return out

    def floor(self) -> float:
        present = [e for e in self.floors().values() if e is not None]
        return min(present) if present else float("inf")


class BackgroundCompactor:
    """Barrier-paced leveled compactor for a manifest-owning Hummock
    store. Owned by the BarrierCoordinator; `on_barrier` runs in the
    synchronous between-epochs window. At most one merge is in flight."""

    def __init__(self, store, serving=None, logstore=None):
        self.store = store
        self.pins = PinRegistry()
        self.pins.serving = serving
        self.pins.logstore = logstore
        # pacing/trigger knobs (Session CONFIG_VARS plumb here)
        self.interval = 1            # pulse every N barriers; 0 disables
        self.l0_trigger = 4          # start merging once L0 exceeds this
        self.budget_bytes = 8 << 20  # credit accrued per pulse
        self.max_runs = 8            # runs per merge (bounded work)
        self.credit_cap_bytes = 512 << 20
        self.event_log = None
        self.retention: Optional[BrokerRetentionManager] = None
        # state
        self._barriers = 0
        self._credit = 0
        self._floor_sources: set = set()
        self._job: Optional[asyncio.Task] = None
        self._task: Optional[CompactionTask] = None
        # counters for SHOW compaction / the soak gate
        self.runs_total = 0
        self.bytes_rewritten_total = 0
        self.keys_dropped_total = 0
        self.installs_abandoned = 0
        self.merge_failures = 0
        self.last_output: Optional[dict] = None

    # --------------------------------------------------------------- admin
    @property
    def active(self) -> bool:
        return (self.interval > 0
                and isinstance(self.store, HummockStateStore)
                and self.store.manifest_owner)

    def configure(self, interval: Optional[int] = None,
                  l0_trigger: Optional[int] = None,
                  budget_bytes: Optional[int] = None,
                  max_runs: Optional[int] = None) -> None:
        if interval is not None:
            self.interval = int(interval)
        if l0_trigger is not None:
            self.l0_trigger = max(1, int(l0_trigger))
        if budget_bytes is not None:
            self.budget_bytes = max(0, int(budget_bytes))
        if max_runs is not None:
            self.max_runs = max(2, int(max_runs))
        self._sync_inline_flag()

    def _sync_inline_flag(self) -> None:
        """The commit path runs inline full merges ONLY while no live
        compactor owns the store (standalone stores, or the operator
        disabled the compactor with SET compaction_interval=0)."""
        if isinstance(self.store, HummockStateStore) \
                and self.store.manifest_owner:
            self.store.inline_compaction = not self.active

    # -------------------------------------------------------------- pulse
    def on_barrier(self, epoch: int) -> None:
        self._sync_inline_flag()
        if not self.active:
            return
        self._barriers += 1
        if self._barriers % self.interval:
            return
        self._pulse(epoch)
        if self.retention is not None:
            self.retention.on_barrier(epoch)

    def _pulse(self, epoch: int) -> None:
        store = self.store
        LSM_L0_RUNS.set(float(store.l0_run_count()))
        LSM_READ_AMP.set(float(store.read_amp()))
        floors = self.pins.floors()
        for source, ep in floors.items():
            retention_floor_gauge(source).set(
                float(ep if ep is not None else -1))
        # a pin source that vanished (DROP SINK, subscription gone) must
        # take its labelled gauge with it, or /metrics grows forever
        from ..utils.metrics import GLOBAL_METRICS
        for source in self._floor_sources - set(floors):
            GLOBAL_METRICS.remove("retention_floor_epoch", source=source)
        self._floor_sources = set(floors)
        self._harvest()
        self._credit = min(self._credit + self.budget_bytes * self.interval,
                           self.credit_cap_bytes)
        if self._job is not None or self._task is not None:
            return                      # one merge in flight at a time
        # write-amplification-aware trigger: merge when the read fan-out
        # exceeds the configured depth (every extra L0 run is one more
        # sorted run each read consults)
        if store.l0_run_count() <= self.l0_trigger:
            return
        present = [e for e in floors.values() if e is not None]
        floor = min(present) if present else epoch
        task = store.plan_compaction(floor, self.max_runs, self._credit)
        if task is None:
            return
        self._credit = max(0, self._credit - task.input_bytes)
        self._task = task
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            loop = None
        if loop is None:                # synchronous harness (unit tests)
            self._merge(task)
            self._harvest()
        else:
            self._job = loop.create_task(asyncio.to_thread(
                self._merge, task))

    def _merge(self, task: CompactionTask) -> None:
        """Worker-thread half: merge + upload (store.merge_compaction is
        thread-safe). Timing and fault injection live here."""
        if FAULTS.active \
                and FAULTS.hit("compaction_merge",
                               sst_id=task.out_sst_id) is not None:
            from ..utils.faults import FaultInjected
            raise FaultInjected("compaction_merge")
        t0 = time.monotonic()
        self.store.merge_compaction(task)
        COMPACTION_SECONDS.observe(time.monotonic() - t0)

    def _harvest(self) -> None:
        """Loop-side half: collect a finished merge and install it under
        one manifest swap. A merge failure is NOT fatal — the invariant
        is that at worst an orphan object exists, which the scrubber
        sweeps — so it is recorded and the trigger simply refires."""
        job, task = self._job, self._task
        if task is None or (job is not None and not job.done()):
            return
        self._job, self._task = None, None
        if job is not None:
            exc = None if job.cancelled() else job.exception()
            if job.cancelled() or exc is not None:
                self.store.abandon_compaction(task)
                self.merge_failures += 1
                if self.event_log is not None and exc is not None:
                    self.event_log.emit("compaction_failed",
                                        sst_id=task.out_sst_id,
                                        error=repr(exc))
                return
        if task.data is None:           # merge never ran (aborted early)
            self.store.abandon_compaction(task)
            return
        obsolete = self.store.install_compaction(task)
        if obsolete is None:            # manifest moved underneath us
            self.installs_abandoned += 1
            return
        self.runs_total += 1
        self.bytes_rewritten_total += task.input_bytes
        self.keys_dropped_total += task.keys_in - task.keys_out
        COMPACTION_RUNS.inc()
        COMPACTION_BYTES_REWRITTEN.inc(task.input_bytes)
        LSM_L0_RUNS.set(float(self.store.l0_run_count()))
        LSM_READ_AMP.set(float(self.store.read_amp()))
        self.last_output = {
            "out_sst": task.out_sst_id, "inputs": obsolete,
            "into_l1": task.into_l1, "bytes": task.input_bytes,
            "keys_dropped": task.keys_in - task.keys_out,
        }
        if self.event_log is not None:
            self.event_log.emit("compaction_run", **self.last_output)

    # ----------------------------------------------------------- lifecycle
    async def drain(self) -> None:
        """Wait out an in-flight merge and install it (backup/shutdown
        quiesce — mirrors BarrierCoordinator.drain_uploads)."""
        if self._job is not None:
            try:
                await self._job
            except Exception:  # noqa: BLE001 — recorded by _harvest
                pass
        self._harvest()

    def abort(self) -> None:
        """Recovery entry (mirrors abort_uploads): drop the in-flight
        merge. The thread may still finish its upload — that object is
        an orphan no manifest references; the scrubber sweeps it."""
        if self._job is not None:
            self._job.cancel()
        if self._task is not None:
            self.store.abandon_compaction(self._task)
        self._job, self._task = None, None

    # ----------------------------------------------------------- reporting
    def report(self) -> list[tuple[str, str]]:
        rows = [
            ("enabled", str(self.active).lower()),
            ("interval", str(self.interval)),
            ("l0_trigger", str(self.l0_trigger)),
            ("budget_bytes", str(self.budget_bytes)),
            ("max_runs", str(self.max_runs)),
            ("credit_bytes", str(self._credit)),
            ("in_flight", str(self._task is not None).lower()),
            ("runs_total", str(self.runs_total)),
            ("bytes_rewritten_total", str(self.bytes_rewritten_total)),
            ("keys_dropped_total", str(self.keys_dropped_total)),
            ("installs_abandoned", str(self.installs_abandoned)),
            ("merge_failures", str(self.merge_failures)),
        ]
        if isinstance(self.store, HummockStateStore):
            rows += [("l0_runs", str(self.store.l0_run_count())),
                     ("read_amp", str(self.store.read_amp()))]
        for source, ep in self.pins.floors().items():
            rows.append((f"floor_{source}",
                         "-" if ep is None else str(ep)))
        if self.last_output is not None:
            rows.append(("last_run", str(self.last_output)))
        if self.retention is not None:
            rows.extend(self.retention.report())
        return rows


class BrokerRetentionManager:
    """Pushes earliest-DURABLE-offset floors to brokers so they can drop
    whole sealed segments (and key-compact changelog topics) below what
    every consumer has checkpointed. Floors come from the source
    executors' committed-offset history: the newest per-split offset
    snapshot whose epoch the store has committed — never the live
    connector offset, which runs ahead of the checkpoint and would
    reopen the exactly-once window on recovery."""

    def __init__(self, store, source_execs: Callable[[], dict]):
        self.store = store
        self.source_execs = source_execs
        self.interval = 0               # barriers between pushes; 0 = off
        self.event_log = None
        self._barriers = 0
        self._job: Optional[asyncio.Task] = None
        self.segments_dropped_total = 0
        self.floors_pushed: dict[tuple[str, int], int] = {}
        self.push_failures = 0

    def configure(self, interval: Optional[int] = None) -> None:
        if interval is not None:
            self.interval = int(interval)

    def _durable_floors(self) -> dict[tuple[str, int], tuple[int, object]]:
        """(topic, partition) -> (min committed offset, client). A
        partition consumed by ANY split without a committed offset yet
        contributes floor 0 (drop nothing)."""
        committed = self.store.committed_epoch()
        floors: dict[tuple[str, int], tuple[int, object]] = {}
        for ex in self.source_execs().values():
            hist = getattr(ex, "offset_history", None)
            durable: dict = {}
            if hist:
                for ep, offs in reversed(hist):
                    if ep <= committed:
                        durable = offs
                        break
            for sid, conn in getattr(ex, "splits", []):
                topic = getattr(conn, "topic", None)
                part = getattr(conn, "partition", None)
                client = getattr(conn, "client", None)
                if topic is None or part is None or client is None:
                    continue
                off = int(durable.get(sid, 0))
                key = (topic, int(part))
                if key not in floors or off < floors[key][0]:
                    floors[key] = (off, client)
        return floors

    def on_barrier(self, epoch: int) -> None:
        if self.interval <= 0:
            return
        self._barriers += 1
        if self._barriers % self.interval:
            return
        if self._job is not None:
            if not self._job.done():
                return
            self._job = None
        floors = {k: v for k, v in self._durable_floors().items()
                  if v[0] > 0 and self.floors_pushed.get(k) != v[0]}
        if not floors:
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            self._push(floors)
            return
        self._job = loop.create_task(asyncio.to_thread(self._push, floors))

    def _push(self, floors: dict) -> None:
        """Worker-thread half: one RPC per changed partition floor."""
        for (topic, part), (off, client) in floors.items():
            try:
                res = client.set_retention_floor(topic, part, off)
            except Exception:  # noqa: BLE001 — broker away: retry later
                self.push_failures += 1
                continue
            self.floors_pushed[(topic, part)] = off
            dropped = int((res or {}).get("segments_dropped", 0))
            if dropped:
                self.segments_dropped_total += dropped
                RETENTION_SEGMENTS_DROPPED.inc(dropped)
                if self.event_log is not None:
                    self.event_log.emit(
                        "broker_segments_dropped", topic=topic,
                        partition=part, floor=off, segments=dropped)

    def report(self) -> list[tuple[str, str]]:
        return [
            ("retention_interval", str(self.interval)),
            ("retention_floors_pushed", str(len(self.floors_pushed))),
            ("retention_segments_dropped",
             str(self.segments_dropped_total)),
            ("retention_push_failures", str(self.push_failures)),
        ]

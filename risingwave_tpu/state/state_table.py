"""StateTable — the single state abstraction every stateful executor uses.

Reference: src/stream/src/common/table/state_table.rs (1602 LoC): typed rows
over a LocalStateStore; key = vnode(dist_key) ++ memcomparable(pk); a
mem-table buffers writes between barriers; `commit(new_epoch)` flushes and
seals the epoch. API parity targets: init_epoch (:179), get_row (:708),
insert/delete/update (:875-921), write_chunk (:946), update_watermark (:1029),
commit (:1036), iter_with_vnode/iter_with_prefix (:1255,1315),
update_vnode_bitmap (:778).

TPU division of labor: device executors keep their *compute* state resident
in HBM; the StateTable is the *durability* path — at each barrier the
executor writes its state delta here, `commit` flushes to the state store,
and recovery rebuilds HBM arrays by scanning this table. Consistency checks
(insert-must-not-exist etc.) mirror the reference's OpConsistencyLevel
(mem_table.rs) and catch changelog bugs early.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from ..common.types import Schema
from ..common.vnode import VNODE_COUNT, compute_vnodes_numpy
from .serde import RowSerde, encode_memcomparable, decode_memcomparable
from .store import StateStore, WriteBatch, encode_table_key


class StateTableError(Exception):
    pass


class StateTable:
    def __init__(
        self,
        store: StateStore,
        table_id: int,
        schema: Schema,
        pk_indices: Sequence[int],
        dist_key_indices: Optional[Sequence[int]] = None,
        vnode_bitmap: Optional[np.ndarray] = None,
        pk_descending: Optional[Sequence[bool]] = None,
        check_consistency: bool = True,
    ):
        self.store = store
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = tuple(pk_indices)
        # dist key defaults to the pk prefix = first pk column (reference
        # defaults dist key ⊆ pk); empty tuple = singleton (vnode 0).
        self.dist_key_indices = tuple(dist_key_indices if dist_key_indices is not None
                                      else self.pk_indices[:1])
        self.vnode_bitmap = (np.ones(VNODE_COUNT, dtype=bool)
                             if vnode_bitmap is None else np.asarray(vnode_bitmap, dtype=bool))
        self.pk_descending = tuple(pk_descending) if pk_descending is not None else None
        self.check_consistency = check_consistency
        self._pk_types = tuple(schema[i].data_type for i in self.pk_indices)
        self._serde = RowSerde(schema)
        # mem-table: full key -> (op, row|None, enc|None); op in {+1 put,
        # -1 delete}. Batch writes store pre-ENCODED values (native codec)
        # and decode lazily on read-through.
        self._mem: dict[bytes, tuple[int, Optional[tuple], Optional[bytes]]] = {}
        self.epoch: Optional[int] = None
        self._all_i64 = all(
            np.dtype(f.data_type.np_dtype).kind in "i" and
            np.dtype(f.data_type.np_dtype).itemsize == 8 for f in schema)

    # ------------------------------------------------------------- keys
    def _vnode_of(self, row: tuple) -> int:
        if not self.dist_key_indices:
            return 0
        cols = [np.asarray([0 if row[i] is None else row[i]])
                for i in self.dist_key_indices]
        # match column dtypes so host hash == device hash
        cols = [c.astype(self.schema[i].data_type.np_dtype)
                for c, i in zip(cols, self.dist_key_indices)]
        return int(compute_vnodes_numpy(cols)[0])

    def _key_of(self, row: tuple) -> bytes:
        pk = tuple(row[i] for i in self.pk_indices)
        return encode_table_key(
            self.table_id, self._vnode_of(row),
            encode_memcomparable(pk, self._pk_types, self.pk_descending))

    def key_of_pk(self, pk: tuple, vnode: int) -> bytes:
        return encode_table_key(
            self.table_id, vnode, encode_memcomparable(pk, self._pk_types, self.pk_descending))

    def vnode_of_pk(self, pk: tuple) -> int:
        """Vnode for a pk tuple (requires dist_key ⊆ pk, the reference's
        batch point-get precondition)."""
        if not self.dist_key_indices:
            return 0
        pos = [self.pk_indices.index(i) for i in self.dist_key_indices]
        cols = [np.asarray([pk[p]]).astype(
            self.schema[i].data_type.np_dtype)
            for p, i in zip(pos, self.dist_key_indices)]
        return int(compute_vnodes_numpy(cols)[0])

    def vnode_key_range(self, vnode: int) -> tuple[bytes, bytes]:
        """[start, end) covering one vnode of this table."""
        start = encode_table_key(self.table_id, vnode, b"")
        end = (encode_table_key(self.table_id, vnode + 1, b"")
               if vnode + 1 < VNODE_COUNT
               else (self.table_id + 1).to_bytes(4, "big"))
        return start, end

    # ------------------------------------------------------------ writes
    def init_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def insert(self, row: tuple) -> None:
        k = self._key_of(row)
        prev = self._mem.get(k)
        if self.check_consistency and prev is not None and prev[0] > 0:
            raise StateTableError(f"double insert for key {row!r} in table {self.table_id}")
        self._mem[k] = (1, tuple(row), None)

    def delete(self, row: tuple) -> None:
        # Always record a tombstone: an insert+delete within one epoch must
        # still delete any version of the key from a PRIOR epoch in the store
        # (cancelling the put alone would resurrect the old row).
        self._mem[self._key_of(row)] = (-1, None, None)

    def update(self, old_row: tuple, new_row: tuple) -> None:
        ko, kn = self._key_of(old_row), self._key_of(new_row)
        if ko == kn:
            self._mem[kn] = (1, tuple(new_row), None)
        else:
            self.delete(old_row)
            self.insert(new_row)

    def write_chunk_rows(self, rows: Sequence[tuple[int, tuple]]) -> None:
        """rows: (op, values) with chunk Op encoding (write_chunk :946).
        Vnodes for the whole batch are hashed in one vectorized pass — this
        is the per-barrier hot path."""
        from ..common.chunk import OP_INSERT, OP_UPDATE_INSERT
        if not rows:
            return
        vnodes = self._vnodes_of_batch([r for _, r in rows])
        for (op, row), vn in zip(rows, vnodes):
            k = self.key_of_pk(tuple(row[i] for i in self.pk_indices), int(vn))
            if op in (OP_INSERT, OP_UPDATE_INSERT):
                self._mem[k] = (1, tuple(row), None)
            else:
                self._mem[k] = (-1, None, None)

    def _vnodes_of_batch(self, rows: Sequence[tuple]) -> np.ndarray:
        if not self.dist_key_indices:
            return np.zeros(len(rows), dtype=np.int32)
        # NULL dist-key values hash as 0 — this MUST agree with the
        # device-side hash, which sees an invalid lane's canonical 0 data
        # (outer-join padding rows route through dispatchers that way)
        cols = [
            np.asarray([0 if r[i] is None else r[i] for r in rows],
                       dtype=self.schema[i].data_type.np_dtype)
            for i in self.dist_key_indices
        ]
        return compute_vnodes_numpy(cols)

    def write_chunk_columns(self, ops: np.ndarray, cols: Sequence[np.ndarray],
                            vis: np.ndarray) -> None:
        """Columnar batch write — the per-barrier persistence hot path.

        For all-int64 schemas with ascending pk, key and value encoding run
        in the native C++ codec (risingwave_tpu/native) over the whole
        batch; otherwise falls back to the per-row path. `ops` uses chunk
        Op encoding; rows with vis False are skipped."""
        from ..common.chunk import OP_INSERT, OP_UPDATE_INSERT
        ops = np.asarray(ops)
        vis = np.asarray(vis, dtype=bool)
        idx = np.flatnonzero(vis)
        if idx.size == 0:
            return
        native_ok = (self._all_i64 and self.pk_descending is None)
        enc_keys = enc_vals = None
        if native_ok:
            from ..native import crc32_i64_batch, mc_encode_i64_batch,                 row_encode_i64_batch
            pk_mat = np.stack([np.asarray(cols[i], dtype=np.int64)[idx]
                               for i in self.pk_indices], axis=1)
            mc = mc_encode_i64_batch(pk_mat)
            if mc is not None:
                if self.dist_key_indices:
                    # MUST match compute_vnodes_numpy / the device hash
                    # (splitmix64) — the native crc32 batch is for the
                    # serialization goldens only; using it here would
                    # place batch-written rows under different keys than
                    # per-row gets/deletes compute
                    dist = [np.asarray(cols[i], dtype=np.int64)[idx]
                            for i in self.dist_key_indices]
                    vns = compute_vnodes_numpy(dist).astype(np.uint8)
                else:
                    vns = np.zeros(idx.size, dtype=np.uint8)
                prefix = np.frombuffer(
                    self.table_id.to_bytes(4, "big"), dtype=np.uint8)
                enc_keys = np.concatenate([
                    np.broadcast_to(prefix, (idx.size, 4)),
                    vns[:, None], mc], axis=1)
                all_mat = np.stack(
                    [np.asarray(c, dtype=np.int64)[idx] for c in cols],
                    axis=1)
                enc_vals = row_encode_i64_batch(
                    all_mat, self._serde._nbytes_nulls)
        if enc_keys is not None:
            ops_v = ops[idx]
            put = (ops_v == OP_INSERT) | (ops_v == OP_UPDATE_INSERT)
            for r in range(idx.size):
                k = enc_keys[r].tobytes()
                if put[r]:
                    self._mem[k] = (1, None, enc_vals[r].tobytes())
                else:
                    self._mem[k] = (-1, None, None)
            return
        rows = [(int(ops[i]), tuple(
            np.asarray(cols[j])[i].item() for j in range(len(cols))))
            for i in idx]
        self.write_chunk_rows(rows)

    # ------------------------------------------------------------- reads
    def get_row(self, pk: tuple, dist_values: Optional[tuple] = None) -> Optional[tuple]:
        """Read-through: mem-table first, then the store (:708)."""
        row_for_vnode = [None] * len(self.schema)
        for j, i in enumerate(self.pk_indices):
            row_for_vnode[i] = pk[j]
        if dist_values is not None:
            for j, i in enumerate(self.dist_key_indices):
                row_for_vnode[i] = dist_values[j]
        k = self._key_of(tuple(row_for_vnode))
        if k in self._mem:
            op, row, enc = self._mem[k]
            if op <= 0:
                return None
            return row if row is not None else self._serde.decode(enc)
        v = self.store.get(k)
        return self._serde.decode(v) if v is not None else None

    def get_rows(self, pks: Sequence[tuple]) -> list:
        """Batch point-get (requires dist_key ⊆ pk): vnodes for the whole
        batch hash in one vectorized pass, mem-table first, then the
        store's committed + sealed view via `get_many`. This is the
        evicted-range read-through: a reload of spilled state resolves
        every touched key in one pass instead of N `get_row` calls."""
        if not pks:
            return []
        if self.dist_key_indices:
            pos = [self.pk_indices.index(i) for i in self.dist_key_indices]
            cols = [np.asarray([0 if pk[p] is None else pk[p]
                                for pk in pks]).astype(
                        self.schema[i].data_type.np_dtype)
                    for p, i in zip(pos, self.dist_key_indices)]
            vns = compute_vnodes_numpy(cols)
        else:
            vns = np.zeros(len(pks), dtype=np.int32)
        keys = [self.key_of_pk(tuple(pk), int(vn))
                for pk, vn in zip(pks, vns)]
        out: list = []
        pending_keys, pending_pos = [], []
        for i, k in enumerate(keys):
            if k in self._mem:
                op, row, enc = self._mem[k]
                out.append(None if op <= 0 else
                           (row if row is not None
                            else self._serde.decode(enc)))
            else:
                out.append(None)
                pending_keys.append(k)
                pending_pos.append(i)
        for i, v in zip(pending_pos, self.store.get_many(pending_keys)):
            if v is not None:
                out[i] = self._serde.decode(v)
        return out

    def iter_vnode(self, vnode: int) -> Iterator[tuple[bytes, tuple]]:
        """All rows of one vnode, pk order, mem-table merged (:1255)."""
        start, end = self.vnode_key_range(vnode)
        merged: dict[bytes, Optional[tuple]] = {}
        for k, v in self.store.iter_range(start, end):
            merged[k] = self._serde.decode(v)
        for k, (op, row, enc) in self._mem.items():
            if start <= k < end:
                if op <= 0:
                    merged[k] = None
                else:
                    merged[k] = (row if row is not None
                                 else self._serde.decode(enc))
        for k in sorted(merged):
            if merged[k] is not None:
                yield k, merged[k]

    def iter_all(self) -> Iterator[tuple[bytes, tuple]]:
        for vn in np.flatnonzero(self.vnode_bitmap):
            yield from self.iter_vnode(int(vn))

    # ----------------------------------------------------------- barrier
    def commit(self, new_epoch: int) -> int:
        """Flush mem-table to the store and advance the epoch (:1036).
        Returns number of kv writes."""
        assert self.epoch is not None, "init_epoch not called"
        puts: dict[bytes, Optional[bytes]] = {}
        for k, (op, row, enc) in self._mem.items():
            if op <= 0:
                puts[k] = None
            else:
                puts[k] = enc if enc is not None else self._serde.encode(row)
        n = len(puts)
        if puts:
            self.store.ingest_batch(WriteBatch(self.table_id, self.epoch, puts))
        self._mem.clear()
        self.epoch = new_epoch
        return n

    def update_vnode_bitmap(self, bitmap: np.ndarray) -> None:
        """Scaling: this instance now owns a different vnode set (:778).
        Mem-table must be empty (only called at barriers)."""
        assert not self._mem, "dirty mem-table during reschedule"
        self.vnode_bitmap = np.asarray(bitmap, dtype=bool)

"""StorageTable — the batch/serving read path over an MV's committed state.

Reference: src/storage/src/table/batch_table/storage_table.rs:56,646-661 —
batch queries point-get and range-scan a materialized table at a pinned
snapshot epoch (the Hummock version meta committed), never seeing
uncommitted streaming writes.

TPU build: reads are HOST-side (serving pulls rows out of the system, so
there is nothing to gain — and on a tunneled TPU much to lose — from
routing them through the device). Snapshot isolation comes from the
store's `committed_only` read mode: Hummock serves only SSTs under the
manifest; streaming epochs still in the shared buffer are invisible. Key
construction is DELEGATED to a StateTable (one copy of the
`table_id ++ vnode ++ memcomparable(pk)` layout), so batch reads always
find streaming writes.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from ..common.types import Schema
from ..common.vnode import VNODE_COUNT
from .serde import RowSerde
from .state_table import StateTable
from .store import StateStore


class StorageTable:
    """Read-only batch access to a (materialized) table's committed state."""

    def __init__(self, store: StateStore, table_id: int, schema: Schema,
                 pk_indices: Sequence[int],
                 dist_key_indices: Optional[Sequence[int]] = None,
                 pk_descending: Optional[Sequence[bool]] = None):
        # a private StateTable carries the key layout; its mem-table is
        # never written (reads here are store-only, committed snapshot)
        self._layout = StateTable(
            store, table_id=table_id, schema=schema, pk_indices=pk_indices,
            dist_key_indices=dist_key_indices, pk_descending=pk_descending)
        self.store = store
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = tuple(pk_indices)
        self._serde = RowSerde(schema)

    @classmethod
    def for_state_table(cls, t: StateTable) -> "StorageTable":
        """Batch-read view of an existing StateTable (same key layout)."""
        return cls(t.store, t.table_id, t.schema, t.pk_indices,
                   dist_key_indices=t.dist_key_indices,
                   pk_descending=t.pk_descending)

    # --------------------------------------------------------------- reads
    def get_row(self, pk: tuple) -> Optional[tuple]:
        """Committed point lookup by primary key
        (storage_table.rs point-get path)."""
        pk = tuple(pk)
        key = self._layout.key_of_pk(pk, self._layout.vnode_of_pk(pk))
        for _, row in self._iter_keyrange(key, key + b"\xff"):
            return row
        return None

    def _iter_keyrange(self, start: bytes, end: bytes
                       ) -> Iterator[tuple[bytes, tuple]]:
        for k, v in self.store.iter_range(start, end, committed_only=True):
            yield k, self._serde.decode(v)

    def batch_iter_vnode(self, vnode: int) -> Iterator[tuple]:
        """Committed rows of one vnode in pk order
        (storage_table.rs:646 batch_iter_vnode)."""
        start, end = self._layout.vnode_key_range(vnode)
        for _, row in self._iter_keyrange(start, end):
            yield row

    def batch_iter(self, vnode_bitmap: Optional[np.ndarray] = None
                   ) -> Iterator[tuple]:
        """Full committed scan (optionally restricted to a vnode subset —
        the distributed-scan unit the batch scheduler hands each task)."""
        vnodes = (range(VNODE_COUNT) if vnode_bitmap is None
                  else np.flatnonzero(vnode_bitmap))
        for vn in vnodes:
            yield from self.batch_iter_vnode(int(vn))

    def to_numpy(self, vnode_bitmap: Optional[np.ndarray] = None
                 ) -> list[np.ndarray]:
        """Whole committed table as one numpy column set (RowSeqScan's
        chunk form, the input to batch expression evaluation)."""
        rows = list(self.batch_iter(vnode_bitmap))
        if not rows:
            return [np.empty(0, dtype=f.data_type.np_dtype)
                    for f in self.schema]
        return [np.asarray([r[j] for r in rows],
                           dtype=f.data_type.np_dtype)
                for j, f in enumerate(self.schema)]

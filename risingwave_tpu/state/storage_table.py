"""StorageTable — the batch/serving read path over an MV's committed state.

Reference: src/storage/src/table/batch_table/storage_table.rs:56,646-661 —
batch queries point-get and range-scan a materialized table at a pinned
snapshot epoch (the Hummock version meta committed), never seeing
uncommitted streaming writes.

TPU build: reads are HOST-side (serving pulls rows out of the system, so
there is nothing to gain — and on a tunneled TPU much to lose — from
routing them through the device). Snapshot isolation comes from the
store's `committed_only` read mode: Hummock serves only SSTs under the
manifest; streaming epochs still in the shared buffer are invisible. Key
construction is DELEGATED to a StateTable (one copy of the
`table_id ++ vnode ++ memcomparable(pk)` layout), so batch reads always
find streaming writes.
"""

from __future__ import annotations

from typing import Iterator, Optional, Sequence

import numpy as np

from ..common.types import Schema
from ..common.vnode import VNODE_COUNT
from .serde import RowSerde
from .state_table import StateTable
from .store import StateStore


class StorageTable:
    """Read-only batch access to a (materialized) table's committed state."""

    def __init__(self, store: StateStore, table_id: int, schema: Schema,
                 pk_indices: Sequence[int],
                 dist_key_indices: Optional[Sequence[int]] = None,
                 pk_descending: Optional[Sequence[bool]] = None):
        # a private StateTable carries the key layout; its mem-table is
        # never written (reads here are store-only, committed snapshot)
        self._layout = StateTable(
            store, table_id=table_id, schema=schema, pk_indices=pk_indices,
            dist_key_indices=dist_key_indices, pk_descending=pk_descending)
        self.store = store
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = tuple(pk_indices)
        self._serde = RowSerde(schema)

    @classmethod
    def for_state_table(cls, t: StateTable) -> "StorageTable":
        """Batch-read view of an existing StateTable (same key layout)."""
        return cls(t.store, t.table_id, t.schema, t.pk_indices,
                   dist_key_indices=t.dist_key_indices,
                   pk_descending=t.pk_descending)

    # --------------------------------------------------------------- reads
    def get_row(self, pk: tuple) -> Optional[tuple]:
        """Committed point lookup by primary key
        (storage_table.rs point-get path)."""
        pk = tuple(pk)
        key = self._layout.key_of_pk(pk, self._layout.vnode_of_pk(pk))
        for _, row in self._iter_keyrange(key, key + b"\xff"):
            return row
        return None

    def _iter_keyrange(self, start: bytes, end: bytes
                       ) -> Iterator[tuple[bytes, tuple]]:
        for k, v in self.store.iter_range(start, end, committed_only=True):
            yield k, self._serde.decode(v)

    def scan_vnode_after(self, vnode: int, after_pk: Optional[tuple],
                         limit: int, max_epoch: Optional[int] = None
                         ) -> tuple[list[tuple], bool]:
        """Up to `limit` rows of one vnode with pk STRICTLY after
        `after_pk` (None = from the vnode's start), in pk order — the
        backfill snapshot-batch read (no_shuffle_backfill.rs's per-epoch
        snapshot stream). max_epoch bounds staged-epoch visibility so the
        read is consistent with a specific barrier. Returns (rows,
        vnode_exhausted)."""
        start, end = self._layout.vnode_key_range(vnode)
        if after_pk is not None:
            # memcomparable keys order like their pk tuples: the next key
            # strictly after an exact pk is key ++ 0x00
            start = self._layout.key_of_pk(tuple(after_pk), vnode) + b"\x00"
        rows: list[tuple] = []
        for k, v in self.store.iter_range(start, end, committed_only=False,
                                          max_epoch=max_epoch):
            rows.append(self._serde.decode(v))
            if len(rows) > limit:
                break
        if len(rows) > limit:
            return rows[:limit], False
        return rows, True

    def batch_iter_vnode(self, vnode: int) -> Iterator[tuple]:
        """Committed rows of one vnode in pk order
        (storage_table.rs:646 batch_iter_vnode)."""
        start, end = self._layout.vnode_key_range(vnode)
        for _, row in self._iter_keyrange(start, end):
            yield row

    def batch_iter(self, vnode_bitmap: Optional[np.ndarray] = None
                   ) -> Iterator[tuple]:
        """Full committed scan (optionally restricted to a vnode subset —
        the distributed-scan unit the batch scheduler hands each task)."""
        vnodes = (range(VNODE_COUNT) if vnode_bitmap is None
                  else np.flatnonzero(vnode_bitmap))
        for vn in vnodes:
            yield from self.batch_iter_vnode(int(vn))

    def snapshot_with_keys(self, max_epoch: Optional[int] = None,
                           committed_only: bool = False
                           ) -> tuple[list[tuple], list[bytes]]:
        """(rows, store keys) of the whole table in key order, with
        staged (shared-buffer) epochs <= `max_epoch` visible on top of
        the committed base — the serving cache's build scan: at barrier
        collection this sees EXACTLY the epochs the barrier sealed,
        whether or not the background uploader has committed them yet,
        so the cache and the changelog hook agree on where incremental
        maintenance takes over. `committed_only=True` restricts to the
        manifest snapshot — the changelog subscription's backfill read,
        which must align exactly with `store.committed_epoch()` so the
        tail (committed log entries > that epoch) overlaps nothing."""
        rows: list[tuple] = []
        keys: list[bytes] = []
        for vn in range(VNODE_COUNT):
            start, end = self._layout.vnode_key_range(vn)
            for k, v in self.store.iter_range(start, end,
                                              committed_only=committed_only,
                                              max_epoch=max_epoch):
                keys.append(k)
                rows.append(self._serde.decode(v))
        return rows, keys

    def to_numpy(self, vnode_bitmap: Optional[np.ndarray] = None
                 ) -> list[np.ndarray]:
        """Whole committed table as one numpy column set (RowSeqScan's
        chunk form, the input to batch expression evaluation)."""
        return self.to_numpy_with_validity(vnode_bitmap)[0]

    def to_numpy_with_validity(
            self, vnode_bitmap: Optional[np.ndarray] = None
    ) -> tuple[list[np.ndarray], list[np.ndarray]]:
        """(columns, validity masks) — NULL cells decode as None in row
        form; here they become (0, valid=False) so the batch path carries
        real NULL semantics instead of fabricating values (ADVICE r2 #2)."""
        return rows_to_columns(self.schema,
                               list(self.batch_iter(vnode_bitmap)))


def rows_to_columns(schema: Schema, rows: list
                    ) -> tuple[list[np.ndarray], list[np.ndarray]]:
    """Shared rows->(columns, validity) conversion: the ONE place the
    None-cell convention (0 + valid=False) is encoded."""
    cols, valids = [], []
    for j, f in enumerate(schema):
        vals = [r[j] for r in rows]
        valid = np.asarray([v is not None for v in vals], dtype=bool)
        arr = np.asarray([0 if v is None else v for v in vals],
                         dtype=f.data_type.np_dtype)
        cols.append(arr)
        valids.append(valid)
    return cols, valids

from .store import StateStore, MemoryStateStore, WriteBatch, encode_table_key
from .state_table import StateTable, StateTableError
from .serde import RowSerde, encode_memcomparable, decode_memcomparable
from .hummock import HummockStateStore
from .compactor import BackgroundCompactor, BrokerRetentionManager, PinRegistry
from .object_store import (ObjectStore, InMemObjectStore,
                           LocalFsObjectStore, ResilientObjectStore,
                           TransientObjectError, ObjectStoreUnavailable)
from .storage_table import StorageTable

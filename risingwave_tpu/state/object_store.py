"""Object store layer — where checkpoint SSTs live.

Reference: src/object_store/src/object/mod.rs (ObjectStore trait: upload /
read / delete / list) with S3 / in-mem / local-fs backends, wrapped in the
reference's RetryCondition/timeout layer (object/src/object/mod.rs
ObjectStoreConfig: every op retries transient errors with bounded
exponential backoff under a per-op deadline). Here the durable backend is
the local filesystem (atomic tmp+rename uploads, fsync'd), which is what a
TPU-VM pod slice sees for /tmp-class scratch and what the restart tests
exercise; an in-memory backend backs pure-unit tests of the LSM layer.
`ResilientObjectStore` is the retry layer every Hummock handle wraps its
backend in: transient faults are absorbed BELOW the recovery machinery
(bounded retries, seeded backoff + jitter, per-op deadline), persistent
faults classify out immediately and take the existing fail-stop ->
recovery path, so correctness is never weaker than fail-stop.
"""

from __future__ import annotations

import os
import random
import time
from typing import Optional


class ObjectStore:
    def upload(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError


class InMemObjectStore(ObjectStore):
    """Reference: object/mem.rs — for tests of the layers above."""

    def __init__(self):
        self._objects: dict[str, bytes] = {}

    def upload(self, path: str, data: bytes) -> None:
        self._objects[path] = bytes(data)

    def read(self, path: str) -> bytes:
        return self._objects[path]

    def delete(self, path: str) -> None:
        self._objects.pop(path, None)

    def list(self, prefix: str) -> list[str]:
        return sorted(p for p in self._objects if p.startswith(prefix))

    def exists(self, path: str) -> bool:
        return path in self._objects


# a stranded `.tmp` (crash between write and rename) older than this is
# swept at open; the age guard keeps a concurrent opener (cluster compute
# nodes share the store directory) from deleting a sibling's IN-FLIGHT
# upload tmp — a live upload never lives anywhere near this long
TMP_SWEEP_AGE_S = 300.0


class LocalFsObjectStore(ObjectStore):
    """Durable local-dir backend (reference: object/opendal_engine/fs.rs).

    Uploads are atomic (write tmp, fsync, rename) so a crash mid-upload can
    never leave a torn object visible — the manifest-swap recovery protocol
    depends on this. The crash DOES strand the `.tmp` file forever
    (`list()` hides them but the directory grows unboundedly), so open
    sweeps stale ones (see TMP_SWEEP_AGE_S).
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._sweep_stale_tmp()

    def _sweep_stale_tmp(self) -> None:
        from ..utils.metrics import OBJECT_TMP_SWEPT
        now = time.time()
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                if not name.endswith(".tmp"):
                    continue
                p = os.path.join(dirpath, name)
                try:
                    if now - os.path.getmtime(p) >= TMP_SWEEP_AGE_S:
                        os.remove(p)
                        OBJECT_TMP_SWEPT.inc()
                except OSError:
                    pass          # raced another opener / live upload

    def _abs(self, path: str) -> str:
        root = os.path.normpath(self.root)
        p = os.path.normpath(os.path.join(root, path))
        # exact-prefix-with-separator check (plain startswith would admit
        # sibling roots like root+"2"); raise, never assert — containment
        # must hold under python -O too
        if p != root and not p.startswith(root + os.sep):
            raise ValueError(f"object path escapes store root: {path!r}")
        return p

    def upload(self, path: str, data: bytes) -> None:
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def read(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def delete(self, path: str) -> None:
        try:
            os.remove(self._abs(path))
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> list[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                if name.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))


class TransientObjectError(OSError):
    """A fault the retry layer may absorb (injected faults and real
    I/O-class errors classify here). Deliberately an OSError so an
    unwrapped backend raising it still takes the fail-stop path."""


class ObjectStoreUnavailable(RuntimeError):
    """Retries/deadline exhausted on a transient fault — the PERSISTENT
    outcome: falls through to the existing fail-stop -> recovery-radius
    machinery, exactly like any other store error."""


def _path_kind(path: str) -> str:
    """Coarse object class for fault-rule filtering and metrics labels."""
    if path.startswith("ssts/"):
        return "sst"
    if path == "MANIFEST":
        return "manifest"
    if path == "CATALOG":
        return "catalog"
    if path.startswith("dict/"):
        return "dict"
    return "other"


def _corrupt_bytes(data: bytes) -> bytes:
    """Deterministic payload corruption for the object_get_corrupt fault
    point: flip a byte in the middle (past any magic) so checksums fail
    but framing-magic checks still route to the crc branch."""
    if len(data) <= 8:
        return bytes(len(data))
    i = len(data) // 2
    return data[:i] + bytes([data[i] ^ 0xFF]) + data[i + 1:]


class ResilientObjectStore(ObjectStore):
    """Bounded-retry wrapper every Hummock handle puts around its backend.

    * transient faults (I/O-class OSErrors, injected `object_put_fail` /
      `object_get_fail`) retry up to `max_attempts` with seeded
      exponential backoff + jitter under a per-op deadline — absorbed
      below the recovery machinery, `object_store_retries_total{op}`
      counts them;
    * persistent faults (missing object, path escape, type errors)
      raise immediately;
    * exhausted retries raise ObjectStoreUnavailable — the persistent
      outcome falls through to today's fail-stop -> radius engine, so
      correctness is never weaker than without the wrapper;
    * `object_get_corrupt` injects payload corruption AFTER the read so
      the caller's checksum-retry path (state/hummock.py `_read_sst`)
      exercises exactly like torn-cache media corruption.

    `object_store_op_seconds{op}` histograms every op. Attribute reads
    that miss here delegate to the wrapped backend (`root`, test pokes),
    so existing `getattr(store.objects, "root", ...)` call sites keep
    working.
    """

    # persistent: retrying cannot help; the error is the answer
    _PERSISTENT = (FileNotFoundError, IsADirectoryError, NotADirectoryError,
                   PermissionError, KeyError, ValueError, TypeError)

    _OP_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 30.0)

    def __init__(self, inner: ObjectStore, max_attempts: int = 4,
                 backoff_base_ms: float = 10.0,
                 backoff_cap_ms: float = 1000.0,
                 op_deadline_s: float = 30.0, seed: int = 0):
        self._inner = inner
        self.max_attempts = int(max_attempts)
        self.backoff_base_ms = float(backoff_base_ms)
        self.backoff_cap_ms = float(backoff_cap_ms)
        self.op_deadline_s = float(op_deadline_s)
        self._rng = random.Random(seed)
        from ..utils.metrics import GLOBAL_METRICS
        self._metrics = GLOBAL_METRICS
        self._m_retries: dict[str, object] = {}
        self._m_seconds: dict[str, object] = {}

    @classmethod
    def wrap(cls, store: ObjectStore) -> "ResilientObjectStore":
        """Idempotent: wrapping a wrapper returns it unchanged (cluster
        compute nodes and meta both construct Hummock handles over the
        same directory)."""
        return store if isinstance(store, cls) else cls(store)

    @property
    def inner(self) -> ObjectStore:
        return self._inner

    def __getattr__(self, name):
        # only reached when normal lookup fails: backend-specific
        # attributes (root, _objects, ...) pass through
        return getattr(self._inner, name)

    def _classify_transient(self, exc: BaseException) -> bool:
        if isinstance(exc, TransientObjectError):
            return True
        if isinstance(exc, self._PERSISTENT):
            return False
        return isinstance(exc, (OSError, TimeoutError))

    def _retry_counter(self, op: str):
        c = self._m_retries.get(op)
        if c is None:
            c = self._metrics.counter("object_store_retries_total", op=op)
            self._m_retries[op] = c
        return c

    def _op_hist(self, op: str):
        h = self._m_seconds.get(op)
        if h is None:
            h = self._metrics.histogram("object_store_op_seconds",
                                        buckets=self._OP_BUCKETS, op=op)
            self._m_seconds[op] = h
        return h

    def _do(self, op: str, path: str, fn):
        from ..utils.faults import FAULTS
        from ..utils.metrics import OBJECT_RETRIES
        t0 = time.monotonic()
        kind = _path_kind(path)
        attempt = 0
        while True:
            attempt += 1
            try:
                if FAULTS.active and op in ("put", "get"):
                    if FAULTS.hit(f"object_{op}_fail", path=path,
                                  kind=kind, attempt=attempt) is not None:
                        raise TransientObjectError(
                            f"injected object_{op}_fail for {path!r} "
                            f"(attempt {attempt})")
                out = fn()
                if op == "get" and FAULTS.active:
                    if FAULTS.hit("object_get_corrupt", path=path,
                                  kind=kind) is not None:
                        out = _corrupt_bytes(out)
                self._op_hist(op).observe(time.monotonic() - t0)
                return out
            except BaseException as e:  # noqa: BLE001 — classified below
                if not self._classify_transient(e):
                    raise
                elapsed = time.monotonic() - t0
                if attempt >= self.max_attempts \
                        or elapsed >= self.op_deadline_s:
                    self._op_hist(op).observe(time.monotonic() - t0)
                    raise ObjectStoreUnavailable(
                        f"object {op} {path!r} failed after {attempt} "
                        f"attempts in {elapsed:.3f}s") from e
                self._retry_counter(op).inc()
                OBJECT_RETRIES.inc()
                delay_ms = min(self.backoff_cap_ms,
                               self.backoff_base_ms * (2 ** (attempt - 1)))
                # +-50% jitter off a seeded RNG — deterministic per
                # process for the chaos harness, decorrelated in a fleet
                time.sleep(delay_ms / 1e3 * (0.5 + self._rng.random()))

    # ------------------------------------------------------------- ops
    def upload(self, path: str, data: bytes) -> None:
        self._do("put", path, lambda: self._inner.upload(path, data))

    def read(self, path: str) -> bytes:
        return self._do("get", path, lambda: self._inner.read(path))

    def delete(self, path: str) -> None:
        self._do("delete", path, lambda: self._inner.delete(path))

    def list(self, prefix: str) -> list[str]:
        return self._do("list", prefix, lambda: self._inner.list(prefix))

    def exists(self, path: str) -> bool:
        return self._do("exists", path, lambda: self._inner.exists(path))

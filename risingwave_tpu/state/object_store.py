"""Object store layer — where checkpoint SSTs live.

Reference: src/object_store/src/object/mod.rs (ObjectStore trait: upload /
read / delete / list) with S3 / in-mem / local-fs backends. Here the durable
backend is the local filesystem (atomic tmp+rename uploads, fsync'd), which
is what a TPU-VM pod slice sees for /tmp-class scratch and what the restart
tests exercise; an in-memory backend backs pure-unit tests of the LSM layer.
"""

from __future__ import annotations

import os


class ObjectStore:
    def upload(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read(self, path: str) -> bytes:
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def list(self, prefix: str) -> list[str]:
        raise NotImplementedError

    def exists(self, path: str) -> bool:
        raise NotImplementedError


class InMemObjectStore(ObjectStore):
    """Reference: object/mem.rs — for tests of the layers above."""

    def __init__(self):
        self._objects: dict[str, bytes] = {}

    def upload(self, path: str, data: bytes) -> None:
        self._objects[path] = bytes(data)

    def read(self, path: str) -> bytes:
        return self._objects[path]

    def delete(self, path: str) -> None:
        self._objects.pop(path, None)

    def list(self, prefix: str) -> list[str]:
        return sorted(p for p in self._objects if p.startswith(prefix))

    def exists(self, path: str) -> bool:
        return path in self._objects


class LocalFsObjectStore(ObjectStore):
    """Durable local-dir backend (reference: object/opendal_engine/fs.rs).

    Uploads are atomic (write tmp, fsync, rename) so a crash mid-upload can
    never leave a torn object visible — the manifest-swap recovery protocol
    depends on this.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _abs(self, path: str) -> str:
        root = os.path.normpath(self.root)
        p = os.path.normpath(os.path.join(root, path))
        # exact-prefix-with-separator check (plain startswith would admit
        # sibling roots like root+"2"); raise, never assert — containment
        # must hold under python -O too
        if p != root and not p.startswith(root + os.sep):
            raise ValueError(f"object path escapes store root: {path!r}")
        return p

    def upload(self, path: str, data: bytes) -> None:
        dst = self._abs(path)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        tmp = dst + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, dst)

    def read(self, path: str) -> bytes:
        with open(self._abs(path), "rb") as f:
            return f.read()

    def delete(self, path: str) -> None:
        try:
            os.remove(self._abs(path))
        except FileNotFoundError:
            pass

    def list(self, prefix: str) -> list[str]:
        out = []
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                if name.endswith(".tmp"):
                    continue
                rel = os.path.relpath(os.path.join(dirpath, name), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)

    def exists(self, path: str) -> bool:
        return os.path.exists(self._abs(path))

"""State store interfaces + in-memory implementation.

Reference: `StateStore`/`LocalStateStore` traits (src/storage/src/store.rs:
172-257) — epoch-versioned KV with table-scoped reads, per-epoch `sync` for
checkpoint durability. Keys follow the reference layout
`table_id ++ vnode ++ memcomparable(pk)` (hummock_sdk/src/key.rs) so range
scans per vnode are contiguous.

`MemoryStateStore` is the reference's `MemoryStateStore`
(src/storage/src/memory.rs): a sorted map, epochs tracked for sync semantics
but everything stays in RAM. The durable LSM variant is state/hummock.py.
"""

from __future__ import annotations

import bisect
import heapq
from dataclasses import dataclass, field
from typing import Iterator, Optional


def encode_table_key(table_id: int, vnode: int, pk_bytes: bytes) -> bytes:
    return table_id.to_bytes(4, "big") + vnode.to_bytes(1, "big") + pk_bytes


def lazy_merge_ranges(streams):
    """K-way merge of (key, value|None) iterators, each ascending by key,
    ordered NEWEST FIRST; yields live (key, value) lazily with the newest
    version of each key winning. Lazy matters: backfill snapshot batches
    stop after `limit` rows, and an eager range materialization would make
    every per-barrier batch O(remaining rows) instead of O(limit)."""
    h = []
    for pri, it in enumerate(streams):
        it = iter(it)
        for k, v in it:
            heapq.heappush(h, (k, pri, v, it))
            break
    prev_key = None
    while h:
        k, pri, v, it = heapq.heappop(h)
        for nk, nv in it:
            heapq.heappush(h, (nk, pri, nv, it))
            break
        if k == prev_key:
            continue
        prev_key = k
        if v is not None:
            yield k, v


@dataclass
class WriteBatch:
    table_id: int
    epoch: int
    # key -> value (None = tombstone/delete)
    puts: dict[bytes, Optional[bytes]]


class StateStore:
    """Epoch-versioned KV. Writes are staged per epoch and become readable
    immediately to the writer (mem-table semantics handled by StateTable);
    `sync(epoch)` makes everything up to `epoch` durable.

    Deferred-flush protocol (the async-checkpoint hook): a stateful
    executor's barrier-time persist splits into a device-dispatch half
    (runs at the barrier) and a staged host half registered here via
    `defer_flush(epoch, *stages)`, each stage a `(wait, cont)` pair:

      * `wait()` -> payload: a PURE device wait / host computation (an
        `np.asarray` of an already-dispatched buffer, `utils/d2h.py
        fetch_flat`). The background uploader runs it on a worker
        thread. It MUST NOT dispatch jax ops — a second thread
        dispatching concurrently with the event loop deadlocks jax.
      * `cont(payload)`: runs on the event loop; may dispatch follow-up
        device ops (count-dependent prefix slicing/packing) and write/
        commit state tables.

    With `defer_enabled` False (the default — unit tests driving
    executors directly, inline-sync mode) all stages run immediately in
    order, which is exactly the pre-pipeline behavior. The barrier
    coordinator's background uploader enables deferral and drains the
    queue before sealing each epoch, so the stream never waits for the
    d2h + encode + ingest cost."""

    def __init__(self):
        # FIFO of (epoch, stages, table_id); epoch = the shared-buffer
        # epoch the flush writes into (must run before that epoch seals);
        # table_id attributes the flush to its owning executor's primary
        # state table so per-fragment recovery can discard exactly the
        # rebuilt fragment's pending flushes (None = untagged, never
        # discarded selectively)
        self._deferred: list[tuple] = []
        self.defer_enabled = False

    @staticmethod
    def _run_stages(stages) -> None:
        for wait, cont in stages:
            cont(wait() if wait is not None else None)

    def defer_flush(self, epoch: int, *stages, table_id=None) -> None:
        if self.defer_enabled:
            self._deferred.append((epoch, stages, table_id))
        else:
            self._run_stages(stages)

    def take_deferred(self, epoch: int) -> list[tuple]:
        """Pop every stage list registered for epochs <= epoch, in
        registration order."""
        taken = [st for e, st, _t in self._deferred if e <= epoch]
        self._deferred = [t for t in self._deferred if t[0] > epoch]
        return taken

    def discard_staged_tables(self, table_ids) -> None:
        """Per-fragment recovery: drop the STAGED (uncommitted shared-
        buffer) writes and pending deferred flushes of exactly these
        tables. The rest of the shared buffer — surviving fragments'
        partial-epoch writes — stays put and commits with the next
        checkpoint (`seal` sweeps every staged epoch <= its target), so
        a survivor whose dirty tracking already cleared at the failed
        barrier never loses its flushed rows. The rebuilt fragment
        re-reads its tables at the committed view and re-stages the
        replayed intervals itself."""
        ids = set(table_ids)
        self._deferred = [t for t in self._deferred if t[2] not in ids]
        for buf in getattr(self, "_shared", {}).values():
            for k in [k for k in buf
                      if int.from_bytes(k[:4], "big") in ids]:
                del buf[k]

    def run_deferred(self, epoch: int) -> None:
        for stages in self.take_deferred(epoch):
            self._run_stages(stages)

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def get_committed(self, key: bytes) -> Optional[bytes]:
        """Point get against the COMMITTED snapshot only — staged and
        sealed-but-uncommitted epochs are invisible. The log store's
        delivery cursor reads through here: a cursor staged by a
        checkpoint that never committed must not be resumed from
        (logstore/log.py)."""
        raise NotImplementedError

    def get_many(self, keys) -> list:
        """Batch point-get over the same read view as `get` (mem-table
        merging is the StateTable's job): the evicted-range read-through
        path — a reload of spilled state resolves its keys against the
        committed + sealed (staged) view in one call. Backends with a
        cheaper batched lookup override this."""
        return [self.get(k) for k in keys]

    def iter_range(self, start: bytes, end: bytes,
                   committed_only: bool = False,
                   max_epoch: Optional[int] = None
                   ) -> Iterator[tuple[bytes, bytes]]:
        """committed_only=True restricts to the committed (synced)
        snapshot. max_epoch bounds which STAGED (shared-buffer) epochs are
        visible — the backfill snapshot-read isolation: a reader at
        barrier E must not see epochs the upstream ingested past E
        (no_shuffle_backfill.rs reads the upstream table at exactly the
        barrier epoch)."""
        raise NotImplementedError

    def ingest_batch(self, batch: WriteBatch) -> None:
        raise NotImplementedError

    def sync(self, epoch: int) -> dict:
        """Flush everything sealed up to `epoch` durable; returns sync info
        (sst ids etc.) for the checkpoint manifest."""
        raise NotImplementedError

    def committed_epoch(self) -> int:
        raise NotImplementedError


class MemoryStateStore(StateStore):
    """Sorted base map + per-epoch shared buffers (the same staging shape
    as Hummock-lite, minus durability): `ingest_batch` stages, `sync`
    applies destructively. Keeping staged epochs distinct is what lets
    `iter_range(max_epoch=...)` serve the backfill's epoch-consistent
    snapshot reads on the in-memory store too."""

    def __init__(self):
        super().__init__()
        self._keys: list[bytes] = []       # sorted, synced base
        self._vals: dict[bytes, bytes] = {}
        self._shared: dict[int, dict[bytes, Optional[bytes]]] = {}
        self._committed_epoch = 0

    def get(self, key: bytes) -> Optional[bytes]:
        for epoch in sorted(self._shared, reverse=True):
            buf = self._shared[epoch]
            if key in buf:
                return buf[key]
        return self._vals.get(key)

    def get_committed(self, key: bytes) -> Optional[bytes]:
        # the synced base map IS the committed view (sync() applies
        # destructively — the in-memory analogue of the manifest)
        return self._vals.get(key)

    def iter_range(self, start: bytes, end: bytes,
                   committed_only: bool = False,
                   max_epoch: Optional[int] = None):
        streams = []
        if not committed_only:
            for epoch in sorted(self._shared, reverse=True):  # newest first
                if max_epoch is not None and epoch > max_epoch:
                    continue
                buf = self._shared[epoch]
                streams.append(sorted(
                    (k, v) for k, v in buf.items() if start <= k < end))

        def base():
            i = bisect.bisect_left(self._keys, start)
            while i < len(self._keys) and self._keys[i] < end:
                k = self._keys[i]
                yield k, self._vals[k]
                i += 1
        streams.append(base())
        yield from lazy_merge_ranges(streams)

    def ingest_batch(self, batch: WriteBatch) -> None:
        self._shared.setdefault(batch.epoch, {}).update(batch.puts)

    def sync(self, epoch: int) -> dict:
        self.run_deferred(epoch)
        for e in sorted(e for e in self._shared if e <= epoch):
            for k, v in self._shared.pop(e).items():
                if v is None:
                    if k in self._vals:
                        del self._vals[k]
                        i = bisect.bisect_left(self._keys, k)
                        if i < len(self._keys) and self._keys[i] == k:
                            self._keys.pop(i)
                else:
                    if k not in self._vals:
                        bisect.insort(self._keys, k)
                    self._vals[k] = v
        self._committed_epoch = max(self._committed_epoch, epoch)
        return {"uncommitted_ssts": []}

    def committed_epoch(self) -> int:
        return self._committed_epoch

    def reset_uncommitted(self) -> None:
        """Recovery entry point (see HummockStateStore.reset_uncommitted)."""
        self._shared.clear()
        self._deferred.clear()

"""State store interfaces + in-memory implementation.

Reference: `StateStore`/`LocalStateStore` traits (src/storage/src/store.rs:
172-257) — epoch-versioned KV with table-scoped reads, per-epoch `sync` for
checkpoint durability. Keys follow the reference layout
`table_id ++ vnode ++ memcomparable(pk)` (hummock_sdk/src/key.rs) so range
scans per vnode are contiguous.

`MemoryStateStore` is the reference's `MemoryStateStore`
(src/storage/src/memory.rs): a sorted map, epochs tracked for sync semantics
but everything stays in RAM. The durable LSM variant is state/hummock.py.
"""

from __future__ import annotations

import bisect
from dataclasses import dataclass, field
from typing import Iterator, Optional


def encode_table_key(table_id: int, vnode: int, pk_bytes: bytes) -> bytes:
    return table_id.to_bytes(4, "big") + vnode.to_bytes(1, "big") + pk_bytes


@dataclass
class WriteBatch:
    table_id: int
    epoch: int
    # key -> value (None = tombstone/delete)
    puts: dict[bytes, Optional[bytes]]


class StateStore:
    """Epoch-versioned KV. Writes are staged per epoch and become readable
    immediately to the writer (mem-table semantics handled by StateTable);
    `sync(epoch)` makes everything up to `epoch` durable."""

    def get(self, key: bytes) -> Optional[bytes]:
        raise NotImplementedError

    def iter_range(self, start: bytes, end: bytes,
                   committed_only: bool = False
                   ) -> Iterator[tuple[bytes, bytes]]:
        """committed_only=True restricts to the committed snapshot where
        the store can distinguish (Hummock); in-memory test stores apply
        writes destructively and serve latest either way."""
        raise NotImplementedError

    def ingest_batch(self, batch: WriteBatch) -> None:
        raise NotImplementedError

    def sync(self, epoch: int) -> dict:
        """Flush everything sealed up to `epoch` durable; returns sync info
        (sst ids etc.) for the checkpoint manifest."""
        raise NotImplementedError

    def committed_epoch(self) -> int:
        raise NotImplementedError


class MemoryStateStore(StateStore):
    def __init__(self):
        self._keys: list[bytes] = []       # sorted
        self._vals: dict[bytes, bytes] = {}
        self._committed_epoch = 0
        self._pending_epochs: set[int] = set()

    def get(self, key: bytes) -> Optional[bytes]:
        return self._vals.get(key)

    def iter_range(self, start: bytes, end: bytes,
                   committed_only: bool = False):
        i = bisect.bisect_left(self._keys, start)
        while i < len(self._keys) and self._keys[i] < end:
            k = self._keys[i]
            yield k, self._vals[k]
            i += 1

    def ingest_batch(self, batch: WriteBatch) -> None:
        self._pending_epochs.add(batch.epoch)
        for k, v in batch.puts.items():
            if v is None:
                if k in self._vals:
                    del self._vals[k]
                    i = bisect.bisect_left(self._keys, k)
                    if i < len(self._keys) and self._keys[i] == k:
                        self._keys.pop(i)
            else:
                if k not in self._vals:
                    bisect.insort(self._keys, k)
                self._vals[k] = v

    def sync(self, epoch: int) -> dict:
        self._pending_epochs = {e for e in self._pending_epochs if e > epoch}
        self._committed_epoch = max(self._committed_epoch, epoch)
        return {"uncommitted_ssts": []}

    def committed_epoch(self) -> int:
        return self._committed_epoch

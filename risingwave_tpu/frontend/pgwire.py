"""pgwire — the PostgreSQL wire protocol server (layer 1, client protocol).

Reference: src/utils/pgwire/src/pg_server.rs:173 (tcp accept loop),
pg_protocol.rs:391 (message dispatch), :548 (simple query). This is NOT a
port of that 6k-LoC crate: it implements the subset a stock `psql`/driver
needs for the simple-query flow —

  SSLRequest            -> 'N' (no TLS)
  StartupMessage        -> AuthenticationOk, ParameterStatus*,
                           BackendKeyData, ReadyForQuery
  Query ('Q')           -> per ';'-separated statement: RowDescription +
                           DataRow* + CommandComplete (SELECT) or
                           CommandComplete (DDL) or ErrorResponse; ONE
                           ReadyForQuery at the end
  Terminate ('X')       -> close

Extended protocol (pg_protocol.rs:394-412): Parse/Bind/Describe/
Execute/Close/Flush/Sync with named or unnamed statements/portals and
TEXT-format parameters ($1..$n substituted at bind). Describe(portal)
of a SELECT runs the batch query and caches the rows for Execute (the
libpq PQexecParams flow: Parse, Bind, Describe, Execute, Sync). After
an error, messages are skipped until Sync (the protocol's error
recovery rule). Binary format codes are refused. All values transfer
in text format (format code 0), NULL as the -1 length sentinel.

The server shares the Session's asyncio loop: DDL statements await
`Session.execute` (which runs barrier rounds), SELECTs call the batch
engine over committed snapshots — identical semantics to the REPL.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional

from ..common.types import DataType
from ..serving.pool import ServingTimeout
from . import sql as ast
from .binder import BindError
from .sql import SqlError

# per-connection extended-protocol state bounds: long-lived connections
# (pools, ORMs) Parse named statements forever; without a cap the dicts
# grow without limit. Least-recently-USED entries evict first (access
# moves a name to the tail of the insertion-ordered dict).
MAX_PREPARED_STATEMENTS = 64
MAX_PORTALS = 64


def _lru_touch(d: dict, name: str) -> None:
    d[name] = d.pop(name)


def _lru_insert(d: dict, name: str, value, cap: int) -> None:
    d.pop(name, None)
    d[name] = value
    while len(d) > cap:
        del d[next(iter(d))]

# text-format type OIDs (pg_catalog): int8, float8, text, bool
_OID = {
    DataType.INT64: 20, DataType.INT32: 23, DataType.INT16: 21,
    DataType.FLOAT64: 701, DataType.FLOAT32: 700,
    DataType.VARCHAR: 25, DataType.BOOLEAN: 16,
}


def _oid(t) -> int:
    return _OID.get(t, 20)      # timestamps/decimals ride as int8 micros


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!i", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgServer:
    """asyncio TCP server speaking the v3 protocol against one Session."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 4566):
        self.session = session
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "PgServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def addr(self):
        return self._server.sockets[0].getsockname()

    # ------------------------------------------------------- connection
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        # per-connection extended-protocol state
        stmts: dict[str, str] = {}       # name -> sql text
        portals: dict[str, dict] = {}    # name -> {sql, cached}
        skip_to_sync = False
        try:
            if not await self._startup(reader, writer):
                return
            while True:
                hdr = await reader.readexactly(5)
                tag, ln = hdr[:1], struct.unpack("!i", hdr[1:])[0]
                if ln < 4 or ln > (1 << 26):
                    return               # malformed frame: close cleanly
                payload = await reader.readexactly(ln - 4)
                if tag == b"X":
                    return
                if skip_to_sync and tag != b"S":
                    # protocol error recovery: discard until Sync
                    continue
                try:
                    if tag == b"Q":
                        sql_text = payload.rstrip(b"\x00").decode()
                        await self._simple_query(writer, sql_text)
                    elif tag == b"P":
                        self._parse_msg(writer, payload, stmts)
                    elif tag == b"B":
                        self._bind_msg(writer, payload, stmts, portals)
                    elif tag == b"D":
                        await self._describe_msg(writer, payload, stmts,
                                                 portals)
                    elif tag == b"E":
                        await self._execute_msg(writer, payload, portals)
                    elif tag == b"C":
                        kind = payload[:1]
                        name = payload[1:].split(b"\x00")[0].decode()
                        (stmts if kind == b"S" else portals).pop(
                            name, None)
                        writer.write(_msg(b"3", b""))   # CloseComplete
                    elif tag == b"H":                    # Flush
                        pass
                    elif tag == b"S":                    # Sync
                        # statement boundary (autocommit): the unnamed
                        # portal closes here per the protocol, and any
                        # cached result rows are dropped — close-portal
                        # cleanup for drivers that never send Close
                        portals.pop("", None)
                        for p in portals.values():
                            p["cached"] = None
                        skip_to_sync = False
                        self._ready(writer)
                    else:
                        self._error(writer, "0A000",
                                    f"unsupported message {tag!r}")
                        skip_to_sync = True
                except _PgUserError as e:
                    self._error(writer, e.code, str(e))
                    skip_to_sync = True
                except ServingTimeout as e:
                    # pg's query_canceled: the client sees the timeout
                    # immediately; the abandoned worker thread finishes
                    # in the background
                    self._error(writer, "57014", str(e))
                    skip_to_sync = True
                except (ValueError, struct.error, IndexError,
                        UnicodeDecodeError) as e:
                    self._error(writer, "08P01",
                                f"malformed message: {e}")
                    skip_to_sync = True
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def _startup(self, reader, writer) -> bool:
        while True:
            ln = struct.unpack("!i", await reader.readexactly(4))[0]
            body = await reader.readexactly(ln - 4)
            code = struct.unpack("!i", body[:4])[0]
            if code in (80877103, 80877104):  # SSLRequest / GSSENCRequest
                writer.write(b"N")
                await writer.drain()
                continue
            if code == 80877102:              # CancelRequest
                return False
            break                              # StartupMessage
        writer.write(_msg(b"R", struct.pack("!i", 0)))   # AuthenticationOk
        for k, v in (("server_version", "9.5.0"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8"),
                     ("DateStyle", "ISO"),
                     ("standard_conforming_strings", "on"),
                     ("integer_datetimes", "on")):
            writer.write(_msg(b"S", _cstr(k) + _cstr(v)))
        writer.write(_msg(b"K", struct.pack("!ii", 0, 0)))
        self._ready(writer)
        await writer.drain()
        return True

    def _ready(self, writer) -> None:
        writer.write(_msg(b"Z", b"I"))

    def _error(self, writer, code: str, message: str) -> None:
        fields = (b"S" + _cstr("ERROR") + b"C" + _cstr(code)
                  + b"M" + _cstr(message) + b"\x00")
        writer.write(_msg(b"E", fields))

    # ------------------------------------------------------ simple query
    async def _simple_query(self, writer, sql_text: str) -> None:
        parts = [p for p in _split_statements(sql_text) if p.strip()]
        if not parts:
            writer.write(_msg(b"I", b""))     # EmptyQueryResponse
            self._ready(writer)
            return
        for part in parts:
            try:
                stmt = ast.parse(part)
                if isinstance(stmt, ast.Select):
                    names, types, rows = \
                        await self.session.run_serving_select(stmt)
                    self._row_description(writer, names, types)
                    for row in rows:
                        self._data_row(writer, row)
                    writer.write(_msg(b"C", _cstr(f"SELECT {len(rows)}")))
                elif isinstance(stmt, (ast.Explain, ast.Show)):
                    rows = await self.session.execute(part)
                    ncols = len(rows[0]) if rows else 1
                    names = (["QUERY PLAN"] if isinstance(stmt, ast.Explain)
                             else ["name", "setting"][:ncols])
                    self._row_description(
                        writer, names, [DataType.VARCHAR] * ncols)
                    for row in rows:
                        self._data_row(writer, row)
                    writer.write(_msg(b"C", _cstr(f"SELECT {len(rows)}")))
                elif isinstance(stmt, ast.Insert):
                    n = await self.session.execute(part)
                    writer.write(_msg(b"C", _cstr(f"INSERT 0 {n}")))
                else:
                    await self.session.execute(part)
                    writer.write(_msg(b"C", _cstr(_tag_of(stmt))))
            except (BindError, SqlError) as e:
                self._error(writer, "42601", str(e))
                break     # v3: a failing statement aborts the rest
            except ServingTimeout as e:
                self._error(writer, "57014", str(e))
                break
            except Exception as e:  # noqa: BLE001 — surface, don't kill
                self._error(writer, "XX000", f"{type(e).__name__}: {e}")
                break
        self._ready(writer)

    # -------------------------------------------------- extended protocol
    def _parse_msg(self, writer, payload: bytes, stmts: dict) -> None:
        name, rest = payload.split(b"\x00", 1)
        sql_text, rest = rest.split(b"\x00", 1)
        noids = struct.unpack_from("!h", rest, 0)[0] if len(rest) >= 2 \
            else 0
        oids = struct.unpack_from(f"!{noids}i", rest, 2) if noids else ()
        _lru_insert(stmts, name.decode(), (sql_text.decode(), tuple(oids)),
                    MAX_PREPARED_STATEMENTS)
        writer.write(_msg(b"1", b""))         # ParseComplete

    def _bind_msg(self, writer, payload: bytes, stmts: dict,
                  portals: dict) -> None:
        portal, rest = payload.split(b"\x00", 1)
        stmt_name, rest = rest.split(b"\x00", 1)
        if stmt_name.decode() not in stmts:
            raise _PgUserError(
                "26000", f"unknown statement {stmt_name.decode()!r}")
        off = 0
        nfmt = struct.unpack_from("!h", rest, off)[0]
        off += 2
        fmts = struct.unpack_from(f"!{nfmt}h", rest, off)
        off += 2 * nfmt
        if any(f == 1 for f in fmts):
            raise _PgUserError("0A000", "binary parameters unsupported")
        nparams = struct.unpack_from("!h", rest, off)[0]
        off += 2
        params: list[Optional[str]] = []
        for _ in range(nparams):
            plen = struct.unpack_from("!i", rest, off)[0]
            off += 4
            if plen == -1:
                params.append(None)
            else:
                params.append(rest[off:off + plen].decode())
                off += plen
        # result-format codes: text only (a silently-ignored binary
        # request would make the client decode ASCII as binary)
        nrfmt = struct.unpack_from("!h", rest, off)[0]
        off += 2
        rfmts = struct.unpack_from(f"!{nrfmt}h", rest, off)
        if any(f == 1 for f in rfmts):
            raise _PgUserError("0A000", "binary result format unsupported")
        _lru_touch(stmts, stmt_name.decode())
        sql_text, oids = stmts[stmt_name.decode()]
        sql_text = _substitute_params(sql_text, params, oids)
        _lru_insert(portals, portal.decode(),
                    {"sql": sql_text, "cached": None}, MAX_PORTALS)
        writer.write(_msg(b"2", b""))         # BindComplete

    async def _describe_msg(self, writer, payload: bytes, stmts: dict,
                            portals: dict) -> None:
        kind, name = payload[:1], payload[1:].split(b"\x00")[0].decode()
        if kind == b"S":
            if name not in stmts:
                raise _PgUserError("26000", f"unknown statement {name!r}")
            sql_text, _ = stmts[name]
            n = _count_params(sql_text)
            writer.write(_msg(b"t", struct.pack("!h", n)
                              + b"".join(struct.pack("!i", 25)
                                         for _ in range(n))))
            # statement-level row description (JDBC/npgsql describe
            # HERE, not at the portal): best-effort plan with NULL
            # parameters; anything unplannable answers NoData
            try:
                probe = _substitute_params(sql_text, [None] * n)
                stmt = ast.parse(probe)
                if isinstance(stmt, ast.Select):
                    names, types, _rows = \
                        await self.session.run_serving_select(stmt)
                    self._row_description(writer, names, types)
                    return
            except Exception:  # noqa: BLE001 — describe must not fail
                pass
            writer.write(_msg(b"n", b""))     # NoData
            return
        if name not in portals:
            raise _PgUserError("34000", f"unknown portal {name!r}")
        p = portals[name]
        try:
            stmt = ast.parse(p["sql"])
        except (BindError, SqlError) as e:
            raise _PgUserError("42601", str(e))
        if isinstance(stmt, ast.Select):
            try:
                names, types, rows = \
                    await self.session.run_serving_select(stmt)
            except (BindError, SqlError) as e:
                raise _PgUserError("42601", str(e))
            p["cached"] = (names, types, rows)
            self._row_description(writer, names, types)
        elif isinstance(stmt, ast.Explain):
            self._row_description(writer, ["QUERY PLAN"],
                                  [DataType.VARCHAR])
        elif isinstance(stmt, ast.Show):
            self._row_description(writer, ["setting"],
                                  [DataType.VARCHAR])
        else:
            writer.write(_msg(b"n", b""))     # NoData

    async def _execute_msg(self, writer, payload: bytes,
                           portals: dict) -> None:
        name = payload.split(b"\x00")[0].decode()
        if name not in portals:
            raise _PgUserError("34000", f"unknown portal {name!r}")
        p = portals[name]
        try:
            stmt = ast.parse(p["sql"])
        except (BindError, SqlError) as e:
            raise _PgUserError("42601", str(e))
        if isinstance(stmt, ast.Select):
            if p["cached"] is None:
                try:
                    p["cached"] = \
                        await self.session.run_serving_select(stmt)
                except (BindError, SqlError) as e:
                    raise _PgUserError("42601", str(e))
            _, _, rows = p["cached"]
            p["cached"] = None       # a re-Execute re-runs the query
            for row in rows:
                self._data_row(writer, row)
            writer.write(_msg(b"C", _cstr(f"SELECT {len(rows)}")))
        elif isinstance(stmt, (ast.Explain, ast.Show)):
            try:
                rows = await self.session.execute(p["sql"])
            except (BindError, SqlError) as e:
                raise _PgUserError("42601", str(e))
            for row in rows:
                self._data_row(writer, row)
            writer.write(_msg(b"C", _cstr(f"SELECT {len(rows)}")))
        elif isinstance(stmt, ast.Insert):
            try:
                n = await self.session.execute(p["sql"])
            except (BindError, SqlError) as e:
                raise _PgUserError("42601", str(e))
            writer.write(_msg(b"C", _cstr(f"INSERT 0 {n}")))
        else:
            try:
                await self.session.execute(p["sql"])
            except (BindError, SqlError) as e:
                raise _PgUserError("42601", str(e))
            writer.write(_msg(b"C", _cstr(_tag_of(stmt))))

    def _row_description(self, writer, names, types) -> None:
        body = struct.pack("!h", len(names))
        for name, t in zip(names, types):
            body += (_cstr(name)
                     + struct.pack("!ihihih", 0, 0, _oid(t),
                                   -1, -1, 0))
        writer.write(_msg(b"T", body))

    def _data_row(self, writer, row) -> None:
        body = struct.pack("!h", len(row))
        for v in row:
            if v is None:
                body += struct.pack("!i", -1)
            else:
                # pg text format: booleans are 't'/'f' (OID 16 contract)
                s = (b"t" if v else b"f") if isinstance(v, bool) \
                    else str(v).encode()
                body += struct.pack("!i", len(s)) + s
        writer.write(_msg(b"D", body))


class _PgUserError(Exception):
    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code


def _split_statements(text: str) -> list[str]:
    """Split on top-level ';' (quotes respected) — one 'Q' frame may
    carry several statements (psql -c 'a; b')."""
    out, cur, in_q = [], [], False
    for ch in text:
        if ch == "'":
            in_q = not in_q
            cur.append(ch)
        elif ch == ";" and not in_q:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    out.append("".join(cur))
    return out


import re

_NUMERIC = re.compile(r"-?\d+(\.\d+)?\Z")


def _param_spans(sql_text: str):
    """(start, end, index) for every $n OUTSIDE string literals."""
    out, i, n, in_q = [], 0, len(sql_text), False
    while i < n:
        ch = sql_text[i]
        if ch == "'":
            in_q = not in_q
        elif ch == "$" and not in_q:
            j = i + 1
            while j < n and sql_text[j].isdigit():
                j += 1
            if j > i + 1:
                out.append((i, j, int(sql_text[i + 1:j])))
                i = j
                continue
        i += 1
    return out


def _count_params(sql_text: str) -> int:
    ids = [k for _, _, k in _param_spans(sql_text)]
    return max(ids) if ids else 0


_TEXT_OIDS = {25, 1043, 1042, 18, 19}     # text, varchar, bpchar, ...
_NUM_OIDS = {20, 21, 23, 26, 700, 701, 1700}


def _substitute_params(sql_text: str, params: list, oids=()) -> str:
    """$n -> SQL literal (text-format params). A Parse-declared text
    OID always quotes; a numeric OID inlines bare; with no declared
    type, only strict SQL numerics inline (Python's int()/float()
    accept '1_0', 'inf', '1e5', which the SQL lexer does not) and
    everything else quotes with '' escaping. $n inside string literals
    is left alone."""

    def lit(i: int, v) -> str:
        if v is None:
            return "NULL"
        oid = oids[i] if i < len(oids) else 0
        if oid in _TEXT_OIDS:
            return "'" + v.replace("'", "''") + "'"
        if oid in _NUM_OIDS or _NUMERIC.match(v):
            if not _NUMERIC.match(v):
                raise _PgUserError(
                    "22P02", f"invalid numeric parameter ${i + 1}: {v!r}")
            return v
        return "'" + v.replace("'", "''") + "'"

    out, last = [], 0
    for start, end, k in _param_spans(sql_text):
        i = k - 1
        if i < 0 or i >= len(params):
            raise _PgUserError(
                "08P01", f"parameter ${k} not bound "
                f"({len(params)} supplied)")
        out.append(sql_text[last:start])
        out.append(lit(i, params[i]))
        last = end
    out.append(sql_text[last:])
    return "".join(out)


def _tag_of(stmt) -> str:
    if isinstance(stmt, ast.CreateTable):
        return "CREATE_TABLE"
    if isinstance(stmt, ast.Drop):
        return "DROP_" + stmt.kind.upper()
    if isinstance(stmt, ast.CreateSource):
        return "CREATE_SOURCE"
    if isinstance(stmt, ast.CreateMV):
        return "CREATE_MATERIALIZED_VIEW"
    if isinstance(stmt, ast.CreateSink):
        return "CREATE_SINK"
    if isinstance(stmt, ast.AlterParallelism):
        return "ALTER_MATERIALIZED_VIEW"
    if isinstance(stmt, ast.SetVar):
        return "SET"
    return "OK"

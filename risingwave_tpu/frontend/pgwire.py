"""pgwire — the PostgreSQL wire protocol server (layer 1, client protocol).

Reference: src/utils/pgwire/src/pg_server.rs:173 (tcp accept loop),
pg_protocol.rs:391 (message dispatch), :548 (simple query). This is NOT a
port of that 6k-LoC crate: it implements the subset a stock `psql`/driver
needs for the simple-query flow —

  SSLRequest            -> 'N' (no TLS)
  StartupMessage        -> AuthenticationOk, ParameterStatus*,
                           BackendKeyData, ReadyForQuery
  Query ('Q')           -> RowDescription + DataRow* + CommandComplete
                           (SELECT) or CommandComplete (DDL) or
                           ErrorResponse, then ReadyForQuery
  Terminate ('X')       -> close

Extended protocol (Parse/Bind/Execute) is answered with ErrorResponse so
drivers fall back to simple queries where possible. All values transfer
in text format (format code 0), NULL as the -1 length sentinel.

The server shares the Session's asyncio loop: DDL statements await
`Session.execute` (which runs barrier rounds), SELECTs call the batch
engine over committed snapshots — identical semantics to the REPL.
"""

from __future__ import annotations

import asyncio
import struct
from typing import Optional

from ..common.types import DataType
from . import sql as ast
from .binder import BindError
from .sql import SqlError

# text-format type OIDs (pg_catalog): int8, float8, text, bool
_OID = {
    DataType.INT64: 20, DataType.INT32: 23, DataType.INT16: 21,
    DataType.FLOAT64: 701, DataType.FLOAT32: 700,
    DataType.VARCHAR: 25, DataType.BOOLEAN: 16,
}


def _oid(t) -> int:
    return _OID.get(t, 20)      # timestamps/decimals ride as int8 micros


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!i", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgServer:
    """asyncio TCP server speaking the v3 protocol against one Session."""

    def __init__(self, session, host: str = "127.0.0.1", port: int = 4566):
        self.session = session
        self.host = host
        self.port = port
        self._server: Optional[asyncio.AbstractServer] = None

    async def start(self) -> "PgServer":
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port)
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

    @property
    def addr(self):
        return self._server.sockets[0].getsockname()

    # ------------------------------------------------------- connection
    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            if not await self._startup(reader, writer):
                return
            while True:
                hdr = await reader.readexactly(5)
                tag, ln = hdr[:1], struct.unpack("!i", hdr[1:])[0]
                payload = await reader.readexactly(ln - 4)
                if tag == b"X":
                    return
                if tag == b"Q":
                    sql_text = payload.rstrip(b"\x00").decode()
                    await self._simple_query(writer, sql_text)
                else:
                    # extended protocol / unknown: error + ready
                    self._error(writer, "0A000",
                                f"unsupported message {tag!r} (simple "
                                f"query protocol only)")
                    self._ready(writer)
                await writer.drain()
        except (asyncio.IncompleteReadError, ConnectionResetError):
            pass
        finally:
            writer.close()

    async def _startup(self, reader, writer) -> bool:
        while True:
            ln = struct.unpack("!i", await reader.readexactly(4))[0]
            body = await reader.readexactly(ln - 4)
            code = struct.unpack("!i", body[:4])[0]
            if code in (80877103, 80877104):  # SSLRequest / GSSENCRequest
                writer.write(b"N")
                await writer.drain()
                continue
            if code == 80877102:              # CancelRequest
                return False
            break                              # StartupMessage
        writer.write(_msg(b"R", struct.pack("!i", 0)))   # AuthenticationOk
        for k, v in (("server_version", "9.5.0"),
                     ("server_encoding", "UTF8"),
                     ("client_encoding", "UTF8"),
                     ("DateStyle", "ISO"),
                     ("standard_conforming_strings", "on"),
                     ("integer_datetimes", "on")):
            writer.write(_msg(b"S", _cstr(k) + _cstr(v)))
        writer.write(_msg(b"K", struct.pack("!ii", 0, 0)))
        self._ready(writer)
        await writer.drain()
        return True

    def _ready(self, writer) -> None:
        writer.write(_msg(b"Z", b"I"))

    def _error(self, writer, code: str, message: str) -> None:
        fields = (b"S" + _cstr("ERROR") + b"C" + _cstr(code)
                  + b"M" + _cstr(message) + b"\x00")
        writer.write(_msg(b"E", fields))

    # ------------------------------------------------------ simple query
    async def _simple_query(self, writer, sql_text: str) -> None:
        sql_text = sql_text.strip()
        if not sql_text or sql_text == ";":
            writer.write(_msg(b"I", b""))     # EmptyQueryResponse
            self._ready(writer)
            return
        try:
            stmt = ast.parse(sql_text)
            if isinstance(stmt, ast.Select):
                from .batch import run_batch_select_full
                names, types, rows = run_batch_select_full(
                    self.session.catalog, stmt)
                self._row_description(writer, names, types)
                for row in rows:
                    self._data_row(writer, row)
                writer.write(_msg(b"C", _cstr(f"SELECT {len(rows)}")))
            else:
                await self.session.execute(sql_text)
                writer.write(_msg(b"C", _cstr(_tag_of(stmt))))
        except (BindError, SqlError) as e:
            self._error(writer, "42601", str(e))
        except Exception as e:  # noqa: BLE001 — surface, don't kill conn
            self._error(writer, "XX000", f"{type(e).__name__}: {e}")
        self._ready(writer)

    def _row_description(self, writer, names, types) -> None:
        body = struct.pack("!h", len(names))
        for name, t in zip(names, types):
            body += (_cstr(name)
                     + struct.pack("!ihihih", 0, 0, _oid(t),
                                   -1, -1, 0))
        writer.write(_msg(b"T", body))

    def _data_row(self, writer, row) -> None:
        body = struct.pack("!h", len(row))
        for v in row:
            if v is None:
                body += struct.pack("!i", -1)
            else:
                # pg text format: booleans are 't'/'f' (OID 16 contract)
                s = (b"t" if v else b"f") if isinstance(v, bool) \
                    else str(v).encode()
                body += struct.pack("!i", len(s)) + s
        writer.write(_msg(b"D", body))


def _tag_of(stmt) -> str:
    if isinstance(stmt, ast.CreateSource):
        return "CREATE_SOURCE"
    if isinstance(stmt, ast.CreateMV):
        return "CREATE_MATERIALIZED_VIEW"
    if isinstance(stmt, ast.CreateSink):
        return "CREATE_SINK"
    if isinstance(stmt, ast.AlterParallelism):
        return "ALTER_MATERIALIZED_VIEW"
    if isinstance(stmt, ast.SetVar):
        return "SET"
    return "OK"

"""System catalog tables — `rw_catalog` over live telemetry.

Reference: rw_catalog system tables (`rw_actors`, `rw_fragments`,
`rw_event_logs`, ...) make observability *queryable*: operators (and
the controller itself) answer "what happened / who is slow" in SQL
instead of scraping. Same shape here: each `rw_*` name binds to a
relation SYNTHESIZED at query time from the live telemetry owners —
StreamingStats (actors), catalog deployments (fragments), the
metrics-history store (utils/metrics_history.py), the event log and
the recovery ring — and then the NORMAL batch pipeline runs over it,
so filters / aggregates / joins (including rw_* ⋈ MV) come free:

    SELECT actor, max(value) FROM rw_metrics
     WHERE name = 'stream_actor_busy_seconds_total' GROUP BY actor

Wiring: `make_system_scan(session)` returns a `_bind_rel` scan that
serves the `rw_*` names and defers everything else to the stock MV
scan; frontend/session.py routes a SELECT through it whenever the
FROM clause mentions a system table (they are not MVs — the serving
pin path would reject them).
"""

from __future__ import annotations

import json

import numpy as np

from ..common.types import DataType, Field, GLOBAL_DICT, Schema
from .batch import _Rel, _scan_mv
from .binder import Scope

SCHEMAS = {
    "rw_actors": Schema((
        Field("actor_id", DataType.INT64),
        Field("fragment_id", DataType.INT64),
        Field("mv", DataType.VARCHAR),
        Field("executor", DataType.VARCHAR),
    )),
    "rw_fragments": Schema((
        Field("fragment_id", DataType.INT64),
        Field("mv", DataType.VARCHAR),
        Field("parallelism", DataType.INT64),
        Field("actor_ids", DataType.VARCHAR),
    )),
    "rw_metrics": Schema((
        Field("name", DataType.VARCHAR),
        Field("actor", DataType.VARCHAR),
        Field("labels", DataType.VARCHAR),
        Field("epoch", DataType.INT64),
        Field("ts", DataType.FLOAT64),
        Field("value", DataType.FLOAT64),
    )),
    "rw_events": Schema((
        Field("seq", DataType.INT64),
        Field("ts", DataType.FLOAT64),
        Field("worker", DataType.VARCHAR),
        Field("kind", DataType.VARCHAR),
        Field("details", DataType.VARCHAR),
    )),
    "rw_recoveries": Schema((
        Field("ts", DataType.FLOAT64),
        Field("scope", DataType.VARCHAR),
        Field("cause", DataType.VARCHAR),
        Field("duration_ms", DataType.FLOAT64),
        Field("actors", DataType.VARCHAR),
    )),
}

SYSTEM_TABLES = frozenset(SCHEMAS)


def is_system_table(name: str) -> bool:
    return name in SYSTEM_TABLES


# ------------------------------------------------------------ row sources
def _actor_rows(session) -> list:
    # fragment ids live on the deployments; actors on StreamingStats
    frag_of = {}
    for defs in (session.catalog.mvs, session.catalog.sinks):
        for d in defs.values():
            dep = getattr(d, "deployment", None)
            frag_of.update(getattr(dep, "actor_fragment", {}) or {})
    rows = []
    for actor_id, (actor, root, scope) in sorted(
            getattr(session.coord.stats, "_regs", {}).items()):
        rows.append((int(actor_id), frag_of.get(actor_id),
                     str(scope) if scope else None,
                     getattr(root, "identity", None)))
    return rows


def _fragment_rows(session) -> list:
    rows = []
    for defs in (session.catalog.mvs, session.catalog.sinks):
        for name, d in sorted(defs.items()):
            dep = getattr(d, "deployment", None)
            for fid, ids in sorted(
                    (getattr(dep, "frag_actor_ids", {}) or {}).items()):
                rows.append((int(fid), name, len(ids),
                             json.dumps(sorted(int(i) for i in ids))))
    return rows


def _metric_rows(session) -> list:
    hist = getattr(session, "metrics_history", None) \
        or getattr(session.coord, "metrics_history", None)
    if hist is None:
        return []
    rows = []
    for r in hist.rows():
        labels = r["labels"]
        rows.append((r["name"], labels.get("actor"),
                     json.dumps(labels, sort_keys=True) if labels else None,
                     int(r["epoch"]), float(r["ts"]), float(r["value"])))
    return rows


def _event_rows(session) -> list:
    rows = []
    for rec in session.event_log.records():
        details = {k: v for k, v in rec.items()
                   if k not in ("seq", "ts", "kind")}
        rows.append((int(rec.get("seq", 0)), float(rec.get("ts", 0.0)),
                     "meta", rec.get("kind"),
                     json.dumps(details, default=str, sort_keys=True)))
    # cluster mode: worker-local records the meta has stitched (the
    # async SHOW events / /debug/events fan-out refreshes this cache —
    # a sync batch scan cannot await worker RPCs)
    for worker, recs in sorted(
            (getattr(session, "_worker_events_cache", None) or {}).items()):
        for rec in recs:
            details = {k: v for k, v in rec.items()
                       if k not in ("seq", "ts", "kind")}
            rows.append((int(rec.get("seq", 0)),
                         float(rec.get("ts", 0.0)), f"w{worker}",
                         rec.get("kind"),
                         json.dumps(details, default=str, sort_keys=True)))
    rows.sort(key=lambda r: r[1])
    return rows


def _recovery_rows(session) -> list:
    rows = []
    for r in getattr(session, "recovery_ring").recoveries:
        rows.append((float(r.get("at_ns", 0)) / 1e9, r.get("scope"),
                     r.get("cause"),
                     float(r.get("duration_ns", 0)) / 1e6,
                     json.dumps(list(r.get("actors", ())))))
    return rows


_SOURCES = {
    "rw_actors": _actor_rows,
    "rw_fragments": _fragment_rows,
    "rw_metrics": _metric_rows,
    "rw_events": _event_rows,
    "rw_recoveries": _recovery_rows,
}


# --------------------------------------------------------------- binding
def _to_rel(schema: Schema, rows: list, qualifier) -> _Rel:
    n = len(rows)
    cols, valids = [], []
    for i, f in enumerate(schema):
        vals = np.zeros(n, dtype=f.data_type.np_dtype)
        valid = np.zeros(n, dtype=bool)
        for j, row in enumerate(rows):
            v = row[i]
            if v is None:
                continue
            if f.data_type is DataType.VARCHAR:
                vals[j] = GLOBAL_DICT.get_or_insert(str(v))
            elif f.data_type in (DataType.FLOAT32, DataType.FLOAT64):
                vals[j] = float(v)
            else:
                vals[j] = int(v)
            valid[j] = True
        cols.append(vals)
        valids.append(valid)
    return _Rel(cols, valids, Scope.of(schema, qualifier))


def make_system_scan(session):
    """A `_bind_rel` scan serving the rw_* system tables and deferring
    every other name to the stock MV scan — so `rw_actors ⋈ some_mv`
    binds like any join."""
    def scan(catalog, name: str, alias):
        if name in SYSTEM_TABLES:
            rows = _SOURCES[name](session)
            return _to_rel(SCHEMAS[name], rows, alias or name)
        return _scan_mv(catalog, name, alias)
    return scan

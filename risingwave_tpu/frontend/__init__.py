from .session import Catalog, MvDef, Session, SourceDef
from .sql import SqlError, parse
from .binder import BindError

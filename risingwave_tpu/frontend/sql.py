"""SQL tokenizer + recursive-descent parser (thin frontend, layer 3).

Reference: src/sqlparser (a 19.7k-LoC sqlparser-rs fork). This is NOT a
port — it covers the streaming-SQL subset the engine executes today:

  CREATE SOURCE name WITH (connector='nexmark', table='bid', ...)
  CREATE MATERIALIZED VIEW name AS SELECT ...
  SELECT <exprs> FROM <rel> [WHERE e] [GROUP BY cols]
  <rel> := table | TUMBLE(table, col, N) | HOP(table, col, slide, size)
         | <rel> JOIN <rel> ON conj
  exprs: + - * / % comparisons AND OR NOT, literals, idents (qualified),
         function calls, COUNT(*)/SUM/MIN/MAX/AVG

Produces plain-dataclass ASTs the binder lowers onto the fragment-graph IR.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional, Sequence, Union

KEYWORDS = {
    "select", "from", "where", "group", "by", "as", "create",
    "materialized", "view", "source", "with", "join", "on", "and", "or",
    "not", "tumble", "hop", "count", "sum", "min", "max", "avg", "limit",
    "order", "desc", "asc", "offset", "between", "emit", "table", "sink",
    "alter", "set", "parallelism", "left", "right", "full", "outer",
    "inner", "over", "partition", "rows", "unbounded", "preceding",
    "current", "row", "for", "system_time", "of", "proctime",
    "case", "when", "then", "else", "end", "in", "is",
    "explain", "show", "insert", "into", "values", "drop",
}

_TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>\d+\.\d+|\d+)
    | (?P<str>'(?:[^']|'')*')
    | (?P<ident>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<op><>|<=|>=|!=|=|<|>|\+|-|\*|/|%|\(|\)|,|\.|\;)
    )""", re.VERBOSE)


@dataclass
class Tok:
    kind: str   # num | str | ident | kw | op | eof
    val: str


def tokenize(sql: str) -> list[Tok]:
    out, pos = [], 0
    while pos < len(sql):
        m = _TOKEN_RE.match(sql, pos)
        if not m or m.end() == pos:
            if sql[pos:].strip() == "":
                break
            raise SqlError(f"cannot tokenize at: {sql[pos:pos+20]!r}")
        pos = m.end()
        if m.group("num"):
            out.append(Tok("num", m.group("num")))
        elif m.group("str"):
            out.append(Tok("str", m.group("str")[1:-1].replace("''", "'")))
        elif m.group("ident"):
            low = m.group("ident").lower()
            out.append(Tok("kw" if low in KEYWORDS else "ident", low))
        else:
            out.append(Tok("op", m.group("op")))
    out.append(Tok("eof", ""))
    return out


class SqlError(Exception):
    pass


# ----------------------------------------------------------------- AST

@dataclass
class Lit:
    value: object


@dataclass
class ColRef:
    name: str
    qualifier: Optional[str] = None


@dataclass
class Func:
    name: str
    args: list
    star: bool = False      # COUNT(*)


@dataclass
class BinOp:
    op: str
    left: object
    right: object


@dataclass
class UnOp:
    op: str
    arg: object


@dataclass
class SelectItem:
    expr: object
    alias: Optional[str]


@dataclass
class TableRel:
    name: str
    alias: Optional[str] = None


@dataclass
class WindowRel:
    kind: str               # "tumble" | "hop"
    inner: TableRel
    time_col: str
    size: int
    slide: Optional[int] = None
    alias: Optional[str] = None


@dataclass
class JoinRel:
    left: object
    right: object
    on: object                  # None = comma join (ON comes from WHERE)
    join_type: str = "inner"    # inner | left | right | full
    temporal: bool = False      # FOR SYSTEM_TIME AS OF PROCTIME()


@dataclass
class WindowFunc:
    """func(...) OVER (PARTITION BY ... ORDER BY ... [frame])."""

    func: "Func"
    partition_by: list
    order_by: list              # [(expr, descending)]
    preceding: Optional[int] = None   # None = UNBOUNDED PRECEDING


@dataclass
class SetVar:
    name: str
    value: object


@dataclass
class CreateTable:
    name: str
    columns: list       # [(name, type_str)]


@dataclass
class Insert:
    name: str
    rows: list          # [[literal values]]


@dataclass
class Drop:
    kind: str           # materialized_view | table | source | sink
    name: str


@dataclass
class Explain:
    stmt: object


@dataclass
class ExplainMv:
    """EXPLAIN MATERIALIZED VIEW <name> — the DEPLOYED graph of a live
    MV annotated with per-executor HBM accounting (state_bytes /
    evicted_bytes / reload_count), so operators can see which MV owns
    the device memory."""
    name: str


@dataclass
class BackupStmt:
    """BACKUP TO '<path>' — incremental, generation-stamped, verified
    copy of the session's durable state into a local-dir object store
    (state/backup.py). The path also becomes the session's quarantine
    repair source (backup_path)."""
    path: str


@dataclass
class RestoreStmt:
    """RESTORE FROM '<path>' [AT GENERATION <n>] — verify the backup,
    copy it into this session's FRESH primary store, reload
    catalog+manifest, replay the DDL log (cold-start disaster
    recovery). AT GENERATION picks an older retained generation from
    the ledger (point-in-time restore) instead of the newest."""
    path: str
    generation: Optional[int] = None


@dataclass
class Show:
    what: str           # sources|tables|materialized_views|sinks|all|<var>
    limit: object = None   # SHOW events LIMIT n — tail bound
    # SHOW events KIND 'recovery' / SINCE <unix-ts> — filter parity
    # with /debug/events?kind=&since= (meta/monitor_service.py)
    kind: object = None
    since: object = None


@dataclass
class SubqueryRel:
    select: object              # Select
    alias: str


@dataclass
class Select:
    items: list[SelectItem]
    rel: object
    where: Optional[object] = None
    group_by: list = field(default_factory=list)
    order_by: list = field(default_factory=list)   # (expr, descending)
    limit: Optional[int] = None
    offset: int = 0
    emit_on_close: bool = False     # EMIT ON WINDOW CLOSE


@dataclass
class CreateSource:
    name: str
    options: dict


@dataclass
class CreateMV:
    name: str
    select: Select


@dataclass
class CreateSink:
    name: str
    select: Select
    options: dict


@dataclass
class AlterParallelism:
    name: str
    parallelism: int


# --------------------------------------------------------------- parser

class Parser:
    def __init__(self, sql: str):
        self.toks = tokenize(sql)
        self.i = 0

    def peek(self) -> Tok:
        return self.toks[self.i]

    def next(self) -> Tok:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, val: Optional[str] = None) -> Optional[Tok]:
        t = self.peek()
        if t.kind == kind and (val is None or t.val == val):
            return self.next()
        return None

    def expect(self, kind: str, val: Optional[str] = None) -> Tok:
        t = self.accept(kind, val)
        if t is None:
            raise SqlError(f"expected {val or kind}, got {self.peek().val!r}")
        return t

    # ------------------------------------------------------- statements
    def parse_statement(self):
        stmt = self._statement()
        if self.peek().kind != "eof":
            raise SqlError(f"unexpected trailing input at "
                           f"{self.peek().val!r} (unsupported clause?)")
        return stmt

    def _statement(self):
        # BACKUP/RESTORE lead with plain idents (not reserved keywords:
        # a column named `backup` keeps working everywhere else)
        t = self.peek()
        if t.kind == "ident" and t.val == "backup":
            self.next()
            self.expect("ident", "to")
            path = self.expect("str").val
            self.accept("op", ";")
            return BackupStmt(path)
        if t.kind == "ident" and t.val == "restore":
            self.next()
            self.expect("kw", "from")
            path = self.expect("str").val
            generation = None
            if self.accept("ident", "at"):
                self.expect("ident", "generation")
                generation = int(self.expect("num").val)
            self.accept("op", ";")
            return RestoreStmt(path, generation)
        if self.accept("kw", "explain"):
            # EXPLAIN MATERIALIZED VIEW <name>: live deployed graph +
            # memory accounting (a bare EXPLAIN CREATE ... still plans
            # without deploying, below)
            if self.peek().kind == "kw" and self.peek().val == "materialized":
                self.next()
                self.expect("kw", "view")
                name = self.expect("ident").val
                self.accept("op", ";")
                return ExplainMv(name)
            return Explain(self._statement())
        if self.accept("kw", "show"):
            t = self.next()
            if t.kind not in ("ident", "kw"):
                raise SqlError("SHOW needs a target "
                               "(sources|tables|sinks|all|<variable>)")
            what = t.val.lower()
            if what == "materialized":
                if not self.accept("kw", "view"):
                    self.expect("ident", "views")
                what = "materialized_views"
            # else: object class or a session variable name
            limit = kind = since = None
            # KIND '<kind>' / SINCE <unix-ts> / LIMIT n in any order
            # (SHOW events only; other targets simply never match)
            while True:
                if self.accept("kw", "limit"):
                    limit = int(self.expect("num").val)
                elif self.accept("ident", "kind"):
                    kind = self.expect("str").val
                elif self.accept("ident", "since"):
                    since = float(self.expect("num").val)
                else:
                    break
            self.accept("op", ";")
            return Show(what, limit=limit, kind=kind, since=since)
        if self.accept("kw", "set"):
            # SET var = value — session config (reference: session_config/)
            name = self.next().val
            self.expect("op", "=")
            t = self.next()
            val = (float(t.val) if t.kind == "num" and "." in t.val
                   else int(t.val) if t.kind == "num" else t.val)
            self.accept("op", ";")
            return SetVar(name, val)
        if self.accept("kw", "alter"):
            self.expect("kw", "materialized")
            self.expect("kw", "view")
            name = self.expect("ident").val
            self.expect("kw", "set")
            self.expect("kw", "parallelism")
            self.expect("op", "=")
            n = int(self.expect("num").val)
            self.accept("op", ";")
            return AlterParallelism(name, n)
        if self.accept("kw", "drop"):
            if self.accept("kw", "materialized"):
                self.expect("kw", "view")
                kind = "materialized_view"
            elif self.accept("kw", "table"):
                kind = "table"
            elif self.accept("kw", "source"):
                kind = "source"
            elif self.accept("kw", "sink"):
                kind = "sink"
            else:
                raise SqlError(
                    "DROP supports MATERIALIZED VIEW / TABLE / SOURCE "
                    "/ SINK")
            name = self.expect("ident").val
            self.accept("op", ";")
            return Drop(kind, name)
        if self.accept("kw", "insert"):
            self.expect("kw", "into")
            name = self.expect("ident").val
            self.expect("kw", "values")
            rows = []
            while True:
                self.expect("op", "(")
                row = [self._expr()]
                while self.accept("op", ","):
                    row.append(self._expr())
                self.expect("op", ")")
                rows.append(row)
                if not self.accept("op", ","):
                    break
            self.accept("op", ";")
            return Insert(name, rows)
        if self.accept("kw", "create"):
            if self.accept("kw", "table"):
                name = self.expect("ident").val
                t = self.peek()
                if t.kind == "op" and t.val == "(":
                    # CREATE TABLE name (col type, ...) — a DML-able
                    # base table (reference: CREATE TABLE + dml.rs)
                    self.next()
                    cols = []
                    while True:
                        cn = self.expect("ident").val
                        ct = self.next().val
                        cols.append((cn, ct))
                        if not self.accept("op", ","):
                            break
                    self.expect("op", ")")
                    self.accept("op", ";")
                    return CreateTable(name, cols)
                # legacy: CREATE TABLE name WITH (...) = CREATE SOURCE
                self.expect("kw", "with")
                opts = self._with_options()
                self.accept("op", ";")
                return CreateSource(name, opts)
            if self.accept("kw", "source"):
                return self._create_source()
            if self.accept("kw", "sink"):
                name = self.expect("ident").val
                self.expect("kw", "as")
                sel = self._select()
                self.expect("kw", "with")
                opts = self._with_options()
                self.accept("op", ";")
                return CreateSink(name, sel, opts)
            self.expect("kw", "materialized")
            self.expect("kw", "view")
            name = self.expect("ident").val
            self.expect("kw", "as")
            sel = self._select()
            self.accept("op", ";")
            return CreateMV(name, sel)
        sel = self._select()
        self.accept("op", ";")
        return sel

    def _create_source(self) -> CreateSource:
        name = self.expect("ident").val
        self.expect("kw", "with")
        opts = self._with_options()
        self.accept("op", ";")
        return CreateSource(name, opts)

    def _with_options(self) -> dict:
        self.expect("op", "(")
        opts = {}
        while True:
            k = self.next().val
            self.expect("op", "=")
            t = self.next()
            opts[k] = int(t.val) if t.kind == "num" else t.val
            if not self.accept("op", ","):
                break
        self.expect("op", ")")
        return opts

    def _select(self) -> Select:
        self.expect("kw", "select")
        items = [self._select_item()]
        while self.accept("op", ","):
            items.append(self._select_item())
        self.expect("kw", "from")
        rel = self._relation()
        while self.accept("op", ","):
            rel = JoinRel(rel, self._relation(), None)
        where = None
        if self.accept("kw", "where"):
            where = self._expr()
        group_by = []
        if self.accept("kw", "group"):
            self.expect("kw", "by")
            group_by.append(self._expr())
            while self.accept("op", ","):
                group_by.append(self._expr())
        order_by = []
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                e = self._expr()
                desc = bool(self.accept("kw", "desc"))
                if not desc:
                    self.accept("kw", "asc")
                order_by.append((e, desc))
                if not self.accept("op", ","):
                    break
        limit = None
        offset = 0
        if self.accept("kw", "limit"):
            limit = int(self.expect("num").val)
        if self.accept("kw", "offset"):
            offset = int(self.expect("num").val)
        eowc = False
        if self.accept("kw", "emit"):
            self.expect("kw", "on")
            self.expect("ident", "window")
            self.expect("ident", "close")
            eowc = True
        return Select(items, rel, where, group_by, order_by, limit,
                      offset, emit_on_close=eowc)

    def _select_item(self) -> SelectItem:
        if self.accept("op", "*"):
            return SelectItem(ColRef("*"), None)
        e = self._expr()
        alias = None
        if self.accept("kw", "as"):
            alias = self.next().val
        elif self.peek().kind == "ident":
            alias = self.next().val
        return SelectItem(e, alias)

    def _relation(self):
        rel = self._rel_primary()
        while True:
            jt = "inner"
            if self.accept("kw", "inner"):
                pass
            elif self.accept("kw", "left"):
                jt = "left"
                self.accept("kw", "outer")
            elif self.accept("kw", "right"):
                jt = "right"
                self.accept("kw", "outer")
            elif self.accept("kw", "full"):
                jt = "full"
                self.accept("kw", "outer")
            elif self.peek().kind == "kw" and self.peek().val == "join":
                pass
            else:
                break
            self.expect("kw", "join")
            right = self._rel_primary()
            temporal = False
            if self.accept("kw", "for"):
                self.expect("kw", "system_time")
                self.expect("kw", "as")
                self.expect("kw", "of")
                self.expect("kw", "proctime")
                self.expect("op", "(")
                self.expect("op", ")")
                temporal = True
            self.expect("kw", "on")
            on = self._expr()
            rel = JoinRel(rel, right, on, jt, temporal)
        return rel

    def _rel_primary(self):
        for kind in ("tumble", "hop"):
            if self.accept("kw", kind):
                self.expect("op", "(")
                inner = TableRel(self.expect("ident").val)
                self.expect("op", ",")
                time_col = self.expect("ident").val
                self.expect("op", ",")
                a = int(self.expect("num").val)
                b = None
                if self.accept("op", ","):
                    b = int(self.expect("num").val)
                self.expect("op", ")")
                alias = None
                if self.accept("kw", "as"):
                    alias = self.next().val
                elif self.peek().kind == "ident":
                    alias = self.next().val
                if kind == "hop":
                    if b is None:
                        raise SqlError("HOP needs (table, col, slide, size)")
                    return WindowRel("hop", inner, time_col, size=b,
                                     slide=a, alias=alias)
                return WindowRel("tumble", inner, time_col, size=a,
                                 alias=alias)
        if self.accept("op", "("):
            if self.peek().kind == "kw" and self.peek().val == "select":
                sub = self._select()
                self.expect("op", ")")
                alias = None
                if self.accept("kw", "as"):
                    alias = self.next().val
                elif self.peek().kind == "ident" \
                        and self.peek().val not in KEYWORDS:
                    alias = self.next().val
                if alias is None:
                    raise SqlError("FROM subquery needs an alias")
                return SubqueryRel(sub, alias)
            rel = self._relation()
            self.expect("op", ")")
            return rel
        name = self.expect("ident").val
        alias = None
        if self.accept("kw", "as"):
            alias = self.next().val
        elif self.peek().kind == "ident" and self.peek().val not in KEYWORDS:
            alias = self.next().val
        return TableRel(name, alias)

    # ------------------------------------------------------ expressions
    def _expr(self):
        return self._or()

    def _or(self):
        e = self._and()
        while self.accept("kw", "or"):
            e = BinOp("or", e, self._and())
        return e

    def _and(self):
        e = self._not()
        while self.accept("kw", "and"):
            e = BinOp("and", e, self._not())
        return e

    def _not(self):
        if self.accept("kw", "not"):
            return UnOp("not", self._not())
        return self._cmp()

    def _cmp(self):
        e = self._add()
        t = self.peek()
        if t.kind == "op" and t.val in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            op = {"=": "equal", "<>": "not_equal", "!=": "not_equal",
                  "<": "less_than", "<=": "less_than_or_equal",
                  ">": "greater_than", ">=": "greater_than_or_equal"}[t.val]
            return BinOp(op, e, self._add())
        if self.accept("kw", "between"):
            lo = self._add()
            self.expect("kw", "and")
            hi = self._add()
            return BinOp("and",
                         BinOp("greater_than_or_equal", e, lo),
                         BinOp("less_than_or_equal", e, hi))
        if self.accept("kw", "is"):
            neg = bool(self.accept("kw", "not"))
            self.expect("ident", "null")
            return Func("is_not_null" if neg else "is_null", [e])
        neg = bool(self.accept("kw", "not"))
        if self.accept("kw", "in"):
            # x IN (a, b, c) -> equality OR-chain (NULL semantics match:
            # x = NULL is NULL, and Kleene OR propagates it)
            self.expect("op", "(")
            items = [self._expr()]
            while self.accept("op", ","):
                items.append(self._expr())
            self.expect("op", ")")
            out = BinOp("equal", e, items[0])
            for it in items[1:]:
                out = BinOp("or", out, BinOp("equal", e, it))
            return UnOp("not", out) if neg else out
        if neg:
            self.expect("kw", "in")   # NOT here only prefixes IN
        return e

    def _add(self):
        e = self._mul()
        while True:
            if self.accept("op", "+"):
                e = BinOp("add", e, self._mul())
            elif self.accept("op", "-"):
                e = BinOp("subtract", e, self._mul())
            else:
                return e

    def _mul(self):
        e = self._unary()
        while True:
            if self.accept("op", "*"):
                e = BinOp("multiply", e, self._unary())
            elif self.accept("op", "/"):
                e = BinOp("divide", e, self._unary())
            elif self.accept("op", "%"):
                e = BinOp("modulus", e, self._unary())
            else:
                return e

    def _unary(self):
        if self.accept("op", "-"):
            return UnOp("neg", self._unary())
        return self._primary()

    def _primary(self):
        t = self.next()
        if t.kind == "kw" and t.val == "case":
            # searched (CASE WHEN c THEN v ...) or simple
            # (CASE x WHEN v THEN r ...) form; both lower to the `case`
            # device function (first-match-wins pairs + optional else)
            operand = None
            if not (self.peek().kind == "kw"
                    and self.peek().val == "when"):
                operand = self._expr()
            args = []
            while self.accept("kw", "when"):
                c = self._expr()
                self.expect("kw", "then")
                v = self._expr()
                if operand is not None:
                    c = BinOp("equal", operand, c)
                args += [c, v]
            if not args:
                raise SqlError("CASE needs at least one WHEN")
            if self.accept("kw", "else"):
                args.append(self._expr())
            self.expect("kw", "end")
            return Func("case", args)
        if t.kind == "ident" and t.val.lower() == "null":
            return Lit(None)
        if t.kind == "num":
            return Lit(float(t.val) if "." in t.val else int(t.val))
        if t.kind == "str":
            return Lit(t.val)
        if t.kind == "op" and t.val == "(":
            e = self._expr()
            self.expect("op", ")")
            return e
        if t.kind in ("ident", "kw"):
            name = t.val
            if self.accept("op", "("):
                if name == "count" and self.accept("op", "*"):
                    self.expect("op", ")")
                    return Func("count", [], star=True)
                args = []
                if not self.accept("op", ")"):
                    args.append(self._expr())
                    while self.accept("op", ","):
                        args.append(self._expr())
                    self.expect("op", ")")
                f = Func(name, args)
                if self.accept("kw", "over"):
                    return self._over_clause(f)
                return f
            if self.accept("op", "."):
                col = self.next().val
                return ColRef(col, qualifier=name)
            return ColRef(name)
        raise SqlError(f"unexpected token {t.val!r}")

    def _over_clause(self, f: Func) -> WindowFunc:
        """OVER (PARTITION BY cols ORDER BY col [DESC], ...
        [ROWS BETWEEN n PRECEDING AND CURRENT ROW
         | ROWS UNBOUNDED PRECEDING])"""
        self.expect("op", "(")
        partition_by, order_by, preceding = [], [], None
        if self.accept("kw", "partition"):
            self.expect("kw", "by")
            partition_by.append(self._expr())
            while self.accept("op", ","):
                partition_by.append(self._expr())
        if self.accept("kw", "order"):
            self.expect("kw", "by")
            while True:
                e = self._expr()
                desc = bool(self.accept("kw", "desc"))
                if not desc:
                    self.accept("kw", "asc")
                order_by.append((e, desc))
                if not self.accept("op", ","):
                    break
        if self.accept("kw", "rows"):
            if self.accept("kw", "between"):
                if self.accept("kw", "unbounded"):
                    self.expect("kw", "preceding")
                else:
                    preceding = int(self.expect("num").val)
                    self.expect("kw", "preceding")
                self.expect("kw", "and")
                self.expect("kw", "current")
                self.expect("kw", "row")
            else:
                self.expect("kw", "unbounded")
                self.expect("kw", "preceding")
        self.expect("op", ")")
        return WindowFunc(f, partition_by, order_by, preceding)


def parse(sql: str):
    return Parser(sql).parse_statement()

"""Numpy interpreter over the Expr IR — the batch/serving evaluator.

Reference: batch expressions evaluate with the same vectorized
`Expression::eval` as streaming; here the SERVING path deliberately stays
off the accelerator (results leave the system anyway, and on a tunneled
TPU any device->host transfer degrades the streaming dataflow sharing the
process), so the same Expr tree is interpreted over numpy columns.
Returns (values, valid) pairs with strict NULL propagation.
"""

from __future__ import annotations

import numpy as np

from ..common.types import GLOBAL_DICT
from ..expr.ir import Expr, FuncCall, InputRef, Literal

_BINOPS = {
    "add": np.add, "subtract": np.subtract, "multiply": np.multiply,
    "equal": np.equal, "not_equal": np.not_equal,
    "less_than": np.less, "less_than_or_equal": np.less_equal,
    "greater_than": np.greater, "greater_than_or_equal": np.greater_equal,
}


def eval_numpy(e: Expr, cols: list[np.ndarray], valids=None):
    """-> (values ndarray, valid ndarray bool). `valids` threads per-column
    NULL masks from the storage layer (ADVICE r2 #2); None = all valid."""
    n = len(cols[0]) if cols else 0
    if isinstance(e, InputRef):
        v = (valids[e.index] if valids is not None
             and valids[e.index] is not None else np.ones(n, dtype=bool))
        return cols[e.index], v
    if isinstance(e, Literal):
        if e.value is None:
            return np.zeros(n), np.zeros(n, dtype=bool)
        v = e.value
        if isinstance(v, str):
            v = GLOBAL_DICT.get_or_insert(v)
        return np.full(n, v), np.ones(n, dtype=bool)
    if isinstance(e, FuncCall):
        args = [eval_numpy(a, cols, valids) for a in e.args]
        name = e.name
        if name in _BINOPS:
            (a, av), (b, bv) = args
            return _BINOPS[name](a, b), av & bv
        if name == "divide":
            # match streaming semantics (functions.py _div): integer
            # division floors; division by zero is NULL
            (a, av), (b, bv) = args
            safe = np.where(b == 0, 1, b)
            if (np.issubdtype(np.asarray(a).dtype, np.integer)
                    and np.issubdtype(np.asarray(b).dtype, np.integer)):
                val = np.floor_divide(a, safe)
            else:
                val = np.divide(a, safe)
            return val, av & bv & (b != 0)
        if name == "modulus":
            # streaming _mod: x % 0 is NULL
            (a, av), (b, bv) = args
            return np.mod(a, np.where(b == 0, 1, b)), av & bv & (b != 0)
        if name == "neg":
            (a, av), = args
            return -a, av
        if name == "not":
            (a, av), = args
            return ~a.astype(bool), av
        if name == "and":
            (a, av), (b, bv) = args
            a = a.astype(bool)
            b = b.astype(bool)
            # Kleene: False AND NULL = False
            val = a & b
            valid = (av & bv) | (av & ~a) | (bv & ~b)
            return val, valid
        if name == "or":
            (a, av), (b, bv) = args
            a = a.astype(bool)
            b = b.astype(bool)
            val = a | b
            valid = (av & bv) | (av & a) | (bv & b)
            return val, valid
        if name == "abs":
            (a, av), = args
            return np.abs(a), av
        if name == "is_null":
            (a, av), = args
            return ~av, np.ones_like(av)
        if name == "is_not_null":
            (a, av), = args
            return av, np.ones_like(av)
        if name == "case":
            n_args = len(args)
            has_else = n_args % 2 == 1
            if has_else:
                v, valid = args[-1]
                v = np.asarray(v).copy()
                valid = np.asarray(valid).copy()
            else:
                # the default branch must carry the expression's TYPE:
                # float64 zeros would leak "5.0" for an INT64 CASE
                v = np.zeros(n, dtype=e.ret_type.np_dtype)
                valid = np.zeros(n, dtype=bool)
            v, valid = np.broadcast_to(v, (n,)).copy(), \
                np.broadcast_to(valid, (n,)).copy()
            for i in reversed(range(n_args // 2)):
                c, cv = args[2 * i]
                rv, rvv = args[2 * i + 1]
                hit = np.broadcast_to(
                    np.asarray(c, dtype=bool) & cv, (n,))
                v = np.where(hit, rv, v)
                valid = np.where(hit, np.broadcast_to(rvv, (n,)), valid)
            return v, valid
        if name == "coalesce":
            v, valid = args[0]
            for (b, bv) in args[1:]:
                v = np.where(valid, v, b)
                valid = valid | bv
            return v, valid
        if name in ("lower", "upper", "trim", "ltrim", "rtrim",
                    "reverse", "md5", "length", "char_length", "ascii",
                    "like", "starts_with", "ends_with", "contains",
                    "substr"):
            # PURE NUMPY gather through the same host-built dictionary
            # mapping the streaming kernels use — the serving path must
            # stay off the accelerator (module docstring)
            from ..expr.strings import numpy_string_eval
            (a, av) = args[0]
            return numpy_string_eval(e, np.asarray(a, dtype=np.int64)), av
        if name in ("tumble_start", "tumble_end"):
            (a, av), (w, _) = args
            start = a - a % w
            return (start if name == "tumble_start" else start + w), av
        raise NotImplementedError(f"numpy eval for {name}")
    raise NotImplementedError(f"numpy eval for {type(e).__name__}")

"""Session — SQL in, materialized views + batch query results out.

Reference: SessionImpl::run_statement (src/frontend/src/session.rs:866) +
handler::handle dispatching DDL/queries, with the catalog tracking every
object. One Session owns one state store; each CREATE MATERIALIZED VIEW
deploys a fragment graph with its own barrier coordinator over that store
(meta-lite: single process, many dataflows); SELECT over an MV runs the
batch path (StorageTable committed-snapshot scan + numpy evaluation —
serving reads stay off the device, which on a tunneled TPU is also the
only fast option).
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..common.types import Schema
from ..connectors.nexmark import BID_SCHEMA, PERSON_SCHEMA, AUCTION_SCHEMA
from ..meta.barrier_manager import BarrierCoordinator
from ..plan import BuildEnv, Deployment, build_graph
from ..state import MemoryStateStore, StorageTable
from . import sql as ast
from .binder import (BindError, Scope, StreamPlanner, bind_scalar,
                     expand_star)
from .np_eval import eval_numpy

_NEXMARK_SCHEMAS = {"bid": BID_SCHEMA, "person": PERSON_SCHEMA,
                    "auction": AUCTION_SCHEMA}


@dataclass
class SourceDef:
    name: str
    schema: Schema
    options: dict      # builder args for the source node


@dataclass
class MvDef:
    name: str
    schema: Schema
    pk_indices: tuple
    deployment: Deployment
    coord: BarrierCoordinator
    mv_fragment: int

    @property
    def table(self):
        return self.deployment.roots[self.mv_fragment][0].table


class Catalog:
    def __init__(self):
        self.sources: dict[str, SourceDef] = {}
        self.mvs: dict[str, MvDef] = {}

    def source(self, name: str) -> SourceDef:
        if name not in self.sources:
            raise BindError(f"unknown source {name!r}")
        return self.sources[name]


class Session:
    def __init__(self, store=None):
        self.store = store if store is not None else MemoryStateStore()
        self.catalog = Catalog()
        self._next_table_id = 1

    # --------------------------------------------------------------- DDL
    async def execute(self, sql_text: str):
        stmt = ast.parse(sql_text)
        if isinstance(stmt, ast.CreateSource):
            return self._create_source(stmt)
        if isinstance(stmt, ast.CreateMV):
            return await self._create_mv(stmt)
        if isinstance(stmt, ast.Select):
            return self.query_select(stmt)
        raise BindError(f"unsupported statement {stmt!r}")

    def _create_source(self, stmt: ast.CreateSource) -> SourceDef:
        opts = dict(stmt.options)
        connector = opts.pop("connector", "nexmark")
        if connector != "nexmark":
            raise BindError(f"unknown connector {connector!r}")
        table = opts.pop("table", stmt.name)
        if table not in _NEXMARK_SCHEMAS:
            raise BindError(f"unknown nexmark table {table!r}")
        args = {"table": table,
                "chunk_size": int(opts.pop("chunk_size", 4096))}
        cfg = {}
        for k in ("inter_event_us", "base_time_us"):
            if k in opts:
                cfg[k] = int(opts.pop(k))
        if cfg:
            args["cfg"] = cfg
        if "emit_watermarks" in opts:
            v = opts.pop("emit_watermarks")
            args["emit_watermarks"] = v in (True, 1, "1", "true", "t", "on")
        for k in ("watermark_lag_us", "rate_limit"):
            if k in opts:
                args[k] = int(opts.pop(k))
        src = SourceDef(stmt.name, _NEXMARK_SCHEMAS[table], args)
        self.catalog.sources[stmt.name] = src
        return src

    async def _create_mv(self, stmt: ast.CreateMV) -> MvDef:
        planner = StreamPlanner(self.catalog)
        plan = planner.plan_select(stmt.select)
        coord = BarrierCoordinator(self.store)
        env = BuildEnv(self.store, coord)
        # table ids must be unique ACROSS deployments on the shared store
        env._next_table_id = self._next_table_id
        dep = build_graph(plan.graph, env)
        self._next_table_id = env._next_table_id
        dep.spawn()
        mv = MvDef(stmt.name, plan.schema, plan.pk_indices, dep, coord,
                   plan.mv_fragment)
        self.catalog.mvs[stmt.name] = mv
        # the Initial barrier brings the dataflow up
        await coord.run_rounds(0)
        return mv

    # ------------------------------------------------------------ runtime
    async def tick(self, rounds: int = 1,
                   interval_s: Optional[float] = None) -> None:
        """Advance every MV's barrier loop (meta's periodic injection)."""
        # snapshot: CREATE MV may run concurrently with a background ticker
        for mv in list(self.catalog.mvs.values()):
            await mv.coord.run_rounds(rounds, interval_s=interval_s)

    async def drop_all(self) -> None:
        for mv in list(self.catalog.mvs.values()):
            await mv.deployment.stop()
        self.catalog.mvs.clear()

    # -------------------------------------------------------- batch query
    def query(self, sql_text: str) -> list[tuple]:
        stmt = ast.parse(sql_text)
        assert isinstance(stmt, ast.Select), "query() takes SELECT"
        return self.query_select(stmt)

    def query_select(self, sel: ast.Select) -> list[tuple]:
        """Serving path: committed-snapshot scan of an MV + numpy eval
        (reference: batch local execution over StorageTable,
        scheduler/local.rs + storage_table.rs:646)."""
        if not isinstance(sel.rel, ast.TableRel):
            raise BindError("batch queries read one MV")
        mv = self.catalog.mvs.get(sel.rel.name)
        if mv is None:
            raise BindError(f"unknown MV {sel.rel.name!r}")
        if sel.group_by:
            raise BindError("batch GROUP BY lands with the batch engine")
        st = StorageTable.for_state_table(mv.table)
        cols = st.to_numpy()
        scope = Scope.of(mv.schema, sel.rel.alias or sel.rel.name)
        mask = np.ones(len(cols[0]) if cols else 0, dtype=bool)
        if sel.where is not None:
            pred = bind_scalar(sel.where, scope)
            v, valid = eval_numpy(pred, cols)
            mask &= v.astype(bool) & valid
        out_cols = []
        items = expand_star(sel.items, mv.schema)
        for it in items:
            e = bind_scalar(it.expr, scope)
            v, _ = eval_numpy(e, cols)
            out_cols.append(np.asarray(v)[mask] if np.ndim(v) else
                            np.full(int(mask.sum()), v))
        n = len(out_cols[0]) if out_cols else 0
        return [tuple(c[i].item() for c in out_cols) for i in range(n)]

"""Session — SQL in, materialized views + batch query results out.

Reference: SessionImpl::run_statement (src/frontend/src/session.rs:866) +
handler::handle dispatching DDL/queries, with the catalog tracking every
object. One Session owns one state store; each CREATE MATERIALIZED VIEW
deploys a fragment graph with its own barrier coordinator over that store
(meta-lite: single process, many dataflows); SELECT over an MV runs the
batch path (StorageTable committed-snapshot scan + numpy evaluation —
serving reads stay off the device, which on a tunneled TPU is also the
only fast option).
"""

from __future__ import annotations

import asyncio
import json
import os
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from ..common.types import Schema
from ..connectors.nexmark import BID_SCHEMA, PERSON_SCHEMA, AUCTION_SCHEMA
from ..meta.barrier_manager import BarrierCoordinator
from ..plan import BuildEnv, Deployment, build_graph
from ..state import MemoryStateStore, StorageTable
from . import sql as ast
from .binder import (BindError, Scope, StreamPlanner, bind_scalar,
                     contains_agg, expand_star)
from .np_eval import eval_numpy

_NEXMARK_SCHEMAS = {"bid": BID_SCHEMA, "person": PERSON_SCHEMA,
                    "auction": AUCTION_SCHEMA}


@dataclass
class SourceDef:
    name: str
    schema: Schema
    options: dict      # builder args for the source node


@dataclass
class SinkDef:
    name: str
    schema: Schema
    deployment: Deployment
    sink_fragment: int
    upstream_taps: tuple = ()
    sql: str = ""
    sources: tuple = ()                # source names this sink reads

    @property
    def executor(self):
        return self.deployment.roots[self.sink_fragment][0]


@dataclass
class MvDef:
    name: str
    schema: Schema
    pk_indices: tuple
    deployment: Deployment
    coord: BarrierCoordinator
    mv_fragment: int
    tap: object = None                 # TapDispatcher on the MV root actor
    upstream_taps: tuple = ()          # (upstream MvDef, Channel) to detach
    sql: str = ""                      # original DDL (durable catalog)
    append_only: bool = False          # changelog has no retractions
    parallelism: int = 1
    sources: tuple = ()                # source names this MV reads

    @property
    def table(self):
        return self.deployment.roots[self.mv_fragment][0].table


class Catalog:
    def __init__(self):
        self.sources: dict[str, SourceDef] = {}
        self.mvs: dict[str, MvDef] = {}
        self.sinks: dict[str, SinkDef] = {}

    def source(self, name: str) -> SourceDef:
        if name not in self.sources:
            raise BindError(f"unknown source {name!r}")
        return self.sources[name]


CATALOG_PATH = "CATALOG"

# per-process engine counter: two Sessions in one process (the stitched
# cross-engine gate does exactly that) must not share an engine id
_ENGINE_SEQ = 0


def _parse_metric_level(v) -> str:
    """SET metric_level validator: canonical lowercase name, rejects
    unknown levels at SET time (not at the next barrier)."""
    from ..stream.monitor import MetricLevel
    return MetricLevel.parse(v).name.lower()


class Session:
    """One coordinator drives EVERY dataflow of the session (the reference
    has one GlobalBarrierManager for all streaming jobs): MV-on-MV needs
    all MVs on a single aligned epoch stream."""

    # session variables (reference: common/src/session_config/ — a 40+
    # field derive struct; this is the streaming-relevant subset) with
    # (default, validator)
    CONFIG_VARS = {
        "streaming_join_capacity": (1 << 17, int),
        "streaming_join_match_factor": (64, int),
        "streaming_agg_capacity": (1 << 16, int),
        "streaming_watchdog": (1, int),      # 0 disables d2h error fetches
        "streaming_parallelism": (1, int),
        # >1 deploys hash-distributed agg/join fragments as SINGLE
        # actors whose state is sharded over an N-device jax Mesh on the
        # vnode axis (stream/sharded_agg.py, sharded_join.py) — the TPU
        # analogue of the reference's parallel-unit placement
        # (meta/src/stream/stream_graph/schedule.rs)
        "streaming_parallelism_devices": (1, int),
        # 1 (default): mesh fragments run the FUSED data plane — the
        # exchange into a sharded agg/join is an in-program
        # lax.all_to_all over ICI (parallel/exchange.mesh_ingest_chunk),
        # one shard_map program per barrier interval. 0 restores the
        # replicated-chunk + per-shard-mask plane.
        "streaming_mesh_shuffle": (1, int),
        # per-(src,dst) send-bucket sizing for the fused shuffle: 0 =
        # zero-drop (bucket = the full per-shard slice, overflow
        # impossible under any key skew); k > 0 = k * ceil(slice/shards)
        # — near-linear per-shard compute for balanced keys, with
        # on-device overflow counting that FAIL-STOPS the epoch
        # (mesh_shuffle_dropped_rows_total) if the skew beats the slack
        "streaming_mesh_shuffle_slack": (0, int),
        # 1 (default): when the manual slack is 0, send-bucket sizing
        # ADAPTS to the observed per-shard receive demand (EWMA + peak,
        # refreshed at each barrier watchdog fetch, 2x pow2 headroom) —
        # zero-drop sizing until enough intervals are observed, fail-stop
        # overflow semantics unchanged. 0 pins zero-drop sizing.
        "streaming_mesh_shuffle_adaptive": (1, int),
        # 1 (default): fuse eligible producer->shuffle->consumer CHAINS
        # onto the mesh (plan/build._fuse_mesh_chains): stateless
        # producer stages (project / hop_window over a source) hollow out
        # and run INSIDE the downstream sharded executor's fused program
        # — zero host hops per steady barrier interval
        # (mesh_host_round_trips_total{chain} == 0). 0 keeps eligible
        # chains on the per-chunk host plane (counter still runs — the
        # unfused baseline scripts/mesh_profile.py compares against).
        "streaming_mesh_chain": (1, int),
        "streaming_over_window_capacity": (1 << 14, int),
        "streaming_top_n_capacity": (1 << 14, int),
        "streaming_dynamic_filter_capacity": (1 << 14, int),
        # "host:port" of a running fragment worker
        # (python -m risingwave_tpu.worker): join fragments deploy there
        # over the DCN tier; requires streaming_durability = 0 in v1
        "streaming_fragment_worker": ("", str),
        # 0 disables the snapshot join-agg fusion (binder.py
        # _try_snapshot_join_agg) — the q17 shape then plans the
        # generic changelog join cascade
        "streaming_snapshot_fuse": (1, int),
        # 0 = in-memory state backend for stateful executors (reference:
        # the in-memory hummock backend) — no per-barrier state-table
        # flush; crash recovery then replays sources from scratch
        "streaming_durability": (1, int),
        # > 0: exchange receivers pack runs of consecutive small chunks
        # between barriers into one chunk of up to this total capacity
        # (power-of-two bucketed shapes, zero steady-state recompiles) —
        # each downstream stateful executor then pays one dispatch per
        # interval instead of one per chunk (common/chunk.py
        # ChunkCoalescer). 0 = off.
        "streaming_chunk_coalesce": (0, int),
        # bounded window of sealed-but-uncommitted checkpoint epochs the
        # background uploader may hold (meta/barrier_manager.py): barriers
        # complete at seal, SST build/upload/manifest-swap overlap the next
        # epochs' compute. 0 = inline sync on the barrier path.
        "checkpoint_max_inflight": (2, int),
        # HBM budget for device-resident executor state (memory/): 0 =
        # accounting only; > 0 = the coordinator's MemoryManager evicts
        # cold key groups to host at barriers (read-through reload on a
        # later touch) so the accounted total stays under budget
        "hbm_budget_bytes": (0, int),
        # 'lru' = epoch-stamped coldest-first (the only policy); 'none'
        # disables eviction while keeping accounting
        "memory_eviction_policy": ("lru", str),
        # serving pool admission bound (serving/pool.py): at most this
        # many batch queries execute concurrently on worker threads
        "serving_max_concurrency": (4, int),
        # per-query serving timeout in ms; 0 = unbounded
        "serving_query_timeout_ms": (0, int),
        # 1 = per-MV snapshot caches maintained incrementally from the
        # changelog (epoch-pinned reads, pk point-lookup index); 0 =
        # every SELECT re-scans the committed LSM snapshot
        "serving_cache": (1, int),
        # observability plane (stream/monitor.py): off = no per-actor
        # instrumentation at all; info (default) = epoch-trace phase
        # splits only; debug = full per-actor/per-channel labelled
        # series (stream_actor_row_count{actor,executor}, queue depth,
        # blocked-put seconds, hash occupancy, ...)
        "metric_level": ("info", lambda v: _parse_metric_level(v)),
        # monitor HTTP endpoint (meta/monitor_service.py): /metrics,
        # /healthz, /debug/traces, /debug/await_tree. 0 = off (default)
        "monitor_port": (0, int),
        # changelog subscription endpoint (logstore/subscription.py):
        # serving replicas connect here over the control-plane wire,
        # subscribe to an MV's changelog with backfill-then-tail, and
        # answer point lookups from their own snapshot cache. 0 = off.
        "subscription_port": (0, int),
        # durable-cursor lease (logstore/): a NAMED subscription cursor
        # with no live subscriber renewing it for this long stops
        # pinning MV changelog retention — resubscribing within the TTL
        # still resumes the tail; after it, the subscription falls back
        # to backfill-then-tail. 0 (default) = cursors never expire
        # (drop_sub_cursor is the only release).
        "subscription_cursor_ttl_ms": (0, int),
        # stuck-barrier watchdog threshold: an in-flight epoch older
        # than this logs format_stuck_barrier_report once and bumps
        # barrier_stalls_total; 0 disables the watchdog
        "barrier_stall_threshold_ms": (60000, int),
        # ---- metrics history (utils/metrics_history.py) ----
        # sample the allowlisted series every N collected barriers into
        # bounded per-series rings (the rw_metrics system table + the
        # autoscaler's time-series substrate). 0 disables sampling.
        "metrics_history_interval": (1, int),
        # newest samples kept per series at full resolution; the same
        # count again survives downsampled (every k-th evicted sample)
        "metrics_history_retention": (512, int),
        # coarse-tier keep ratio: 1 of every k evicted samples survives
        "metrics_history_downsample": (8, int),
        # comma-separated series allowlist; '' = the built-in default
        # (barrier latency, exchange pressure, source lag, HBM, ...)
        "metrics_history_series": ("", str),
        # 1 = also append each pulse to a crc-framed log next to the
        # event log (subdir "metrics", torn-tail framing) so rw_metrics
        # history survives a restart; 0 (default) = ring only
        "metrics_history_durable": (0, int),
        # 1 (default): exchange channels buffer the uncommitted message
        # suffix (trimmed at every checkpoint commit) and an actor
        # failure whose blast radius is contained to ONE terminal
        # fragment rebuilds only that fragment's actors from the last
        # committed epoch — upstream fragments keep their device state
        # and replay the in-flight interval from the channel buffers.
        # 0: every failure takes the full stop-the-world recovery.
        "partial_recovery": (1, int),
        # exponential-backoff base between CONSECUTIVE auto-recovery
        # attempts inside one tick (the first recovery is immediate; a
        # persistent fault then waits base*2^(n-1) with +-50% jitter,
        # capped at 5s, instead of hot-looping through max_recoveries).
        # 0 disables the backoff. recovery_backoff_seconds_total counts
        # the waited seconds.
        "recovery_backoff_ms": (50, int),
        # flap detection: more than this many recoveries of the SAME
        # cause within the trailing window (utils/metrics.py
        # RECOVERY_FLAP_WINDOW_S) marks that cause FLAPPING — the
        # backoff base escalates toward the 5s cap even on the first
        # attempt of a tick (a fault that keeps coming back must stop
        # hammering rebuilds), `recovery_flapping{cause}` flips to 1 in
        # /metrics, and /healthz reports `degraded`. 0 disables.
        "recovery_flap_threshold": (3, int),
        # ---- fault-tolerant storage plane (state/) ----
        # quarantine repair source: a local-dir backup written by
        # BACKUP TO (which also sets this). When set, a durably-corrupt
        # SST restores from its checksum-verified backup copy instead
        # of crash-looping; '' detaches.
        "backup_path": ("", str),
        # background scrubber cadence (state/scrub.py): verify a batch
        # of manifest-referenced objects + sweep orphan SSTs every N
        # collected barriers. 0 disables the scrubber.
        "storage_scrub_interval": (16, int),
        # objects integrity-verified per scrub pulse
        "storage_scrub_batch": (2, int),
        # background compaction (state/compactor.py): consider a merge
        # every N collected barriers. 0 disables and falls back to the
        # inline commit-path merge (standalone-store behavior).
        "compaction_interval": (1, int),
        # L0 run count that arms a merge (read amp stays near this)
        "compaction_l0_trigger": (4, int),
        # rewrite budget credited per barrier interval — paces merge
        # work against ingest so compaction can't starve the loop
        "compaction_budget_bytes": (8 << 20, int),
        # max L0 runs folded per merge (bounds single-task latency)
        "compaction_max_runs": (8, int),
        # broker retention (state/compactor.py): push earliest-durable-
        # offset floors to brokers every N barriers so they drop whole
        # sealed segments below every consumer's checkpoint. 0 = off.
        "broker_retention_interval": (0, int),
        # backup generations kept point-in-time restorable in the
        # ledger (RESTORE FROM ... AT GENERATION n)
        "backup_keep_generations": (8, int),
        # bounded retry budget of the ResilientObjectStore wrapper: a
        # transient PUT/GET absorbs up to N-1 retries (seeded backoff +
        # jitter) below the recovery machinery before it surfaces as a
        # persistent fail-stop fault
        "object_store_retries": (4, int),
        # deterministic fault injection (utils/faults.py): named fault
        # points armed by spec, e.g.
        #   SET fault_injection = 'actor_crash:actor=4,at=2'
        #   SET fault_injection = 'upload_fail;recovery_crash:phase=full'
        # '' disarms. ZERO hot-path cost when off (sites guard on one
        # attribute read). Consumed by scripts/chaos_profile.py.
        "fault_injection": ("", str),
        # cluster mode (cluster/): comma-separated compute-node
        # addresses ("host:port,host:port"). Setting it attaches the
        # session's coordinator to the workers as a meta service: every
        # subsequent CREATE MV/SINK deploys vnode-partitioned fragments
        # ACROSS the workers, barriers inject/collect per worker over
        # RPC, and checkpoints commit only after all workers report
        # sealed state. '' detaches. Requires a shared-filesystem
        # Hummock store and streaming_durability = 1.
        "cluster": ("", str),
    }

    def __init__(self, store=None):
        self.store = store if store is not None else MemoryStateStore()
        self.catalog = Catalog()
        # restore the string dictionary BEFORE anything can mint ids
        # (bind-time literals, parsers): MV state on this store holds
        # dict ids from the previous incarnation (common/types.py)
        objects = getattr(self.store, "objects", None)
        dict_restored = 0
        if objects is not None:
            from ..common.types import load_dict_log
            dict_restored = load_dict_log(objects)
        self.coord = BarrierCoordinator(self.store)
        self.coord.dict_cursor = dict_restored
        self.env = BuildEnv(self.store, self.coord)
        self.env.session = self
        self.config = {k: v for k, (v, _) in self.CONFIG_VARS.items()}
        # durable catalog: ordered DDL log + the table-id floor each MV was
        # built at, so a replay rebinds the SAME state-table ids
        # (reference: catalog in the meta store, meta/src/manager/catalog/).
        # The persisted log loads EAGERLY: a session that issues DDL on an
        # existing store without calling recover() must append to the
        # stored log, not clobber it.
        self._ddl_log: list[dict] = []
        self._recovering = False
        blob = self._load_catalog_blob()
        if blob:
            self._ddl_log = list(json.loads(blob)["ddl"])
        self.recoveries = 0
        # most recent auto-recovery: {"scope","cause","duration_s",
        # "actors"} — surfaced by /healthz (meta/monitor_service.py)
        self.last_recovery = None
        # (monotonic time, cause) of recent recoveries — the flap
        # detector's window (recovery_flap_threshold)
        from collections import deque as _deque
        self._recovery_log = _deque(maxlen=256)
        self.env.partial_recovery = bool(self.config["partial_recovery"])
        # durable event log (meta/event_log.py): notable cluster events
        # append next to the object store and survive restart; memory-
        # only ring on a pure in-memory store. SESSION-owned so it
        # survives the coordinator swap a full recovery performs.
        from ..meta.event_log import EventLog
        self.event_log = EventLog(getattr(objects, "root", None))
        # barrier-paced metrics history (utils/metrics_history.py),
        # session-owned like the event log (a recovery's coordinator
        # swap must not truncate telemetry history); _apply_obs_config
        # points the live coordinator at it
        from ..utils.metrics_history import MetricsHistory
        self.metrics_history = MetricsHistory()
        # engine identity stamped into broker sink batch metas so a
        # downstream engine's ingest spans link back across the broker
        # (utils/trace.py stitch_chrome_traces); unique per process
        global _ENGINE_SEQ
        _ENGINE_SEQ += 1
        self.engine_id = f"engine-{os.getpid()}-{_ENGINE_SEQ}"
        # worker-local event records last stitched by the cluster
        # SHOW events / /debug/events fan-out (worker_id -> records);
        # the rw_events system table reads this cache synchronously
        self._worker_events_cache: dict = {}
        # recovery post-mortem spans, session-owned for the same reason
        # (/debug/traces must describe the recovery that replaced the
        # coordinator whose tracer used to hold them)
        from ..utils.trace import RecoveryRing
        self.recovery_ring = RecoveryRing()
        # monitor HTTP endpoint (SET monitor_port / start_monitor)
        self.monitor = None
        # changelog subscription endpoint (SET subscription_port /
        # start_subscription_server); reads self.coord live, so it
        # serves across auto-recovery coordinator swaps
        self.subscriptions = None
        # cluster manager (SET cluster = 'host:port,...'): when set, the
        # session IS the meta node and deploys onto compute nodes
        self.cluster = None
        self._apply_memory_config()
        self._apply_serving_config()
        self._apply_obs_config()
        self._apply_logstore_config()
        self._apply_storage_config()

    def _apply_storage_config(self) -> None:
        """Plumb the storage-plane session vars to the live store +
        coordinator scrubber (re-applied after auto-recovery swaps the
        coordinator): scrub cadence, object-store retry budget, and the
        quarantine repair source (backup_path)."""
        self.coord.scrubber.configure(
            interval=self.config.get("storage_scrub_interval", 16),
            batch=self.config.get("storage_scrub_batch", 2))
        objects = getattr(self.store, "objects", None)
        if objects is not None and hasattr(objects, "max_attempts"):
            objects.max_attempts = max(
                1, self.config.get("object_store_retries", 4))
        comp = getattr(self.coord, "compactor", None)
        if comp is not None:
            comp.configure(
                interval=self.config.get("compaction_interval", 1),
                l0_trigger=self.config.get("compaction_l0_trigger", 4),
                budget_bytes=self.config.get("compaction_budget_bytes",
                                             8 << 20),
                max_runs=self.config.get("compaction_max_runs", 8))
            comp.retention.configure(
                interval=self.config.get("broker_retention_interval", 0))
        if hasattr(self.store, "backup_store"):
            path = self.config.get("backup_path", "")
            if path:
                from ..state import LocalFsObjectStore
                cur = getattr(self.store.backup_store, "root", None)
                if cur != path:
                    self.store.backup_store = LocalFsObjectStore(path)
            else:
                self.store.backup_store = None

    def _apply_memory_config(self) -> None:
        """Plumb the memory session vars to the live coordinator's
        MemoryManager (re-applied after auto-recovery rebuilds it)."""
        self.coord.memory.configure(
            budget_bytes=self.config["hbm_budget_bytes"],
            policy=self.config["memory_eviction_policy"])

    def _apply_serving_config(self) -> None:
        """Plumb the serving session vars to the live coordinator's
        ServingManager (re-applied after auto-recovery rebuilds it)."""
        self.coord.serving.configure(
            enabled=bool(self.config["serving_cache"]),
            max_concurrency=self.config["serving_max_concurrency"],
            timeout_ms=self.config["serving_query_timeout_ms"])

    def _apply_obs_config(self) -> None:
        """Plumb the observability session vars to the live coordinator:
        metric level re-instruments deployed actors in place, the stall
        threshold feeds the stuck-barrier watchdog (re-applied after
        auto-recovery rebuilds the coordinator)."""
        self.coord.stats.configure(self.config["metric_level"])
        thr = self.config["barrier_stall_threshold_ms"]
        self.coord.stall_threshold_ms = float(thr) if thr > 0 else None
        # attach the session-owned durable event log to every emitter
        # living on the (swappable) coordinator — re-running this after
        # auto-recovery re-attaches it to the new incarnation
        self.coord.event_log = self.event_log
        self.coord.scrubber.event_log = self.event_log
        self.coord.logstore.event_log = self.event_log
        # metrics history: session-owned store, coordinator-paced pulse
        objects = getattr(self.store, "objects", None)
        durable = bool(self.config.get("metrics_history_durable", 0))
        root = getattr(objects, "root", None) if durable else None
        self.metrics_history.configure(
            interval=self.config.get("metrics_history_interval", 1),
            retention=self.config.get("metrics_history_retention", 512),
            downsample=self.config.get("metrics_history_downsample", 8),
            series=self.config.get("metrics_history_series", ""),
            root=root)
        self.coord.metrics_history = self.metrics_history

    def _apply_logstore_config(self) -> None:
        """Plumb the log-store session vars to the live hub (re-applied
        after auto-recovery swaps the coordinator)."""
        self.coord.logstore.sub_cursor_ttl_ms = self.config.get(
            "subscription_cursor_ttl_ms", 0)

    async def start_monitor(self, port: int = 0):
        """Start (or move) the monitor HTTP endpoint; port 0 binds an
        ephemeral port (the chosen one lands in `self.monitor.port`)."""
        from ..meta.monitor_service import MonitorService
        if self.monitor is not None:
            await self.monitor.stop()
        self.monitor = await MonitorService(self, port=port).start()
        return self.monitor

    async def stop_monitor(self) -> None:
        if self.monitor is not None:
            await self.monitor.stop()
            self.monitor = None

    async def start_subscription_server(self, port: int = 0):
        """Start (or move) the changelog subscription endpoint; port 0
        binds an ephemeral port (chosen one in
        `self.subscriptions.port`)."""
        from ..logstore.subscription import SubscriptionServer
        if self.subscriptions is not None:
            await self.subscriptions.stop()
        self.subscriptions = await SubscriptionServer(
            self, port=port).start()
        return self.subscriptions

    async def stop_subscription_server(self) -> None:
        if self.subscriptions is not None:
            await self.subscriptions.stop()
            self.subscriptions = None

    # ------------------------------------------------------ durable catalog
    def _persist_catalog(self) -> None:
        if self._recovering:
            return
        blob = json.dumps({"format": 1, "ddl": self._ddl_log}).encode()
        objects = getattr(self.store, "objects", None)
        if objects is not None:          # Hummock: atomic object swap
            # same self-checksummed framing the MANIFEST carries: a
            # bit-rotted catalog is detected at load, not replayed
            from ..state.sstable import frame_meta
            objects.upload(CATALOG_PATH, frame_meta(blob))
        else:                            # in-memory: survives in-process
            self.store._catalog_blob = blob
    def _load_catalog_blob(self):
        objects = getattr(self.store, "objects", None)
        if objects is not None:
            if objects.exists(CATALOG_PATH):
                from ..state.sstable import unframe_meta
                return unframe_meta(objects.read(CATALOG_PATH),
                                    CATALOG_PATH)
            return None
        return getattr(self.store, "_catalog_blob", None)

    async def backup(self, dest_object_store) -> dict:
        """Consistent backup of the session's durable state (manifest,
        SSTs, catalog/DDL log) into another object store — INCREMENTAL
        and generation-stamped: only objects the destination does not
        already hold at the recorded checksum copy (SSTs are immutable,
        so a steady-state backup moves just the new generation's
        objects), each copy read back + verified before it enters the
        backup ledger (state/backup.py). Holds the coordinator's rounds
        lock so no sync/compaction/manifest swap runs mid-copy
        (reference: src/storage/backup/src/, the meta snapshot taken
        under the barrier manager's pause). Registered in-process
        brokers' data directories ride the same ledger under
        `broker/<name>/...` (their batch framing makes a torn active-
        segment tail harmless on restore, so appends need no quiesce);
        `extract_backup_prefix` materializes them back."""
        import os as _os
        from ..state.backup import backup_objects
        objects = getattr(self.store, "objects", None)
        if objects is None:
            raise BindError("backup needs a durable (Hummock) store")
        from ..broker.server import _INPROC
        from ..state import LocalFsObjectStore
        aux = {}
        for bname, broker in sorted(_INPROC.items()):
            root = getattr(broker, "root", None)
            if root and _os.path.isdir(root):
                aux[f"broker/{bname}"] = LocalFsObjectStore(root)
        async with self.coord._rounds_lock:
            # the rounds lock stops NEW barriers; the background uploader
            # may still hold sealed-but-uncommitted epochs — drain them so
            # no manifest swap runs mid-copy
            await self.coord.drain_uploads()
            # the rounds lock quiesces sync/compaction (every MANIFEST
            # swap), but DDL catalog uploads run outside it — snapshot
            # the catalog NOW and write the snapshot last, so the backup
            # is (catalog-as-of-start, manifest quiesced): concurrent
            # DDL can only leave unreferenced extra state in the copy,
            # never a catalog pointing at absent state
            extra = ({CATALOG_PATH: objects.read(CATALOG_PATH)}
                     if objects.exists(CATALOG_PATH) else None)
            # the copy itself runs off-loop so pgwire/sinks/actors stay
            # responsive during a large backup
            return await asyncio.to_thread(
                backup_objects, objects, dest_object_store, extra, aux,
                max(1, self.config.get("backup_keep_generations", 8)))

    async def restore_from(self, path: str,
                           generation: Optional[int] = None) -> dict:
        """Cold-start disaster recovery (RESTORE FROM '<path>'
        [AT GENERATION <n>]): verify EVERY object of the backup against
        its ledger checksum, copy the chosen generation's verified set
        (default: newest; older retained generations resolve
        superseded bytes from the archive — point-in-time restore)
        into this session's FRESH primary store, re-point
        the store at the restored manifest, reload the string dictionary
        and DDL log, then replay the DDL log — the restored session
        converges from the backup's committed epoch exactly like a
        normal post-crash recovery. Refuses a non-empty session/store:
        restoring over a live world would interleave two histories."""
        from ..state import LocalFsObjectStore
        from ..state.backup import restore_objects
        objects = getattr(self.store, "objects", None)
        if objects is None:
            raise BindError("restore needs a durable (Hummock) store")
        if self.catalog.mvs or self.catalog.sinks or self._ddl_log:
            raise BindError(
                "RESTORE FROM requires an empty session (no DDL log, "
                "no live flows) over a fresh store")
        backup = LocalFsObjectStore(path)
        # verification + copy run off-loop (reads every backup object)
        meta = await asyncio.to_thread(restore_objects, backup, objects,
                                       generation)
        # re-point the live handles at the restored world
        self.store.refresh_manifest()
        from ..common.types import load_dict_log
        self.coord.dict_cursor = load_dict_log(objects)
        self.coord._prev_epoch = max(self.coord._prev_epoch,
                                     self.store.committed_epoch())
        blob = self._load_catalog_blob()
        if blob:
            self._ddl_log = list(json.loads(blob)["ddl"])
        # the backup that restored us is by construction a valid
        # quarantine repair source going forward
        self.config["backup_path"] = path
        self._apply_storage_config()
        await self.recover()
        return meta

    async def recover(self) -> None:
        """Replay the persisted DDL log: re-register sources, re-deploy
        every MV with its original table ids (their materialized state is
        already in the store; sources re-seek their committed offsets).
        The playground calls this on startup with --data."""
        log = list(self._ddl_log)
        if not log:
            return
        self._recovering = True
        saved_config = dict(self.config)
        try:
            for entry in log:
                self.env._next_table_id = entry.get(
                    "table_id_floor", self.env._next_table_id)
                self._replay_parallelism = entry.get("parallelism", 1)
                # each entry replays under ITS OWN planning-time config;
                # entries without one (sources, old logs) use the defaults
                self.config = {**saved_config, **entry.get("config", {})}
                self.env.chunk_coalesce_max = self.config.get(
                    "streaming_chunk_coalesce", 0)
                await self.execute(entry["sql"])
        finally:
            self.config = saved_config
            self._recovering = False
            self._replay_parallelism = 1
        self._ddl_log = list(log)
        # one Initial barrier over the fully-reattached topology
        if self.catalog.mvs:
            await self.coord.run_rounds(0)

    # --------------------------------------------------------------- DDL
    async def execute(self, sql_text: str):
        stmt = ast.parse(sql_text)
        if isinstance(stmt, ast.CreateSource):
            out = self._create_source(stmt)
            if not self._recovering:
                self._ddl_log = [e for e in self._ddl_log if not (
                    e["kind"] == "source" and e["name"] == stmt.name)]
                self._ddl_log.append({"kind": "source", "name": stmt.name,
                                      "sql": sql_text})
                self._persist_catalog()
            return out
        if isinstance(stmt, ast.CreateSink):
            if stmt.name in self.catalog.sinks:
                raise BindError(f"sink {stmt.name!r} already exists")
            floor = self.env._next_table_id   # BEFORE build, like MVs
            out = await self._create_sink(stmt, sql_text)
            if not self._recovering:
                self._ddl_log = [e for e in self._ddl_log if not (
                    e["kind"] == "sink" and e["name"] == stmt.name)]
                self._ddl_log.append({"kind": "sink", "name": stmt.name,
                                      "sql": sql_text,
                                      "table_id_floor": floor,
                                      "config": dict(self.config)})
                self._persist_catalog()
            return out
        if isinstance(stmt, ast.CreateMV):
            if stmt.name in self.catalog.mvs:
                raise BindError(f"MV {stmt.name!r} already exists")
            floor = self.env._next_table_id
            out = await self._create_mv(
                stmt, sql_text,
                parallelism=getattr(self, "_replay_parallelism", 1)
                if self._recovering
                else self.config["streaming_parallelism"])
            if not self._recovering:
                self._ddl_log = [e for e in self._ddl_log if not (
                    e["kind"] == "mv" and e["name"] == stmt.name)]
                # the session config the MV was planned under persists with
                # it: recovery must rebuild the SAME capacities/tuning
                entry = {"kind": "mv", "name": stmt.name,
                         "sql": sql_text, "table_id_floor": floor,
                         "config": dict(self.config)}
                if self.cluster is not None:
                    # cluster MVs MUST replay at their planned
                    # parallelism: the vnode bitmaps the durable state
                    # was partitioned under are per-actor-idx
                    entry["parallelism"] = out.parallelism
                self._ddl_log.append(entry)
                self._persist_catalog()
            return out
        if isinstance(stmt, ast.AlterParallelism):
            return await self.alter_parallelism(stmt.name, stmt.parallelism)
        if isinstance(stmt, ast.Drop):
            return await self._drop(stmt)
        if isinstance(stmt, ast.CreateTable):
            # a DML-able BASE TABLE (reference: CREATE TABLE + dml.rs +
            # TableSource): composed from the jsonl source (the
            # append-only file IS the durable DML log — replayable
            # offsets, open-vocabulary dict durability included) plus an
            # auto-materialization so batch SELECTs and MV-on-MV work.
            # Both sub-DDLs land in the catalog log, so recovery replays
            # them in order.
            if stmt.name in self.catalog.sources \
                    or stmt.name in self.catalog.mvs:
                raise BindError(f"{stmt.name!r} already exists")
            colspec = ", ".join(f"{n} {t}" for n, t in stmt.columns)
            path = self._dml_path(stmt.name)
            # TRUNCATE: a re-created table must not resurrect a dropped
            # incarnation's rows (recovery replays the SOURCE DDL, not
            # CreateTable, so replay never truncates)
            open(path, "w").close()
            await self.execute(
                f"CREATE SOURCE {stmt.name} WITH (connector='jsonl', "
                f"path='{path}', columns='{colspec}', is_table=1)")
            return await self.execute(
                f"CREATE MATERIALIZED VIEW {stmt.name} AS "
                f"SELECT * FROM {stmt.name}")
        if isinstance(stmt, ast.Insert):
            return self._insert(stmt)
        if isinstance(stmt, ast.BackupStmt):
            from ..state import LocalFsObjectStore
            meta = await self.backup(LocalFsObjectStore(stmt.path))
            # the backup destination doubles as the quarantine repair
            # source from here on (SET backup_path to change/detach)
            self.config["backup_path"] = stmt.path
            self._apply_storage_config()
            self.event_log.emit(
                "backup", path=stmt.path,
                generation=meta.get("generation"),
                epoch=meta.get("epoch"))
            return meta
        if isinstance(stmt, ast.RestoreStmt):
            meta = await self.restore_from(stmt.path,
                                           stmt.generation)
            self.event_log.emit(
                "restore", path=stmt.path,
                generation=(meta or {}).get("generation")
                if isinstance(meta, dict) else None)
            return meta
        if isinstance(stmt, ast.Explain):
            return self.explain(stmt.stmt)
        if isinstance(stmt, ast.ExplainMv):
            return self.explain_mv(stmt.name)
        if isinstance(stmt, ast.Show):
            if self.cluster is not None and stmt.what in ("cluster",
                                                          "memory",
                                                          "events"):
                return await self._show_cluster(
                    stmt.what, limit=getattr(stmt, "limit", None),
                    kind=getattr(stmt, "kind", None),
                    since=getattr(stmt, "since", None))
            return self.show(stmt.what,
                             limit=getattr(stmt, "limit", None),
                             kind=getattr(stmt, "kind", None),
                             since=getattr(stmt, "since", None))
        if isinstance(stmt, ast.SetVar):
            if stmt.name not in self.CONFIG_VARS:
                raise BindError(f"unknown session variable {stmt.name!r}")
            _, conv = self.CONFIG_VARS[stmt.name]
            self.config[stmt.name] = conv(stmt.value)
            if stmt.name == "streaming_chunk_coalesce":
                # build-time knob, read by build_graph when wiring
                # exchange receivers (plan/build.py)
                self.env.chunk_coalesce_max = self.config[stmt.name]
            elif stmt.name == "checkpoint_max_inflight":
                # runtime-mutable on the LIVE coordinator (the ALTER
                # SYSTEM analogue): takes effect at the next barrier
                self.coord.checkpoint_max_inflight = self.config[stmt.name]
            elif stmt.name in ("hbm_budget_bytes",
                               "memory_eviction_policy"):
                # runtime-mutable on the live MemoryManager: enabling a
                # budget starts LRU tracking on every deployed executor;
                # in cluster mode the budget is PARTITIONED across the
                # live workers and forwarded to each
                self._apply_memory_config()
                if self.cluster is not None:
                    await self.cluster.push_config()
            elif stmt.name in ("serving_max_concurrency",
                               "serving_query_timeout_ms",
                               "serving_cache"):
                # runtime-mutable on the live ServingManager/pool
                self._apply_serving_config()
            elif stmt.name in ("metric_level",
                               "barrier_stall_threshold_ms",
                               "metrics_history_interval",
                               "metrics_history_retention",
                               "metrics_history_downsample",
                               "metrics_history_series",
                               "metrics_history_durable"):
                # runtime-mutable: re-instruments live actors / adjusts
                # the stuck-barrier watchdog (cluster-wide when attached)
                self._apply_obs_config()
                if self.cluster is not None:
                    await self.cluster.push_config()
            elif stmt.name == "subscription_cursor_ttl_ms":
                # runtime-mutable on the live LogStoreHub: the next
                # commit pulse re-evaluates which durable cursors still
                # pin changelog retention
                self._apply_logstore_config()
            elif stmt.name in ("backup_path", "storage_scrub_interval",
                               "storage_scrub_batch",
                               "object_store_retries",
                               "compaction_interval",
                               "compaction_l0_trigger",
                               "compaction_budget_bytes",
                               "compaction_max_runs",
                               "broker_retention_interval"):
                # runtime-mutable on the live store/scrubber/compactor:
                # the next pulse and the next object op see the new
                # policy
                self._apply_storage_config()
            elif stmt.name == "partial_recovery":
                # build-time knob: channels allocated after this carry
                # (or not) the replay buffers; classification also
                # re-checks it at failure time
                self.env.partial_recovery = bool(self.config[stmt.name])
                if self.cluster is not None:
                    await self.cluster.push_config()
            elif stmt.name == "fault_injection":
                from ..utils.faults import FAULTS
                try:
                    FAULTS.arm(self.config[stmt.name])
                except ValueError as e:
                    raise BindError(str(e))
                if self.cluster is not None:
                    # cluster fault points (dcn_drop, worker_crash_
                    # partial) fire inside WORKER processes — forward
                    # the spec so their process-global injectors arm too
                    await self.cluster.push_config()
            elif stmt.name == "cluster":
                await self._configure_cluster(self.config[stmt.name])
            elif stmt.name == "monitor_port":
                # 0 stops the endpoint; a port starts/moves it
                port = self.config[stmt.name]
                if port > 0:
                    await self.start_monitor(port)
                else:
                    await self.stop_monitor()
            elif stmt.name == "subscription_port":
                port = self.config[stmt.name]
                if port > 0:
                    await self.start_subscription_server(port)
                else:
                    await self.stop_subscription_server()
            return self.config[stmt.name]
        if isinstance(stmt, ast.Select):
            return self.query_select(stmt)
        raise BindError(f"unsupported statement {stmt!r}")

    async def _drop(self, stmt: ast.Drop) -> str:
        """DROP ... (reference: handler/drop_*.rs; dependents refuse)."""
        kind, name = stmt.kind, stmt.name
        if kind == "sink":
            if name not in self.catalog.sinks:
                raise BindError(f"unknown sink {name!r}")
            await self.drop_sink(name)
            return "DROP_SINK"
        if kind == "materialized_view":
            if name not in self.catalog.mvs:
                raise BindError(f"unknown materialized view {name!r}")
            await self.drop_mv(name)
            return "DROP_MATERIALIZED_VIEW"
        # table = its auto-materialization + its source; source = just
        # the catalog entry (a source has no running deployment of its
        # own — deployments embed their connector at build time).
        # Dependent MVs/sinks refuse the drop: their DDL-log entries
        # could never replay after the source entry is pruned.
        src = self.catalog.sources.get(name)
        if src is None:
            raise BindError(f"unknown {kind} {name!r}")
        is_table = bool(src.options.get("is_table"))
        if kind == "table" and not (is_table and name in self.catalog.mvs):
            raise BindError(f"{name!r} is not a table")
        if kind == "source" and is_table:
            raise BindError(f"{name!r} is a table (use DROP TABLE)")
        deps = [d.name
                for d in (list(self.catalog.mvs.values())
                          + list(self.catalog.sinks.values()))
                if name in getattr(d, "sources", ()) and d.name != name]
        if deps:
            raise BindError(f"cannot drop {name!r}: {deps} read it")
        if kind == "table":
            await self.drop_mv(name)
        self.catalog.sources.pop(name, None)
        self._ddl_log = [e for e in self._ddl_log
                         if not (e["kind"] == "source"
                                 and e["name"] == name)]
        self._persist_catalog()
        if is_table:
            import os as _os
            try:
                _os.remove(src.options["path"])
            except OSError:
                pass
        return "DROP_TABLE" if kind == "table" else "DROP_SOURCE"

    def _dml_path(self, table: str) -> str:
        """Stable per-table DML log path: inside the durable store's
        root when there is one (survives restarts), else a
        session-stable temp dir (in-process recovery reuses it)."""
        import os
        import tempfile
        objects = getattr(self.store, "objects", None)
        root = getattr(objects, "root", None) if objects else None
        if root is None:
            root = getattr(self.store, "_dml_dir", None)
            if root is None:
                root = tempfile.mkdtemp(prefix="rwtpu_dml_")
                self.store._dml_dir = root
        d = os.path.join(root, "dml")
        os.makedirs(d, exist_ok=True)
        return os.path.join(d, f"{table}.jsonl")

    def _insert(self, stmt: ast.Insert) -> int:
        """INSERT INTO <jsonl-backed table> VALUES ... — append whole
        JSON lines; the tailing source picks them up at the next
        barrier (reference: dml.rs rows ride a channel into the
        TableSource; exactly-once from the committed line offset)."""
        src = self.catalog.sources.get(stmt.name)
        if src is None or src.options.get("connector") != "jsonl":
            raise BindError(
                f"{stmt.name!r} is not an INSERT-able table (CREATE "
                "TABLE name (col type, ...) or a jsonl source)")
        from ..common.types import DataType
        names = list(src.schema.names)
        lines = []
        for row in stmt.rows:
            if len(row) != len(names):
                raise BindError(
                    f"INSERT row has {len(row)} values, table "
                    f"{stmt.name!r} has {len(names)} columns")
            obj = {}
            for f, v in zip(src.schema, row):
                if isinstance(v, ast.UnOp) and v.op == "neg" \
                        and isinstance(v.arg, ast.Lit) \
                        and isinstance(v.arg.value, (int, float)):
                    val = -v.arg.value
                elif isinstance(v, ast.Lit):
                    val = v.value
                else:
                    raise BindError("INSERT VALUES must be literals")
                if val is None:
                    continue
                dt = f.data_type
                ok = (isinstance(val, str)
                      if dt in (DataType.VARCHAR, DataType.BYTEA,
                                DataType.JSONB)
                      else isinstance(val, bool)
                      if dt is DataType.BOOLEAN
                      else isinstance(val, (int, float))
                      and not isinstance(val, bool)
                      if dt.is_float
                      else isinstance(val, int)
                      and not isinstance(val, bool))
                if not ok:
                    raise BindError(
                        f"INSERT value {val!r} does not fit column "
                        f"{f.name} ({dt.value})")
                obj[f.name] = val
            lines.append(json.dumps(obj))
        with open(src.options["path"], "a") as f:
            f.write("".join(ln + "\n" for ln in lines))
        return len(lines)

    def explain(self, stmt) -> list:
        """EXPLAIN: plan WITHOUT deploying, return the fragment graph as
        text rows (reference: handler/explain.rs over the planner's
        explain output; snapshot format shared with tests/goldens)."""
        from ..plan.graph import render_graph
        # same parallelism the CREATE path would deploy with — EXPLAIN
        # must preview the actual topology
        planner = StreamPlanner(
            self.catalog, config=self.config,
            parallelism=self.config["streaming_parallelism"])
        if isinstance(stmt, ast.CreateMV):
            plan = planner.plan_select(stmt.select)
        elif isinstance(stmt, ast.CreateSink):
            plan = planner.plan_sink(stmt.select, dict(stmt.options))
        elif isinstance(stmt, ast.Select):
            # a bare SELECT executes on the numpy BATCH engine over a
            # committed snapshot — explain THAT pipeline, not a
            # streaming plan that never runs
            return [(ln,) for ln in _render_batch_plan(stmt)]
        else:
            raise BindError(
                "EXPLAIN supports SELECT / CREATE MATERIALIZED VIEW / "
                "CREATE SINK")
        return [(ln,) for ln in render_graph(plan.graph)]

    def explain_mv(self, name: str) -> list:
        """EXPLAIN MATERIALIZED VIEW <name>: the LIVE deployed executor
        chains annotated with per-executor HBM accounting — which MV owns
        the device memory, what spilled, how often reloads hit."""
        from ..memory.accounting import format_bytes
        from ..plan.build import _iter_executor_chain
        if name not in self.catalog.mvs:
            raise BindError(f"unknown materialized view {name!r}")
        mv = self.catalog.mvs[name]
        participants = {id(p) for p in
                        self.coord.memory._participants.values()}
        lines = [f"materialized view {name} "
                 f"(parallelism={mv.parallelism})"]
        for fid in sorted(mv.deployment.roots):
            lines.append(f"fragment {fid}")
            for root in mv.deployment.roots[fid]:
                for ex in _iter_executor_chain(root):
                    if id(ex) in participants:
                        lines.append(
                            f"  {ex.identity}: "
                            f"state_bytes={ex.state_bytes()} "
                            f"({format_bytes(ex.state_bytes())}) "
                            f"evicted_bytes="
                            f"{getattr(ex, 'mem_evicted_bytes', 0)} "
                            f"reload_count="
                            f"{getattr(ex, 'mem_reload_count', 0)} "
                            f"spilled_rows="
                            f"{getattr(ex, 'mem_spilled_rows', 0)}")
                    else:
                        lines.append(f"  {ex.identity}")
        return [(ln,) for ln in lines]

    async def _configure_cluster(self, addrs: str) -> None:
        """SET cluster = 'host:port,host:port' — attach this session's
        coordinator to the compute nodes as the meta service ('' to
        detach). Must precede any streaming DDL: a topology cannot be
        half local, half clustered."""
        from ..cluster.meta_service import ClusterManager
        if self.cluster is not None:
            await self.cluster.stop()
            self.cluster = None
        if not addrs.strip():
            return
        if self.catalog.mvs or self.catalog.sinks:
            raise BindError(
                "SET cluster must run before any MV/sink exists "
                "(drop them first)")
        if not self.config.get("streaming_durability", 1):
            raise BindError(
                "cluster mode requires streaming_durability = 1 "
                "(workers flush vnode-partitioned state to the shared "
                "store; recovery replays from the committed epoch)")
        if self.config.get("checkpoint_max_inflight", 2) < 1:
            # the cluster commit point is inherently asynchronous (all
            # workers must report sealed); a zero window has no meaning
            self.config["checkpoint_max_inflight"] = 1
            self.coord.checkpoint_max_inflight = 1
        mgr = ClusterManager(
            self, [a.strip() for a in addrs.split(",") if a.strip()])
        await mgr.connect()
        self.cluster = mgr

    async def _show_cluster(self, what: str, limit=None, kind=None,
                            since=None) -> list:
        if what == "cluster":
            return self.cluster.registry_rows()
        if what == "events":
            # meta's own records plus every worker's local log, stitched
            # on the wall timestamp and tagged by origin — the incident
            # record survives any single worker's crash
            per_worker = await self.cluster.events_all(
                limit=limit, kind=kind, since=since)
            self._worker_events_cache = per_worker
            merged = [("meta", r) for r in self.event_log.records(
                limit=limit, kind=kind, since=since)]
            for wid, recs in sorted(per_worker.items()):
                merged.extend((f"w{wid}", r) for r in recs)
            merged.sort(key=lambda e: e[1].get("ts", 0))
            if limit is not None:
                merged = merged[-int(limit):]
            rows = []
            for origin, r in merged:
                extra = {k: v for k, v in r.items()
                         if k not in ("seq", "ts", "kind")}
                rows.append((origin, str(r.get("seq", "")),
                             f"{r.get('ts', 0):.3f}", r.get("kind"),
                             json.dumps(extra, sort_keys=True,
                                        default=str)))
            return rows
        # SHOW memory, cluster-wide: the meta rows (usually none — the
        # actors live in the workers) plus every worker's, labelled
        rows = [(r["executor"], str(r["state_bytes"]),
                 str(r["evicted_bytes"]), str(r["reload_count"]),
                 str(r["spilled_rows"]))
                for r in self.coord.memory.report()]
        for r in await self.cluster.memory_report_all():
            rows.append((r["executor"], str(r["state_bytes"]),
                         str(r["evicted_bytes"]), str(r["reload_count"]),
                         str(r["spilled_rows"])))
        return rows

    def show(self, what: str, limit=None, kind=None, since=None) -> list:
        """SHOW <objects|variable> (reference: handler/show.rs +
        session_config reads)."""
        if what == "events":
            # the durable event log, newest last: (seq, ts, kind,
            # details-json). Filter parity with /debug/events:
            # `SHOW events KIND 'recovery' SINCE <ts> LIMIT n`
            rows = []
            for r in self.event_log.records(limit=limit or 32,
                                            kind=kind, since=since):
                extra = {k: v for k, v in r.items()
                         if k not in ("seq", "ts", "kind")}
                rows.append((str(r["seq"]),
                             f"{r['ts']:.3f}", r["kind"],
                             json.dumps(extra, sort_keys=True)))
            return rows
        if what == "memory":
            # per-executor HBM accounting from the memory manager
            return [(r["executor"], str(r["state_bytes"]),
                     str(r["evicted_bytes"]), str(r["reload_count"]),
                     str(r["spilled_rows"]))
                    for r in self.coord.memory.report()]
        if what == "serving":
            # per-MV snapshot-cache state from the serving manager:
            # (mv, cache epoch, rows, hits, misses, point_lookups)
            return [(r["mv"], str(r["epoch"]), str(r["rows"]),
                     str(r["hits"]), str(r["misses"]),
                     str(r["point_lookups"]))
                    for r in self.coord.serving.report()]
        if what == "sources":
            # one row PER LIVE SPLIT: (source, split, offset, lag) —
            # lag is broker-high-watermark minus consumed offset for
            # broker splits, "-" for connectors with no external
            # watermark; a source with no running executor (no MV/sink
            # reads it yet) shows a placeholder row
            rows = []
            live: dict[str, list] = {}
            for aid in sorted(self.coord.source_execs):
                ex = self.coord.source_execs[aid]
                live.setdefault(ex.source_name, []).extend(
                    ex.split_report())
            for n in sorted(self.catalog.sources):
                if n in live:
                    for sid, off, lag in sorted(live[n]):
                        rows.append((n, str(sid), str(off),
                                     "-" if lag is None else str(lag)))
                else:
                    rows.append((n, "-", "-", "-"))
            return rows
        if what == "storage":
            # the storage plane's operator surface: retry/scrub/orphan/
            # quarantine/backup state as (key, value) rows — the SQL
            # twin of the storage_* series in /metrics
            from ..state.backup import load_backup_manifest
            from ..utils.metrics import (BACKUP_GENERATION,
                                         OBJECT_RETRIES,
                                         OBJECT_TMP_SWEPT,
                                         STORAGE_CRC_RETRIES,
                                         STORAGE_RESTORED)
            rows = [("object_store_retries_total",
                     str(int(OBJECT_RETRIES.value))),
                    ("object_store_tmp_swept_total",
                     str(int(OBJECT_TMP_SWEPT.value))),
                    ("crc_retries_total",
                     str(int(STORAGE_CRC_RETRIES.value))),
                    ("restored_from_backup_total",
                     str(int(STORAGE_RESTORED.value)))]
            for k, v in sorted(self.coord.scrubber.report().items()):
                rows.append((f"scrub_{k}", str(v)))
            q = getattr(self.store, "quarantined", None)
            if q is not None:
                rows.append(("quarantined_objects",
                             ",".join(q) if q else "0"))
            path = self.config.get("backup_path", "")
            rows.append(("backup_path", path or "-"))
            gen = int(BACKUP_GENERATION.value)
            if path and not gen:
                # a repair source attached without a backup run this
                # process: read the generation off the ledger itself
                try:
                    from ..state import LocalFsObjectStore
                    m = load_backup_manifest(LocalFsObjectStore(path))
                    gen = m["generation"] if m else 0
                except Exception:  # noqa: BLE001 — display-only
                    gen = 0
            rows.append(("backup_generation", str(gen) if gen else "-"))
            return rows
        if what == "compaction":
            # the background compaction + retention plane as (key,
            # value) rows: knobs, run/rewrite counters, L0 depth / read
            # amp, per-source retention floors, last merge, broker
            # floor pushes (state/compactor.py)
            return [(k, v) for k, v in self.coord.compactor.report()]
        if what in ("tables", "materialized_views"):
            return [(n,) for n in sorted(self.catalog.mvs)]
        if what == "sinks":
            return [(n,) for n in sorted(self.catalog.sinks)]
        if what == "subscriptions":
            # (name, kind, cursor, delivered, state) for sink delivery
            # tasks and live changelog subscriptions (logstore/)
            return self.coord.logstore.report()
        if what == "all":
            return [(k, str(v)) for k, v in sorted(self.config.items())]
        if what in self.CONFIG_VARS:
            return [(str(self.config[what]),)]
        raise BindError(f"unknown SHOW target {what!r}")

    def _create_source(self, stmt: ast.CreateSource) -> SourceDef:
        opts = dict(stmt.options)
        connector = opts.pop("connector", "nexmark")
        if connector == "broker":
            # external broker ingress (connectors/broker.py): splits are
            # the topic's partitions, offsets are dense record offsets
            # committed in barrier state, and partition growth is picked
            # up live by the split enumerator at a barrier
            from ..broker.client import BrokerClient
            from ..connectors.file_source import parse_columns
            topic = opts.pop("topic", None)
            brokers = opts.pop("brokers", None)
            colspec = opts.pop("columns", None)
            if not topic or not brokers or not colspec:
                raise BindError(
                    "broker connector needs topic=..., brokers=... and "
                    "columns='name type, ...'")
            try:
                schema = parse_columns(colspec)
            except ValueError as e:
                raise BindError(str(e))
            args = {"connector": "broker", "topic": topic,
                    "brokers": brokers, "columns": colspec,
                    "chunk_size": int(opts.pop("chunk_size", 256)),
                    "partitions": int(opts.pop("partitions", 1)),
                    "discovery_interval_ms":
                        int(opts.pop("discovery_interval_ms", 1000)),
                    # topics can carry changelog ops (engine->engine
                    # pipelines ship retractions as `__op` records);
                    # append_only=1 opts into the insert-only fast paths
                    "append_only": bool(int(opts.pop("append_only", 0)))}
            for k in ("rate_limit",):
                if k in opts:
                    args[k] = int(opts.pop(k))
            if "primary_key" in opts:
                pk_name = opts.pop("primary_key")
                if pk_name not in schema.names:
                    raise BindError(
                        f"primary_key {pk_name!r} not a column")
                args["primary_key"] = list(schema.names).index(pk_name)
            if opts:
                raise BindError(f"unknown broker options {sorted(opts)}")
            if not args["append_only"] and "primary_key" not in args:
                # changelog records (`__op` deletes) must address rows:
                # a keyless retracting stream cannot plan. Insert-only
                # topics opt into the fast paths explicitly.
                raise BindError(
                    "broker source needs primary_key=... (changelog "
                    "topics) or append_only=1 (insert-only topics)")
            # ensure the topic + bind the CURRENT partition count (the
            # binder's parallelism bound; the count only ever grows, and
            # the build re-reads the live count)
            try:
                client = BrokerClient(brokers)
                args["splits"] = client.create_topic(
                    topic=topic, partitions=args["partitions"])
                client.close()
            except (OSError, ConnectionError, RuntimeError) as e:
                raise BindError(f"broker {brokers!r} unreachable: {e}")
            src = SourceDef(stmt.name, schema, args)
            self.catalog.sources[stmt.name] = src
            return src
        if connector == "jsonl":
            # external file-tailing source (connectors/file_source.py):
            # a split = one append-only JSONL file, offset = line number
            from ..connectors.file_source import parse_columns
            path = opts.pop("path", None)
            colspec = opts.pop("columns", None)
            if not path or not colspec:
                raise BindError(
                    "jsonl connector needs path=... and "
                    "columns='name type, ...'")
            try:
                schema = parse_columns(colspec)
            except ValueError as e:
                raise BindError(str(e))
            args = {"connector": "jsonl", "path": path,
                    "columns": colspec,
                    "chunk_size": int(opts.pop("chunk_size", 256))}
            if "rate_limit" in opts:
                args["rate_limit"] = int(opts.pop("rate_limit"))
            if "primary_key" in opts:
                pk_name = opts.pop("primary_key")
                if pk_name not in schema.names:
                    raise BindError(
                        f"primary_key {pk_name!r} not a column")
                args["primary_key"] = list(schema.names).index(pk_name)
            if "is_table" in opts:
                args["is_table"] = bool(int(opts.pop("is_table")))
            if opts:
                raise BindError(
                    f"unknown jsonl options {sorted(opts)}")
            src = SourceDef(stmt.name, schema, args)
            self.catalog.sources[stmt.name] = src
            return src
        if connector == "tpch":
            from ..connectors.tpch import TPCH_SCHEMAS
            schemas = TPCH_SCHEMAS
        elif connector == "nexmark":
            schemas = _NEXMARK_SCHEMAS
        else:
            raise BindError(f"unknown connector {connector!r}")
        table = opts.pop("table", stmt.name)
        if table not in schemas:
            raise BindError(f"unknown {connector} table {table!r}")
        if connector == "tpch":
            bad = {"emit_watermarks", "watermark_lag_us", "inter_event_us",
                   "base_time_us"} & set(opts)
            if bad:
                raise BindError(
                    f"options {sorted(bad)} are not supported by the "
                    "tpch connector (no event-time column)")
        args = {"connector": connector, "table": table,
                "chunk_size": int(opts.pop("chunk_size", 4096))}
        if "splits" in opts:
            args["splits"] = int(opts.pop("splits"))
        cfg = {}
        for k in ("inter_event_us", "base_time_us"):
            if k in opts:
                cfg[k] = int(opts.pop(k))
        if cfg:
            args["cfg"] = cfg
        if "emit_watermarks" in opts:
            v = opts.pop("emit_watermarks")
            args["emit_watermarks"] = v in (True, 1, "1", "true", "t", "on")
        if "primary_key" in opts:
            # reference: PRIMARY KEY on CREATE TABLE/SOURCE — declares a
            # unique column so downstream state needs no generated row id
            pk_name = opts.pop("primary_key")
            names = list(schemas[table].names)
            if pk_name not in names:
                raise BindError(f"primary_key {pk_name!r} not a column")
            args["primary_key"] = names.index(pk_name)
        for k in ("watermark_lag_us", "rate_limit"):
            if k in opts:
                args[k] = int(opts.pop(k))
        src = SourceDef(stmt.name, schemas[table], args)
        self.catalog.sources[stmt.name] = src
        return src

    async def _create_mv(self, stmt: ast.CreateMV,
                         sql_text: str = "",
                         parallelism: int = 1,
                         table_id_floor=None) -> MvDef:
        from ..stream import TapDispatcher
        if table_id_floor is not None:
            self.env._next_table_id = table_id_floor
        if self.cluster is not None:
            return await self._create_mv_cluster(stmt, sql_text,
                                                 parallelism)
        planner = StreamPlanner(self.catalog, parallelism=parallelism,
                                config=self.config)
        plan = planner.plan_select(stmt.select)
        # bring-up holds the rounds lock: actor registration + tap attach
        # must not interleave with an in-flight barrier round (the
        # reference pauses the barrier loop around an Add command)
        async with self.coord._rounds_lock:
            self.env.pending_taps = []
            self.env.memory_scope = stmt.name
            dep = build_graph(plan.graph, self.env)
            self.env.memory_scope = None
            root = dep.roots[plan.mv_fragment][0]
            actor = next(a for a in dep.actors if a.consumer is root)
            assert actor.dispatcher is None, "MV fragment must be terminal"
            tap = TapDispatcher()
            actor.dispatcher = tap
            dep.spawn()
            # upstream taps learn this deployment's actor set so a Stop
            # barrier covering it detaches the channel at the barrier
            dep_ids = {a.actor_id for a in dep.actors}
            for up, ch in self.env.pending_taps:
                up.tap.set_consumers(ch, dep_ids)
            mv = MvDef(stmt.name, plan.schema, plan.pk_indices, dep,
                       self.coord, plan.mv_fragment, tap=tap,
                       upstream_taps=tuple(self.env.pending_taps),
                       sql=sql_text,
                       append_only=getattr(plan, "append_only", False),
                       parallelism=parallelism,
                       sources=tuple(sorted(
                           getattr(planner, "used_sources", ()))))
            self.catalog.mvs[stmt.name] = mv
            # serving registration: every Materialize executor publishes
            # its effective changelog through a hook (one per actor — a
            # parallel materialize's vnode-disjoint changelogs merge at
            # the barrier); the per-MV snapshot cache builds lazily on
            # first query touch
            roots = dep.roots[plan.mv_fragment]
            hooks = self.coord.serving.register_mv(
                stmt.name, roots[0].table, roots[0].table.schema,
                roots[0].table.pk_indices, n_hooks=len(roots))
            for r, h in zip(roots, hooks):
                r.serving_hook = h
            # durable changelog log (logstore/): the feed for changelog
            # subscriptions + serving replicas. Allocated AFTER the
            # graph build so recovery replay (which re-floors table ids
            # and rebuilds the same graph) derives the same log id.
            # Lazy: writers drop their buffer until a subscription
            # activates the log.
            clog = self.coord.logstore.register_mv(
                stmt.name, self.env.alloc_table_id(),
                roots[0].table.schema, roots[0].table.pk_indices,
                state_table=roots[0].table, n_writers=len(roots))
            for r, w in zip(roots, clog.writers):
                r.changelog_log = w
        # bring the new dataflow up: the first MV gets the Initial
        # barrier; later MVs initialize on the next ordinary barrier.
        # During catalog recovery NO barrier may run until the WHOLE
        # topology is reattached — a barrier between two re-created MVs
        # would advance upstream state while a finished-backfill consumer
        # is not yet tapped, losing its delta forever (the reference's
        # recovery rebuilds all actors before resuming barriers,
        # meta/src/barrier/recovery.rs:332).
        if not self._recovering:
            await self.coord.run_rounds(0 if not self.coord._started else 1)
        return mv

    async def _create_mv_cluster(self, stmt: ast.CreateMV,
                                 sql_text: str,
                                 parallelism: int) -> MvDef:
        """CREATE MV onto the cluster: the whole graph deploys across
        the compute nodes (vnode-partitioned fragments, cross-worker
        exchange over the DCN tier); meta keeps only a shadow handle on
        the MV's shared state table so batch SELECTs scan the committed
        snapshot the cluster commit protocol publishes."""
        n_live = len(self.cluster.live_workers())
        if not self._recovering:
            # fresh DDL spreads over every live worker; recovery keeps
            # the ORIGINAL parallelism (the vnode bitmaps the durable
            # state was written under), re-placed over the survivors
            parallelism = max(parallelism, n_live)
        planner = StreamPlanner(self.catalog, parallelism=parallelism,
                                config=self.config)
        plan = planner.plan_select(stmt.select)
        async with self.coord._rounds_lock:
            dep = await self.cluster.deploy(
                plan.graph, scope=stmt.name,
                mv_fragment=plan.mv_fragment, want_table=True)
            mv = MvDef(stmt.name, plan.schema, plan.pk_indices, dep,
                       self.coord, plan.mv_fragment, tap=None,
                       sql=sql_text,
                       append_only=getattr(plan, "append_only", False),
                       parallelism=parallelism,
                       sources=tuple(sorted(
                           getattr(planner, "used_sources", ()))))
            self.catalog.mvs[stmt.name] = mv
            # NO serving-cache registration: the materialize changelog
            # stays in the workers; meta serves from the committed
            # snapshot (ROADMAP item 3's replica direction lifts this)
        if not self._recovering:
            await self.coord.run_rounds(0 if not self.coord._started
                                        else 1)
        return mv

    # ------------------------------------------------------------ runtime
    def _check_sink_options(self, opts: dict) -> None:
        """Reject invalid sink options BEFORE the graph builds: a
        builder exception mid-build leaves half-registered actors on
        the coordinator (they never collect -> every later barrier
        hangs), so anything checkable from the options alone must fail
        here, at bind time."""
        if opts.get("connector") != "broker":
            return
        if not opts.get("topic") or not opts.get("brokers"):
            raise BindError("broker sink needs topic=... and brokers=...")
        force = opts.get("type") == "append-only" or str(
            opts.get("force_append_only", "")).lower() in ("true", "1")
        if int(opts.get("partitions", 1)) > 1 and not force:
            raise BindError(
                "broker sink with partitions > 1 requires an "
                "append-only changelog (WITH type='append-only'): one "
                "delivery batch lands whole in one partition, and "
                "retractions need the single-partition total order")
        try:
            from ..broker.client import BrokerClient
            client = BrokerClient(opts["brokers"])
            client.ping()
            client.close()
        except (OSError, ConnectionError, RuntimeError) as e:
            raise BindError(
                f"broker {opts['brokers']!r} unreachable: {e}")

    async def _create_sink(self, stmt, sql_text: str = "") -> "SinkDef":
        self._check_sink_options(dict(stmt.options))
        if self.cluster is not None:
            return await self._create_sink_cluster(stmt, sql_text)
        planner = StreamPlanner(self.catalog, config=self.config)
        plan = planner.plan_sink(stmt.select, stmt.options)
        async with self.coord._rounds_lock:
            self.env.pending_taps = []
            self.env.memory_scope = stmt.name
            dep = build_graph(plan.graph, self.env)
            self.env.memory_scope = None
            dep_ids = {a.actor_id for a in dep.actors}
            for up, ch in self.env.pending_taps:
                up.tap.set_consumers(ch, dep_ids)
            dep.spawn()
            sink = SinkDef(stmt.name, plan.schema, dep, plan.mv_fragment,
                           upstream_taps=tuple(self.env.pending_taps),
                           sql=sql_text,
                           sources=tuple(sorted(
                               getattr(planner, "used_sources", ()))))
            self.catalog.sinks[stmt.name] = sink
        if not self._recovering:
            await self.coord.run_rounds(
                0 if not self.coord._started else 1)
        return sink

    async def _create_sink_cluster(self, stmt, sql_text: str) -> "SinkDef":
        n_live = len(self.cluster.live_workers())
        planner = StreamPlanner(self.catalog, parallelism=n_live,
                                config=self.config)
        plan = planner.plan_sink(stmt.select, stmt.options)
        async with self.coord._rounds_lock:
            dep = await self.cluster.deploy(
                plan.graph, scope=stmt.name,
                mv_fragment=plan.mv_fragment, want_table=False)
            sink = SinkDef(stmt.name, plan.schema, dep, plan.mv_fragment,
                           sql=sql_text,
                           sources=tuple(sorted(
                               getattr(planner, "used_sources", ()))))
            self.catalog.sinks[stmt.name] = sink
        if not self._recovering:
            await self.coord.run_rounds(0 if not self.coord._started
                                        else 1)
        return sink

    async def alter_parallelism(self, name: str, n: int) -> MvDef:
        """Online rescale (reference: ALTER ... SET PARALLELISM, riding a
        meta reschedule — scale.rs:370): stop ONE MV's actors at a barrier
        (state flushes durably), rebuild its graph with the hash fragments
        at parallelism n binding the SAME table ids, and resume — other
        dataflows keep running throughout; the vnode-sliced state tables
        are re-read per new actor bitmap (state_table.rs:778)."""
        if name not in self.catalog.mvs:
            raise BindError(f"unknown MV {name!r}")
        mv = self.catalog.mvs[name]
        dependents = [d.name for d in list(self.catalog.mvs.values())
                      + list(self.catalog.sinks.values())
                      if any(up.name == name for up, _ in d.upstream_taps)]
        if dependents:
            raise BindError(
                f"cannot rescale {name!r}: {dependents} tap it "
                f"(drop them first)")
        entry = next(e for e in self._ddl_log
                     if e["kind"] == "mv" and e["name"] == name)
        await mv.deployment.stop()
        for up, ch in mv.upstream_taps:
            up.tap.remove(ch)
        del self.catalog.mvs[name]
        stmt = ast.parse(entry["sql"])
        self._recovering = True     # suppress log append inside execute
        saved_next_tid = self.env._next_table_id
        try:
            out = await self._create_mv(
                stmt, entry["sql"], parallelism=n,
                table_id_floor=entry["table_id_floor"])
        finally:
            self._recovering = False
            # the rebuild rewound the allocator to the MV's old floor;
            # restore the high-watermark or later DDL would hand out
            # table ids already owned by OTHER live MVs
            self.env._next_table_id = max(self.env._next_table_id,
                                          saved_next_tid)
        entry["parallelism"] = n
        self._persist_catalog()
        await self.coord.run_rounds(1)
        return out

    async def drop_sink(self, name: str) -> None:
        sink = self.catalog.sinks.pop(name)
        # stop drains uploads AND sink delivery (stop_all's quiesce), so
        # the final epoch reaches the target before the task dies here
        await sink.deployment.stop()
        self.coord.logstore.unregister_sink(name)
        for up, ch in sink.upstream_taps:
            up.tap.remove(ch)
        self._ddl_log = [e for e in self._ddl_log
                         if not (e["kind"] == "sink" and e["name"] == name)]
        self._persist_catalog()

    async def tick(self, rounds: int = 1,
                   interval_s: Optional[float] = None,
                   max_recoveries: int = 3) -> None:
        """Advance the session's barrier loop (meta's periodic injection).

        Barrier-collection failure (a dead actor) triggers AUTOMATIC
        recovery and the tick is retried; no operator in the loop
        (reference: meta/src/barrier/recovery.rs:332-625). The failure
        is first CLASSIFIED (`_classify_failure`): a blast radius
        contained to one terminal fragment rebuilds only that
        fragment's actors from the last committed epoch (upstream
        keeps its device state, channels replay the in-flight
        interval); anything wider falls back to the full stop-the-world
        rebuild. Consecutive attempts back off exponentially with
        jitter (`recovery_backoff_ms`) so a persistent fault cannot
        hot-loop through `max_recoveries`; a crash DURING recovery
        (mid DDL replay) counts as an attempt and is retried too."""
        flows_logged = any(e["kind"] in ("mv", "sink")
                           for e in self._ddl_log)
        if not self.catalog.mvs and not self.catalog.sinks \
                and not flows_logged:
            return
        attempts = 0
        while True:
            try:
                if flows_logged and not self.catalog.mvs \
                        and not self.catalog.sinks:
                    # a prior recovery died mid-DDL-replay (catalog
                    # cleared, log intact — e.g. the broker a sink
                    # targets was still down): resume recovering
                    # instead of silently no-opping the tick
                    raise RuntimeError(
                        "catalog empty with flows in the DDL log; "
                        "resuming interrupted recovery")
                await self.coord.run_rounds(rounds, interval_s=interval_s)
                return
            except RuntimeError:
                recovered = False
                while not recovered:
                    attempts += 1
                    if attempts > max_recoveries:
                        raise
                    await self._recovery_backoff(attempts)
                    try:
                        await self._recover_auto(
                            cause_hint="recovery_retry"
                            if attempts > 1 else None)
                        recovered = True
                    except asyncio.CancelledError:
                        raise
                    except BaseException:
                        # recovery itself died (kill-during-recovery):
                        # the DDL log is intact, the next attempt
                        # replays it from scratch
                        continue

    def flapping_causes(self) -> list[str]:
        """Causes whose recovery rate exceeds `recovery_flap_threshold`
        within the trailing flap window — non-empty means the session is
        DEGRADED (recoveries keep converging but the fault keeps coming
        back; /healthz surfaces it, the backoff escalates on it)."""
        import time as _time
        from ..utils.metrics import RECOVERY_FLAP_WINDOW_S
        thr = self.config.get("recovery_flap_threshold", 3)
        if thr <= 0 or not self._recovery_log:
            return []
        now = _time.monotonic()
        counts: dict[str, int] = {}
        for t, cause in self._recovery_log:
            if now - t <= RECOVERY_FLAP_WINDOW_S:
                counts[cause] = counts.get(cause, 0) + 1
        return sorted(c for c, n in counts.items() if n > thr)

    def _flap_excess(self) -> int:
        """How far past the flap threshold the worst cause is — feeds
        the backoff exponent so a flapping fault escalates toward the
        5s cap instead of hammering immediate rebuilds."""
        import time as _time
        from ..utils.metrics import RECOVERY_FLAP_WINDOW_S
        thr = self.config.get("recovery_flap_threshold", 3)
        if thr <= 0 or not self._recovery_log:
            return 0
        now = _time.monotonic()
        counts: dict[str, int] = {}
        for t, cause in self._recovery_log:
            if now - t <= RECOVERY_FLAP_WINDOW_S:
                counts[cause] = counts.get(cause, 0) + 1
        return max((n - thr for n in counts.values()), default=0)

    async def _recovery_backoff(self, attempt: int) -> None:
        """Exponential backoff with +-50% jitter between consecutive
        recovery attempts; the FIRST recovery of a tick is immediate
        (fast path for the common one-shot fault) UNLESS the flap
        detector says this fault keeps coming back — then even the
        first attempt waits, with the excess recovery rate feeding the
        exponent (recovery_total{cause} rates -> backoff base)."""
        base = self.config.get("recovery_backoff_ms", 50) / 1000.0
        effective = attempt + self._flap_excess()
        if effective < 2 or base <= 0:
            return
        import random
        from ..utils.metrics import RECOVERY_BACKOFF
        delay = min(base * (2 ** (effective - 2)), 5.0) \
            * (0.5 + random.random())
        RECOVERY_BACKOFF.inc(delay)
        await asyncio.sleep(delay)

    # ------------------------------------------------------------ recovery
    @staticmethod
    def _terminal_fid(flow):
        return (flow.mv_fragment if isinstance(flow, MvDef)
                else flow.sink_fragment)

    def _classify_failure(self):
        """Blast-radius classification (reference: the recovery scope
        decision in meta/src/barrier/recovery.rs — regional vs global).
        Returns a LIST of recovery units, one per independently
        recoverable radius:

            ("fragment", cause, flow, {terminal_fid})   terminal only
            ("cone",     cause, flow, cone_fids)        {failed + its
                                                        downstream cone}
            ("mesh",     cause, flow, cone_fids)        a fused mesh
                                                        fragment failed
            ("worker",   cause, None, plan)             cluster radius
            ("full",     cause, None, None)             stop-the-world

        Failures spanning SEVERAL deployments classify per deployment —
        two simultaneous contained faults recover independently instead
        of collapsing to one global full recovery. Any radius the
        classifier cannot prove contained is a single "full" unit with
        the cause named; correctness never weakens."""
        coord = self.coord
        if self.cluster is not None:
            return [self._classify_cluster_failure()]
        if coord._upload_failure is not None:
            return [("full", "upload_failure", None, None)]
        if coord.logstore.failure is not None:
            return [("full", "sink_delivery", None, None)]
        failed = dict(coord.failed_actors)
        if not failed:
            return [("full", "unknown", None, None)]
        if any(aid < 0 for aid in failed):
            return [("full", "worker_death", None, None)]
        if not bool(self.config.get("partial_recovery", 1)):
            return [("full", "partial_recovery_off", None, None)]
        # group the failed actors by owning deployment: the coordinator
        # records ALL failed actors, and each affected flow classifies
        # (and recovers) on its own
        by_dep: dict[int, tuple] = {}
        for aid in failed:
            for f in (list(self.catalog.mvs.values())
                      + list(self.catalog.sinks.values())):
                fid = getattr(f.deployment, "actor_fragment",
                              {}).get(aid)
                if fid is not None:
                    ent = by_dep.setdefault(id(f.deployment), (f, set()))
                    ent[1].add(fid)
                    break
            else:
                return [("full", "unknown_actor", None, None)]
        units = [self._classify_flow(f, fids)
                 for f, fids in by_dep.values()]
        for u in units:
            if u[0] == "full":
                return [u]        # one global rebuild covers everything
        return units

    def _classify_cluster_failure(self):
        """Cluster radius: a single worker's death (lease/connection
        loss) or a contained worker-reported actor failure (e.g. a
        severed DCN leg) rebuilds the affected actors — re-placed onto
        survivors when their worker died — plus their downstream
        closure; surviving workers keep their stores open at the
        committed manifest and every actor outside the closure keeps
        running. Anything wider is a full cluster recovery with the
        cause named."""
        coord = self.coord
        mgr = self.cluster
        if not bool(self.config.get("partial_recovery", 1)):
            return ("full", "partial_recovery_off", None, None)
        dead = sorted(wid for wid, h in mgr.workers.items()
                      if not h.info.alive)
        if len(dead) > 1:
            return ("full", "multi_worker", None, None)
        if coord.logstore.failure is not None:
            return ("full", "sink_delivery", None, None)
        # an upload failure raised by the dead worker's vanished sealed
        # report is subsumed by the worker radius (the aborted epochs
        # replay from the committed manifest); any OTHER upload failure
        # is a real store error
        if coord._upload_failure is not None and not dead:
            return ("full", "upload_failure", None, None)
        failed = dict(coord.failed_actors)
        # positive ids are worker-REPORTED actor failures (the worker
        # process itself is alive); negative ids are worker pseudo-
        # actors whose epochs failed
        actor_ids = sorted(aid for aid in failed if aid > 0)
        if not dead and not actor_ids:
            return ("full", "unknown", None, None)
        plan = mgr.plan_partial(dead[0] if dead else None, actor_ids)
        if plan is None:
            return ("full", "cluster", None, None)
        return ("worker", "worker_death" if dead else "dcn_failure",
                None, plan)

    def _classify_flow(self, flow, failed_fids):
        """One deployment's radius: the failed fragments plus their
        transitive downstream consumers (the CONE — every consumer saw
        part of the aborted interval's output, so its uncommitted state
        is tainted and it rebuilds with the failure). The cone's inbound
        frontier must be fully replay-buffered; upstream producers keep
        their device state."""
        dep = flow.deployment
        if dep.rebuild_info is None:
            return ("full", "unsupported_deployment", None, None)
        graph = dep.rebuild_info["graph"]
        cone = set(failed_fids)
        changed = True
        while changed:
            changed = False
            for fid in list(cone):
                for d, _k in dep.fragment_consumers.get(fid, ()):
                    if d not in cone:
                        cone.add(d)
                        changed = True
        mesh = any(aid in self.coord.mesh_fragments
                   for fid in cone
                   for aid in dep.frag_actor_ids.get(fid, ()))
        for fid in cone:
            frag = graph.fragments[fid]
            if getattr(frag, "remote_worker", None):
                return ("full", "remote_fragment", None, None)
            kinds = {n.kind for n in _fragment_node_kinds(frag)}
            if "stream_scan" in kinds:
                return ("full", "backfill_fragment", None, None)
            if fid in failed_fids and "nexmark_source" in kinds:
                # a source fragment has no inbound replay frontier to
                # re-drive it — its cone is the whole deployment with
                # nothing buffered upstream of the failure
                return ("full", "source_fragment", None, None)
        terminal = self._terminal_fid(flow)
        tap = getattr(flow, "tap", None)
        if terminal in cone and tap is not None and tap.channels:
            # a live MV-on-MV consumer taps the terminal — it saw part
            # of the aborted interval through a channel outside the
            # deployment's rebuild scope
            return ("full", "downstream_tap", None, None)
        # the flow must be durable: a volatile fragment has no committed
        # state to rebuild from
        entry = next((e for e in self._ddl_log
                      if e["name"] == flow.name
                      and e["kind"] in ("mv", "sink")), None)
        if entry is None or entry.get("config", {}).get(
                "streaming_durability", 1) == 0:
            return ("full", "volatile", None, None)
        # every edge ENTERING the cone (the inbound frontier) must carry
        # a replay buffer; intra-cone edges are reset and re-driven by
        # the rebuilt producers themselves
        for (u, d, k), mat in dep.rebuild_info["channels"].items():
            if d not in cone or u in cone:
                continue
            for row in mat:
                for ch in row:
                    if not ch.replay_enabled:
                        return ("full", "unbuffered_edge", None, None)
        scope = ("mesh" if mesh
                 else "fragment" if cone == {terminal}
                 else "cone")
        return (scope, "actor_exception", flow, cone)

    async def _recover_auto(self, cause_hint=None) -> None:
        """Classify, then recover every unit at its narrowest correct
        scope. Any exception during a partial path falls back to ONE
        full rebuild — partial recovery is an optimization, never a
        weaker correctness mode."""
        import time as _time
        t0 = _time.monotonic_ns()
        units = self._classify_failure()
        cause = units[0][1]
        if cause == "unknown" and cause_hint:
            # a retry after a crashed recovery starts from a fresh
            # coordinator with no failure marker — name it honestly
            cause = cause_hint
        if units[0][0] != "full":
            try:
                # independent radii recover one after another; each
                # notes its own scope/duration/actors so the metrics
                # and /healthz reflect every contained rebuild
                for scope, u_cause, flow, plan in units:
                    t_u = _time.monotonic_ns()
                    if scope == "worker":
                        rebuilt = await self._worker_partial_recover(plan)
                    else:
                        rebuilt = await self._partial_recover(flow, plan)
                    self._note_recovery(scope, u_cause, t_u, rebuilt)
                return
            except asyncio.CancelledError:
                raise
            except BaseException:
                cause = "partial_recovery_failed"
        await self._auto_recover()
        all_ids = sorted(
            a.actor_id
            for f in (list(self.catalog.mvs.values())
                      + list(self.catalog.sinks.values()))
            for a in f.deployment.actors)
        self._note_recovery("full", cause, t0, all_ids)

    def _note_recovery(self, scope: str, cause: str, t0_ns: int,
                       actors) -> None:
        import time as _time
        from ..utils.metrics import (GLOBAL_METRICS, RECOVERY_BUCKETS,
                                     RECOVERY_DURATION, RECOVERY_TOTAL)
        dur_ns = _time.monotonic_ns() - t0_ns
        RECOVERY_TOTAL.inc()
        GLOBAL_METRICS.counter("recovery_total", scope=scope,
                               cause=cause).inc()
        RECOVERY_DURATION.observe(dur_ns / 1e9)
        GLOBAL_METRICS.histogram("recovery_duration_seconds",
                                 buckets=RECOVERY_BUCKETS,
                                 scope=scope).observe(dur_ns / 1e9)
        self.last_recovery = {"scope": scope, "cause": cause,
                              "duration_s": round(dur_ns / 1e9, 6),
                              "actors": list(actors)}
        self.coord.tracer.note_recovery(scope, cause, dur_ns, actors)
        # session-owned ring: survives the coordinator swap a FULL
        # recovery performs (the tracer above dies with it)
        self.recovery_ring.note_recovery(scope, cause, dur_ns, actors)
        self.event_log.emit("recovery", scope=scope, cause=cause,
                            duration_s=round(dur_ns / 1e9, 6),
                            actors=list(actors))
        # flap detection: the recovery RATE per cause feeds the backoff
        # base and the degraded surface (recovery_flapping{cause})
        self._recovery_log.append((_time.monotonic(), cause))
        flapping = set(self.flapping_causes())
        seen = {c for _, c in self._recovery_log}
        for c in seen:
            GLOBAL_METRICS.gauge("recovery_flapping", cause=c).set(
                1.0 if c in flapping else 0.0)
            if c in flapping:
                self.event_log.emit("flap_detected", cause=c)

    async def _partial_recover(self, flow, cone) -> list[int]:
        """Rebuild one deployment's failure CONE in place (the narrow
        scope the classifier proved safe): cancel the cone's actors,
        discard exactly its staged uncommitted writes, reset the
        intra-cone channels, rebuild the same actor/table ids from the
        committed epoch in topo order, re-attach the terminal plumbing
        (tap, serving hooks, changelog writers) when the cone includes
        the terminal, arm replay on every edge entering the cone (the
        inbound frontier), respawn. The coordinator, every fragment
        UPSTREAM of the cone, and their device state are untouched —
        upstream never re-backfills. `cone` may be a single terminal
        fragment (PR 9's scope), an interior fragment plus its
        downstream consumers, or a cone containing a fused mesh
        fragment. Returns the rebuilt actor ids (the chaos gate asserts
        this set is strictly smaller than the full topology's)."""
        from ..plan.build import rebuild_fragment
        from ..utils.faults import FAULTS, FaultInjected
        coord = self.coord
        dep = flow.deployment
        cone = set(cone) if not isinstance(cone, set) else cone
        terminal = self._terminal_fid(flow)
        self.recoveries += 1
        async with coord._rounds_lock:
            # 1. let fully-collected checkpoints finish committing: after
            # this the ONLY uncommitted staged state belongs to the
            # failed (never-collected) epoch(s). Raises on a parked
            # upload failure -> caller falls back to full recovery.
            # Sink DELIVERY drains too: a rebuilt sink target recovers
            # its committed seq from the target itself (e.g. the
            # FileSink file scan), so an in-flight delivery write racing
            # the rebuild would make the crash-window entry deliver
            # twice.
            await coord.drain_uploads()
            await coord.logstore.drain()
            if FAULTS.active and FAULTS.hit(
                    "recovery_crash", phase="partial") is not None:
                raise FaultInjected("injected crash during partial "
                                    "recovery")
            # 2. cancel every cone fragment's actor tasks (dead + kin)
            ids = set()
            for fid in cone:
                ids.update(dep.frag_actor_ids[fid])
            by_id = {a.actor_id: i for i, a in enumerate(dep.actors)}
            for aid in sorted(ids):
                t = dep.tasks[by_id[aid]]
                if not t.done():
                    t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
            # 3. drop the cone's staged uncommitted writes + pending
            # deferred flushes; fragments upstream of the cone keep
            # their partial-epoch writes, which commit with the next
            # checkpoint (their dirty tracking already cleared at the
            # failed barrier)
            table_ids = set()
            for fid in cone:
                table_ids.update(dep.frag_tables.get(fid, {}).values())
            clog = coord.logstore.mv_logs.get(flow.name)
            if isinstance(flow, MvDef) and terminal in cone \
                    and clog is not None:
                table_ids.add(clog.table_id)
            discard = getattr(self.store, "discard_staged_tables", None)
            if discard is not None and table_ids:
                discard(table_ids)
            # 4. the coordinator survives: clear the failure marker and
            # the never-collected epochs; injection resumes at the same
            # epoch stream every surviving actor already follows
            coord.clear_failure()
            # 5. reset INTRA-cone channels: both ends are rebuilt, so
            # queued leftovers and the buffered suffix belong to dead
            # incarnations — the rebuilt producers re-derive and
            # re-emit the suffix themselves (starting with the
            # synthetic INITIAL they receive from the frontier)
            for (u, d, k), mat in dep.rebuild_info["channels"].items():
                if d in cone and u in cone:
                    for row in mat:
                        for ch in row:
                            ch.reset_for_rebuild()
            # 5b. channel-free mesh replay (ROADMAP 3d): capture each
            # mesh-resident agg's uncommitted ingest suffix — sealed
            # uncommitted MeshIngestLog intervals, the log's open
            # interval, and undrained pending chunks — BEFORE the
            # rebuild discards the executors. The suffix is preloaded
            # straight into the rebuilt fused program (one fused scan
            # at the first post-INITIAL barrier) and the frontier
            # channels skip exactly these chunk objects by identity,
            # so recovery re-runs ZERO per-chunk host dispatches.
            # Identity matching requires the channel message object ==
            # the logged object, so coalescing disables the fast path.
            def _mesh_preload_exec(fid):
                for root in dep.roots.get(fid, []):
                    node = root
                    while node is not None:
                        if hasattr(node, "preload_replay"):
                            return node
                        node = getattr(node, "input", None)
                return None
            mesh_preload: dict[int, list] = {}
            if getattr(self.env, "chunk_coalesce_max", 0) == 0:
                for fid in cone:
                    # a rebuilt (intra-cone) producer re-derives and
                    # re-emits the suffix itself — preloading too would
                    # double-apply it
                    if any(u in cone
                           for (u, d, _k) in dep.rebuild_info["channels"]
                           if d == fid):
                        continue
                    ex = _mesh_preload_exec(fid)
                    if ex is None:
                        continue
                    chunks = []
                    log = getattr(ex, "ingest_log", None)
                    if log is not None:
                        for _ep, chs in log.entries():
                            chunks.extend(chs)
                        chunks.extend(log._pending)
                    chunks.extend(getattr(ex, "_pending_chunks", []))
                    if chunks:
                        mesh_preload[fid] = chunks
            # 6. rebuild the cone's actors in topo order (same ids,
            # same tables — producers exist before their consumers
            # poll, exactly like the initial build)
            graph = dep.rebuild_info["graph"]
            order = [f for f in graph.topo_order() if f in cone]
            new_actors = []
            self.env.memory_scope = flow.name
            try:
                for fid in order:
                    new_actors.extend(rebuild_fragment(dep, fid))
            finally:
                self.env.memory_scope = None
            # 6b. hand the captured suffix to the REBUILT executors
            # (installed into the pending queue at their INITIAL
            # barrier, after the durable state rebuild)
            for fid, chunks in list(mesh_preload.items()):
                ex = _mesh_preload_exec(fid)
                if ex is not None:
                    ex.preload_replay(chunks)
                else:
                    del mesh_preload[fid]
            # 7. re-attach terminal plumbing when the cone includes it
            if isinstance(flow, MvDef) and terminal in cone:
                roots = dep.roots[terminal]
                root_actor = next(a for a in new_actors
                                  if a.consumer is roots[0])
                assert root_actor.dispatcher is None
                root_actor.dispatcher = flow.tap     # empty by contract
                hooks = coord.serving.register_mv(
                    flow.name, roots[0].table, roots[0].table.schema,
                    roots[0].table.pk_indices, n_hooks=len(roots))
                for r, h in zip(roots, hooks):
                    r.serving_hook = h
                if clog is not None:
                    # same durable log (subscriptions keep their pumps);
                    # FRESH writers — the old ones hold the aborted
                    # interval's rows, which replay recomputes
                    from ..logstore.log import MvChangelogWriter
                    clog.state_table = roots[0].table
                    clog.writers = [MvChangelogWriter(clog, i)
                                    for i in range(len(roots))]
                    for r, w in zip(roots, clog.writers):
                        r.changelog_log = w
            # 8. arm replay on every FRONTIER edge (entering the cone),
            # THEN spawn: the rebuilt consumers see a synthetic INITIAL
            # barrier at the committed point, the buffered uncommitted
            # suffix, then the live stream (queue duplicates skipped by
            # sequence number); interior rebuilt fragments propagate
            # that INITIAL + their recomputed output through the reset
            # intra-cone channels
            for (u, d, k), mat in dep.rebuild_info["channels"].items():
                if d not in cone or u in cone:
                    continue
                skips = mesh_preload.get(d)
                for row in mat:
                    for ch in row:
                        if skips:
                            ch.begin_replay(
                                skip_refs={id(c) for c in skips})
                        else:
                            ch.begin_replay()
            for a in new_actors:
                dep.tasks[by_id[a.actor_id]] = a.spawn()
        return sorted(ids)

    async def _worker_partial_recover(self, plan) -> list[int]:
        """Cluster radius (cluster/meta_service.py owns the protocol):
        re-place the dead worker's actors onto survivors and rebuild
        their downstream closure in place — surviving workers keep
        their stores open at the committed manifest and every actor
        outside the closure keeps running."""
        self.recoveries += 1
        async with self.coord._rounds_lock:
            # stale worker failure reports racing the rebuild are
            # dropped by the push handler while this is set (their
            # actors are already being torn down)
            self._recovering = True
            try:
                return await self.cluster.partial_recover(plan)
            finally:
                self._recovering = False

    async def _auto_recover(self) -> None:
        """Tear down every actor, drop uncommitted store state, rebuild
        all dataflows from the DDL log at the committed epoch, resume."""
        self.recoveries += 1
        await self.crash()
        # VOLATILE sessions (every MV planned with streaming_durability
        # = 0) recover by recomputing from scratch: stateful executors
        # lost their state, but source offsets and MV tables would
        # otherwise SURVIVE in the still-alive in-memory store —
        # resuming sources past state the executors no longer have
        # silently loses joins/aggregates (found round 5: pre-crash
        # person rows x post-crash auction rows vanished). A whole-store
        # reset is the reference's in-memory-backend semantics: process
        # state dies with the failure, everything replays from offset 0
        # and the rebuilt MVs converge exactly.
        flows = [e for e in self._ddl_log
                 if e["kind"] in ("mv", "sink")]
        all_volatile = flows and all(
            e.get("config", {}).get("streaming_durability", 1) == 0
            for e in flows)
        if all_volatile and isinstance(self.store, MemoryStateStore):
            blob = getattr(self.store, "_catalog_blob", None)
            self.store = MemoryStateStore()
            if blob is not None:
                self.store._catalog_blob = blob
        else:
            reset = getattr(self.store, "reset_uncommitted", None)
            if reset is not None:
                reset()
        # fresh coordinator: epochs re-floor at the committed epoch, no
        # stale in-flight state (the dict-delta cursor carries over — the
        # dictionary itself survives in-process recovery)
        old_cursor = self.coord.dict_cursor
        self.coord = BarrierCoordinator(
            self.store,
            checkpoint_max_inflight=self.config.get(
                "checkpoint_max_inflight", 2))
        self.coord.dict_cursor = old_cursor
        self.env = BuildEnv(
            self.store, self.coord,
            chunk_coalesce_max=self.config.get(
                "streaming_chunk_coalesce", 0),
            partial_recovery=bool(self.config.get("partial_recovery", 1)))
        self.env.session = self
        self._apply_memory_config()
        # fresh ServingManager with the coordinator: every cache is
        # invalidated and rebuilds from the recovered epoch on its next
        # touch (the recovery-consistency contract)
        self._apply_serving_config()
        # fresh StreamingStats/watchdog ride the new coordinator; the
        # monitor endpoint (if any) reads `self.coord` live, so it keeps
        # serving across the swap
        self._apply_obs_config()
        self._apply_logstore_config()
        # fresh scrubber rides the new coordinator; retry budget +
        # quarantine repair source re-attach to the (surviving) store
        self._apply_storage_config()
        if self.cluster is not None:
            # prune dead workers, reset survivors (reopen their store
            # handles at the committed manifest, fresh SST blocks) and
            # re-attach them to the new coordinator; the DDL replay
            # below re-places every fragment over the smaller live set
            await self.cluster.on_recovery()
        self.catalog.mvs.clear()
        self.catalog.sinks.clear()
        log = list(self._ddl_log)
        self._recovering = True
        saved_config = dict(self.config)
        from ..utils.faults import FAULTS, FaultInjected
        try:
            for i, entry in enumerate(log):
                if FAULTS.active and FAULTS.hit(
                        "recovery_crash", phase="full",
                        entry=i) is not None:
                    # kill-during-recovery (chaos harness): the DDL log
                    # is intact, tick retries the whole recovery
                    raise FaultInjected(
                        f"injected crash during recovery replay "
                        f"(entry {i})")
                self.env._next_table_id = entry.get(
                    "table_id_floor", self.env._next_table_id)
                self._replay_parallelism = entry.get("parallelism", 1)
                # each entry replays under ITS OWN planning-time config;
                # entries without one (sources, old logs) use the defaults
                self.config = {**saved_config, **entry.get("config", {})}
                self.env.chunk_coalesce_max = self.config.get(
                    "streaming_chunk_coalesce", 0)
                await self.execute(entry["sql"])
        finally:
            self.config = saved_config
            self._recovering = False
            self._replay_parallelism = 1
        self._ddl_log = log
        await self.coord.run_rounds(0)

    async def drop_mv(self, name: str) -> None:
        """Stop one MV's actors and detach its upstream taps. MVs that
        READ this one must be dropped first (the reference rejects
        dropping a relation with dependents)."""
        dependents = [d.name for d in list(self.catalog.mvs.values())
                      + list(self.catalog.sinks.values())
                      if any(up.name == name for up, _ in d.upstream_taps)]
        if dependents:
            raise BindError(
                f"cannot drop {name!r}: {dependents} read it")
        mv = self.catalog.mvs.pop(name)
        self.coord.serving.unregister_mv(name)
        self.coord.logstore.unregister_mv(name)
        await mv.deployment.stop()
        for up, ch in mv.upstream_taps:
            up.tap.remove(ch)
        self._ddl_log = [e for e in self._ddl_log
                         if not (e["kind"] == "mv" and e["name"] == name)]
        self._persist_catalog()

    async def crash(self) -> None:
        """Abandon every actor task WITHOUT the stop protocol — the
        process-kill simulation used by restart/recovery tests. Catalog
        and store are left as-is (a real crash persists both). The
        background uploader dies with the process too: sealed-but-
        uncommitted epochs are dropped (commit point = manifest swap, so
        nothing torn is ever visible) and recovery replays from the last
        committed epoch."""
        for d in (list(self.catalog.mvs.values())
                  + list(self.catalog.sinks.values())):
            for t in d.deployment.tasks:
                if not t.done():
                    t.cancel()
                try:
                    await t
                except (asyncio.CancelledError, Exception):
                    pass
        if self.cluster is not None:
            # workers abandon their actors too (a real meta crash takes
            # the control connections down and the workers self-reset;
            # in-process crash simulation must do it explicitly)
            await self.cluster.reset_all()
        await self.coord.abort_uploads()

    async def drop_all(self) -> None:
        for name in reversed(list(self.catalog.sinks)):
            await self.drop_sink(name)
        # reverse creation order: downstream MVs tap upstream ones
        for name in reversed(list(self.catalog.mvs)):
            await self.drop_mv(name)

    async def shutdown(self) -> None:
        """Graceful stop WITHOUT dropping: actors stop at a barrier, the
        durable catalog and state stay for the next incarnation (the
        playground's exit path under --data; drop_all would erase the
        DDL log)."""
        await self.stop_monitor()
        await self.stop_subscription_server()
        self.event_log.close()
        self.metrics_history.close()
        if self.cluster is not None:
            for name in reversed(list(self.catalog.sinks)):
                sink = self.catalog.sinks.pop(name)
                await sink.deployment.stop()
            for name in reversed(list(self.catalog.mvs)):
                await self.catalog.mvs[name].deployment.stop()
            self.catalog.mvs.clear()
            await self.cluster.stop()
            self.cluster = None
            return
        for name in reversed(list(self.catalog.sinks)):
            sink = self.catalog.sinks.pop(name)
            await sink.deployment.stop()
            for up, ch in sink.upstream_taps:
                up.tap.remove(ch)
        for name in reversed(list(self.catalog.mvs)):
            mv = self.catalog.mvs[name]
            await mv.deployment.stop()
            for up, ch in mv.upstream_taps:
                up.tap.remove(ch)
        self.catalog.mvs.clear()

    # -------------------------------------------------------- batch query
    def query(self, sql_text: str) -> list[tuple]:
        stmt = ast.parse(sql_text)
        assert isinstance(stmt, ast.Select), "query() takes SELECT"
        return self.query_select(stmt)

    def query_select(self, sel: ast.Select) -> list[tuple]:
        """Serving path, synchronous form (REPL / tests on the loop
        thread): pinned snapshot caches + point-lookup index when the
        MVs are cached, else the batch engine over committed MV
        snapshots (reference: local batch execution, scheduler/local.rs
        over batch/src/executor/ — scan/filter/join/agg/sort/limit)."""
        return self.query_select_full(sel)[2]

    def query_select_full(self, sel: ast.Select):
        """-> (names, types, rows), synchronously. A cache miss marks
        the MV wanted (the next collected barrier builds its cache) and
        falls back to the full-scan path."""
        from .batch import run_batch_select_full
        from ..serving.executor import rel_mv_names, run_pinned_select
        from .system_tables import SYSTEM_TABLES, make_system_scan
        serving = self.coord.serving
        names = rel_mv_names(sel.rel)
        if names and any(n in SYSTEM_TABLES for n in names):
            # rw_* system tables: synthesized relations through the
            # stock batch pipeline (they are not MVs — never pinned)
            return run_batch_select_full(
                self.catalog, sel, scan=make_system_scan(self))
        pins = serving.pin(names) if names else None
        if pins is None:
            return run_batch_select_full(self.catalog, sel)
        try:
            return run_pinned_select(self.catalog, sel, pins, serving)
        finally:
            serving.unpin(pins)

    async def run_serving_select(self, sel: ast.Select):
        """-> (names, types, rows). The concurrent serving path (pgwire
        and any async caller): snapshots pin ON THE LOOP (atomic wrt
        barrier-time cache advancement), then the pure-numpy pipeline
        runs on a ServingPool worker thread under admission control and
        the per-query timeout — a big scan no longer stalls barrier
        injection. Uncached queries stay on the loop (the legacy
        committed-snapshot scan) and mark their MVs wanted."""
        from .batch import run_batch_select_full
        from ..serving.executor import rel_mv_names, run_pinned_select
        from .system_tables import SYSTEM_TABLES, make_system_scan
        serving = self.coord.serving
        names = rel_mv_names(sel.rel)
        if names and any(n in SYSTEM_TABLES for n in names):
            return run_batch_select_full(
                self.catalog, sel, scan=make_system_scan(self))
        pins = serving.pin(names) if names else None
        if pins is None:
            return run_batch_select_full(self.catalog, sel)
        return await serving.pool.run(
            lambda: run_pinned_select(self.catalog, sel, pins, serving),
            cleanup=lambda: serving.unpin(pins))


def _fragment_node_kinds(frag) -> list:
    """Every plan Node of one fragment's tree (Exchange leaves excluded)
    — the blast-radius classifier checks kinds (e.g. stream_scan) here."""
    from ..plan.graph import Exchange
    out = []

    def walk(n):
        if isinstance(n, Exchange):
            return
        out.append(n)
        for i in n.inputs:
            walk(i)

    walk(frag.root)
    return out


def _render_batch_plan(sel) -> list:
    """Batch (serving) pipeline of a bare SELECT as text — mirrors the
    executor order in frontend/batch.py."""
    def rel_lines(rel, depth):
        pad = "  " * depth
        if isinstance(rel, ast.TableRel):
            return [f"{pad}batch_scan {rel.name}"
                    + (f" AS {rel.alias}" if rel.alias else "")]
        if isinstance(rel, ast.JoinRel):
            jt = getattr(rel, "join_type", "inner")
            return ([f"{pad}batch_hash_join type={jt}"]
                    + rel_lines(rel.left, depth + 1)
                    + rel_lines(rel.right, depth + 1))
        return [f"{pad}{type(rel).__name__}"]

    out = []
    depth = 0
    if sel.limit is not None or sel.offset:
        out.append("batch_limit "
                   f"limit={sel.limit} offset={sel.offset}")
        depth += 1
    if sel.order_by:
        out.append("  " * depth + "batch_sort")
        depth += 1
    if sel.group_by or any(contains_agg(it.expr) for it in sel.items):
        out.append("  " * depth + "batch_hash_agg")
        depth += 1
    out.append("  " * depth + "batch_project")
    depth += 1
    if sel.where is not None:
        out.append("  " * depth + "batch_filter")
        depth += 1
    out.extend(rel_lines(sel.rel, depth))
    return out

"""Batch engine — numpy host executors over committed MV snapshots.

Reference: src/batch/src/executor/ — RowSeqScan, Filter, HashAgg
(hash_agg.rs), HashJoin (hash_join.rs), Sort (sort.rs), Limit (limit.rs),
Project. Serving reads pull rows OUT of the system, so this path stays on
the host deliberately (a tunneled-TPU d2h per query would also poison the
streaming dataflow sharing the process).

Pipeline: scan (with per-column validity from the serde — NULL cells are
real NULLs here) -> filter -> join -> group-agg -> project -> sort ->
limit/offset. All vectorized numpy; aggregates follow SQL NULL semantics
(count(x) skips NULLs, sum/min/max ignore NULLs, avg = sum/count).
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ..common.types import DataType, Field, GLOBAL_DICT, Schema
from ..expr.agg import AggKind
from ..state.storage_table import StorageTable
from . import sql as ast
from .binder import (AGG_FUNCS, BindError, Scope, bind_scalar, contains_agg,
                     expand_star, split_conjuncts, equi_pair, auto_name)
from .np_eval import eval_numpy


class _Rel:
    """A bound batch relation: columns + validity + name scope."""

    def __init__(self, cols, valids, scope: Scope):
        self.cols = cols
        self.valids = valids
        self.scope = scope

    @property
    def n(self) -> int:
        return len(self.cols[0]) if self.cols else 0

    def mask(self, m: np.ndarray) -> "_Rel":
        return _Rel([c[m] for c in self.cols],
                    [v[m] for v in self.valids], self.scope)


def _scan_mv(catalog, name: str, alias: Optional[str]) -> _Rel:
    mv = catalog.mvs.get(name)
    if mv is None:
        raise BindError(f"unknown MV {name!r}")
    st = StorageTable.for_state_table(mv.table)
    cols, valids = st.to_numpy_with_validity()
    return _Rel(cols, valids, Scope.of(mv.schema, alias or name))


def _bind_rel(catalog, rel, scan=_scan_mv) -> _Rel:
    if isinstance(rel, ast.TableRel):
        return scan(catalog, rel.name, rel.alias)
    if isinstance(rel, ast.JoinRel):
        left = _bind_rel(catalog, rel.left, scan)
        right = _bind_rel(catalog, rel.right, scan)
        return _hash_join(left, right, rel.on,
                          getattr(rel, "join_type", "inner"))
    raise BindError(f"batch queries cannot read {rel!r}")


def _hash_join(left: _Rel, right: _Rel, on, join_type: str = "inner") -> _Rel:
    """Equi-join, all JoinTypes (batch/src/executor/hash_join.rs): build
    on the right, probe with the left. The ON residue filters MATCHED
    pairs (outer-join semantics: a left row whose matches all fail the
    residue still emits NULL-padded), then unmatched rows are appended
    with the other side's columns NULL."""
    lkeys, rkeys, residue = [], [], []
    for conj in split_conjuncts(on):
        pair = equi_pair(conj, left.scope, right.scope)
        if pair is not None:
            lkeys.append(pair[0])
            rkeys.append(pair[1])
        else:
            residue.append(conj)
    if not lkeys:
        raise BindError("batch join needs at least one equi condition")
    # composite keys -> sort/searchsorted merge; NULL keys never match
    lvalid = np.ones(left.n, dtype=bool)
    rvalid = np.ones(right.n, dtype=bool)
    for i in lkeys:
        lvalid &= left.valids[i]
    for i in rkeys:
        rvalid &= right.valids[i]
    lkc = [np.asarray(left.cols[i]) for i in lkeys]
    rkc = [np.asarray(right.cols[i]) for i in rkeys]
    if len(lkc) > 1:
        # composite keys -> ONE dense rank over the combined tuples, so
        # the probe below stays a single vectorized searchsorted (the
        # same rank-space trick sorted_join.py uses on device)
        both = [np.concatenate([l, r]) for l, r in zip(lkc, rkc)]
        oo = np.lexsort(tuple(reversed(both)))
        same = np.ones(max(0, len(oo) - 1), dtype=bool)
        for c in both:
            sc = c[oo]
            same &= sc[1:] == sc[:-1]
        run = np.concatenate([[True], ~same])   # new run if ANY col differs
        rank_sorted = np.cumsum(run) - 1
        rank = np.empty(len(oo), dtype=np.int64)
        rank[oo] = rank_sorted
        lkc = [rank[:left.n]]
        rkc = [rank[left.n:]]
    order = np.argsort(rkc[0], kind="stable")
    order = order[rvalid[order]]
    rs = [rkc[0][order]]
    lo = np.searchsorted(rs[0], lkc[0], "left")
    hi = np.searchsorted(rs[0], lkc[0], "right")
    lens = np.where(lvalid, hi - lo, 0)
    li = np.repeat(np.arange(left.n), lens)
    starts = np.repeat(lo, lens)
    within = np.arange(len(li)) - np.repeat(
        np.cumsum(lens) - lens, lens)
    ri = order[starts + within]

    scope = Scope.join(left.scope, right.scope)
    if residue:
        e = residue[0]
        for r in residue[1:]:
            e = ast.BinOp("and", e, r)
        pred = bind_scalar(e, scope)
        pcols = [c[li] for c in left.cols] + [c[ri] for c in right.cols]
        pvalids = [v[li] for v in left.valids] + [v[ri] for v in right.valids]
        v, valid = eval_numpy(pred, pcols, pvalids)
        keep = np.asarray(v, dtype=bool) & valid
        li, ri = li[keep], ri[keep]

    if join_type == "inner":
        cols = [c[li] for c in left.cols] + [c[ri] for c in right.cols]
        valids = ([v[li] for v in left.valids]
                  + [v[ri] for v in right.valids])
        return _Rel(cols, valids, scope)

    # outer joins: append unmatched rows with the other side NULL-padded
    extra_l = np.empty(0, dtype=np.int64)
    extra_r = np.empty(0, dtype=np.int64)
    if join_type in ("left", "full"):
        lmatched = np.zeros(left.n, dtype=bool)
        lmatched[li] = True
        extra_l = np.nonzero(~lmatched)[0]
    if join_type in ("right", "full"):
        rmatched = np.zeros(right.n, dtype=bool)
        rmatched[ri] = True
        extra_r = np.nonzero(~rmatched)[0]

    def pad(c, n):
        return np.zeros(n, dtype=np.asarray(c).dtype)

    cols, valids = [], []
    for c, v in zip(left.cols, left.valids):
        c = np.asarray(c)
        cols.append(np.concatenate([c[li], c[extra_l], pad(c, len(extra_r))]))
        valids.append(np.concatenate(
            [v[li], v[extra_l], np.zeros(len(extra_r), dtype=bool)]))
    for c, v in zip(right.cols, right.valids):
        c = np.asarray(c)
        cols.append(np.concatenate([c[ri], pad(c, len(extra_l)), c[extra_r]]))
        valids.append(np.concatenate(
            [v[ri], np.zeros(len(extra_l), dtype=bool), v[extra_r]]))
    return _Rel(cols, valids, scope)


def _agg_reduce(kind: AggKind, vals, valid, seg_id, n_groups):
    """Per-group reduction with SQL NULL semantics."""
    if kind is AggKind.COUNT:
        return np.bincount(seg_id, weights=valid.astype(np.float64),
                           minlength=n_groups).astype(np.int64), None
    out_valid = np.bincount(seg_id, weights=valid.astype(np.float64),
                            minlength=n_groups) > 0
    if kind is AggKind.SUM:
        w = np.where(valid, vals, 0)
        if np.issubdtype(vals.dtype, np.integer):
            acc = np.zeros(n_groups, dtype=np.int64)
            np.add.at(acc, seg_id, w.astype(np.int64))   # exact int sums
            return acc, out_valid
        return np.bincount(seg_id, weights=w.astype(np.float64),
                           minlength=n_groups), out_valid
    # min/max: mask invalid with +-inf sentinels
    if np.issubdtype(vals.dtype, np.integer):
        lo, hi = np.iinfo(vals.dtype).min, np.iinfo(vals.dtype).max
    else:
        lo, hi = -np.inf, np.inf
    out = np.full(n_groups, lo if kind is AggKind.MAX else hi,
                  dtype=vals.dtype)
    sentinel = lo if kind is AggKind.MAX else hi
    w = np.where(valid, vals, sentinel)
    op = np.maximum if kind is AggKind.MAX else np.minimum
    np_op_at = op.at
    np_op_at(out, seg_id, w)
    return out, out_valid


_AGG_KINDS = {"count": AggKind.COUNT, "sum": AggKind.SUM,
              "min": AggKind.MIN, "max": AggKind.MAX}


def run_batch_select(catalog, sel: ast.Select) -> list[tuple]:
    return run_batch_select_full(catalog, sel)[2]


def run_batch_select_full(catalog, sel: ast.Select, scan=None):
    """-> (names, DataTypes, rows) — the wire layer needs the row
    description, not just the rows. `scan` overrides how a TableRel
    materializes (the serving layer injects pinned-snapshot relations
    here); the default is the StorageTable committed-snapshot scan."""
    rel = _bind_rel(catalog, sel.rel, scan if scan is not None else _scan_mv)
    if sel.where is not None:
        pred = bind_scalar(sel.where, rel.scope)
        v, valid = eval_numpy(pred, rel.cols, rel.valids)
        rel = rel.mask(np.asarray(v, dtype=bool) & valid)

    items = expand_star(sel.items, rel.scope.schema)
    has_agg = bool(sel.group_by) or any(contains_agg(it.expr)
                                        for it in items)
    if has_agg:
        out_cols, out_valids, out_names, out_types = _run_agg(
            rel, sel, items)
    else:
        out_cols, out_valids, out_names, out_types = [], [], [], []
        for j, it in enumerate(items):
            e = bind_scalar(it.expr, rel.scope)
            v, valid = eval_numpy(e, rel.cols, rel.valids)
            if np.ndim(v) == 0:
                v = np.full(rel.n, v)
                valid = np.ones(rel.n, dtype=bool)
            out_cols.append(np.asarray(v))
            out_valids.append(valid)
            out_names.append(it.alias or auto_name(it.expr, j))
            out_types.append(e.ret_type)

    # ---- ORDER BY (batch/src/executor/sort.rs) ----
    if sel.order_by and out_cols and len(out_cols[0]):
        keys = []
        for e, desc in reversed(sel.order_by):
            j = _order_col(e, out_cols, out_names)
            arr = out_cols[j]
            if out_types[j] is DataType.VARCHAR:
                # dict ids are insertion-ordered, not lexicographic:
                # rank by decoded strings
                strs = np.asarray([GLOBAL_DICT.decode(int(x))
                                   for x in arr])
                _, rank = np.unique(strs, return_inverse=True)
            else:
                # rank-space keys: negation-free DESC (int negation
                # overflows at the dtype edges)
                _, rank = np.unique(arr, return_inverse=True)
            if desc:
                rank = rank.max(initial=0) - rank
            keys.append(rank)
        order = np.lexsort(tuple(keys))
        out_cols = [c[order] for c in out_cols]
        out_valids = [v[order] for v in out_valids]

    # ---- LIMIT / OFFSET (limit.rs) ----
    if sel.offset or sel.limit is not None:
        stop = (sel.offset + sel.limit) if sel.limit is not None else None
        out_cols = [c[sel.offset:stop] for c in out_cols]
        out_valids = [v[sel.offset:stop] for v in out_valids]

    n = len(out_cols[0]) if out_cols else 0

    def cell(j, i):
        if not out_valids[j][i]:
            return None
        v = out_cols[j][i].item()
        if out_types[j] is DataType.VARCHAR:
            return GLOBAL_DICT.decode(int(v))
        if out_types[j] is DataType.BOOLEAN:
            return bool(v)   # the row serde stores booleans as ints
        return v

    return out_names, out_types, [
        tuple(cell(j, i) for j in range(len(out_cols))) for i in range(n)]


def _order_col(e, out_cols, out_names) -> int:
    """ORDER BY resolves against output positions (1-based literal ints)
    then output aliases."""
    if isinstance(e, ast.Lit) and isinstance(e.value, int):
        idx = e.value - 1
        if not 0 <= idx < len(out_cols):
            raise BindError(f"ORDER BY position {e.value} out of range")
        return idx
    if isinstance(e, ast.ColRef) and e.qualifier is None \
            and e.name in out_names:
        return out_names.index(e.name)
    raise BindError(f"ORDER BY must reference an output column: {e!r}")


def _run_agg(rel: _Rel, sel: ast.Select, items):
    """GROUP BY + aggregates (batch/src/executor/hash_agg.rs): group ids
    via lexsort runs; per-call reductions via bincount / ufunc.at."""
    keys = [bind_scalar(g, rel.scope) for g in sel.group_by]
    key_vals = []
    key_valids = []
    for k in keys:
        v, valid = eval_numpy(k, rel.cols, rel.valids)
        key_vals.append(np.asarray(v))
        key_valids.append(valid)

    if keys and rel.n:
        # zero out NULL cells first: a computed key's invalid lanes carry
        # garbage values, and SQL groups all NULL keys together
        key_vals = [np.where(valid, v, 0)
                    for v, valid in zip(key_vals, key_valids)]
        sort_cols = []
        for v, valid in zip(reversed(key_vals), reversed(key_valids)):
            sort_cols.append(v)
            sort_cols.append(~valid)
        order = np.lexsort(tuple(sort_cols))
        # a new group starts where ANY key column differs from the
        # previous sorted row (the old &= ~same demanded EVERY key
        # change, collapsing multi-key GROUP BY into far too few groups
        # — caught by the approx_count_distinct oracle, round 5)
        run_start = np.zeros(rel.n, dtype=bool)
        for v, valid in zip(key_vals, key_valids):
            sv, svd = v[order], valid[order]
            diff = (sv[1:] != sv[:-1]) | (svd[1:] != svd[:-1])
            run_start[1:] |= diff
        run_start[0] = True
        gid_sorted = np.cumsum(run_start) - 1
        n_groups = int(gid_sorted[-1]) + 1 if rel.n else 0
        seg_id = np.empty(rel.n, dtype=np.int64)
        seg_id[order] = gid_sorted
        rep = order[run_start]           # representative row per group
    elif keys:
        n_groups = 0
        seg_id = np.empty(0, dtype=np.int64)
        rep = np.empty(0, dtype=np.int64)
    else:
        n_groups = 1
        seg_id = np.zeros(rel.n, dtype=np.int64)
        rep = None

    def eval_agg(e):
        """-> (values [n_groups], valid) for one aggregate call."""
        assert isinstance(e, ast.Func) and e.name in AGG_FUNCS
        if e.name in ("bool_and", "bool_or"):
            ee = bind_scalar(e.args[0], rel.scope)
            v, valid = eval_numpy(ee, rel.cols, rel.valids)
            b = np.asarray(v, dtype=bool)
            cn = np.bincount(seg_id, weights=valid.astype(np.float64),
                             minlength=n_groups)
            want = (valid & ~b) if e.name == "bool_and" else (valid & b)
            cf = np.bincount(seg_id, weights=want.astype(np.float64),
                             minlength=n_groups)
            out = (cf == 0) if e.name == "bool_and" else (cf > 0)
            return out, cn > 0
        if e.name == "approx_count_distinct":
            # same deterministic 64-register HLL as the streaming path
            # (expr/hll.py) so the two engines agree EXACTLY
            from ..expr.hll import hll_estimate_numpy
            ee = bind_scalar(e.args[0], rel.scope)
            v, valid = eval_numpy(ee, rel.cols, rel.valids)
            return hll_estimate_numpy(
                np.asarray(v), np.asarray(valid), seg_id, n_groups)
        if e.name == "avg":
            sv, svalid = eval_agg(ast.Func("sum", e.args))
            cv, _ = eval_agg(ast.Func("count", e.args))
            safe = np.where(cv == 0, 1, cv)
            if svalid is None:
                svalid = np.ones(n_groups, dtype=bool)
            return sv / safe, svalid & (cv > 0)
        if e.name == "count" and (not e.args or (
                isinstance(e.args[0], ast.ColRef)
                and e.args[0].name == "*")):
            vals = np.ones(rel.n, dtype=np.int64)
            valid = np.ones(rel.n, dtype=bool)
        else:
            ee = bind_scalar(e.args[0], rel.scope)
            v, valid = eval_numpy(ee, rel.cols, rel.valids)
            vals = np.asarray(v)
            if (ee.ret_type is DataType.VARCHAR
                    and e.name in ("min", "max")):
                # dict ids are insertion-ordered; min/max over VARCHAR
                # must rank lexicographically (ADVICE r3 #3): reduce over
                # ranks of the decoded strings, then map the winning rank
                # back to its dict id
                uniq, inv = np.unique(vals, return_inverse=True)
                if len(uniq) == 0:
                    return (np.zeros(n_groups, dtype=np.int64),
                            np.zeros(n_groups, dtype=bool))
                strs = np.asarray(GLOBAL_DICT.decode_many(uniq))
                order = np.argsort(strs)          # rank -> uniq position
                rank_of = np.empty(len(uniq), dtype=np.int64)
                rank_of[order] = np.arange(len(uniq))
                ranks, out_valid = _agg_reduce(_AGG_KINDS[e.name],
                                               rank_of[inv], valid,
                                               seg_id, n_groups)
                safe = np.clip(ranks, 0, len(uniq) - 1)
                return uniq[order][safe].astype(np.int64), out_valid
        out, out_valid = _agg_reduce(_AGG_KINDS[e.name], vals, valid,
                                     seg_id, n_groups)
        return out, out_valid

    def eval_item(e):
        """Scalar-over-aggregates evaluation at the group level."""
        if isinstance(e, ast.Lit):
            return np.full(n_groups, e.value), np.ones(n_groups, bool)
        if not contains_agg(e):
            # agg-free expressions match a GROUP BY key AS A WHOLE first
            # (`auction % 7` with GROUP BY auction % 7 — found by the
            # SQL fuzzer), then fall through to decomposition so
            # expressions OVER keys (`auction + 1` with GROUP BY
            # auction) still evaluate
            eb = bind_scalar(e, rel.scope)
            for j2, _k in enumerate(keys):
                if repr(bind_scalar(sel.group_by[j2],
                                    rel.scope)) == repr(eb):
                    assert rep is not None
                    return key_vals[j2][rep], key_valids[j2][rep]
            if not isinstance(e, (ast.BinOp, ast.UnOp)):
                raise BindError(
                    f"{e!r} must be an aggregate or appear in GROUP BY")
        if isinstance(e, ast.Func) and e.name in AGG_FUNCS:
            v, valid = eval_agg(e)
            if valid is None:                  # COUNT: always valid
                valid = np.ones(n_groups, dtype=bool)
            return v, valid
        if isinstance(e, ast.BinOp):
            a, av = eval_item(e.left)
            b, bv = eval_item(e.right)
            import operator
            ops = {"add": operator.add, "subtract": operator.sub,
                   "multiply": operator.mul,
                   "equal": operator.eq, "not_equal": operator.ne,
                   "less_than": operator.lt,
                   "less_than_or_equal": operator.le,
                   "greater_than": operator.gt,
                   "greater_than_or_equal": operator.ge}
            if e.op == "divide":
                safe = np.where(np.asarray(b) == 0, 1, b)
                return np.asarray(a) / safe, av & bv & (np.asarray(b) != 0)
            if e.op not in ops:
                raise BindError(
                    f"unsupported operator {e.op!r} over aggregates")
            return ops[e.op](np.asarray(a), np.asarray(b)), av & bv
        raise BindError(f"{e!r} must be an aggregate or appear in GROUP BY")

    out_cols, out_valids, out_names, out_types = [], [], [], []
    for j, it in enumerate(items):
        v, valid = eval_item(it.expr)
        if valid is None:
            valid = np.ones(n_groups, dtype=bool)
        arr = np.asarray(v)
        out_cols.append(arr)
        out_valids.append(np.asarray(valid, dtype=bool))
        out_names.append(it.alias or auto_name(it.expr, j))
        out_types.append(_item_type(it.expr, rel, keys, sel))
    return out_cols, out_valids, out_names, out_types


def _item_type(e, rel, keys, sel) -> DataType:
    if isinstance(e, ast.Func) and e.name in AGG_FUNCS:
        if e.name == "count":
            return DataType.INT64
        if e.name == "avg":
            return DataType.FLOAT64
        try:
            return bind_scalar(e.args[0], rel.scope).ret_type
        except BindError:
            return DataType.INT64
    try:
        return bind_scalar(e, rel.scope).ret_type
    except BindError:
        return DataType.INT64

"""Binder + streaming planner: SQL AST -> fragment-graph IR.

Reference: src/frontend binder/ + planner/ + stream_fragmenter (AST ->
bound algebra -> stream plan -> StreamFragmentGraph cut at exchanges).
This thin version binds names against the catalog, lowers expressions onto
the engine's Expr IR, and emits a `StreamGraph` directly:

  FROM source            -> source fragment
  TUMBLE(...)            -> + project appending window_start/window_end
  HOP(...)               -> + hop_window node
  JOIN ... ON            -> two upstream fragments + hash_join fragment
                            (equi conjunctions become key columns, the
                            rest becomes the non-equi condition)
  WHERE                  -> filter node
  GROUP BY + aggregates  -> pre-project (group keys + agg args), hash_agg
                            fragment hash-dispatched on the keys, post-
                            project for SELECT order / AVG = SUM/COUNT
  plain SELECT           -> project (+ row_id for the MV pk)
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..common.types import DataType, Schema
from ..expr.agg import AggCall, AggKind
from ..expr.ir import Expr, call, col, lit
from ..plan import Exchange, Fragment, Node, StreamGraph
from . import sql as ast

AGG_FUNCS = {"count", "sum", "min", "max", "avg", "bool_and", "bool_or",
             "approx_count_distinct"}


class BindError(Exception):
    pass


@dataclass
class Scope:
    """Visible columns: (qualifier, name) -> (index, dtype)."""

    schema: Schema
    names: dict = field(default_factory=dict)

    @classmethod
    def of(cls, schema: Schema, qualifier: Optional[str]) -> "Scope":
        s = cls(schema)
        for i, f in enumerate(schema):
            s.names.setdefault((None, f.name), (i, f.data_type))
            if qualifier:
                s.names[(qualifier, f.name)] = (i, f.data_type)
        return s

    @classmethod
    def join(cls, left: "Scope", right: "Scope") -> "Scope":
        fields = tuple(left.schema) + tuple(right.schema)
        s = cls(Schema(fields))
        off = len(left.schema)
        for (q, n), (i, t) in left.names.items():
            s.names.setdefault((q, n), (i, t))
        for (q, n), (i, t) in right.names.items():
            if (q, n) in s.names and q is None:
                # ambiguous unqualified name: drop it
                del s.names[(q, n)]
                continue
            s.names[(q, n)] = (i + off, t)
        return s

    def resolve(self, ref: ast.ColRef) -> tuple[int, DataType]:
        # a qualified name must match its qualifier exactly — falling back
        # to the unqualified name would silently bind b.x inside a's scope
        key = (ref.qualifier, ref.name)
        if key in self.names:
            return self.names[key]
        raise BindError(f"unknown column {ref.qualifier or ''}.{ref.name}")


def bind_scalar(e, scope: Scope) -> Expr:
    """SQL expression AST -> engine Expr IR (no aggregates allowed)."""
    if isinstance(e, ast.Lit):
        return lit(e.value)
    if isinstance(e, ast.ColRef):
        i, t = scope.resolve(e)
        return col(i, t)
    if isinstance(e, ast.UnOp):
        return call(e.op, bind_scalar(e.arg, scope))
    if isinstance(e, ast.BinOp):
        return call(e.op, bind_scalar(e.left, scope),
                    bind_scalar(e.right, scope))
    if isinstance(e, ast.Func):
        if e.name in AGG_FUNCS:
            raise BindError(f"aggregate {e.name} not allowed here")
        return call(e.name, *[bind_scalar(a, scope) for a in e.args])
    raise BindError(f"cannot bind {e!r}")


def contains_agg(e) -> bool:
    if isinstance(e, ast.Func):
        return e.name in AGG_FUNCS or any(contains_agg(a) for a in e.args)
    if isinstance(e, ast.BinOp):
        return contains_agg(e.left) or contains_agg(e.right)
    if isinstance(e, ast.UnOp):
        return contains_agg(e.arg)
    return False


@dataclass
class RelInfo:
    """Stream properties the reference tracks in plan_base: the STREAM KEY
    (positions in the relation's output that uniquely identify a changelog
    row — what retractions address), append-only-ness, and the columns a
    WATERMARK flows on (reference: watermark_columns in plan_base, derived
    by the watermark inference pass — drives join/agg state cleaning)."""

    stream_key: Optional[tuple] = None      # None = keyless (needs row_id)
    append_only: bool = True
    wm_cols: frozenset = frozenset()


# date_time column index per nexmark table (the connector's declared
# watermark column, connectors/nexmark.py watermark_col)
_NEXMARK_WM_COL = {"bid": 5, "person": 6, "auction": 5}


@dataclass
class BoundPlan:
    graph: StreamGraph
    mv_fragment: int            # the fragment whose root will materialize
    schema: Schema
    pk_indices: tuple
    append_only: bool = True


class TumbleStartTransform:
    """Monotone watermark transform `v -> window_start(v)` as a
    PICKLABLE callable: Node args ship to cluster compute nodes as the
    wire IR, and a closure would refuse to pickle."""

    __slots__ = ("size",)

    def __init__(self, size: int):
        self.size = size

    def __call__(self, v):
        return v - v % self.size


class TumbleEndTransform(TumbleStartTransform):
    def __call__(self, v):
        return (v - v % self.size) + self.size


class StreamPlanner:
    def __init__(self, catalog, parallelism: int = 1, config=None):
        self.catalog = catalog
        self.parallelism = parallelism   # hash-distributed fragments
        self.config = config or {}
        self.graph = StreamGraph()
        self._next_fid = 1

    def cfg(self, name: str, default):
        return self.config.get(name, default)

    def durable(self) -> bool:
        """Stateful executors flush to state tables at barriers unless the
        session selected the in-memory state backend."""
        return bool(self.cfg("streaming_durability", 1))

    def fid(self) -> int:
        f = self._next_fid
        self._next_fid = f + 1
        return f

    def source_fragment(self, name: str) -> int:
        """Source fragments are SHARED within one plan (reference: the
        source-sharing rewrite, ShareSourceRewriter) — a query reading
        `bid` twice (q7: raw stream + windowed agg over it) runs ONE
        generator/connector, not two. The cached fragment stays bare;
        consumers attach through Exchange so later grafts (WHERE, window
        projects) never mutate a shared root."""
        if not hasattr(self, "_source_frags"):
            self._source_frags = {}
        if not hasattr(self, "used_sources"):
            self.used_sources = set()
        self.used_sources.add(name)
        if name not in self._source_frags:
            src = self.catalog.source(name)
            node = Node("nexmark_source", dict(src.options, durable=True,
                                               source_name=name))
            # split-managed sources scale with the session parallelism,
            # bounded by their split count (source_manager.rs assignment)
            n_splits = int(src.options.get("splits", 1))
            f = self.graph.add(Fragment(
                self.fid(), node, dispatch="broadcast",
                parallelism=max(1, min(self.parallelism, n_splits))))
            self._source_frags[name] = f.fid
        return self._source_frags[name]

    # ----------------------------------------------------------- relations
    def plan_rel(self, rel) -> tuple[int, Scope, RelInfo]:
        """Returns (fragment id, scope over its output, stream info)."""
        if isinstance(rel, ast.TableRel):
            # an MV name resolves to a backfilled stream scan over it
            # (MV-on-MV, reference StreamScan/Chain); sources otherwise
            if rel.name in getattr(self.catalog, "mvs", {}):
                mv = self.catalog.mvs[rel.name]
                node = Node("stream_scan", dict(mv=rel.name))
                f = self.graph.add(Fragment(self.fid(), node,
                                            dispatch="broadcast"))
                return (f.fid, Scope.of(mv.schema, rel.alias or rel.name),
                        RelInfo(stream_key=tuple(mv.pk_indices),
                                append_only=getattr(mv, "append_only",
                                                    False)))
            src = self.catalog.source(rel.name)
            sfid = self.source_fragment(rel.name)
            # indirection fragment: WHERE/project grafts land here, the
            # shared source root stays untouched
            f = self.graph.add(Fragment(self.fid(), Node(
                "no_op", {}, inputs=(Exchange(sfid),)),
                dispatch="broadcast"))
            wm = frozenset()
            wmcol = _NEXMARK_WM_COL.get(src.options.get("table"))
            if src.options.get("emit_watermarks") and wmcol is not None:
                wm = frozenset({wmcol})
            pk_opt = src.options.get("primary_key")
            # generator/file sources only ever insert; a broker topic
            # can carry changelog ops (`__op`), so it declares
            # append-only explicitly or plans retract-capable
            ao = bool(src.options.get("append_only", True))
            return (f.fid, Scope.of(src.schema, rel.alias or rel.name),
                    RelInfo(None if pk_opt is None else (pk_opt,), ao,
                            wm))
        if isinstance(rel, ast.WindowRel):
            src = self.catalog.source(rel.inner.name)
            scope = Scope.of(src.schema, None)
            i, t = scope.resolve(ast.ColRef(rel.time_col))
            src_node = Exchange(self.source_fragment(rel.inner.name))
            if rel.kind == "tumble":
                exprs = [col(j, f.data_type)
                         for j, f in enumerate(src.schema)]
                exprs.append(call("tumble_start", col(i, t), lit(rel.size)))
                exprs.append(call("tumble_end", col(i, t), lit(rel.size)))
                names = list(src.schema.names) + ["window_start",
                                                  "window_end"]
                W = rel.size
                node = Node("project", dict(
                    exprs=exprs, names=names,
                    watermark_transforms={
                        i: [(len(names) - 2, TumbleStartTransform(W)),
                            (len(names) - 1, TumbleEndTransform(W))]}),
                    inputs=(src_node,))
                f = self.graph.add(Fragment(self.fid(), node,
                                            dispatch="broadcast"))
                out_schema = Schema(tuple(
                    list(src.schema)
                    + [type(src.schema[0])("window_start", t),
                       type(src.schema[0])("window_end", t)]))
            else:
                node = Node("hop_window", dict(
                    time_col=i, slide_us=rel.slide, size_us=rel.size),
                    inputs=(src_node,))
                f = self.graph.add(Fragment(self.fid(), node,
                                            dispatch="broadcast"))
                from ..common.types import Field
                out_schema = Schema(tuple(
                    list(src.schema) + [Field("window_start", t),
                                        Field("window_end", t)]))
            wm = frozenset()
            if src.options.get("emit_watermarks"):
                # tumble transforms the event-time watermark onto BOTH
                # window columns; hop emits it on window_start only
                wm = (frozenset({len(src.schema), len(src.schema) + 1})
                      if rel.kind == "tumble"
                      else frozenset({len(src.schema)}))
            # tumble is 1:1 so a declared source pk remains a stream key;
            # hop emits one row PER WINDOW so the key widens to
            # (pk, window_start)
            pk_opt = src.options.get("primary_key")
            sk = None
            if pk_opt is not None:
                sk = ((pk_opt,) if rel.kind == "tumble"
                      else (pk_opt, len(src.schema)))
            return (f.fid, Scope.of(out_schema, rel.alias or rel.inner.name),
                    RelInfo(sk, True, wm))
        if isinstance(rel, ast.JoinRel):
            lf, ls, li = self.plan_rel(rel.left)
            rf, rs, ri = self.plan_rel(rel.right)
            # Join-state pk must be a REAL stream key (reference: plan_base
            # stream_key). A keyless append-only side gets a row_id column
            # (ADVICE r2 #5); a side that already HAS a stream key (MV
            # scan, agg subquery) keeps it — generating fresh row ids for
            # retraction rows would orphan every delete.
            from ..common.types import Field

            def side_key(fid_, scope_, info_):
                if info_.stream_key is not None:
                    return scope_, tuple(info_.stream_key)
                if not info_.append_only:
                    raise BindError("keyless retracting join input")
                frag_ = self.graph.fragments[fid_]
                frag_.root = Node("row_id_gen", {}, inputs=(frag_.root,))
                sch = Schema(tuple(scope_.schema)
                             + (Field("_row_id", DataType.SERIAL),))
                return Scope(sch, dict(scope_.names)), (len(sch) - 1,)

            ls, lpk = side_key(lf, ls, li)
            rs, rpk = side_key(rf, rs, ri)
            jscope = Scope.join(ls, rs)
            lkeys, rkeys, residue = [], [], []
            for conj in split_conjuncts(rel.on):
                pair = equi_pair(conj, ls, rs)
                if pair is not None:
                    lkeys.append(pair[0])
                    rkeys.append(pair[1])
                else:
                    residue.append(conj)
            if not lkeys:
                raise BindError("join needs at least one equi condition")
            cond = None
            if residue:
                e = residue[0]
                for r in residue[1:]:
                    e = ast.BinOp("and", e, r)
                cond = bind_scalar(e, jscope)
            jt = getattr(rel, "join_type", "inner")
            temporal = getattr(rel, "temporal", False)
            if temporal and jt not in ("inner", "left"):
                raise BindError("temporal joins are INNER or LEFT")
            if temporal and not li.append_only:
                # a retractable stream side would emit deletes for rows
                # downstream never saw (the table side's emissions are
                # suppressed) — the reference requires append-only too
                raise BindError(
                    "temporal joins need an append-only stream side")
            # --- watermark-driven state cleaning (reference: the stream
            # planner's watermark inference + interval-join condition
            # analysis, optimizer/plan_node/stream_hash_join.rs clean_*):
            # a side may evict rows below its watermark on column c when
            # future matches against them are impossible — (1) c is an
            # equi-key whose partner column is also watermarked (windowed
            # joins: both sides advance together), or (2) a residual
            # conjunct bands c against watermarked columns of the other
            # side (interval joins: old rows fall out of every future
            # band). Outer joins never clean (degree accounting).
            clean_l = clean_r = None
            if jt == "inner":
                for kpos, (lk, rk) in enumerate(zip(lkeys, rkeys)):
                    if lk in li.wm_cols and rk in ri.wm_cols:
                        clean_l = ("pair", lk, kpos)
                        clean_r = ("pair", rk, kpos)
                        break
                if clean_l is None and clean_r is None:
                    for conj in residue:
                        b = band_bound(conj, ls, rs, li.wm_cols, ri.wm_cols)
                        if b is None:
                            continue
                        bside, own_col, other_col, delta = b
                        info_side = li if bside == "l" else ri
                        own_wm = own_col in info_side.wm_cols
                        # a RETRACTING side may still emit deletes for
                        # rows the band bound already evicted (the other
                        # side's watermark can run ahead of ours). Safe
                        # only if the side is append-only, or its own
                        # column is watermarked so the executor caps the
                        # bound at min(own wm, band bound).
                        if not (info_side.append_only or own_wm):
                            continue
                        cap = (own_col if own_wm
                               and not info_side.append_only else None)
                        spec = ("band", own_col, other_col, delta, cap)
                        if bside == "l" and clean_l is None:
                            clean_l = spec
                        elif bside == "r" and clean_r is None:
                            clean_r = spec
                        if clean_l is not None and clean_r is not None:
                            break
            # The sorted-merge join (fast path: dense sorted state, no
            # chain walks) requires integer-comparable keys — true for
            # every engine type except FLOAT64 (varchar = dict ids,
            # decimal = scaled int, timestamps = int64). Non-integer keys
            # fall back to the chained hash join.
            import numpy as np
            key_int = all(
                np.issubdtype(sc.schema[i].data_type.np_dtype, np.integer)
                for sc, keys in ((ls, lkeys), (rs, rkeys)) for i in keys)
            wd = 1 if self.cfg("streaming_watchdog", 1) else None
            # per-side match buffers: probing a side whose rows are
            # UNIQUE per join key (stream key covered by its equi keys)
            # yields at most one match per probe row; the wide default
            # factor is only for skewed many-per-key sides
            mf = self.cfg("streaming_join_match_factor", 64)
            mf_l = min(2, mf) if set(rpk) <= set(rkeys) else mf
            mf_r = min(2, mf) if set(lpk) <= set(lkeys) else mf
            if key_int:
                node = Node("sorted_join", dict(
                    left_key_indices=lkeys, right_key_indices=rkeys,
                    left_pk_indices=list(lpk),
                    right_pk_indices=list(rpk),
                    condition=cond, join_type=jt, temporal=temporal,
                    capacity=self.cfg("streaming_join_capacity", 1 << 17),
                    match_factor=mf, match_factors=(mf_l, mf_r),
                    append_only=(li.append_only, ri.append_only),
                    clean_specs=(clean_l, clean_r),
                    mesh_devices=self.cfg(
                        "streaming_parallelism_devices", 1),
                    mesh_shuffle=self.cfg("streaming_mesh_shuffle", 1),
                    mesh_shuffle_slack=self.cfg(
                        "streaming_mesh_shuffle_slack", 0),
                    mesh_shuffle_adaptive=self.cfg(
                        "streaming_mesh_shuffle_adaptive", 1),
                    mesh_chain=self.cfg("streaming_mesh_chain", 1),
                    watchdog_interval=wd,
                    durable=self.durable()),
                    inputs=(Exchange(lf), Exchange(rf)))
            else:
                if jt != "inner" or temporal:
                    raise BindError(
                        "outer/temporal joins require integer-comparable "
                        "keys")
                node = Node("hash_join", dict(
                    left_key_indices=lkeys, right_key_indices=rkeys,
                    left_pk_indices=list(lpk),
                    right_pk_indices=list(rpk),
                    condition=cond,
                    match_factor=self.cfg("streaming_join_match_factor", 64),
                    watchdog_interval=wd,
                    durable=self.durable()),
                    inputs=(Exchange(lf), Exchange(rf)))
            f = self.graph.add(Fragment(self.fid(), node,
                                        dispatch="broadcast"))
            rw = self.cfg("streaming_fragment_worker", "")
            if rw and node.kind == "sorted_join":
                # DCN placement: this fragment deploys in the worker
                # process (stream/remote_fragment.py). v1 runs the
                # remote fragment volatile, so the SESSION must be
                # volatile too (recovery then replays sources from 0
                # and the materialize upsert converges the MV)
                if self.durable():
                    raise BindError(
                        "streaming_fragment_worker requires "
                        "streaming_durability = 0 (v1: remote fragments "
                        "hold no durable state)")
                if self.parallelism != 1:
                    raise BindError(
                        "streaming_fragment_worker requires "
                        "streaming_parallelism = 1 (remote fragments "
                        "and their upstreams are singleton in v1)")
                f.remote_worker = rw
            # stash for the bind-time optimizer passes (_optimize_join):
            # filter pushdown + join-input pruning run once the consuming
            # SELECT is known
            if not hasattr(self, "_join_frags"):
                self._join_frags = {}
            self._join_frags[f.fid] = dict(
                node=node, nl=len(ls.schema), lsch=ls.schema,
                rsch=rs.schema, jt=jt)
            off = len(ls.schema)
            jkey = tuple(lpk) + tuple(off + i for i in rpk)
            # the executor forwards min-of-sides watermarks on equi-key
            # columns where BOTH sides carry one. Inner joins only: an
            # outer join's NULL-padded rows emit values on the padded
            # side's key column at arbitrary future times, which would
            # violate the advertised watermark downstream.
            out_wm = set()
            if jt == "inner":
                for lk, rk in zip(lkeys, rkeys):
                    if lk in li.wm_cols and rk in ri.wm_cols:
                        out_wm |= {lk, off + rk}
            # temporal: the table side's updates emit nothing, so the
            # output is append-only iff the STREAM side is
            ao_out = ((li.append_only and jt == "inner") if temporal
                      else (li.append_only and ri.append_only
                            and jt == "inner"))
            return (f.fid, jscope,
                    RelInfo(stream_key=jkey, append_only=ao_out,
                            wm_cols=frozenset(out_wm)))
        if isinstance(rel, ast.SubqueryRel):
            # FROM (SELECT ...) alias — plan the inner query WITHOUT
            # materialization; its changelog feeds the outer plan
            # directly (reference: StreamProject/Agg subplans compose,
            # no intermediate MV)
            from ..common.types import Field
            sub_fid, names, types, pk_hint, ao, wm = self._plan_query(
                rel.select)
            schema = Schema(tuple(Field(n, t)
                                  for n, t in zip(names, types)))
            return (sub_fid, Scope.of(schema, rel.alias),
                    RelInfo(stream_key=pk_hint, append_only=ao,
                            wm_cols=wm))
        raise BindError(f"cannot plan relation {rel!r}")

    # -------------------------------------------------------------- select
    def plan_sink(self, sel: ast.Select, options: dict) -> "BoundPlan":
        """CREATE SINK: the plan terminates in a sink node instead of a
        materialize (reference: StreamSink, sink desc from the WITH
        options)."""
        fid, names, types, pk_hint, append_only, _wm = self._plan_query(sel)
        frag = self.graph.fragments[fid]
        from ..common.types import Field
        frag.root = Node("sink", dict(options), inputs=(frag.root,))
        out = Schema(tuple(Field(n, t) for n, t in zip(names, types)))
        return BoundPlan(self.graph, fid, out, tuple(pk_hint or ()),
                         append_only)

    def plan_select(self, sel: ast.Select) -> BoundPlan:
        fid, names, types, pk_hint, append_only, _wm = self._plan_query(sel)
        frag = self.graph.fragments[fid]
        from ..common.types import Field
        if pk_hint is None:
            frag.root = Node("row_id_gen", {}, inputs=(frag.root,))
            mv = self.graph.add(Fragment(self.fid(), Node(
                "materialize", dict(pk_indices=[len(names)]),
                inputs=(Exchange(fid),))))
            out = Schema(tuple(
                [Field(n, t) for n, t in zip(names, types)]
                + [Field("_row_id", DataType.SERIAL)]))
            return BoundPlan(self.graph, mv.fid, out, (len(names),),
                             append_only)
        mv = self.graph.add(Fragment(self.fid(), Node(
            "materialize", dict(pk_indices=list(pk_hint)),
            inputs=(Exchange(fid),))))
        out = Schema(tuple(Field(n, t) for n, t in zip(names, types)))
        return BoundPlan(self.graph, mv.fid, out, tuple(pk_hint),
                         append_only)

    def _plan_query(self, sel: ast.Select):
        """Plan one SELECT (no materialization). Returns (fragment id,
        out names, out DataTypes, pk_hint, append_only) — pk_hint is the
        output positions forming the stream key, or None when the stream
        is keyless append-only (caller adds a row_id)."""
        top_spec = (list(sel.order_by), sel.limit, sel.offset)
        want_top_n = sel.limit is not None
        if (sel.order_by or sel.offset) and not want_top_n:
            raise BindError(
                "streaming ORDER BY needs a LIMIT (a TopN MV); unbounded "
                "ORDER BY belongs in batch SELECTs over the MV")
        if want_top_n and not sel.order_by:
            raise BindError("streaming LIMIT needs ORDER BY (TopN)")
        # comma join: FROM a, b WHERE ... — the join condition lives in
        # WHERE; hoist it into ON (single 2-way comma join supported)
        rel, where = sel.rel, sel.where
        if isinstance(rel, ast.JoinRel) and rel.on is None:
            if isinstance(rel.left, ast.JoinRel) and rel.left.on is None:
                raise BindError("only one comma join is supported")
            if where is None:
                raise BindError("comma join needs join conditions in WHERE")
            rel = ast.JoinRel(rel.left, rel.right, where)
            where = None
        fused = self._try_snapshot_join_agg(ast.Select(
            list(sel.items), rel, where, sel.group_by,
            list(sel.order_by), sel.limit, sel.offset))
        if fused is not None:
            return fused

        fid, scope, info = self.plan_rel(rel)
        frag = self.graph.fragments[fid]
        sel = ast.Select(expand_star(sel.items, scope.schema), rel,
                         where, sel.group_by, list(sel.order_by),
                         sel.limit, sel.offset,
                         emit_on_close=getattr(sel, "emit_on_close",
                                               False))

        jinfo = getattr(self, "_join_frags", {}).get(fid)
        if jinfo is not None and frag.root is jinfo["node"]:
            scope, info, sel = self._optimize_join(jinfo, scope, info, sel)

        # `col > now()`-style conjuncts lower to DynamicFilter against a
        # Now fragment (reference: the NOW() rewrite producing
        # StreamDynamicFilter + StreamNow); the rest become a plain filter
        if sel.where is not None:
            plain, dynamic = [], []
            for conj in split_conjuncts(sel.where):
                df = _now_conjunct(conj, scope)
                if df is None:
                    plain.append(conj)
                else:
                    dynamic.append(df)
            # static predicates graft FIRST: rows they reject must never
            # occupy the dynamic filter's bounded device state
            if dynamic and plain:
                e0 = plain[0]
                for c in plain[1:]:
                    e0 = ast.BinOp("and", e0, c)
                frag.root = Node("filter",
                                 dict(predicate=bind_scalar(e0, scope)),
                                 inputs=(frag.root,))
                plain = []
            for key_col, op in dynamic:
                if info.stream_key is None:
                    if not info.append_only:
                        raise BindError(
                            "keyless retracting dynamic-filter input")
                    from ..common.types import Field
                    frag.root = Node("row_id_gen", {},
                                     inputs=(frag.root,))
                    sch2 = Schema(tuple(scope.schema) + (
                        Field("_row_id", DataType.SERIAL),))
                    scope = Scope(sch2, dict(scope.names))
                    info = RelInfo((len(sch2) - 1,), True, info.wm_cols)
                now_f = self.graph.add(Fragment(
                    self.fid(), Node("now", {}), dispatch="broadcast"))
                frag.root = Node("dynamic_filter", dict(
                    key_col=key_col, op=op,
                    pk_indices=list(info.stream_key),
                    capacity=self.cfg("streaming_dynamic_filter_capacity",
                                      1 << 14),
                    watchdog_interval=(
                        1 if self.cfg("streaming_watchdog", 1) else None)),
                    inputs=(frag.root, Exchange(now_f.fid)))
                # output retracts when the threshold moves
                info = RelInfo(info.stream_key, False, info.wm_cols)
            w = None
            for c in plain:
                w = c if w is None else ast.BinOp("and", w, c)
            sel = ast.Select(sel.items, sel.rel, w, sel.group_by,
                             sel.order_by, sel.limit, sel.offset)
        if sel.where is not None:
            pred = bind_scalar(sel.where, scope)
            frag.root = Node("filter", dict(predicate=pred),
                             inputs=(frag.root,))

        if any(isinstance(it.expr, ast.WindowFunc) for it in sel.items):
            out = self._plan_over_window(sel, fid, scope, info)
            if want_top_n:
                out = self._plan_top_n(top_spec, out)
            return out

        has_agg = bool(sel.group_by) or any(
            contains_agg(it.expr) for it in sel.items)
        from ..expr.ir import InputRef

        def project_wm(exprs):
            """Watermarks survive a projection on InputRef columns (the
            project executor's default watermark_mapping)."""
            return frozenset(
                j for j, e in enumerate(exprs)
                if isinstance(e, InputRef) and e.index in info.wm_cols)

        if not has_agg:
            exprs, names = [], []
            for j, it in enumerate(sel.items):
                exprs.append(bind_scalar(it.expr, scope))
                names.append(it.alias or auto_name(it.expr, j))
            if info.append_only:
                frag.root = Node("project", dict(exprs=exprs, names=names),
                                 inputs=(frag.root,))
                out = (fid, names, [e.ret_type for e in exprs], None, True,
                       project_wm(exprs))
                if want_top_n:
                    out = self._plan_top_n(top_spec, out)
                return out
            # retracting input: its stream key must survive projection so
            # deletes keep addressing the same rows (the reference appends
            # hidden stream-key columns the same way)
            assert info.stream_key is not None
            key_pos = []
            for ki in info.stream_key:
                found = None
                for j, e in enumerate(exprs):
                    if isinstance(e, InputRef) and e.index == ki:
                        found = j
                        break
                if found is None:
                    t = scope.schema[ki].data_type
                    exprs.append(col(ki, t))
                    names.append(f"_sk{ki}")
                    found = len(exprs) - 1
                key_pos.append(found)
            frag.root = Node("project", dict(exprs=exprs, names=names),
                             inputs=(frag.root,))
            out = (fid, names, [e.ret_type for e in exprs],
                   tuple(key_pos), False, project_wm(exprs))
            if want_top_n:
                out = self._plan_top_n(top_spec, out)
            return out

        afid, names, types, pk, wm_out = self._plan_agg(sel, fid, scope,
                                                        info)
        out = (afid, names, types, pk, False, wm_out)
        if want_top_n:
            out = self._plan_top_n(top_spec, out)
        return out

    # ------------------------------------------- snapshot join-agg fusion
    def _try_snapshot_join_agg(self, sel: ast.Select):
        """Fuse the q17 shape — SELECT <global aggs over L> FROM L JOIN
        dim JOIN (SELECT k, <agg exprs> FROM L GROUP BY k) A ON A.k = L.k
        [AND residue] WHERE <single-side filters> — into ONE
        barrier-snapshot executor (stream/snapshot_join_agg.py) when
        every input is append-only. The changelog plan for this shape is
        an inherent retraction storm (each L row shifts its group's
        aggregate, re-emitting the whole group through the join);
        snapshot recompute at barriers is O(n) total. Returns a
        _plan_query result tuple, or None to fall back to the generic
        join plan (SET streaming_snapshot_fuse = 0 forces the fallback).

        Reference: dynamic_filter.rs re-evaluates a changing scalar RHS
        per barrier; this generalizes that to the join-against-own-
        aggregate sub-plan of /root/reference/e2e_test/tpch q17.
        """
        from ..common.types import Field
        from ..expr.ir import InputRef, input_refs, remap_inputs

        if not self.cfg("streaming_snapshot_fuse", 1):
            return None
        if (sel.group_by or sel.order_by or sel.limit is not None
                or sel.offset):
            return None
        if not sel.items or not all(
                isinstance(it.expr, ast.Lit) or contains_agg(it.expr)
                for it in sel.items):
            return None
        if not isinstance(sel.rel, ast.JoinRel):
            return None
        leaves: list = []
        bad: list = []

        def flat(r):
            if isinstance(r, ast.JoinRel):
                if (getattr(r, "join_type", "inner") != "inner"
                        or getattr(r, "temporal", False) or r.on is None):
                    bad.append(r)
                    return
                flat(r.left)
                leaves.append((r.right, r.on))
            else:
                leaves.append((r, None))

        flat(sel.rel)
        if bad or len(leaves) != 3:
            return None
        rels = [l for l, _ in leaves]
        if not isinstance(rels[0], ast.TableRel):
            return None
        sub_pos_leaf = [i for i in (1, 2)
                        if isinstance(rels[i], ast.SubqueryRel)]
        dim_pos_leaf = [i for i in (1, 2)
                        if isinstance(rels[i], ast.TableRel)]
        if len(sub_pos_leaf) != 1 or len(dim_pos_leaf) != 1:
            return None
        fact_rel = rels[0]
        dim_rel = rels[dim_pos_leaf[0]]
        sub_rel = rels[sub_pos_leaf[0]]
        asel = sub_rel.select
        if (not isinstance(asel, ast.Select)
                or len(asel.group_by) != 1 or asel.order_by
                or asel.limit is not None or asel.offset
                or not isinstance(asel.rel, ast.TableRel)
                or asel.rel.name != fact_rel.name):
            return None
        # both scans of L must see identical rows: require a SOURCE
        # (an MV could change between the two logical scans' backfills)
        if fact_rel.name in getattr(self.catalog, "mvs", {}) \
                or dim_rel.name in getattr(self.catalog, "mvs", {}):
            return None
        try:
            fact_src = self.catalog.source(fact_rel.name)
            dim_src = self.catalog.source(dim_rel.name)
        except Exception:
            return None
        dim_pk = dim_src.options.get("primary_key")
        if dim_pk is None:
            return None    # the membership mask needs a UNIQUE dim key
        fscope = Scope.of(fact_src.schema, fact_rel.alias or fact_rel.name)
        dscope = Scope.of(dim_src.schema, dim_rel.alias or dim_rel.name)
        nl, nd = len(fscope.schema), len(dscope.schema)

        # ---- the subquery: key + agg items over its own scan scope
        ascan = Scope.of(fact_src.schema, asel.rel.alias or asel.rel.name)
        try:
            gkey = bind_scalar(asel.group_by[0], ascan)
        except BindError:
            return None
        if not isinstance(gkey, InputRef):
            return None
        fact_key = gkey.index

        def make_decomp(calls: list, scope_: Scope):
            def arg_of(e):
                try:
                    b = bind_scalar(e, scope_)
                except BindError:
                    return None
                return b.index if isinstance(b, InputRef) else None

            def decomp(e):
                if isinstance(e, ast.Func) and e.name in AGG_FUNCS:
                    if e.name == "count":
                        a = None
                        if not getattr(e, "star", False) and e.args:
                            a = arg_of(e.args[0])
                            if a is None:
                                return None
                        calls.append(AggCall(AggKind.COUNT, a,
                                             DataType.INT64, True))
                        return col(len(calls) - 1, DataType.INT64)
                    if not e.args:
                        return None
                    a = arg_of(e.args[0])
                    if a is None:
                        return None
                    at = scope_.schema[a].data_type
                    if at is DataType.VARCHAR and e.name != "count":
                        return None
                    if e.name == "avg":
                        calls.append(AggCall(AggKind.SUM, a,
                                             DataType.FLOAT64, True))
                        s_ = len(calls) - 1
                        calls.append(AggCall(AggKind.COUNT, a,
                                             DataType.INT64, True))
                        return call("divide", col(s_, DataType.FLOAT64),
                                    col(s_ + 1, DataType.INT64))
                    if e.name == "sum":
                        ret = (DataType.FLOAT64
                               if at in (DataType.FLOAT64,
                                         DataType.FLOAT32)
                               else DataType.INT64)
                        calls.append(AggCall(AggKind.SUM, a, ret, True))
                        return col(len(calls) - 1, ret)
                    kind = (AggKind.MIN if e.name == "min"
                            else AggKind.MAX)
                    calls.append(AggCall(kind, a, at, True))
                    return col(len(calls) - 1, at)
                if isinstance(e, ast.Lit):
                    return lit(e.value)
                if isinstance(e, ast.BinOp):
                    l_, r_ = decomp(e.left), decomp(e.right)
                    if l_ is None or r_ is None:
                        return None
                    return call(e.op, l_, r_)
                if isinstance(e, ast.UnOp):
                    a_ = decomp(e.arg)
                    return None if a_ is None else call(e.op, a_)
                return None
            return decomp

        sub_agg_calls: list[AggCall] = []
        decomp_sub = make_decomp(sub_agg_calls, ascan)
        a_fields, a_items, key_item = [], [], None
        for j, it in enumerate(asel.items):
            name = it.alias or auto_name(it.expr, j)
            if not contains_agg(it.expr):
                try:
                    b = bind_scalar(it.expr, ascan)
                except BindError:
                    return None
                if (not isinstance(b, InputRef) or b.index != fact_key
                        or key_item is not None):
                    return None
                key_item = j
                a_fields.append(Field(name, b.ret_type))
                a_items.append(None)
            else:
                e2 = decomp_sub(it.expr)
                if e2 is None:
                    return None
                a_fields.append(Field(name, e2.ret_type))
                a_items.append(e2)
        if key_item is None:
            return None
        sub_filter = None
        if asel.where is not None:
            try:
                sub_filter = bind_scalar(asel.where, ascan)
            except BindError:
                return None

        # ---- classify every ON + WHERE conjunct
        ascope = Scope.of(Schema(tuple(a_fields)), sub_rel.alias)
        parts = {dim_pos_leaf[0]: dscope, sub_pos_leaf[0]: ascope}
        full = Scope.join(Scope.join(fscope, parts[1]), parts[2])
        offs = {1: nl, 2: nl + len(parts[1].schema)}
        dim_off = offs[dim_pos_leaf[0]]
        a_off = offs[sub_pos_leaf[0]]
        na = len(a_fields)
        conjs = []
        for _, on in leaves[1:]:
            conjs += split_conjuncts(on)
        if sel.where is not None:
            conjs += split_conjuncts(sel.where)
        fact_link = dim_link = None
        fact_filters, dim_filters, residues = [], [], []
        for conj in conjs:
            p = equi_pair(conj, fscope, dscope)
            if p is not None:
                if dim_link is not None or p[1] != dim_pk:
                    return None
                dim_link = p[0]
                continue
            p = equi_pair(conj, fscope, ascope)
            if p is not None and p[1] == key_item:
                if fact_link is not None or p[0] != fact_key:
                    return None
                fact_link = p[0]
                continue
            try:
                b = bind_scalar(conj, full)
            except BindError:
                return None
            refs = input_refs(b)
            if all(i < nl for i in refs):
                fact_filters.append(b)
            elif all(dim_off <= i < dim_off + nd for i in refs):
                dim_filters.append(remap_inputs(
                    b, {i: i - dim_off for i in refs}))
            elif all(i < nl or (a_off <= i < a_off + na
                                and i - a_off != key_item)
                     for i in refs):
                residues.append(b)
            else:
                return None
        # membership is computed on the group-key column — the dim must
        # be keyed by the same L column the aggregate groups on
        if fact_link is None or dim_link is None or dim_link != fact_key:
            return None

        sub_items = [e for e in a_items if e is not None]
        sub_idx = {}
        for j, e in enumerate(a_items):
            if e is not None:
                sub_idx[j] = len(sub_idx)

        def combine(es):
            if not es:
                return None
            e = es[0]
            for r in es[1:]:
                e = call("and", e, r)
            return e

        residue = combine(residues)
        if residue is not None:
            refs = input_refs(residue)
            residue = remap_inputs(residue, {
                i: (i if i < nl else nl + sub_idx[i - a_off])
                for i in refs})
        fact_filter = combine(fact_filters)
        dim_filter = combine(dim_filters)

        # ---- final (global) aggregates over L columns only
        final_agg_calls: list[AggCall] = []
        decomp_fin = make_decomp(final_agg_calls, fscope)
        final_items, names, types = [], [], []
        for j, it in enumerate(sel.items):
            e2 = decomp_fin(it.expr)
            if e2 is None:
                return None
            final_items.append(e2)
            names.append(it.alias or auto_name(it.expr, j))
            types.append(e2.ret_type)

        # ---- everything matches: plan the two scans and emit the node
        lf, _, linfo = self.plan_rel(fact_rel)
        df, _, dinfo = self.plan_rel(dim_rel)
        if not (linfo.append_only and dinfo.append_only):
            return None
        wd = 1 if self.cfg("streaming_watchdog", 1) else None
        node = Node("snapshot_join_agg", dict(
            fact_key=fact_key, dim_key=dim_pk,
            sub_agg_calls=sub_agg_calls, sub_items=sub_items,
            residue=residue, final_agg_calls=final_agg_calls,
            final_items=final_items, out_names=names, out_types=types,
            fact_filter=fact_filter, sub_filter=sub_filter,
            dim_filter=dim_filter,
            capacity=self.cfg("streaming_join_capacity", 1 << 17),
            dim_capacity=self.cfg("streaming_agg_capacity", 1 << 16),
            durable=self.durable(), watchdog_interval=wd),
            inputs=(Exchange(lf), Exchange(df)))
        f = self.graph.add(Fragment(self.fid(), node, dispatch="simple"))
        return (f.fid, names, types, (), False, frozenset())

    # ----------------------------------------------------- optimizer passes
    def _optimize_join(self, jinfo, scope: Scope, info: RelInfo,
                       sel: ast.Select):
        """Bind-time rewrite passes on a SELECT directly over a join
        (reference: logical_optimization.rs rules, scoped to the two that
        shape device state):

        1. PREDICATE PUSHDOWN (inner joins): WHERE conjuncts touching one
           side move below the join, shrinking its probe+state input.
           (FilterJoinRule / push_down_filters.)
        2. JOIN INPUT PRUNING: each side's input narrows to the columns
           the join or the SELECT actually uses — on TPU the win is
           direct, join state is dense SoA so every pruned column is HBM
           bandwidth off the per-chunk merge. (PruneJoinRule /
           column pruning.)
        """
        node, nl = jinfo["node"], jinfo["nl"]
        lsch, rsch = jinfo["lsch"], jinfo["rsch"]
        args = node.args

        def refs(e) -> set:
            if isinstance(e, ast.ColRef):
                return {scope.resolve(e)[0]}
            if isinstance(e, ast.BinOp):
                return refs(e.left) | refs(e.right)
            if isinstance(e, ast.UnOp):
                return refs(e.arg)
            if isinstance(e, ast.Func):
                out = set()
                for a in e.args:
                    out |= refs(a)
                return out
            return set()

        # ---- 1. filter pushdown ----
        if sel.where is not None and jinfo["jt"] == "inner":
            from ..expr.ir import remap_inputs

            def push_filter(side: int, pred) -> None:
                inp = node.inputs[side]
                # absorb into a single-consumer upstream fragment so the
                # channel carries filtered chunks; else wrap locally
                if (isinstance(inp, Exchange) and
                        len(self.graph.consumers(inp.upstream)) == 1):
                    up = self.graph.fragments[inp.upstream]
                    up.root = Node("filter", dict(predicate=pred),
                                   inputs=(up.root,))
                else:
                    wrapped = Node("filter", dict(predicate=pred),
                                   inputs=(inp,))
                    node.inputs = tuple(
                        wrapped if i == side else x
                        for i, x in enumerate(node.inputs))

            keep = []
            for conj in split_conjuncts(sel.where):
                cols = refs(conj)
                if cols and max(cols) < nl:
                    push_filter(0, bind_scalar(conj, scope))
                elif cols and min(cols) >= nl:
                    push_filter(1, remap_inputs(
                        bind_scalar(conj, scope),
                        {i: i - nl for i in cols}))
                else:
                    keep.append(conj)
            w = None
            for c in keep:
                w = c if w is None else ast.BinOp("and", w, c)
            sel = ast.Select(sel.items, sel.rel, w, sel.group_by,
                             sel.order_by, sel.limit, sel.offset)

        # ---- 2. join input pruning ----
        used = set(info.stream_key or ())
        for it in sel.items:
            used |= refs(it.expr)
        if sel.where is not None:
            used |= refs(sel.where)
        for g in sel.group_by:
            used |= refs(g)
        for e, _ in sel.order_by:
            try:
                used |= refs(e)          # may be an output alias/ordinal
            except BindError:
                pass
        need_l = {i for i in used if i < nl}
        need_r = {i - nl for i in used if i >= nl}
        need_l |= set(args["left_key_indices"]) | set(args["left_pk_indices"])
        need_r |= set(args["right_key_indices"]) | set(args["right_pk_indices"])
        cond = args.get("condition")
        if cond is not None:
            from ..expr.ir import input_refs
            for i in input_refs(cond):
                (need_l if i < nl else need_r).add(i if i < nl else i - nl)
        specs = args.get("clean_specs") or (None, None)
        for s, spec in enumerate(specs):
            if spec is None:
                continue
            own, other = (need_l, need_r) if s == 0 else (need_r, need_l)
            own.add(spec[1])
            if spec[0] == "band":
                other.add(spec[2])
                if len(spec) > 4 and spec[4] is not None:
                    own.add(spec[4])
        if len(need_l) == nl and len(need_r) == len(rsch):
            return scope, info, sel     # nothing to prune

        keep_l, keep_r = sorted(need_l), sorted(need_r)
        lmap = {o: n for n, o in enumerate(keep_l)}
        rmap = {o: n for n, o in enumerate(keep_r)}
        jmap = {**{o: lmap[o] for o in keep_l},
                **{o + nl: len(keep_l) + rmap[o] for o in keep_r}}
        new_inputs = []
        for keep, sch, inp in ((keep_l, lsch, node.inputs[0]),
                               (keep_r, rsch, node.inputs[1])):
            # prefer absorbing the pruning into the upstream fragment
            # (single-consumer): its projects then COMPUTE only the kept
            # columns and the channel carries narrow chunks
            if (isinstance(inp, Exchange)
                    and self._push_prune_upstream(inp.upstream, keep, sch)):
                new_inputs.append(inp)
            else:
                new_inputs.append(Node("project", dict(
                    exprs=[col(i, sch[i].data_type) for i in keep],
                    names=[sch[i].name for i in keep]),
                    inputs=(inp,)))
        node.inputs = tuple(new_inputs)
        args["left_key_indices"] = [lmap[i] for i in args["left_key_indices"]]
        args["right_key_indices"] = [rmap[i] for i in args["right_key_indices"]]
        args["left_pk_indices"] = [lmap[i] for i in args["left_pk_indices"]]
        args["right_pk_indices"] = [rmap[i] for i in args["right_pk_indices"]]
        if cond is not None:
            from ..expr.ir import remap_inputs
            args["condition"] = remap_inputs(cond, jmap)
        if any(specs):
            def remap_spec(spec, s):
                if spec is None:
                    return None
                m, om = (lmap, rmap) if s == 0 else (rmap, lmap)
                if spec[0] == "band":
                    cap = (m[spec[4]] if len(spec) > 4
                           and spec[4] is not None else None)
                    return ("band", m[spec[1]], om[spec[2]], spec[3], cap)
                return (spec[0], m[spec[1]]) + tuple(spec[2:])
            args["clean_specs"] = (remap_spec(specs[0], 0),
                                   remap_spec(specs[1], 1))
        # rebuild scope / RelInfo over the pruned joined schema
        new_fields = tuple(scope.schema[o]
                           for o in sorted(jmap, key=lambda o: jmap[o]))
        new_scope = Scope(Schema(new_fields),
                          {k: (jmap[i], t) for k, (i, t) in
                           scope.names.items() if i in jmap})
        new_info = RelInfo(
            stream_key=(None if info.stream_key is None
                        else tuple(jmap[i] for i in info.stream_key)),
            append_only=info.append_only,
            wm_cols=frozenset(jmap[i] for i in info.wm_cols if i in jmap))
        return new_scope, new_info, sel

    def _push_prune_upstream(self, up_fid: int, keep: list,
                             sch: Schema) -> bool:
        """Absorb an input pruning into the upstream fragment when this
        join is its only consumer. A `project` root narrows to the kept
        exprs (unneeded window/passthrough computations disappear
        entirely); `row_id_gen` composes through (its serial column is
        always the last kept index); a bare `no_op` gets the project
        grafted above it. Returns False when the upstream is shared or
        has an unsupported root (caller falls back to a local project)."""
        if len(self.graph.consumers(up_fid)) != 1:
            return False
        frag = self.graph.fragments[up_fid]
        # a hash-dispatching fragment routes on OUTPUT positions — they
        # move with the pruning (or block it if a dist key is dropped)
        if frag.dispatch == "hash" and frag.dist_key_indices:
            pos = {o: n for n, o in enumerate(keep)}
            if not all(d in pos for d in frag.dist_key_indices):
                return False
            new_dist = tuple(pos[d] for d in frag.dist_key_indices)
        else:
            new_dist = None

        def prune_project(p: Node, keep_idx: list) -> Node:
            exprs = p.args["exprs"]
            names = p.args.get("names") or [f"e{i}"
                                            for i in range(len(exprs))]
            args = dict(exprs=[exprs[i] for i in keep_idx],
                        names=[names[i] for i in keep_idx])
            tf = p.args.get("watermark_transforms")
            if tf:
                pos = {o: n for n, o in enumerate(keep_idx)}
                new_tf = {}
                for in_col, spec in tf.items():
                    specs = spec if isinstance(spec, list) else [spec]
                    kept = [(pos[o], fn) for o, fn in specs if o in pos]
                    if kept:
                        new_tf[in_col] = kept
                if new_tf:
                    args["watermark_transforms"] = new_tf
            return Node("project", args, inputs=p.inputs)

        def graft(inner: Node, keep_idx: list) -> Node:
            return Node("project", dict(
                exprs=[col(i, sch[i].data_type) for i in keep_idx],
                names=[sch[i].name for i in keep_idx]),
                inputs=(inner,))

        root = frag.root
        if root.kind == "project":
            frag.root = prune_project(root, keep)
        elif root.kind == "row_id_gen":
            rid = len(sch) - 1
            if rid not in keep:
                return False
            inner_keep = [i for i in keep if i < rid]
            inner = root.inputs[0]
            root.inputs = ((prune_project(inner, inner_keep)
                            if inner.kind == "project"
                            else graft(inner, inner_keep)),)
        else:
            # any other root (filter, no_op, stream_scan, agg...): graft
            # the narrowing project on top — the channel still narrows
            frag.root = graft(root, keep)
        if new_dist is not None:
            frag.dist_key_indices = new_dist
        return True

    def _plan_over_window(self, sel: ast.Select, fid: int, scope: Scope,
                          info: RelInfo):
        """SELECT items with OVER clauses -> a general_over_window node
        computing every window function in one pass (reference:
        StreamOverWindow from LogicalOverWindow; all calls must share one
        window definition, like the reference's OverWindow grouping)."""
        from ..common.types import Field
        from ..stream.general_over_window import WindowSpec
        frag = self.graph.fragments[fid]
        if sel.group_by:
            raise BindError(
                "window functions cannot be combined with GROUP BY in "
                "one SELECT; aggregate in a subquery first")
        wfs = [it.expr for it in sel.items
               if isinstance(it.expr, ast.WindowFunc)]
        over0 = (tuple(map(repr, wfs[0].partition_by)),
                 tuple((repr(e), d) for e, d in wfs[0].order_by))
        for w in wfs[1:]:
            if (tuple(map(repr, w.partition_by)),
                    tuple((repr(e), d) for e, d in w.order_by)) != over0:
                raise BindError(
                    "all window functions in one SELECT must share the "
                    "same OVER (PARTITION BY ... ORDER BY ...) clause")

        def col_of(e) -> int:
            if not isinstance(e, ast.ColRef):
                raise BindError(
                    "window PARTITION BY / ORDER BY / arguments must be "
                    "plain columns")
            return scope.resolve(e)[0]

        partition_by = [col_of(e) for e in wfs[0].partition_by]
        order_specs = []
        for e, desc in wfs[0].order_by:
            i = col_of(e)
            if scope.schema[i].data_type is DataType.VARCHAR:
                raise BindError(
                    "window ORDER BY over VARCHAR is unsupported (dict "
                    "encoding is not lexicographic)")
            order_specs.append((i, bool(desc)))
        if not order_specs:
            raise BindError("window functions need ORDER BY in OVER()")

        # retractions address rows by the stream key; keyless append-only
        # inputs get a generated row id (same as join inputs)
        sk = info.stream_key
        if sk is None:
            if not info.append_only:
                raise BindError("keyless retracting over-window input")
            frag.root = Node("row_id_gen", {}, inputs=(frag.root,))
            sch2 = Schema(tuple(scope.schema)
                          + (Field("_row_id", DataType.SERIAL),))
            scope = Scope(sch2, dict(scope.names))
            sk = (len(sch2) - 1,)

        windows = []
        for j, w in enumerate(wfs):
            name = w.func.name
            if name in ("row_number", "rank", "dense_rank"):
                windows.append(WindowSpec(name, name=f"w{j}"))
            elif name in ("lag", "lead"):
                if not w.func.args:
                    raise BindError(f"window {name}() needs an argument")
                ai = col_of(w.func.args[0])
                off = 1
                if len(w.func.args) > 1:
                    a1 = w.func.args[1]
                    if not (isinstance(a1, ast.Lit)
                            and isinstance(a1.value, int)
                            and a1.value >= 1):
                        raise BindError(
                            f"{name}() offset must be a positive "
                            "integer literal")
                    off = a1.value
                windows.append(WindowSpec(
                    name, arg=ai, offset=off, name=f"w{j}"))
            elif name == "first_value":
                if not w.func.args:
                    raise BindError("first_value() needs an argument")
                windows.append(WindowSpec(
                    name, arg=col_of(w.func.args[0]), name=f"w{j}"))
            elif name in ("sum", "count", "avg"):
                if not w.func.args:
                    raise BindError(f"window {name}() needs an argument")
                ai = col_of(w.func.args[0])
                if (name in ("sum", "avg")
                        and scope.schema[ai].data_type
                        is DataType.VARCHAR):
                    raise BindError(
                        f"window {name}() over VARCHAR is meaningless "
                        "(dict ids are not numbers)")
                windows.append(WindowSpec(
                    name, arg=ai, preceding=w.preceding, name=f"w{j}"))
            else:
                raise BindError(
                    f"unsupported window function {name!r} (have: "
                    "row_number, rank, dense_rank, lag, lead, "
                    "first_value, sum, count, avg)")

        eowc = getattr(sel, "emit_on_close", False)
        if eowc:
            # EMIT ON WINDOW CLOSE: the leading ORDER BY column must be
            # watermarked ascending so row finality is decidable
            oc, odesc = order_specs[0]
            if odesc or oc not in info.wm_cols:
                raise BindError(
                    "EMIT ON WINDOW CLOSE needs the leading window "
                    "ORDER BY column ascending and watermarked")
            if any(w.kind == "lead" for w in windows):
                raise BindError(
                    "EMIT ON WINDOW CLOSE cannot finalize lead()")
        ow_args = dict(
            partition_by=partition_by, order_specs=order_specs,
            windows=windows, pk_indices=list(sk),
            capacity=self.cfg("streaming_over_window_capacity", 1 << 14),
            durable=self.durable())
        if not eowc:
            # mesh mode: partitions shard over the device mesh inside
            # ONE executor (partition-key routing keeps frames local);
            # the EOWC variant stays single-device (frontier state is
            # host-ordered)
            ow_args.update(
                mesh_devices=self.cfg("streaming_parallelism_devices", 1),
                mesh_shuffle=self.cfg("streaming_mesh_shuffle", 1),
                mesh_shuffle_slack=self.cfg(
                    "streaming_mesh_shuffle_slack", 0),
                mesh_shuffle_adaptive=self.cfg(
                    "streaming_mesh_shuffle_adaptive", 1),
                mesh_chain=self.cfg("streaming_mesh_chain", 1),
                watchdog_interval=(
                    1 if self.cfg("streaming_watchdog", 1) else None))
        frag.root = Node(
            "eowc_over_window" if eowc else "general_over_window",
            ow_args, inputs=(frag.root,))
        in_width = len(scope.schema)
        win_fields = []
        out_sch = list(scope.schema)
        for w2 in windows:
            t = w2.ret_type(scope.schema)
            out_sch.append(Field(w2.name, t))
            win_fields.append(t)
        ext_scope = Scope(Schema(tuple(out_sch)), dict(scope.names))

        # final projection: SELECT order + hidden stream-key columns
        exprs, names = [], []
        wj = 0
        for j, it in enumerate(sel.items):
            if isinstance(it.expr, ast.WindowFunc):
                exprs.append(col(in_width + wj, win_fields[wj]))
                names.append(it.alias or f"w{wj}")
                wj += 1
            else:
                exprs.append(bind_scalar(it.expr, ext_scope))
                names.append(it.alias or auto_name(it.expr, j))
        from ..expr.ir import InputRef
        key_pos = []
        for ki in sk:
            found = None
            for j2, e2 in enumerate(exprs):
                if isinstance(e2, InputRef) and e2.index == ki:
                    found = j2
                    break
            if found is None:
                exprs.append(col(ki, ext_scope.schema[ki].data_type))
                names.append(f"_sk{ki}")
                found = len(exprs) - 1
            key_pos.append(found)
        frag.root = Node("project", dict(exprs=exprs, names=names),
                         inputs=(frag.root,))
        # EOWC output is append-only (final rows, exactly once) and
        # carries the watermark forward on the order column if selected
        wm_out = frozenset()
        if eowc:
            oc = order_specs[0][0]
            wm_out = frozenset(
                j2 for j2, e2 in enumerate(exprs)
                if isinstance(e2, InputRef) and e2.index == oc)
        return (fid, names, [e.ret_type for e in exprs], tuple(key_pos),
                eowc, wm_out)

    def _plan_top_n(self, top_spec, planned):
        """Streaming ORDER BY + LIMIT -> RetractableTopN over the query's
        changelog (reference: StreamTopN; retraction-capable because the
        input may be an agg/join changelog)."""
        order_by, limit, offset = top_spec
        fid, names, types, pk_hint, append_only, _wm = planned
        frag = self.graph.fragments[fid]
        order_specs = []
        for e, desc in order_by:
            idx = None
            if isinstance(e, ast.Lit) and isinstance(e.value, int):
                idx = e.value - 1
            elif isinstance(e, ast.ColRef) and e.qualifier is None \
                    and e.name in names:
                idx = names.index(e.name)
            if idx is None or not 0 <= idx < len(names):
                raise BindError(
                    "streaming ORDER BY must name an output column")
            if types[idx] is DataType.VARCHAR:
                # dict ids order by insertion, not lexicographically; a
                # streaming TopN over them would silently return wrong
                # rows (ADVICE r3 #2) — the batch path ranks decoded
                # strings, so point users there
                raise BindError(
                    "streaming ORDER BY over VARCHAR is unsupported "
                    "(dict encoding is not lexicographic); ORDER BY in "
                    "a batch SELECT over the MV instead")
            order_specs.append((idx, bool(desc)))
        if pk_hint is None:
            raise BindError(
                "streaming TopN over a keyless stream is unsupported "
                "(add GROUP BY or aggregate first)")
        # the TopN is a SINGLETON fragment (default parallelism=1)
        # downstream of the (possibly hash-parallel) input: per-shard
        # top-Ns would union to up to limit*parallelism wrong rows
        # (reference: StreamTopN is a singleton below the hash agg).
        # Mesh mode: still ONE actor, but the store shards over the
        # N-device mesh inside the executor (stream-key routing +
        # candidate all_gather keep the global rank exact)
        md = self.cfg("streaming_parallelism_devices", 1)
        wd = 1 if self.cfg("streaming_watchdog", 1) else None
        top = self.graph.add(Fragment(self.fid(), Node(
            "retract_top_n", dict(
                group_key_indices=(), order_specs=order_specs,
                limit=limit, offset=offset, durable=self.durable(),
                pk_indices=list(pk_hint),
                capacity=self.cfg("streaming_top_n_capacity", 1 << 14),
                mesh_devices=md,
                mesh_shuffle=self.cfg("streaming_mesh_shuffle", 1),
                mesh_shuffle_slack=self.cfg(
                    "streaming_mesh_shuffle_slack", 0),
                mesh_shuffle_adaptive=self.cfg(
                    "streaming_mesh_shuffle_adaptive", 1),
                mesh_chain=self.cfg("streaming_mesh_chain", 1),
                watchdog_interval=wd),
            inputs=(Exchange(fid),)), dispatch="simple"))
        # ranks can change retroactively: no watermark survives a TopN
        return top.fid, names, types, pk_hint, False, frozenset()

    def _plan_agg(self, sel: ast.Select, fid: int, scope: Scope,
                  info: RelInfo):
        from ..common.types import Field
        frag = self.graph.fragments[fid]
        # pre-project: group keys then agg args
        keys = [bind_scalar(g, scope) for g in sel.group_by]
        key_names = [auto_name(g, j) for j, g in enumerate(sel.group_by)]
        agg_specs = []           # (kind, pre_col or None)
        pre_exprs = list(keys)
        pre_names = list(key_names)

        def add_arg(e) -> int:
            pre_exprs.append(bind_scalar(e, scope))
            pre_names.append(f"a{len(pre_exprs)}")
            return len(pre_exprs) - 1

        # map SELECT items onto (group key | agg output) slots
        items_plan = []          # per item: ("key", idx) | ("agg", idx) | ("avg", s, c)
        agg_calls: list[AggCall] = []

        def add_call(kind: AggKind, arg: Optional[int],
                     ret: DataType) -> int:
            # append-only inputs get the cheap agg variants (running
            # max/min instead of retractable top-K buffers) — the
            # reference picks them by the same plan property
            agg_calls.append(AggCall(kind, arg, ret,
                                     append_only=info.append_only))
            return len(agg_calls) - 1

        nk = len(keys)

        def agg_post(e) -> Expr:
            """One aggregate call -> its post-project expression over
            [keys..., agg outputs...]."""
            if e.name in ("bool_and", "bool_or"):
                # fully retractable via two counts (reference
                # impl/src/aggregate/bool_and.rs keeps the same pair):
                # cn = non-null inputs, cf = false (bool_and) / true
                # (bool_or) inputs; NULL when cn = 0
                x = e.args[0]
                cn = add_call(AggKind.COUNT, add_arg(x), DataType.INT64)
                inner = ast.UnOp("not", x) if e.name == "bool_and" else x
                hit = ast.Func("case", [inner, ast.Lit(1)])
                cf = add_call(AggKind.COUNT, add_arg(hit),
                              DataType.INT64)
                cond = call("greater_than",
                            col(nk + cn, DataType.INT64), lit(0))
                val = call("equal" if e.name == "bool_and"
                           else "greater_than",
                           col(nk + cf, DataType.INT64), lit(0))
                return call("case", cond, val)
            if e.name == "approx_count_distinct":
                # 8 hidden register-word calls + estimate projection
                # (expr/hll.py); NULL when the group saw no rows
                if not info.append_only:
                    raise BindError(
                        "approx_count_distinct needs an append-only "
                        "input (register max cannot retract)")
                a = add_arg(e.args[0])
                cn = add_call(AggKind.COUNT, a, DataType.INT64)
                lanes = []
                for L in range(8):
                    agg_calls.append(AggCall(
                        AggKind.HLL_REG, a, DataType.INT64,
                        append_only=True, lane=L))
                    lanes.append(len(agg_calls) - 1)
                est = call("hll_estimate",
                           *[col(nk + j, DataType.INT64) for j in lanes])
                cond = call("greater_than",
                            col(nk + cn, DataType.INT64), lit(0))
                return call("case", cond, est)
            if e.name == "count":
                idx = add_call(AggKind.COUNT,
                               None if e.star else add_arg(e.args[0]),
                               DataType.INT64)
                return col(nk + idx, DataType.INT64)
            if e.name == "avg":
                a = add_arg(e.args[0])
                s = add_call(AggKind.SUM, a, DataType.FLOAT64)
                c = add_call(AggKind.COUNT, a, DataType.INT64)
                return call("divide", col(nk + s, DataType.FLOAT64),
                            col(nk + c, DataType.INT64))
            if e.name == "sum":
                a = add_arg(e.args[0])
                at = pre_exprs[a].ret_type
                ret = (DataType.FLOAT64
                       if at in (DataType.FLOAT64, DataType.FLOAT32)
                       else DataType.INT64)
                return col(nk + add_call(AggKind.SUM, a, ret), ret)
            a = add_arg(e.args[0])
            kind = AggKind.MIN if e.name == "min" else AggKind.MAX
            at = pre_exprs[a].ret_type
            if at is DataType.VARCHAR:
                # same hazard as the streaming ORDER BY guard: dict ids
                # are not lexicographic, and the stream agg reduces raw
                # ids — batch SELECTs rank the decoded strings instead
                raise BindError(
                    f"streaming {e.name}() over VARCHAR is unsupported "
                    "(dict encoding is not lexicographic); aggregate in "
                    "a batch SELECT over the MV instead")
            return col(nk + add_call(kind, a, at), at)

        def post_of(e) -> Expr:
            """Scalar expression OVER aggregates/keys (sum(x)/7.0,
            0.2*avg(q), sum(x)*(k+1), ...) -> post-project expression
            (reference: the planner splits such items into LogicalAgg +
            LogicalProject the same way). A GROUP BY key may match at
            ANY level; other agg-free leaves must be literal-only."""
            if isinstance(e, ast.Func) and e.name in AGG_FUNCS:
                return agg_post(e)
            if isinstance(e, ast.Lit):
                return lit(e.value)
            if not contains_agg(e):
                bound = bind_scalar(e, scope)
                for kj, ke in enumerate(keys):
                    if repr(ke) == repr(bound):
                        return col(kj, keys[kj].ret_type)
            if isinstance(e, ast.BinOp):
                return call(e.op, post_of(e.left), post_of(e.right))
            if isinstance(e, ast.UnOp):
                return call(e.op, post_of(e.arg))
            raise BindError(
                f"{e}: non-aggregate parts of a SELECT item must appear "
                f"in GROUP BY")

        post, names = [], []
        for j, it in enumerate(sel.items):
            e = it.expr
            names.append(it.alias or auto_name(e, j))
            if not contains_agg(e):
                bound = bind_scalar(e, scope)
                for kj, ke in enumerate(keys):
                    if repr(ke) == repr(bound):
                        items_plan.append(("key", kj))
                        post.append(col(kj, keys[kj].ret_type))
                        break
                else:
                    raise BindError(
                        f"{it.alias or e}: non-aggregate SELECT item "
                        f"must appear in GROUP BY")
            else:
                items_plan.append(("expr",))
                post.append(post_of(e))

        frag.root = Node("project", dict(exprs=pre_exprs, names=pre_names),
                         inputs=(frag.root,))
        # group keys that are direct refs to watermarked input columns:
        # the first becomes the agg's state-cleaning column (groups below
        # the watermark can never change again — reference: the agg's
        # state-cleaning watermark from watermark inference)
        from ..expr.ir import InputRef
        wm_keys = [kj for kj, ke in enumerate(keys)
                   if isinstance(ke, InputRef) and ke.index in info.wm_cols]
        wd = 1 if self.cfg("streaming_watchdog", 1) else None
        if keys:
            frag.dispatch = "hash"
            frag.dist_key_indices = tuple(range(len(keys)))
            # mesh mode: ONE actor whose state shards over an N-device
            # jax Mesh inside the executor (the dispatcher+merge pair
            # collapses into the jitted step; SURVEY §2.3)
            md = self.cfg("streaming_parallelism_devices", 1)
            agg = self.graph.add(Fragment(self.fid(), Node(
                "hash_agg", dict(
                    group_key_indices=list(range(len(keys))),
                    agg_calls=agg_calls, durable=self.durable(),
                    capacity=self.cfg("streaming_agg_capacity", 1 << 16),
                    cleaning_watermark_col=(wm_keys[0] if wm_keys
                                            else None),
                    mesh_devices=md,
                    mesh_shuffle=self.cfg("streaming_mesh_shuffle", 1),
                    mesh_shuffle_slack=self.cfg(
                        "streaming_mesh_shuffle_slack", 0),
                    mesh_shuffle_adaptive=self.cfg(
                        "streaming_mesh_shuffle_adaptive", 1),
                    mesh_chain=self.cfg("streaming_mesh_chain", 1),
                    watchdog_interval=wd),
                inputs=(Exchange(fid),)),
                dispatch="hash",
                dist_key_indices=tuple(range(len(keys))),
                parallelism=(1 if md > 1 else self.parallelism)))
        else:
            # global aggregation: a singleton SimpleAgg fragment
            # (reference: DistId::Singleton, simple_agg.rs)
            frag.dispatch = "simple"
            agg = self.graph.add(Fragment(self.fid(), Node(
                "simple_agg", dict(agg_calls=agg_calls, durable=self.durable()),
                inputs=(Exchange(fid),)),
                dispatch="simple"))

        # MV pk = the group keys, which must survive projection: append any
        # key not already selected
        pk = []
        key_out = {}
        for kj in range(nk):
            found = None
            for j, plan in enumerate(items_plan):
                if plan[0] == "key" and plan[1] == kj:
                    found = j
                    break
            if found is None:
                post.append(col(kj, keys[kj].ret_type))
                names.append(f"_key{kj}")
                found = len(post) - 1
            pk.append(found)
            key_out[kj] = found
        agg.root = Node("project", dict(exprs=post, names=names),
                        inputs=(agg.root,))
        # group-key watermarks pass through the agg re-indexed, then
        # through the post-project on their InputRef positions
        wm_out = frozenset(key_out[kj] for kj in wm_keys)
        return (agg.fid, names, [e.ret_type for e in post], tuple(pk),
                wm_out)


def split_conjuncts(e) -> list:
    if isinstance(e, ast.BinOp) and e.op == "and":
        return split_conjuncts(e.left) + split_conjuncts(e.right)
    return [e]


def band_bound(conj, ls: Scope, rs: Scope, lwm: frozenset, rwm: frozenset):
    """Interval-join cleaning derivation (reference: the condition
    analysis behind stream interval joins): a comparison conjunct
    normalizing to `X.a > Y.o + d` (op may be any of > >= < <=, the small
    side affine in one column) lets side X evict rows with a below
    wm(Y.o) + d — every FUTURE Y row has o >= wm(Y.o), so old X rows fall
    out of every future band. Requires Y.o to carry a watermark. Returns
    (side_of_X, a_index, o_index, d) or None."""
    if not isinstance(conj, ast.BinOp):
        return None
    if conj.op in ("greater_than", "greater_than_or_equal"):
        big, small = conj.left, conj.right
    elif conj.op in ("less_than", "less_than_or_equal"):
        big, small = conj.right, conj.left
    else:
        return None

    def affine(e):
        if isinstance(e, ast.ColRef):
            return e, 0
        if isinstance(e, ast.BinOp) and e.op in ("add", "subtract"):
            if (isinstance(e.left, ast.ColRef) and isinstance(e.right, ast.Lit)
                    and isinstance(e.right.value, int)):
                return e.left, (e.right.value if e.op == "add"
                                else -e.right.value)
            if (e.op == "add" and isinstance(e.right, ast.ColRef)
                    and isinstance(e.left, ast.Lit)
                    and isinstance(e.left.value, int)):
                return e.right, e.left.value
        return None

    bg = affine(big)
    sm = affine(small)
    if bg is None or sm is None:
        return None
    # normalize (big_col + bd) > (small_col + sd)  ->  big_col >
    # small_col + (sd - bd), so `b.dt <= a.dt + 10` also cleans side a
    big, bd = bg
    other_ref, sd = sm
    delta = sd - bd

    def side_of(ref):
        try:
            return ("l", ls.resolve(ref)[0])
        except BindError:
            pass
        try:
            return ("r", rs.resolve(ref)[0])
        except BindError:
            return None

    sb, so = side_of(big), side_of(other_ref)
    if sb is None or so is None or sb[0] == so[0]:
        return None
    if (so[1] not in lwm) if so[0] == "l" else (so[1] not in rwm):
        return None
    return sb[0], sb[1], so[1], delta


def equi_pair(e, ls: Scope, rs: Scope) -> Optional[tuple[int, int]]:
    """col_of_left = col_of_right -> (left_idx, right_idx)."""
    if not (isinstance(e, ast.BinOp) and e.op == "equal"):
        return None
    a, b = e.left, e.right
    if not (isinstance(a, ast.ColRef) and isinstance(b, ast.ColRef)):
        return None

    def side(ref):
        try:
            return ("l", ls.resolve(ref)[0])
        except BindError:
            pass
        try:
            return ("r", rs.resolve(ref)[0])
        except BindError:
            return None

    sa, sb = side(a), side(b)
    if sa is None or sb is None or sa[0] == sb[0]:
        return None
    if sa[0] == "l":
        return (sa[1], sb[1])
    return (sb[1], sa[1])


def expand_star(items, schema) -> list:
    """SELECT * -> one item per schema column (aliases = column names),
    skipping internal columns like _row_id."""
    out = []
    for it in items:
        if isinstance(it.expr, ast.ColRef) and it.expr.name == "*":
            for f in schema:
                if not f.name.startswith("_"):
                    out.append(ast.SelectItem(ast.ColRef(f.name), f.name))
        else:
            out.append(it)
    return out


def auto_name(e, j: int) -> str:
    if isinstance(e, ast.ColRef):
        return e.name
    if isinstance(e, ast.Func):
        return e.name
    return f"expr{j}"

def _now_conjunct(conj, scope):
    """`col OP now()` (either side) -> (col_index, dynamic-filter op)."""
    if not isinstance(conj, ast.BinOp):
        return None
    ops = {"greater_than", "greater_than_or_equal", "less_than",
           "less_than_or_equal"}
    if conj.op not in ops:
        return None

    def is_now(e):
        return isinstance(e, ast.Func) and e.name == "now" and not e.args

    flip = {"greater_than": "less_than",
            "greater_than_or_equal": "less_than_or_equal",
            "less_than": "greater_than",
            "less_than_or_equal": "greater_than_or_equal"}
    if isinstance(conj.left, ast.ColRef) and is_now(conj.right):
        return scope.resolve(conj.left)[0], conj.op
    if is_now(conj.left) and isinstance(conj.right, ast.ColRef):
        return scope.resolve(conj.right)[0], flip[conj.op]
    return None

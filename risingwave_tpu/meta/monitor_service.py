"""MonitorService — the dependency-free HTTP observability endpoint.

Reference: the compute node's MonitorService (src/compute/src/rpc/
service/monitor_service.rs serves await-tree stack traces + profiling
over gRPC) and the Prometheus exporter every node embeds. Collapsed to
one tiny asyncio HTTP/1.0 listener (stdlib only — no aiohttp, no
prometheus_client) so a REAL Prometheus can scrape a running session
and an operator can curl the stuck-barrier evidence:

    /metrics          full text exposition (render_prometheus)
    /healthz          JSON liveness: committed epoch, barrier p50,
                      in-flight epochs, actor count
    /debug/traces     recent + in-flight epoch spans (the \\trace verb)
    /debug/await_tree every task's await stack (the \\stacks verb)

Off by default; `SET monitor_port = <port>` starts it (0 stops it).
Handlers run on the event loop and only READ host state — a scrape can
never dispatch device work or block a barrier.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional


def merge_worker_label(text: str, worker: str) -> str:
    """Re-emit one compute node's exposition with a `worker` label on
    every series, so the meta /metrics shows the whole cluster under one
    scrape (the reference runs one exporter per node and relies on
    Prometheus relabelling; the dependency-free monitor does the merge
    itself). `# TYPE`/`# HELP` lines pass through — the registry dedupes
    duplicate TYPE lines at parse time on the Prometheus side."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        head, _, value = line.rpartition(" ")
        if not head:
            out.append(line)
            continue
        if "{" in head:
            name, _, rest = head.partition("{")
            out.append(f'{name}{{worker="{worker}",{rest} {value}')
        else:
            out.append(f'{head}{{worker="{worker}"}} {value}')
    return "\n".join(out)


class MonitorService:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self._session = session          # live handle: coord may be
        self._host = host                # swapped by auto-recovery
        self._requested_port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None

    async def start(self) -> "MonitorService":
        self._server = await asyncio.start_server(
            self._handle, self._host, self._requested_port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
            self.port = None

    # ------------------------------------------------------------ routing
    def _route(self, path: str) -> tuple[int, str, str]:
        """-> (status, content_type, body). Pure host reads."""
        from ..utils.metrics import GLOBAL_METRICS
        coord = self._session.coord
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    GLOBAL_METRICS.render_prometheus())
        if path == "/healthz":
            payload = {
                "status": "ok",
                "committed_epoch": self._session.store.committed_epoch(),
                "barrier_latency_p50_s":
                    coord.barrier_latency_percentile(0.5),
                "inflight_epochs": len(coord._epochs),
                "actors": len(coord.actor_ids),
                # fused mesh fragments: actor -> device-shard count (each
                # collects per epoch as ONE actor; plan/build.py
                # _register_mesh)
                "mesh_fragments": {str(aid): n for aid, (n, _)
                                   in coord.mesh_fragments.items()},
                "recoveries": self._session.recoveries,
            }
            # flap detector (frontend/session.py flapping_causes): a
            # cause recovering faster than recovery_flap_threshold per
            # window marks the session DEGRADED — converging, but the
            # fault keeps coming back
            flap = getattr(self._session, "flapping_causes", None)
            causes = flap() if flap is not None else []
            # storage-plane health (state/hummock.py read-path rules):
            # a quarantined object means durable corruption was seen —
            # the session stays DEGRADED (even after a successful
            # restore-from-backup healed the primary copy) until an
            # operator inspects the quarantine/ evidence
            quarantined = list(
                getattr(self._session.store, "quarantined", ()) or ())
            if quarantined:
                payload["storage"] = {
                    "quarantined": quarantined,
                    "restored_from_backup": list(getattr(
                        self._session.store, "restored_objects", ())),
                }
            payload["degraded"] = bool(causes) or bool(quarantined)
            if causes:
                payload["flapping_causes"] = causes
            last = getattr(self._session, "last_recovery", None)
            if last is not None:
                # cause/scope/duration of the most recent auto-recovery
                # (the recovery-time SLO's operator surface)
                payload["last_recovery"] = last
            body = json.dumps(payload)
            return 200, "application/json", body + "\n"
        if path == "/debug/traces":
            lines = []
            stuck = coord.tracer.open_traces()
            if stuck:
                lines.append("== in-flight epochs ==")
                lines.extend(t.render() for t in stuck)
            lines.append("== recent epochs ==")
            lines.extend(t.render() for t in coord.tracer.recent())
            rec = coord.tracer.render_recoveries()
            if rec:
                lines.append("== recoveries ==")
                lines.extend(rec)
            return 200, "text/plain; charset=utf-8", "\n".join(lines) + "\n"
        if path == "/debug/await_tree":
            from ..utils.trace import dump_task_tree
            return (200, "text/plain; charset=utf-8",
                    dump_task_tree() + "\n")
        return 404, "text/plain; charset=utf-8", "not found\n"

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5)
            parts = request.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            path = path.split("?", 1)[0]
            # drain headers (we never need them; HTTP/1.0, close after)
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5)
                if line in (b"\r\n", b"\n", b""):
                    break
            try:
                status, ctype, body = self._route(path)
                cluster = getattr(self._session, "cluster", None)
                if path == "/metrics" and cluster is not None:
                    # one scrape sees the whole cluster: every live
                    # compute node's series merged under worker="wN"
                    # (the meta process's own series carry no label)
                    parts = [body.rstrip("\n")]
                    for wid, text in (await cluster.scrape_all()).items():
                        parts.append(merge_worker_label(text.rstrip("\n"),
                                                        f"w{wid}"))
                    body = "\n".join(parts) + "\n"
            except Exception as e:        # a scrape must never kill us
                status, ctype, body = (500, "text/plain",
                                       f"internal error: {e}\n")
            reason = {200: "OK", 404: "Not Found",
                      500: "Internal Server Error"}.get(status, "OK")
            payload = body.encode("utf-8", "replace")
            writer.write(
                f"HTTP/1.0 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n".encode("latin-1"))
            writer.write(payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

"""MonitorService — the dependency-free HTTP observability endpoint.

Reference: the compute node's MonitorService (src/compute/src/rpc/
service/monitor_service.rs serves await-tree stack traces + profiling
over gRPC) and the Prometheus exporter every node embeds. Collapsed to
one tiny asyncio HTTP/1.0 listener (stdlib only — no aiohttp, no
prometheus_client) so a REAL Prometheus can scrape a running session
and an operator can curl the stuck-barrier evidence:

    /metrics                  full text exposition (render_prometheus)
    /healthz                  JSON liveness: committed epoch, barrier
                              p50, in-flight epochs, actor count
    /debug/traces             recent + in-flight epoch spans — stitched
                              across workers in cluster mode;
                              ?format=json | ?format=chrome (Perfetto)
    /debug/await_tree         every task's await stack; cluster mode
                              appends one section per live worker
    /debug/events?since=ts    the durable event log (meta/event_log.py)
    /debug/profile/cpu?seconds=N    collapsed-stack cpu samples
    /debug/profile/heap?seconds=N   tracemalloc top-N allocation diff
    /debug/profile/device           per-executor HBM + jax live buffers

Off by default; `SET monitor_port = <port>` starts it (0 stops it).
Read-only handlers run on the event loop and only READ host state; the
on-demand profilers run their timed sampling on a worker thread
(`asyncio.to_thread`) so even a 10s profile never blocks a barrier. In
cluster mode every profile/dump endpoint fans out to the live workers
over rpc.py and merges their output under `wN` prefixes, mirroring the
/metrics merge.
"""

from __future__ import annotations

import asyncio
import json
from typing import Optional
from urllib.parse import parse_qsl


def merge_worker_label(text: str, worker: str) -> str:
    """Re-emit one compute node's exposition with a `worker` label on
    every series, so the meta /metrics shows the whole cluster under one
    scrape (the reference runs one exporter per node and relies on
    Prometheus relabelling; the dependency-free monitor does the merge
    itself). `# TYPE`/`# HELP` lines pass through — the registry dedupes
    duplicate TYPE lines at parse time on the Prometheus side."""
    out = []
    for line in text.splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        head, _, value = line.rpartition(" ")
        if not head:
            out.append(line)
            continue
        if "{" in head:
            name, _, rest = head.partition("{")
            out.append(f'{name}{{worker="{worker}",{rest} {value}')
        else:
            out.append(f'{head}{{worker="{worker}"}} {value}')
    return "\n".join(out)


def merge_profile(kind: str, local: str,
                  worker_texts: dict) -> str:
    """Merge per-worker profile text under the local (meta) output.
    cpu profiles are collapsed stacks — the worker becomes the stack
    ROOT frame (`wN;...`), so a flamegraph shows one subtree per
    worker; heap/device rows get a `wN/` path prefix like the
    memory-report merge."""
    parts = [local.rstrip("\n")]
    for wid in sorted(worker_texts):
        pref = f"w{wid}"
        for line in str(worker_texts[wid]).splitlines():
            if not line.strip():
                continue
            if line.startswith("#"):
                parts.append(f"# {pref}: {line.lstrip('# ')}")
            elif kind == "cpu":
                parts.append(f"{pref};{line}")
            else:
                parts.append(f"{pref}/{line}")
    return "\n".join(parts) + "\n"


_TEXT = "text/plain; charset=utf-8"
_JSON = "application/json"


class MonitorService:
    def __init__(self, session, host: str = "127.0.0.1", port: int = 0):
        self._session = session          # live handle: coord may be
        self._host = host                # swapped by auto-recovery
        self._requested_port = port
        self._server: Optional[asyncio.AbstractServer] = None
        self.port: Optional[int] = None

    async def start(self) -> "MonitorService":
        self._server = await asyncio.start_server(
            self._handle, self._host, self._requested_port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None
            self.port = None

    # ------------------------------------------------------------ routing
    def _route(self, path: str) -> tuple[int, str, str]:
        """Sync routes: pure host reads, no awaits."""
        from ..utils.metrics import GLOBAL_METRICS
        coord = self._session.coord
        if path == "/metrics":
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    GLOBAL_METRICS.render_prometheus())
        if path == "/healthz":
            payload = {
                "status": "ok",
                "committed_epoch": self._session.store.committed_epoch(),
                "barrier_latency_p50_s":
                    coord.barrier_latency_percentile(0.5),
                "inflight_epochs": len(coord._epochs),
                "actors": len(coord.actor_ids),
                # fused mesh fragments: actor -> device-shard count (each
                # collects per epoch as ONE actor; plan/build.py
                # _register_mesh)
                "mesh_fragments": {str(aid): n for aid, (n, _)
                                   in coord.mesh_fragments.items()},
                "recoveries": self._session.recoveries,
            }
            # flap detector (frontend/session.py flapping_causes): a
            # cause recovering faster than recovery_flap_threshold per
            # window marks the session DEGRADED — converging, but the
            # fault keeps coming back
            flap = getattr(self._session, "flapping_causes", None)
            causes = flap() if flap is not None else []
            # storage-plane health (state/hummock.py read-path rules):
            # a quarantined object means durable corruption was seen —
            # the session stays DEGRADED (even after a successful
            # restore-from-backup healed the primary copy) until an
            # operator inspects the quarantine/ evidence
            quarantined = list(
                getattr(self._session.store, "quarantined", ()) or ())
            if quarantined:
                payload["storage"] = {
                    "quarantined": quarantined,
                    "restored_from_backup": list(getattr(
                        self._session.store, "restored_objects", ())),
                }
            payload["degraded"] = bool(causes) or bool(quarantined)
            if causes:
                payload["flapping_causes"] = causes
            last = getattr(self._session, "last_recovery", None)
            if last is not None:
                # cause/scope/duration of the most recent auto-recovery
                # (the recovery-time SLO's operator surface)
                payload["last_recovery"] = last
            body = json.dumps(payload)
            return 200, _JSON, body + "\n"
        if path == "/debug/traces":
            # text render is a pure host read; the async router adds
            # the format= variants on top of this same handler
            return self._route_traces({})
        return 404, _TEXT, "not found\n"

    def _recovery_source(self):
        """Recovery spans prefer the SESSION-owned ring (it survives
        the coordinator swap a full recovery performs); the tracer's
        back-compat mirror covers shims without one."""
        ring = getattr(self._session, "recovery_ring", None)
        return ring if ring is not None else self._session.coord.tracer

    def _route_traces(self, params: dict) -> tuple[int, str, str]:
        from ..utils.trace import traces_to_chrome, traces_to_json
        coord = self._session.coord
        stuck = coord.tracer.open_traces()
        recent = coord.tracer.recent()
        fmt = params.get("format", "text")
        if fmt == "json":
            rec = list(self._recovery_source().recoveries)
            body = json.dumps(traces_to_json(stuck + recent, rec))
            return 200, _JSON, body + "\n"
        if fmt == "chrome":
            body = json.dumps(traces_to_chrome(stuck + recent))
            return 200, _JSON, body + "\n"
        lines = []
        if stuck:
            lines.append("== in-flight epochs ==")
            lines.extend(t.render() for t in stuck)
        lines.append("== recent epochs ==")
        lines.extend(t.render() for t in recent)
        rec = self._recovery_source().render_recoveries()
        if rec:
            lines.append("== recoveries ==")
            lines.extend(rec)
        return 200, _TEXT, "\n".join(lines) + "\n"

    async def _route_async(self, path: str,
                           params: dict) -> tuple[int, str, str]:
        """Full router: async routes (cluster fan-outs, timed
        profilers) first, then the sync reads."""
        from ..utils.metrics import GLOBAL_METRICS
        session = self._session
        cluster = getattr(session, "cluster", None)
        if path == "/metrics":
            body = GLOBAL_METRICS.render_prometheus()
            if cluster is not None:
                # one scrape sees the whole cluster: every live
                # compute node's series merged under worker="wN"
                # (the meta process's own series carry no label)
                parts = [body.rstrip("\n")]
                for wid, text in (await cluster.scrape_all()).items():
                    parts.append(merge_worker_label(text.rstrip("\n"),
                                                    f"w{wid}"))
                body = "\n".join(parts) + "\n"
            return (200, "text/plain; version=0.0.4; charset=utf-8",
                    body)
        if path == "/debug/traces":
            return self._route_traces(params)
        if path == "/debug/await_tree":
            from ..utils.trace import dump_task_tree
            body = dump_task_tree() + "\n"
            if cluster is not None:
                for wid, text in sorted(
                        (await cluster.dump_tasks_all()).items()):
                    body += f"== worker w{wid} ==\n{text}\n"
            return 200, _TEXT, body
        if path == "/debug/events":
            log = getattr(session, "event_log", None)
            try:
                limit = (int(params["limit"])
                         if "limit" in params else None)
                since = (float(params["since"])
                         if "since" in params else None)
            except ValueError:
                return 400, _TEXT, "bad since/limit\n"
            kind = params.get("kind")
            recs = [] if log is None else log.records(
                limit=limit, since=since, kind=kind)
            if cluster is not None:
                # one endpoint sees the whole cluster: each worker's
                # durable log stitched in under worker="wN" (meta's
                # own records carry worker="meta"), merged by ts
                recs = [dict(r, worker="meta") for r in recs]
                per_worker = await cluster.events_all(
                    limit=limit, kind=kind, since=since)
                session._worker_events_cache = per_worker
                for wid, wrecs in sorted(per_worker.items()):
                    recs.extend(dict(r, worker=f"w{wid}")
                                for r in wrecs)
                recs.sort(key=lambda r: r.get("ts", 0))
                if limit is not None:
                    recs = recs[-limit:]
            return 200, _JSON, json.dumps(recs) + "\n"
        if path.startswith("/debug/profile/"):
            kind = path.rsplit("/", 1)[-1]
            if kind not in ("cpu", "heap", "device"):
                return 404, _TEXT, f"unknown profile {kind!r}\n"
            try:
                seconds = float(params.get("seconds", 2.0))
            except ValueError:
                return 400, _TEXT, "bad seconds\n"
            from ..utils import profiler
            if kind == "cpu":
                local_coro = asyncio.to_thread(
                    profiler.profile_cpu, seconds)
            elif kind == "heap":
                local_coro = asyncio.to_thread(
                    profiler.profile_heap, seconds)
            else:
                async def _dev():
                    return profiler.profile_device(session.coord)
                local_coro = _dev()
            if cluster is None:
                return 200, _TEXT, await local_coro
            # local profile and worker fan-out sample the SAME window
            local, workers = await asyncio.gather(
                local_coro, cluster.profile_all(kind, seconds))
            return 200, _TEXT, merge_profile(kind, local, workers)
        return self._route(path)

    async def _handle(self, reader: asyncio.StreamReader,
                      writer: asyncio.StreamWriter) -> None:
        try:
            request = await asyncio.wait_for(reader.readline(), timeout=5)
            parts = request.decode("latin-1", "replace").split()
            path = parts[1] if len(parts) >= 2 else "/"
            path, _, query = path.partition("?")
            params = dict(parse_qsl(query))
            # drain headers (we never need them; HTTP/1.0, close after)
            while True:
                line = await asyncio.wait_for(reader.readline(), timeout=5)
                if line in (b"\r\n", b"\n", b""):
                    break
            try:
                status, ctype, body = await self._route_async(path,
                                                              params)
            except Exception as e:        # a scrape must never kill us
                status, ctype, body = (500, "text/plain",
                                       f"internal error: {e}\n")
            reason = {200: "OK", 400: "Bad Request", 404: "Not Found",
                      500: "Internal Server Error"}.get(status, "OK")
            payload = body.encode("utf-8", "replace")
            writer.write(
                f"HTTP/1.0 {status} {reason}\r\n"
                f"Content-Type: {ctype}\r\n"
                f"Content-Length: {len(payload)}\r\n"
                f"Connection: close\r\n\r\n".encode("latin-1"))
            writer.write(payload)
            await writer.drain()
        except (asyncio.TimeoutError, ConnectionError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

"""Barrier coordinator — the system heartbeat (meta-lite).

Reference: meta's GlobalBarrierManager (src/meta/src/barrier/mod.rs:481,634,
669,779) + the CN-side LocalBarrierManager (src/stream/src/task/
barrier_manager.rs) collapsed into one in-process coordinator: paces barrier
injection (`barrier_interval_ms`, system_param/mod.rs:77), pushes barriers
into every source's dedicated channel, waits until every actor reports
collection, then syncs the state store (the Hummock `commit_epoch` step) and
completes the epoch IN ORDER. Barrier latency (inject -> fully synced) is the
headline latency metric (grafana meta_barrier_latency).
"""

from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import Optional

from ..common.epoch import EpochPair, next_epoch, INVALID_EPOCH
from ..state.store import StateStore
from ..stream.message import Barrier, BarrierKind, Mutation


@dataclass
class EpochState:
    barrier: Barrier
    remaining: set[int]
    done: asyncio.Event = field(default_factory=asyncio.Event)


class BarrierCoordinator:
    def __init__(self, store: StateStore, interval_ms: int = 1000,
                 checkpoint_frequency: int = 1):
        self.store = store
        self.interval_ms = interval_ms
        self.checkpoint_frequency = checkpoint_frequency
        self.source_queues: list[asyncio.Queue] = []
        self.actor_ids: set[int] = set()
        self._epochs: dict[int, EpochState] = {}
        # Seed from the store's committed epoch: post-restart epochs must be
        # strictly greater than anything a previous incarnation committed
        # (reference: recovery resumes at the last committed Hummock epoch).
        self._prev_epoch = store.committed_epoch()
        self._barrier_count = 0
        self._started = False
        self.latencies_ns: list[int] = []
        self.committed_epochs: list[int] = []
        self._stopped = False
        self._failure: Optional[tuple] = None
        # Serializes whole ROUNDS (inject..collect) across concurrent
        # callers: the REPL's \tick / DDL bring-up can otherwise interleave
        # with the background ticker on the same coordinator, breaking the
        # in-order epoch completion contract (ADVICE r2 #1).
        self._rounds_lock = asyncio.Lock()
        # open-vocabulary dict durability (common/types.py): strings
        # minted below this cursor are already in the durable delta log.
        # Seeded to the CURRENT dict length when the store was restored
        # from a log (Session sets dict_cursor); 0 on a fresh store so
        # the first checkpoint persists everything minted so far.
        self.dict_cursor = 0
        # headline health metric (reference meta_barrier_latency,
        # grafana/risingwave-dev-dashboard.dashboard.py:894)
        from ..utils.metrics import GLOBAL_METRICS
        self._metrics_latency = GLOBAL_METRICS.histogram(
            "meta_barrier_latency_seconds")
        # per-epoch spans (utils/trace.py — the reference's barrier
        # TracingContext + grafana trace panel analogue)
        from ..utils.trace import EpochTracer
        self.tracer = EpochTracer()
        # print ONE stuck-barrier diagnosis (spans + await tree) when a
        # collection exceeds this many seconds; None disables
        self.stuck_report_s: float | None = 60.0

    # -------------------------------------------------------- registration
    def register_source(self, queue: asyncio.Queue) -> None:
        self.source_queues.append(queue)

    def register_actor(self, actor_id: int) -> None:
        self.actor_ids.add(actor_id)

    # ----------------------------------------------------------- collection
    def collect(self, actor_id: int, barrier: Barrier) -> None:
        st = self._epochs.get(barrier.epoch.curr)
        if st is None:
            return
        self.tracer.collect(barrier.epoch.curr, actor_id)
        st.remaining.discard(actor_id)
        if not st.remaining:
            st.done.set()

    def actor_failed(self, actor_id: int, exc: BaseException) -> None:
        """Failure detection (reference: barrier-collection failure on meta
        triggers global recovery, barrier/recovery.rs:332): a dead actor
        can never collect, so every in-flight and future barrier wait must
        fail fast instead of hanging the coordinator forever."""
        self._failure = (actor_id, exc)
        for st in self._epochs.values():
            st.done.set()

    # ------------------------------------------------------------ injection
    async def inject_barrier(self, mutation: Optional[Mutation] = None,
                             kind: Optional[BarrierKind] = None) -> Barrier:
        if self._failure is not None:
            actor_id, exc = self._failure
            raise RuntimeError(f"actor {actor_id} died") from exc
        curr = next_epoch(self._prev_epoch)
        epoch = EpochPair(curr, self._prev_epoch)
        if kind is None:
            self._barrier_count += 1
            is_ckpt = (self._barrier_count % self.checkpoint_frequency) == 0
            kind = BarrierKind.CHECKPOINT if is_ckpt else BarrierKind.BARRIER
        barrier = Barrier(epoch, kind, mutation, (), time.monotonic_ns())
        self._epochs[curr] = EpochState(barrier, set(self.actor_ids))
        self._prev_epoch = curr
        self.tracer.begin(curr)
        for q in self.source_queues:
            await q.put(barrier)
        return barrier

    async def wait_collected(self, barrier: Barrier) -> None:
        st = self._epochs[barrier.epoch.curr]
        if self.stuck_report_s is None:
            await st.done.wait()
        else:
            # one wait task serves both phases: no shield/wait_for
            # (which would orphan a pending task on timeout or ^C)
            waiter = asyncio.ensure_future(st.done.wait())
            try:
                done, _ = await asyncio.wait(
                    {waiter}, timeout=self.stuck_report_s)
                if not done:
                    # stuck-barrier diagnosis ONCE (reference: risectl
                    # await-tree dump for hung barriers), keep waiting
                    from ..utils.trace import format_stuck_barrier_report
                    print(f"[stuck barrier] epoch {barrier.epoch.curr} "
                          f"not collected after {self.stuck_report_s}s; "
                          f"remaining actors {sorted(st.remaining)}\n"
                          + format_stuck_barrier_report(self), flush=True)
                await waiter
            finally:
                if not waiter.done():
                    waiter.cancel()
        if self._failure is not None:
            # close the span before raising — the FAILED epoch's trace
            # is exactly what a post-mortem \trace wants to show
            self.tracer.end(barrier.epoch.curr)
            actor_id, exc = self._failure
            raise RuntimeError(
                f"actor {actor_id} died; epoch {barrier.epoch.curr} cannot "
                f"complete — recovery must restart from the last committed "
                f"checkpoint") from exc
        # complete IN ORDER (reference mod.rs:779): this epoch seals epoch.prev
        if barrier.kind is BarrierKind.CHECKPOINT and barrier.epoch.prev != INVALID_EPOCH:
            # dict deltas BEFORE the manifest commit: state committed in
            # this epoch may reference freshly-minted string ids, which
            # must be durable no later than the rows that carry them (an
            # orphan dict suffix after a crash is harmless — append-only,
            # stable ids)
            objects = getattr(self.store, "objects", None)
            if objects is not None:
                from ..common.types import persist_dict_delta
                self.dict_cursor = persist_dict_delta(
                    objects, self.dict_cursor)
            t_sync = time.monotonic_ns()
            self.store.sync(barrier.epoch.prev)
            self.committed_epochs.append(barrier.epoch.prev)
            self.tracer.end(barrier.epoch.curr,
                            sync_ns=time.monotonic_ns() - t_sync)
        else:
            self.tracer.end(barrier.epoch.curr)
        lat_ns = time.monotonic_ns() - barrier.inject_time_ns
        self.latencies_ns.append(lat_ns)
        self._metrics_latency.observe(lat_ns / 1e9)
        del self._epochs[barrier.epoch.curr]

    async def run_rounds(self, n: int, interval_s: Optional[float] = None) -> None:
        """Inject n barriers, waiting for each to complete. The very first
        barrier of this coordinator's life is Initial (reference: the Add/
        Initial barrier precedes all data); later calls continue the normal
        cadence — a mid-stream Initial would skip syncing the previous epoch.
        interval_s=None => as fast as collection allows (bench mode);
        otherwise paced like the reference's 1s default."""
        async with self._rounds_lock:
            if not self._started:
                self._started = True
                b = await self.inject_barrier(kind=BarrierKind.INITIAL)
                await self.wait_collected(b)
            for _ in range(n):
                if interval_s:
                    await asyncio.sleep(interval_s)
                b = await self.inject_barrier()
                await self.wait_collected(b)

    async def stop_all(self, actor_ids: Optional[set[int]] = None) -> None:
        from ..stream.message import StopMutation
        async with self._rounds_lock:
            ids = frozenset(actor_ids if actor_ids is not None
                            else self.actor_ids)
            b = await self.inject_barrier(mutation=StopMutation(ids))
            await self.wait_collected(b)

    # -------------------------------------------------------------- metrics
    def barrier_latency_percentile(self, p: float) -> float:
        if not self.latencies_ns:
            return 0.0
        xs = sorted(self.latencies_ns)
        i = min(len(xs) - 1, int(p * len(xs)))
        return xs[i] / 1e9

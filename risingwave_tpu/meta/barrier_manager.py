"""Barrier coordinator — the system heartbeat (meta-lite).

Reference: meta's GlobalBarrierManager (src/meta/src/barrier/mod.rs:481,634,
669,779) + the CN-side LocalBarrierManager (src/stream/src/task/
barrier_manager.rs) collapsed into one in-process coordinator: paces barrier
injection (`barrier_interval_ms`, system_param/mod.rs:77), pushes barriers
into every source's dedicated channel, waits until every actor reports
collection, then completes the epoch IN ORDER. Barrier latency (inject ->
collected) is the headline latency metric (grafana meta_barrier_latency).

Checkpoint durability is PIPELINED (reference: the Hummock event-handler
uploader, src/storage/src/hummock/event_handler/uploader/ — epochs seal at
the barrier, SSTs build/upload in background tasks, version commits apply
in order): a checkpoint barrier only ENQUEUES its epoch to the background
uploader task; the deferred executor flushes (blocking d2h), shared-buffer
seal, SST build/upload and the in-order manifest swap all run behind the
stream, so epoch N+1's compute overlaps epoch N's durable flush. A bounded
in-flight window (`checkpoint_max_inflight`, default 2) backpressures
barrier INJECTION when full — recovery replay distance stays bounded and a
slow object store degrades throughput, never correctness. `committed_epoch`
still advances only at the manifest swap, strictly in epoch order; with
`checkpoint_max_inflight=0` (or a store without seal support) the old
inline `store.sync()` path runs unchanged.
"""

from __future__ import annotations

import asyncio
import sys
import time
from dataclasses import dataclass, field
from typing import Optional

from ..common.epoch import EpochPair, next_epoch, INVALID_EPOCH
from ..memory.manager import MemoryManager
from ..serving.manager import ServingManager
from ..state.store import StateStore
from ..stream.message import Barrier, BarrierKind, Mutation
from ..utils.faults import FAULTS, FaultInjected


@dataclass
class EpochState:
    barrier: Barrier
    remaining: set[int]
    done: asyncio.Event = field(default_factory=asyncio.Event)


@dataclass
class _UploadJob:
    """One checkpoint handed to the background uploader."""
    prev_epoch: int          # the epoch being made durable
    curr_epoch: int          # barrier whose trace gets the phase spans


class BarrierCoordinator:
    def __init__(self, store: StateStore, interval_ms: int = 1000,
                 checkpoint_frequency: int = 1,
                 checkpoint_max_inflight: int = 2):
        self.store = store
        self.interval_ms = interval_ms
        self.checkpoint_frequency = checkpoint_frequency
        self.source_queues: list[asyncio.Queue] = []
        self.actor_ids: set[int] = set()
        self._epochs: dict[int, EpochState] = {}
        # Seed from the store's committed epoch: post-restart epochs must be
        # strictly greater than anything a previous incarnation committed
        # (reference: recovery resumes at the last committed Hummock epoch).
        self._prev_epoch = store.committed_epoch()
        self._barrier_count = 0
        self._started = False
        self.latencies_ns: list[int] = []
        self.committed_epochs: list[int] = []
        self._stopped = False
        self._failure: Optional[tuple] = None
        # EVERY reported failure this generation (actor_id -> exc):
        # `_failure` keeps the first-reported one for messages, but the
        # blast-radius classification must see all of them — two actors
        # dying in one epoch across two fragments is a full recovery,
        # not a partial rebuild of whichever reported last
        self.failed_actors: dict[int, BaseException] = {}
        # exchange channels with replay buffers (plan/build.py): trimmed
        # at every checkpoint commit so each holds exactly the
        # uncommitted message suffix per-fragment recovery would replay
        self.replay_channels: list = []
        # Serializes whole ROUNDS (inject..collect) across concurrent
        # callers: the REPL's \tick / DDL bring-up can otherwise interleave
        # with the background ticker on the same coordinator, breaking the
        # in-order epoch completion contract (ADVICE r2 #1).
        self._rounds_lock = asyncio.Lock()
        # open-vocabulary dict durability (common/types.py): strings
        # minted below this cursor are already in the durable delta log.
        # Seeded to the CURRENT dict length when the store was restored
        # from a log (Session sets dict_cursor); 0 on a fresh store so
        # the first checkpoint persists everything minted so far.
        self.dict_cursor = 0
        # headline health metric (reference meta_barrier_latency,
        # grafana/risingwave-dev-dashboard.dashboard.py:894)
        from ..utils.metrics import GLOBAL_METRICS
        self._metrics_latency = GLOBAL_METRICS.histogram(
            "meta_barrier_latency_seconds")
        # per-epoch spans (utils/trace.py — the reference's barrier
        # TracingContext + grafana trace panel analogue)
        from ..utils.trace import EpochTracer
        self.tracer = EpochTracer()
        # durable event log (meta/event_log.py): the session attaches
        # its log here (and re-attaches after recovery swaps the
        # coordinator); None = no emissions. Every control-plane
        # incident this coordinator detects (barrier stalls, broker
        # split adoptions) goes through the one choke point.
        self.event_log = None
        # barrier-paced metrics history (utils/metrics_history.py): the
        # session swaps in its own long-lived instance (survives
        # recovery's coordinator replacement) and configures retention;
        # compute nodes keep this default so workers sample locally too.
        from ..utils.metrics_history import MetricsHistory
        self.metrics_history = MetricsHistory()
        # stuck-barrier watchdog (the MonitorService/risectl-trace
        # analogue): a background task fires once per stalled epoch when
        # an in-flight barrier exceeds this threshold — logs the full
        # format_stuck_barrier_report and bumps barrier_stalls_total.
        # None/0 disables. SET barrier_stall_threshold_ms plumbs here.
        self.stall_threshold_ms: float | None = 60_000.0
        self._watchdog_task: Optional[asyncio.Task] = None
        self._stalls_reported: set[int] = set()
        from ..utils.metrics import BARRIER_STALLS
        self._m_stalls = BARRIER_STALLS
        # actor-level streaming metrics registrar (stream/monitor.py):
        # build_graph registers every actor chain, SET metric_level
        # re-instruments live actors through Session._apply_obs_config
        from ..stream.monitor import StreamingStats
        self.stats = StreamingStats()
        # HBM budget authority (memory/manager.py): executors register at
        # build time, accounting gauges refresh at every collected
        # barrier, and eviction runs here — between epochs, when every
        # executor is idle — once a budget is configured (Session plumbs
        # hbm_budget_bytes / memory_eviction_policy through).
        self.memory = MemoryManager()
        # Storage scrubber (state/scrub.py): verifies manifest-referenced
        # objects and sweeps orphan SSTs on the same between-epochs pulse
        # the memory manager uses; no-ops on non-durable stores. Session
        # plumbs storage_scrub_interval / storage_scrub_batch here.
        from ..state.scrub import StorageScrubber
        self.scrubber = StorageScrubber(store)
        # Serving authority (serving/manager.py): per-MV snapshot caches
        # advance at every collected barrier — the same between-epochs
        # moment the memory manager uses — so pinned reads always sit on
        # a sealed epoch, consistent across every MV of the coordinator.
        self.serving = ServingManager()
        # Changelog log-store authority (logstore/log.py): per-sink
        # delivery tasks and changelog-subscription pumps wake on the
        # commit pulse this coordinator emits at every checkpoint commit
        # (inline, background-uploader and cluster paths alike); a
        # delivery failure parks here and fail-stops the next injection
        # exactly like an upload failure.
        from ..logstore.log import LogStoreHub
        self.logstore = LogStoreHub(store)
        # Background compaction & retention plane (state/compactor.py):
        # barrier-paced merges off the commit path (attaching it flips
        # HummockStateStore.inline_compaction off), pin-aware GC over
        # serving pins + durable subscription cursors, and broker
        # retention floors from committed source offsets. Pulsed in the
        # same between-epochs window as the scrubber; Session plumbs
        # compaction_* / broker_retention_interval here.
        from ..state.compactor import (BackgroundCompactor,
                                       BrokerRetentionManager)
        self.compactor = BackgroundCompactor(
            store, serving=self.serving, logstore=self.logstore)
        self.compactor.retention = BrokerRetentionManager(
            store, lambda: self.source_execs)
        self.compactor._sync_inline_flag()
        # ---- async epoch uploader (the checkpoint pipeline) ----
        self._upload_q: asyncio.Queue[_UploadJob] = asyncio.Queue()
        self._uploader_task: Optional[asyncio.Task] = None
        self._inflight = 0            # enqueued-but-uncommitted checkpoints
        self._slot_free = asyncio.Event()
        self._slot_free.set()
        self._upload_failure: Optional[BaseException] = None
        self.upload_busy_ns = 0       # total background flush+upload+commit
        self.backpressure_wait_ns = 0  # injection stalls on a full window
        from ..utils.metrics import (
            CHECKPOINT_BACKPRESSURE_SECONDS, CHECKPOINT_COMMIT_SECONDS,
            CHECKPOINT_INFLIGHT, CHECKPOINT_SEAL_SECONDS,
            CHECKPOINT_UPLOAD_SECONDS)
        self._m_seal = CHECKPOINT_SEAL_SECONDS
        self._m_upload = CHECKPOINT_UPLOAD_SECONDS
        self._m_commit = CHECKPOINT_COMMIT_SECONDS
        self._m_inflight = CHECKPOINT_INFLIGHT
        self._m_backpressure = CHECKPOINT_BACKPRESSURE_SECONDS
        # ---- fused mesh fragments (plan/build.py _register_mesh) ----
        # actor_id -> (n_shards, identity). A fused mesh fragment lowers
        # a whole exchange -> sharded-executor chain onto the device mesh
        # as ONE actor: its S shards participate in every epoch as ONE
        # collection (one entry in EpochState.remaining, one fence on the
        # sharded state — a collective boundary), where the host-exchange
        # alternative is S actors = S collections + S per-device fences
        # per epoch. The registry makes that legible to /healthz, tests
        # and the mesh_profile gate.
        self.mesh_fragments: dict[int, tuple[int, str]] = {}
        # ---- fused mesh CHAINS (plan/build.py _fuse_mesh_chains) ----
        # chain label -> {"fids": (producer..., consumer), "hollow": bool,
        # "consumer_actor": id}. A chain spans MULTIPLE fragments whose
        # producer stages were hollowed into the consumer's fused program:
        # one epoch fence covers the whole chain (hollow producers are
        # fence-exempt — they dispatch no device programs), and the
        # mesh_host_round_trips_total{chain} counter asserts the
        # zero-host-hop claim per interval.
        self.mesh_chains: dict[str, dict] = {}
        # ---- cluster mode (cluster/meta_service.py) ----
        # worker_id -> WorkerHandle: barriers are ALSO injected over RPC
        # into every compute node's source queues, each worker collects
        # its own actors and reports ONCE per epoch (the per-worker
        # injection/collection path of the reference GlobalBarrierManager);
        # workers appear in EpochState.remaining as pseudo-actors with
        # NEGATIVE ids (-worker_id), so collection/failure machinery is
        # shared with the in-process path.
        self.workers: dict[int, object] = {}
        # compute-node side: called with (epoch, sst_ids) when this
        # process's store finished seal+upload+local-install for an epoch
        # — the worker's "sealed" report to meta rides it
        self.commit_listener = None
        # ---- split discovery (connectors/broker.py) ----
        # enumerators polled at barrier injection: membership growth in
        # an external source (a topic gaining partitions) comes back as
        # an AddSplitsMutation riding the injected barrier — assignment
        # is totally ordered with data, the source_manager.rs discipline
        self.split_enumerators: list = []
        self._enum_by_frag: dict[int, object] = {}
        # live source executors by actor id (SHOW sources: splits,
        # offsets, lag); builders register, Deployment.stop removes
        self.source_execs: dict[int, object] = {}
        self.checkpoint_max_inflight = checkpoint_max_inflight

    # ------------------------------------------------- checkpoint pipeline
    @property
    def checkpoint_max_inflight(self) -> int:
        return self._ckpt_max_inflight

    @checkpoint_max_inflight.setter
    def checkpoint_max_inflight(self, n: int) -> None:
        """Runtime-mutable (SET checkpoint_max_inflight / ALTER SYSTEM):
        0 restores the inline-sync path; >0 bounds the pipeline window.
        Also flips the store's deferred-flush gate so executors only defer
        their d2h persists when a background uploader will drain them."""
        self._ckpt_max_inflight = int(n)
        if hasattr(self.store, "defer_enabled"):
            self.store.defer_enabled = self.pipelined
        self._slot_free.set()         # re-evaluate any backpressured waiter

    @property
    def pipelined(self) -> bool:
        # cluster mode is ALWAYS pipelined: the commit point is "all
        # workers reported sealed", which by construction runs behind the
        # barrier (there is no inline path across processes)
        if self.workers:
            return True
        return self._ckpt_max_inflight > 0 and hasattr(self.store, "seal")

    # -------------------------------------------------------- registration
    def register_source(self, queue: asyncio.Queue) -> None:
        self.source_queues.append(queue)

    def register_actor(self, actor_id: int) -> None:
        self.actor_ids.add(actor_id)

    def register_mesh_fragment(self, actor_id: int, n_shards: int,
                               identity: str = "") -> None:
        """A fused mesh fragment announces itself: `actor_id` is its ONE
        collection unit covering all `n_shards` device shards."""
        from ..utils.metrics import GLOBAL_METRICS
        self.mesh_fragments[actor_id] = (int(n_shards), identity)
        GLOBAL_METRICS.gauge("mesh_fragment_shards",
                             actor=str(actor_id)).set(float(n_shards))

    def register_mesh_chain(self, chain: str, fids, hollow: bool,
                            consumer_actor: int) -> None:
        """A fused mesh chain announces itself: producer fragments
        `fids[:-1]` run hollow (their stages execute inside the consumer
        fragment's fused program), `fids[-1]` is the consumer whose fence
        covers the chain. hollow=False records an ELIGIBLE chain left on
        the per-chunk host plane (streaming_mesh_chain=0) — the host-hop
        counter still runs, giving the unfused comparison baseline."""
        from ..utils.metrics import GLOBAL_METRICS
        self.mesh_chains[chain] = {"fids": tuple(fids),
                                   "hollow": bool(hollow),
                                   "consumer_actor": int(consumer_actor)}
        GLOBAL_METRICS.gauge("mesh_chain_fragments", chain=chain).set(
            float(len(fids)))

    def unregister_mesh_chain(self, chain: str) -> None:
        from ..utils.metrics import GLOBAL_METRICS
        if self.mesh_chains.pop(chain, None) is not None:
            GLOBAL_METRICS.remove("mesh_chain_fragments", chain=chain)
            GLOBAL_METRICS.remove("mesh_host_round_trips_total",
                                  chain=chain)

    def unregister_mesh_fragment(self, actor_id: int) -> None:
        from ..utils.metrics import GLOBAL_METRICS
        if self.mesh_fragments.pop(actor_id, None) is not None:
            # the labelled series dies with the fragment (same rule as
            # per-actor streaming series)
            GLOBAL_METRICS.remove("mesh_fragment_shards",
                                  actor=str(actor_id))

    def split_enumerator(self, frag_key: int, factory):
        """One enumerator per source fragment, shared by its actors and
        surviving per-fragment rebuilds (keyed by the retained fragment
        object): the first builder call creates+registers it, later
        calls — other actors, a rebuild — reuse it so already-announced
        splits are never re-assigned."""
        en = self._enum_by_frag.get(frag_key)
        if en is None:
            en = factory()
            en.frag_key = frag_key
            self._enum_by_frag[frag_key] = en
            self.split_enumerators.append(en)
        return en

    def unregister_split_enumerator(self, en) -> None:
        if en in self.split_enumerators:
            self.split_enumerators.remove(en)
        if en.frag_key is not None:
            self._enum_by_frag.pop(en.frag_key, None)

    def register_source_exec(self, ex) -> None:
        self.source_execs[ex.source_id] = ex

    def unregister_source_exec(self, actor_id: int) -> None:
        ex = self.source_execs.pop(actor_id, None)
        if ex is not None:
            ex.remove_split_metrics()

    def _poll_split_enumerators(self):
        """Merge every enumerator's newly-discovered splits into one
        mutation (None when nothing changed). Polls are throttled inside
        each enumerator; a poll failure (broker away) skips this round
        — discovery must never fail injection."""
        adds: dict[int, list] = {}
        for en in list(self.split_enumerators):
            try:
                a = en.poll()
            except Exception:  # noqa: BLE001 — discovery is best-effort
                a = None
            if a:
                for sid, sp in a.items():
                    adds.setdefault(sid, []).extend(sp)
        if not adds:
            return None
        if self.event_log is not None:
            # split adoption is a topology event an operator wants in
            # the post-mortem record (rw_event_logs analogue)
            self.event_log.emit(
                "broker_split_adopt",
                splits={str(sid): [getattr(s, "split_id", str(s))
                                   for s in sp]
                        for sid, sp in adds.items()})
        from ..stream.message import AddSplitsMutation
        return AddSplitsMutation(
            {sid: tuple(v) for sid, v in adds.items()})

    def register_worker(self, handle) -> None:
        """Attach a compute node (cluster mode): it participates in every
        epoch as pseudo-actor -worker_id until removed."""
        self.workers[handle.worker_id] = handle
        self.actor_ids.add(-handle.worker_id)

    def remove_worker(self, worker_id: int) -> None:
        self.workers.pop(worker_id, None)
        self.actor_ids.discard(-worker_id)

    def collect_worker(self, worker_id: int, epoch: int) -> None:
        """A compute node reports every one of ITS actors collected the
        epoch (reference: the CN's BarrierComplete RPC)."""
        st = self._epochs.get(epoch)
        if st is None:
            return
        self.tracer.collect(epoch, -worker_id)
        st.remaining.discard(-worker_id)
        if not st.remaining:
            st.done.set()

    def worker_failed(self, worker_id: int, exc: BaseException) -> None:
        """Lease expiry / connection loss: fail in-flight epochs fast,
        exactly like an in-process actor death (the session's tick-path
        auto-recovery then rebuilds over the surviving worker set)."""
        self.actor_failed(-worker_id, exc)

    # ----------------------------------------------------------- collection
    def collect(self, actor_id: int, barrier: Barrier) -> None:
        st = self._epochs.get(barrier.epoch.curr)
        if st is None:
            return
        self.tracer.collect(barrier.epoch.curr, actor_id)
        st.remaining.discard(actor_id)
        if not st.remaining:
            st.done.set()

    def collect_phases(self, actor_id: int, barrier: Barrier,
                       phases: dict) -> None:
        """Actors report their interval phase split (apply / persist /
        align ns, stream/actor.py) just before collecting — it lands on
        the open epoch span so `\\trace` shows who did what."""
        self.tracer.collect_phases(barrier.epoch.curr, actor_id, phases)

    def actor_failed(self, actor_id: int, exc: BaseException) -> None:
        """Failure detection (reference: barrier-collection failure on meta
        triggers global recovery, barrier/recovery.rs:332): a dead actor
        can never collect, so every in-flight and future barrier wait must
        fail fast instead of hanging the coordinator forever."""
        if self._failure is None:
            self._failure = (actor_id, exc)
        self.failed_actors[actor_id] = exc
        for st in self._epochs.values():
            st.done.set()
        # the failure path has its own diagnosis; a stall report on a
        # dead coordinator would be noise (and the task would otherwise
        # poll the never-deleted failed epoch forever)
        self._stop_watchdog()

    def clear_failure(self) -> None:
        """Per-fragment recovery keeps THIS coordinator (surviving actors
        hold references to it): drop the failure marker and every
        never-collected epoch so injection resumes where it left off —
        the next barrier continues from `_prev_epoch`, and a late
        `collect` for a cleared epoch is ignored by construction."""
        self._failure = None
        self.failed_actors.clear()
        for epoch in list(self._epochs):
            self.tracer.end(epoch)
            del self._epochs[epoch]
        self._stalls_reported.clear()

    # ------------------------------------------------ replay-buffer trims
    def register_replay_channels(self, channels) -> None:
        self.replay_channels.extend(channels)

    def unregister_replay_channels(self, channels) -> None:
        drop = {id(c) for c in channels}
        self.replay_channels = [c for c in self.replay_channels
                                if id(c) not in drop]

    def _trim_replay_buffers(self, committed_epoch: int) -> None:
        for ch in self.replay_channels:
            ch.trim_replay(committed_epoch)

    def _trim_at_local_commit(self, epoch: int) -> None:
        """Trim pulse at a LOCAL commit: on a compute node the local
        commit_sealed only installs read-through state — the epoch is
        durable only when META's manifest swap covers it (the
        `committed` push, cluster/compute_node.py rpc_committed). A
        worker trimming at its own seal would throw away exactly the
        suffix per-worker recovery must replay."""
        if getattr(self.store, "manifest_owner", True):
            self._trim_replay_buffers(epoch)

    def clear_upload_failure(self) -> None:
        """Worker-partial recovery subsumes an upload failure caused by
        the dead worker's vanished sealed report: the aborted epochs
        replay from the committed manifest, so the parked error must
        not fail the resumed injection stream."""
        self._upload_failure = None

    # ------------------------------------------------------------ injection
    async def inject_barrier(self, mutation: Optional[Mutation] = None,
                             kind: Optional[BarrierKind] = None) -> Barrier:
        if self._failure is not None:
            actor_id, exc = self._failure
            raise RuntimeError(f"actor {actor_id} died") from exc
        if self._upload_failure is not None:
            exc = self._upload_failure
            raise RuntimeError(
                "checkpoint upload/commit failed; recovery must replay "
                "from the last committed epoch") from exc
        # a parked sink-delivery failure fail-stops injection the same
        # way (the target is unreachable/raising; recovery replays from
        # the committed epoch and delivery resumes after the durable
        # cursor — exactly-once either way)
        self.logstore.check_failure()
        # split discovery rides otherwise-unadorned barriers (a Pause/
        # Stop/Throttle keeps its own mutation; growth waits one round)
        if mutation is None and self.split_enumerators:
            mutation = self._poll_split_enumerators()
        if kind is None:
            self._barrier_count += 1
            is_ckpt = (self._barrier_count % self.checkpoint_frequency) == 0
            kind = BarrierKind.CHECKPOINT if is_ckpt else BarrierKind.BARRIER
        if kind is BarrierKind.CHECKPOINT:
            # bounded in-flight window: a full uploader queue backpressures
            # INJECTION (not collection) so barrier latency stays honest
            # and recovery replay distance stays <= the window
            await self._acquire_ckpt_slot()
        curr = next_epoch(self._prev_epoch)
        epoch = EpochPair(curr, self._prev_epoch)
        barrier = Barrier(epoch, kind, mutation, (), time.monotonic_ns())
        self._epochs[curr] = EpochState(barrier, set(self.actor_ids))
        self._prev_epoch = curr
        self.tracer.begin(curr)
        self._ensure_watchdog()
        for q in self.source_queues:
            await q.put(barrier)
        # per-worker injection (cluster mode): the barrier rides the
        # control RPC into every compute node's local source queues; a
        # send failure IS a worker failure (fail fast, then recovery)
        for wid, handle in list(self.workers.items()):
            try:
                await handle.inject(barrier)
            except Exception as e:  # noqa: BLE001 — connection-level death
                self.worker_failed(wid, e)
        return barrier

    async def inject_remote(self, barrier: Barrier) -> Barrier:
        """Compute-node side of cluster injection: meta already chose the
        epoch/kind/mutation; this LocalBarrierManager role just fans the
        barrier into ITS source queues and tracks ITS actors' collection.
        Returns a rebased barrier whose inject timestamp is local (the
        per-worker latency metric must not mix two monotonic clocks)."""
        if self._failure is not None:
            actor_id, exc = self._failure
            raise RuntimeError(f"actor {actor_id} died") from exc
        if self._upload_failure is not None:
            exc = self._upload_failure
            raise RuntimeError("checkpoint upload failed") from exc
        barrier = Barrier(barrier.epoch, barrier.kind, barrier.mutation,
                          (), time.monotonic_ns())
        curr = barrier.epoch.curr
        st = EpochState(barrier, set(self.actor_ids))
        self._epochs[curr] = st
        if not st.remaining:
            # a worker hosting zero actors of the current topology still
            # participates in the protocol (it reports collected at once)
            st.done.set()
        self._prev_epoch = curr
        self.tracer.begin(curr)
        self._ensure_watchdog()
        for q in self.source_queues:
            await q.put(barrier)
        return barrier

    # --------------------------------------------------- stuck-barrier watchdog
    def _ensure_watchdog(self) -> None:
        """Spawn the watchdog while epochs are in flight (it exits when
        the coordinator drains, so an idle session holds no timer)."""
        if not self.stall_threshold_ms:
            return
        if self._watchdog_task is None or self._watchdog_task.done():
            self._watchdog_task = asyncio.get_running_loop().create_task(
                self._watchdog(), name="barrier-watchdog")

    async def _watchdog(self) -> None:
        """Fire ONCE per stalled epoch: when an in-flight barrier's age
        exceeds `stall_threshold_ms`, log the full diagnosis (partial
        span: who already collected; await tree: where the rest are
        parked) and bump `barrier_stalls_total`. The reference gets this
        from risectl's await-tree dump via the MonitorService; here it is
        automatic."""
        from ..utils.trace import format_stuck_barrier_report
        while True:
            if not self._epochs:
                return        # respawned by the next inject
            thr = self.stall_threshold_ms
            if thr:
                now = time.monotonic_ns()
                for epoch, st in list(self._epochs.items()):
                    tr = self.tracer._open.get(epoch)
                    if tr is None or epoch in self._stalls_reported:
                        continue
                    age_ms = (now - tr.inject_ns) / 1e6
                    if age_ms >= thr:
                        self._stalls_reported.add(epoch)
                        self._m_stalls.inc()
                        remaining = sorted(st.remaining)
                        if self.event_log is not None:
                            self.event_log.emit(
                                "barrier_stall", epoch=epoch,
                                age_ms=round(age_ms, 1),
                                remaining=remaining)
                        # cluster mode: pull every live worker's own
                        # stuck-barrier report (its in-flight remaining
                        # actors + await tree) over rpc.py — the merged
                        # report then names the stalled WORKER, ACTOR
                        # and parked FRAME, not just "phase collect".
                        # The watchdog is an async task, so the fan-out
                        # awaits here without blocking collection.
                        worker_reports = None
                        if self.workers:
                            worker_reports = {}
                            for wid, handle in list(self.workers.items()):
                                try:
                                    worker_reports[wid] = await \
                                        handle.call("dump_tasks",
                                                    timeout=5)
                                except Exception as e:  # noqa: BLE001
                                    worker_reports[wid] = \
                                        f"(unreachable: {e!r})"
                        # stderr, NOT stdout: bench.py and the profile
                        # gates parse this process's stdout for JSON
                        # result lines — a multi-line diagnosis landing
                        # there mid-measurement would corrupt the parse
                        # (the watchdog is a diagnostic channel, and
                        # diagnostics belong on stderr)
                        print(
                            f"[stuck barrier] epoch {epoch} in flight "
                            f"{age_ms:.0f}ms (threshold {thr:.0f}ms); "
                            f"remaining actors {remaining}\n"
                            + format_stuck_barrier_report(
                                self, worker_reports),
                            flush=True, file=sys.stderr)
            poll_s = max(0.02, min(1.0, (thr or 1000.0) / 1e3 / 8))
            await asyncio.sleep(poll_s)

    def _stop_watchdog(self) -> None:
        t = self._watchdog_task
        self._watchdog_task = None
        if t is not None and not t.done():
            t.cancel()

    async def wait_collected(self, barrier: Barrier) -> None:
        st = self._epochs[barrier.epoch.curr]
        await st.done.wait()
        if self._failure is not None:
            # close the span before raising — the FAILED epoch's trace
            # is exactly what a post-mortem \trace wants to show
            self.tracer.end(barrier.epoch.curr)
            actor_id, exc = self._failure
            raise RuntimeError(
                f"actor {actor_id} died; epoch {barrier.epoch.curr} cannot "
                f"complete — recovery must restart from the last committed "
                f"checkpoint") from exc
        # complete IN ORDER (reference mod.rs:779): this epoch seals epoch.prev
        if barrier.kind is BarrierKind.CHECKPOINT and barrier.epoch.prev != INVALID_EPOCH:
            # dict deltas BEFORE the manifest commit: state committed in
            # this epoch may reference freshly-minted string ids, which
            # must be durable no later than the rows that carry them (an
            # orphan dict suffix after a crash is harmless — append-only,
            # stable ids). Manifest owner only: cluster compute nodes
            # share the object store, and concurrent delta writers would
            # race the log rename (their per-process dicts are local —
            # the v1 cluster contract keeps dict-typed columns out of
            # durable state, enforced at deploy).
            objects = getattr(self.store, "objects", None)
            if objects is not None and getattr(self.store,
                                               "manifest_owner", True):
                from ..common.types import persist_dict_delta
                self.dict_cursor = persist_dict_delta(
                    objects, self.dict_cursor)
            if self.pipelined:
                # seal/upload/commit run behind the stream: the barrier
                # completes as soon as the epoch is enqueued, so the
                # latency below excludes the whole durable flush. In
                # cluster mode the same queue carries the epoch to the
                # background committer, which waits for EVERY worker's
                # sealed report before swapping the manifest.
                self._enqueue_upload(barrier)
                self.tracer.end(barrier.epoch.curr)
            else:
                t_sync = time.monotonic_ns()
                res = self.store.sync(barrier.epoch.prev)
                self.committed_epochs.append(barrier.epoch.prev)
                if self.commit_listener is not None:
                    self.commit_listener(
                        barrier.epoch.prev,
                        (res or {}).get("uncommitted_ssts", []))
                self.logstore.on_commit(barrier.epoch.prev)
                self._trim_at_local_commit(barrier.epoch.prev)
                self.tracer.end(barrier.epoch.curr,
                                sync_ns=time.monotonic_ns() - t_sync)
        else:
            self.tracer.end(barrier.epoch.curr)
        lat_ns = time.monotonic_ns() - barrier.inject_time_ns
        self.latencies_ns.append(lat_ns)
        self._metrics_latency.observe(lat_ns / 1e9)
        del self._epochs[barrier.epoch.curr]
        self._stalls_reported.discard(barrier.epoch.curr)
        if not self._epochs:
            self._stop_watchdog()
        # budget check at barrier collection: the epoch is complete and
        # every executor idle, so eviction device work cannot race an
        # in-flight apply; runs synchronously (no awaits) so no actor
        # interleaves mid-eviction
        self.memory.on_barrier(barrier.epoch.curr)
        # serving caches advance to the sealed epoch in the same
        # synchronous window (a wanted-but-absent cache pays its one
        # full build scan here, before incremental maintenance takes
        # over)
        self.serving.on_barrier(barrier)
        # the log-store hub tracks the sealed epoch: it is the
        # activation floor for MV changelog logs (everything <= it is
        # table state a subscription backfills; everything after is
        # logged once active)
        self.logstore.on_barrier(barrier)
        # storage scrub pulse (throttled internally): verify a bounded
        # slice of the referenced objects, account/sweep orphans — in
        # cluster mode orphans are counted but never deleted (a worker's
        # in-flight upload is invisible to meta)
        self.scrubber.on_barrier(barrier.epoch.curr,
                                 cluster_mode=bool(self.workers))
        # compaction & retention pulse (state/compactor.py): harvest a
        # finished background merge (one manifest swap, deletes strictly
        # after), maybe start the next one on a worker thread, and push
        # broker retention floors — the commit path above never merges
        self.compactor.event_log = self.event_log
        self.compactor.retention.event_log = self.event_log
        self.compactor.on_barrier(barrier.epoch.curr)
        # metrics-history pulse LAST: every gauge the pulses above
        # refresh (HBM accounting, serving cache rows, retention
        # floors) is already current when sampled; internally throttled
        # by its interval and never raises into the barrier path
        self.metrics_history.on_barrier(barrier.epoch.curr)
        # cross-engine trace links staged by broker connectors/sinks
        # during the epoch attach to the (just-closed) trace now
        self._drain_trace_links(barrier.epoch.curr)

    def _drain_trace_links(self, epoch: int) -> None:
        """Collect (engine, epoch, span, topic/partition/offset) link
        records staged by BrokerPartitionConnector ingests and
        BrokerSink deliveries onto the epoch's trace."""
        links = []
        for exec_ in list(self.source_execs.values()):
            for _sid, conn in getattr(exec_, "splits", ()):
                drain = getattr(conn, "drain_trace_links", None)
                if drain is not None:
                    try:
                        links.extend(drain())
                    except Exception:
                        pass
        if links:
            self.tracer.add_links(epoch, links)

    async def run_rounds(self, n: int, interval_s: Optional[float] = None) -> None:
        """Inject n barriers, waiting for each to complete. The very first
        barrier of this coordinator's life is Initial (reference: the Add/
        Initial barrier precedes all data); later calls continue the normal
        cadence — a mid-stream Initial would skip syncing the previous epoch.
        interval_s=None => as fast as collection allows (bench mode);
        otherwise paced like the reference's 1s default."""
        async with self._rounds_lock:
            if not self._started:
                self._started = True
                b = await self.inject_barrier(kind=BarrierKind.INITIAL)
                await self.wait_collected(b)
            for _ in range(n):
                if interval_s:
                    await asyncio.sleep(interval_s)
                b = await self.inject_barrier()
                await self.wait_collected(b)
            # settle: uploads overlap ACROSS the rounds above, but callers
            # of run_rounds/tick (tests, the playground ticker, DDL
            # bring-up) expect the committed snapshot to include every
            # ticked epoch once this returns. Latency metrics are already
            # recorded per barrier, so the drain never inflates them; the
            # bench/profile measured loops call inject/wait directly and
            # keep full overlap. Sink delivery drains the same way: once
            # a tick returns, everything it committed has reached the
            # targets (delivery latency never lands in barrier latency).
            await self.drain_uploads()
            await self.logstore.drain()

    async def stop_all(self, actor_ids: Optional[set[int]] = None) -> None:
        from ..stream.message import StopMutation
        async with self._rounds_lock:
            ids = frozenset(actor_ids if actor_ids is not None
                            else self.actor_ids)
            b = await self.inject_barrier(mutation=StopMutation(ids))
            await self.wait_collected(b)
            # a stop is a quiesce point: everything enqueued must be
            # durable — and delivered to sink targets — before the
            # caller reads committed state / tears the deployment down
            await self.drain_uploads()
            await self.logstore.drain()

    # -------------------------------------------------- background uploader
    def _enqueue_upload(self, barrier: Barrier) -> None:
        self._inflight += 1
        self._m_inflight.set(self._inflight)
        self._upload_q.put_nowait(
            _UploadJob(barrier.epoch.prev, barrier.epoch.curr))
        if self._uploader_task is None or self._uploader_task.done():
            self._uploader_task = asyncio.get_running_loop().create_task(
                self._upload_worker(), name="epoch-uploader")

    async def _acquire_ckpt_slot(self) -> None:
        if not self.pipelined:
            return
        t0 = time.monotonic_ns()
        while (self._inflight >= self._ckpt_max_inflight
               and self.pipelined and self._upload_failure is None
               and self._failure is None):
            self._slot_free.clear()
            await self._slot_free.wait()
        waited = time.monotonic_ns() - t0
        if waited:
            self.backpressure_wait_ns += waited
            self._m_backpressure.inc(waited / 1e9)

    async def _upload_worker(self) -> None:
        """Drains the checkpoint queue STRICTLY in order: per epoch, run
        the executors' deferred flush stages (blocking d2h waits on a
        worker thread, count-dependent dispatch continuations back on the
        loop — dispatching from two threads concurrently deadlocks jax),
        seal the shared buffer, build+upload the SST off the loop, then
        swap the manifest on the loop. A failure parks the error for the
        next inject_barrier (fail-stop: recovery replays from the last
        committed epoch, exactly like an actor death)."""
        store = self.store
        while True:
            if self._upload_q.empty():
                return        # respawned by the next enqueue; no parked task
            job = self._upload_q.get_nowait()
            try:
                if self.workers:
                    # cluster commit: the epoch is durable once EVERY
                    # compute node sealed + uploaded its share; only then
                    # does meta install their SSTs and swap the manifest
                    # (the reference's commit_epoch on meta after all CN
                    # barrier-complete reports carry their synced SSTs)
                    t0 = time.monotonic_ns()
                    sst_ids: list[int] = []
                    for handle in list(self.workers.values()):
                        sst_ids.extend(await handle.wait_sealed(
                            job.prev_epoch))
                    t2 = time.monotonic_ns()
                    self.store.commit_remote(job.prev_epoch,
                                             sorted(sst_ids))
                    t3 = time.monotonic_ns()
                    self.committed_epochs.append(job.prev_epoch)
                    self.logstore.on_commit(job.prev_epoch)
                    self._trim_replay_buffers(job.prev_epoch)
                    # confirm the commit to every worker: they drop
                    # their retained sealed batches and trim their
                    # replay buffers (local channels + DCN legs) to the
                    # uncommitted suffix — the cluster-wide twin of the
                    # local trim pulse
                    for handle in list(self.workers.values()):
                        try:
                            await handle.notify_committed(job.prev_epoch)
                        except Exception:  # noqa: BLE001 — detector owns it
                            pass
                    self.upload_busy_ns += t3 - t0
                    self._m_upload.observe((t2 - t0) / 1e9)
                    self._m_commit.observe((t3 - t2) / 1e9)
                    self.tracer.annotate(job.curr_epoch, upload_ns=t2 - t0,
                                         commit_ns=t3 - t2)
                    self._inflight -= 1
                    self._m_inflight.set(self._inflight)
                    self._slot_free.set()
                    self._upload_q.task_done()
                    continue
                t0 = time.monotonic_ns()
                for stages in store.take_deferred(job.prev_epoch):
                    for wait, cont in stages:
                        payload = (await asyncio.to_thread(wait)
                                   if wait is not None else None)
                        cont(payload)
                batch = store.seal(job.prev_epoch)
                t1 = time.monotonic_ns()
                if FAULTS.active:
                    # chaos harness: an injected store fault takes the
                    # exact fail-stop path a real PUT error takes
                    d = FAULTS.hit("upload_delay", epoch=job.prev_epoch)
                    if d is not None:
                        await asyncio.sleep(d.get("ms", 100) / 1e3)
                    if FAULTS.hit("upload_fail",
                                  epoch=job.prev_epoch) is not None:
                        raise FaultInjected(
                            f"injected upload_fail at epoch "
                            f"{job.prev_epoch}")
                await asyncio.to_thread(store.upload_sealed, batch)
                t2 = time.monotonic_ns()
                res = store.commit_sealed(batch)
                t3 = time.monotonic_ns()
                self.committed_epochs.append(job.prev_epoch)
                # annotate BEFORE the commit listener: on a compute node
                # the listener ships this epoch's closed span to meta
                # piggybacked on the sealed report, and the span must
                # already carry its checkpoint-pipeline phases
                self.tracer.annotate(job.curr_epoch, seal_ns=t1 - t0,
                                     upload_ns=t2 - t1, commit_ns=t3 - t2)
                if self.commit_listener is not None:
                    self.commit_listener(
                        job.prev_epoch,
                        (res or {}).get("uncommitted_ssts", []))
                self.logstore.on_commit(job.prev_epoch)
                self._trim_at_local_commit(job.prev_epoch)
                self.upload_busy_ns += t3 - t0
                self._m_seal.observe((t1 - t0) / 1e9)
                self._m_upload.observe((t2 - t1) / 1e9)
                self._m_commit.observe((t3 - t2) / 1e9)
            except asyncio.CancelledError:
                self._inflight -= 1
                self._slot_free.set()
                self._upload_q.task_done()
                raise
            except BaseException as e:  # noqa: BLE001 — park for injection
                self._upload_failure = e
            self._inflight -= 1
            self._m_inflight.set(self._inflight)
            self._slot_free.set()
            self._upload_q.task_done()

    async def drain_uploads(self) -> None:
        """Block until every enqueued checkpoint has committed (or failed).
        Quiesce point for stop/backup/profiling — NOT part of the barrier
        path."""
        if self._uploader_task is not None:
            await self._upload_q.join()
        await self.compactor.drain()
        await self.scrubber.drain()
        if self._upload_failure is not None:
            exc = self._upload_failure
            raise RuntimeError(
                "checkpoint upload/commit failed during drain") from exc

    async def abort_uploads(self) -> None:
        """Crash/recovery entry: cancel the uploader and drop queued jobs
        WITHOUT committing them. An upload already in flight can at worst
        leave an orphan SST no manifest references; the commit point
        (manifest swap) never runs for aborted epochs, so the caller's
        `reset_uncommitted` + replay from `committed_epoch` stays exact.
        Sink delivery and subscription pumps die here too — their
        durable cursors commit with checkpoints, so the rebuilt
        topology's fresh tasks resume exactly-once."""
        self._stop_watchdog()
        self.logstore.abort()
        # in-flight background merge: abandon it — its output (if the
        # thread finishes the upload anyway) is an orphan the scrubber
        # sweeps; no manifest ever references it
        self.compactor.abort()
        t = self._uploader_task
        self._uploader_task = None
        if t is not None and not t.done():
            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, Exception):
                pass
        while not self._upload_q.empty():
            self._upload_q.get_nowait()
            self._upload_q.task_done()
        self._inflight = 0
        self._m_inflight.set(0)
        self._slot_free.set()

    def upload_overlap_pct(self) -> Optional[float]:
        """% of background durable-flush busy time hidden behind compute:
        100 * (1 - injection_backpressure / uploader_busy). None before
        the first pipelined checkpoint commits."""
        if self.upload_busy_ns <= 0:
            return None
        hidden = max(0, self.upload_busy_ns - self.backpressure_wait_ns)
        return round(100.0 * hidden / self.upload_busy_ns, 1)

    # -------------------------------------------------------------- metrics
    def barrier_latency_percentile(self, p: float) -> float:
        if not self.latencies_ns:
            return 0.0
        xs = sorted(self.latencies_ns)
        i = min(len(xs) - 1, int(p * len(xs)))
        return xs[i] / 1e9

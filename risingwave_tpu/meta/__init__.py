from .barrier_manager import BarrierCoordinator

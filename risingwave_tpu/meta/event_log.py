"""Durable event log — the `rw_event_logs` analogue.

Reference: the reference persists operator-relevant incidents (barrier
collection failures, recovery runs, sink faults) into a system table
(`rw_catalog.rw_event_logs`) so a post-mortem can ask "what happened
around 14:02" AFTER the process that suffered it restarted. Same shape
here: every notable control-plane incident — recoveries, barrier
stalls, flap detections, scrub findings/quarantines, backup/restore
generations, sink-delivery parks, broker split adoptions — flows
through ONE choke point (`EventLog.emit(kind, **fields)`) and appends a
crc-framed JSON record to a size-rolled log living NEXT TO the object
store, with the broker segments' torn-tail-tolerant framing
(broker/log.py): a record is a `(len, crc32)` header + JSON body,
appended in a single write+fsync, and a reopen drops a torn trailing
record WHOLE (crc or length mismatch truncates the tail) so a SIGKILL
mid-append can never surface half an event.

Surfaced by `SHOW events [LIMIT n]` (frontend/session.py) and
`/debug/events?since=ts` (meta/monitor_service.py). Sessions over a
non-durable store still get the in-memory ring (post-mortems within
the process); only durability is lost.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import time
from collections import deque

# same frame as the broker segments: (body_len, crc32(body)) big-endian
_FRAME = struct.Struct("!II")

EVENTS_DIR = "events"


class EventLog:
    """Append-only incident log: in-memory ring mirror (fast reads)
    backed by crc-framed, size-rolled segment files when `root` names a
    durable directory (None = ring only)."""

    def __init__(self, root=None, segment_bytes: int = 1 << 20,
                 keep: int = 4096, max_segments: int = 8,
                 subdir: str = EVENTS_DIR):
        self.segment_bytes = int(segment_bytes)
        self.max_segments = max(1, int(max_segments))
        self._ring: deque[dict] = deque(maxlen=keep)
        self._lock = threading.Lock()
        self._seq = 0
        self._dir = None
        self._f = None
        if root:
            self._dir = os.path.join(root, subdir)
            os.makedirs(self._dir, exist_ok=True)
            self._load()

    # ------------------------------------------------------------- load
    def _segments(self) -> list:
        return sorted(f for f in os.listdir(self._dir)
                      if f.endswith(".seg"))

    def _load(self) -> None:
        """Replay every segment into the ring; a torn trailing frame in
        the LAST segment is dropped whole (truncated away) — the
        SIGKILL-mid-append contract the broker segments established."""
        segs = self._segments()
        for i, name in enumerate(segs):
            path = os.path.join(self._dir, name)
            with open(path, "rb") as f:
                data = f.read()
            pos = 0
            while pos + _FRAME.size <= len(data):
                blen, crc = _FRAME.unpack_from(data, pos)
                body = data[pos + _FRAME.size: pos + _FRAME.size + blen]
                if len(body) != blen or _crc(body) != crc:
                    break                       # torn tail: drop whole
                try:
                    rec = json.loads(body)
                except ValueError:
                    break
                self._ring.append(rec)
                self._seq = max(self._seq, int(rec.get("seq", 0)) + 1)
                pos += _FRAME.size + blen
            if pos != len(data) and i == len(segs) - 1:
                with open(path, "ab") as t:
                    t.truncate(pos)

    # ------------------------------------------------------------ append
    def _active_file(self):
        """Open (or roll) the active segment; rolling prunes the oldest
        segments past `max_segments` — the size bound of 'size-rolled'."""
        if self._f is not None and not self._f.closed:
            if self._f.tell() < self.segment_bytes:
                return self._f
            self._f.close()          # roll: a fresh segment takes over
            self._f = None
        segs = self._segments()
        if self._f is None and segs:
            path = os.path.join(self._dir, segs[-1])
            if os.path.getsize(path) < self.segment_bytes:
                self._f = open(path, "ab")
                return self._f
        for name in segs[:-(self.max_segments - 1)] \
                if self.max_segments > 1 else segs:
            try:
                os.remove(os.path.join(self._dir, name))
            except OSError:
                pass
        self._f = open(os.path.join(
            self._dir, f"{self._seq:020d}.seg"), "ab")
        return self._f

    def emit(self, kind: str, **fields) -> dict:
        """THE choke point: one incident in, one framed record out.
        Never raises into the emitter — an unwritable log must not turn
        an observability note into a second failure."""
        with self._lock:
            rec = {"seq": self._seq, "ts": time.time(),
                   "kind": str(kind), **fields}
            self._seq += 1
            self._ring.append(rec)
            if self._dir is None:
                return rec
            try:
                body = json.dumps(rec, default=str).encode()
                frame = _FRAME.pack(len(body), _crc(body)) + body
                f = self._active_file()
                f.write(frame)           # ONE write: torn = whole frame
                f.flush()
                os.fsync(f.fileno())
            except OSError:
                pass
            return rec

    # ------------------------------------------------------------- reads
    def records(self, limit=None, since=None, kind=None) -> list:
        """Newest-last slice of the ring: `since` filters on the wall
        timestamp, `kind` on the event kind, `limit` keeps the newest N."""
        with self._lock:
            out = list(self._ring)
        if since is not None:
            since = float(since)
            out = [r for r in out if r.get("ts", 0) >= since]
        if kind is not None:
            out = [r for r in out if r.get("kind") == kind]
        if limit is not None:
            out = out[-int(limit):]
        return out

    def __len__(self) -> int:
        return len(self._ring)

    def close(self) -> None:
        with self._lock:
            if self._f is not None and not self._f.closed:
                self._f.close()
            self._f = None


def _crc(body: bytes) -> int:
    import zlib
    return zlib.crc32(bytes(body)) & 0xFFFFFFFF

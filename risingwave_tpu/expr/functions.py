"""Scalar function registry — vectorized jnp kernels.

The reference generates ~600 typed kernels with the `#[function("add(*int,
*int)->auto")]` proc-macro (src/expr/macro/, impl/src/scalar/). Here a kernel
is a plain python function over `Column`s traced by XLA; type dispatch is
trace-time (dtype promotion below), so one registration covers all numeric
widths — the macro expansion the reference does at compile time, jnp does by
promotion.

Null discipline: `strict` wraps a data-only kernel with AND-of-valids
propagation (reference strict eval, expr/mod.rs:167); non-strict kernels
(bool ops, case, coalesce, is_null) manage validity themselves with Kleene
semantics.
"""

from __future__ import annotations

from typing import Callable, Sequence

import jax.numpy as jnp

from ..common.chunk import Column
from ..common.types import DataType

_REGISTRY: dict[str, Callable] = {}


def register(name: str):
    def deco(fn):
        _REGISTRY[name] = fn
        return fn
    return deco


def lookup(name: str) -> Callable:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise NotImplementedError(f"scalar function {name!r} not registered") from None


def registered_functions() -> list[str]:
    return sorted(_REGISTRY)


# ---------------------------------------------------------------- helpers

def _and_valid(cols: Sequence[Column]):
    valid = None
    for c in cols:
        if c.valid is not None:
            valid = c.valid if valid is None else (valid & c.valid)
    return valid


def strict(fn):
    """Lift a data-only kernel to null-propagating (strict) semantics."""
    def wrapped(node, cols: Sequence[Column]) -> Column:
        data = fn(node, *[c.data for c in cols])
        return Column(data, _and_valid(cols))
    return wrapped


def _cast_to(data, dtype: DataType):
    return data.astype(dtype.jnp_dtype)


# ------------------------------------------------------------- arithmetic

@register("add")
@strict
def _add(node, a, b):
    return (a + b).astype(node.ret_type.jnp_dtype)


@register("subtract")
@strict
def _sub(node, a, b):
    return (a - b).astype(node.ret_type.jnp_dtype)


@register("multiply")
@strict
def _mul(node, a, b):
    return (a * b).astype(node.ret_type.jnp_dtype)


@register("divide")
def _div(node, cols):
    a, b = cols[0].data, cols[1].data
    valid = _and_valid(cols)
    if node.ret_type.is_float:
        zero = b == 0
        out = jnp.where(zero, 0.0, a / jnp.where(zero, 1, b)).astype(node.ret_type.jnp_dtype)
    else:
        zero = b == 0
        out = jnp.where(zero, 0, a // jnp.where(zero, 1, b)).astype(node.ret_type.jnp_dtype)
    # division by zero -> NULL (non-strict error handling: per-row error => NULL,
    # reference NonStrictExpression, expr/mod.rs:182)
    valid = (~zero) if valid is None else (valid & ~zero)
    return Column(out, valid)


@register("modulus")
def _mod(node, cols):
    a, b = cols[0].data, cols[1].data
    valid = _and_valid(cols)
    zero = b == 0
    out = jnp.where(zero, 0, a % jnp.where(zero, 1, b)).astype(node.ret_type.jnp_dtype)
    valid = (~zero) if valid is None else (valid & ~zero)
    return Column(out, valid)


@register("neg")
@strict
def _neg(node, a):
    return -a


@register("abs")
@strict
def _abs(node, a):
    return jnp.abs(a)


# ------------------------------------------------------------- comparison

def _cmp(op):
    @strict
    def fn(node, a, b):
        return op(a, b)
    return fn

register("equal")(_cmp(lambda a, b: a == b))
register("not_equal")(_cmp(lambda a, b: a != b))
register("less_than")(_cmp(lambda a, b: a < b))
register("less_than_or_equal")(_cmp(lambda a, b: a <= b))
register("greater_than")(_cmp(lambda a, b: a > b))
register("greater_than_or_equal")(_cmp(lambda a, b: a >= b))


@register("greatest")
@strict
def _greatest(node, *args):
    out = args[0]
    for a in args[1:]:
        out = jnp.maximum(out, a)
    return out


@register("least")
@strict
def _least(node, *args):
    out = args[0]
    for a in args[1:]:
        out = jnp.minimum(out, a)
    return out


# ---------------------------------------------------------------- boolean
# Kleene three-valued logic (reference: impl/src/scalar/conjunction.rs)

@register("and")
def _and(node, cols):
    a, b = cols
    av, bv = a.valid_mask(), b.valid_mask()
    data = a.data & b.data
    # NULL unless: any FALSE operand (result FALSE) or both valid
    false_a = av & ~a.data
    false_b = bv & ~b.data
    valid = false_a | false_b | (av & bv)
    if a.valid is None and b.valid is None:
        valid = None
    return Column(data, valid)


@register("or")
def _or(node, cols):
    a, b = cols
    av, bv = a.valid_mask(), b.valid_mask()
    data = a.data | b.data
    true_a = av & a.data
    true_b = bv & b.data
    valid = true_a | true_b | (av & bv)
    if a.valid is None and b.valid is None:
        valid = None
    return Column(data, valid)


@register("not")
@strict
def _not(node, a):
    return ~a


@register("is_null")
def _is_null(node, cols):
    (a,) = cols
    return Column(~a.valid_mask(), None)


@register("is_not_null")
def _is_not_null(node, cols):
    (a,) = cols
    return Column(a.valid_mask(), None)


# ------------------------------------------------------------ conditional

@register("case")
def _case(node, cols):
    """case(cond1, val1, cond2, val2, ..., [else]) — first-match wins."""
    n = len(cols)
    has_else = n % 2 == 1
    pairs = (n - 1) // 2 if has_else else n // 2
    if has_else:
        out, valid = cols[-1].data.astype(node.ret_type.jnp_dtype), cols[-1].valid_mask()
    else:
        out = jnp.zeros_like(cols[1].data, dtype=node.ret_type.jnp_dtype)
        valid = jnp.zeros(cols[1].capacity, dtype=bool)
    for i in reversed(range(pairs)):
        cond, val = cols[2 * i], cols[2 * i + 1]
        hit = cond.valid_mask() & cond.data
        out = jnp.where(hit, val.data.astype(node.ret_type.jnp_dtype), out)
        valid = jnp.where(hit, val.valid_mask(), valid)
    return Column(out, valid)


@register("coalesce")
def _coalesce(node, cols):
    out = cols[-1].data.astype(node.ret_type.jnp_dtype)
    valid = cols[-1].valid_mask()
    for c in reversed(cols[:-1]):
        cv = c.valid_mask()
        out = jnp.where(cv, c.data.astype(node.ret_type.jnp_dtype), out)
        valid = cv | valid
    return Column(out, valid)


# ------------------------------------------------------------------- cast

@register("cast")
def _cast(node, cols):
    (a,) = cols
    src = a.data
    dst = node.ret_type
    if dst is DataType.BOOLEAN:
        out = src != 0
    else:
        out = src.astype(dst.jnp_dtype)
    return Column(out, a.valid)


# --------------------------------------------------------------- datetime
# Timestamps are int64 microseconds; intervals are int64 microseconds.

@register("tumble_start")
@strict
def _tumble_start(node, ts, interval):
    return ts - ts % interval


@register("tumble_end")
@strict
def _tumble_end(node, ts, interval):
    return ts - ts % interval + interval


@register("extract_epoch")
@strict
def _extract_epoch(node, ts):
    return ts // 1_000_000


# ---------------------------------------------------------- type inference

_CMP_FNS = {
    "equal", "not_equal", "less_than", "less_than_or_equal",
    "greater_than", "greater_than_or_equal",
}
_BOOL_FNS = {"and", "or", "not", "is_null", "is_not_null"}
_NUMERIC_ORDER = [
    DataType.BOOLEAN, DataType.INT16, DataType.INT32, DataType.INT64,
    DataType.DECIMAL, DataType.FLOAT32, DataType.FLOAT64,
]


def _promote(types) -> DataType:
    best = DataType.INT16
    for t in types:
        if t in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ, DataType.DATE,
                 DataType.TIME, DataType.INTERVAL):
            return t
        if t not in _NUMERIC_ORDER:
            return t
        if _NUMERIC_ORDER.index(t) > _NUMERIC_ORDER.index(best):
            best = t
    return best


def infer_ret_type(name: str, args) -> DataType:
    if name in _CMP_FNS or name in _BOOL_FNS:
        return DataType.BOOLEAN
    if name in ("tumble_start", "tumble_end"):
        return DataType.TIMESTAMP
    if name == "extract_epoch":
        return DataType.INT64
    if name == "divide":
        t = _promote([a.ret_type for a in args])
        return t
    return _promote([a.ret_type for a in args])

"""Scalar function kernels — bodies behind the declarative registry.

The reference generates ~600 typed kernels with the `#[function("add(*int,
*int)->auto")]` proc-macro (src/expr/macro/, impl/src/scalar/). Here each
kernel is a plain python function over `Column`s traced by XLA and DECLARED
via `registry.kernel` with its type rule and input-kind signature — one
table entry per function, consumed by the batch evaluator, plan-time type
inference, and the mesh prelude/fused-program builder alike (see
registry.py). Type dispatch is trace-time (dtype promotion), so one entry
covers all numeric widths — the macro expansion the reference does at
compile time, jnp does by promotion.

Null discipline: `strict` wraps a data-only kernel with AND-of-valids
propagation (reference strict eval, expr/mod.rs:167); non-strict kernels
(bool ops, case, coalesce, is_null) manage validity themselves with Kleene
semantics.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..common.chunk import Column
from ..common.types import DataType
from .registry import (  # noqa: F401  (re-exported: legacy import surface)
    _and_valid, case_rule, fixed, infer_ret_type, kernel, lookup, promote,
    registered_functions, strict,
)

_BOOL = fixed(DataType.BOOLEAN)
_I64 = fixed(DataType.INT64)
_F64 = fixed(DataType.FLOAT64)
_TS = fixed(DataType.TIMESTAMP)


def _cast_to(data, dtype: DataType):
    return data.astype(dtype.jnp_dtype)


# ------------------------------------------------------------- arithmetic

@kernel("add", input_kinds=("num", "num"))
@strict
def _add(node, a, b):
    return (a + b).astype(node.ret_type.jnp_dtype)


@kernel("subtract", input_kinds=("num", "num"))
@strict
def _sub(node, a, b):
    return (a - b).astype(node.ret_type.jnp_dtype)


@kernel("multiply", input_kinds=("num", "num"))
@strict
def _mul(node, a, b):
    return (a * b).astype(node.ret_type.jnp_dtype)


@kernel("divide", input_kinds=("num", "num"))
def _div(node, cols):
    a, b = cols[0].data, cols[1].data
    valid = _and_valid(cols)
    if node.ret_type.is_float:
        zero = b == 0
        out = jnp.where(zero, 0.0, a / jnp.where(zero, 1, b)).astype(node.ret_type.jnp_dtype)
    else:
        zero = b == 0
        out = jnp.where(zero, 0, a // jnp.where(zero, 1, b)).astype(node.ret_type.jnp_dtype)
    # division by zero -> NULL (non-strict error handling: per-row error => NULL,
    # reference NonStrictExpression, expr/mod.rs:182)
    valid = (~zero) if valid is None else (valid & ~zero)
    return Column(out, valid)


@kernel("modulus", input_kinds=("num", "num"))
def _mod(node, cols):
    a, b = cols[0].data, cols[1].data
    valid = _and_valid(cols)
    zero = b == 0
    out = jnp.where(zero, 0, a % jnp.where(zero, 1, b)).astype(node.ret_type.jnp_dtype)
    valid = (~zero) if valid is None else (valid & ~zero)
    return Column(out, valid)


@kernel("neg", input_kinds=("num",))
@strict
def _neg(node, a):
    return -a


@kernel("abs", input_kinds=("num",))
@strict
def _abs(node, a):
    return jnp.abs(a)


# ------------------------------------------------------------- comparison

def _cmp(op):
    @strict
    def fn(node, a, b):
        return op(a, b)
    return fn

kernel("equal", type_rule=_BOOL,
       input_kinds=("num", "num"))(_cmp(lambda a, b: a == b))
kernel("not_equal", type_rule=_BOOL,
       input_kinds=("num", "num"))(_cmp(lambda a, b: a != b))
kernel("less_than", type_rule=_BOOL,
       input_kinds=("num", "num"))(_cmp(lambda a, b: a < b))
kernel("less_than_or_equal", type_rule=_BOOL,
       input_kinds=("num", "num"))(_cmp(lambda a, b: a <= b))
kernel("greater_than", type_rule=_BOOL,
       input_kinds=("num", "num"))(_cmp(lambda a, b: a > b))
kernel("greater_than_or_equal", type_rule=_BOOL,
       input_kinds=("num", "num"))(_cmp(lambda a, b: a >= b))


@kernel("greatest", input_kinds=("num",), variadic=True)
@strict
def _greatest(node, *args):
    out = args[0]
    for a in args[1:]:
        out = jnp.maximum(out, a)
    return out


@kernel("least", input_kinds=("num",), variadic=True)
@strict
def _least(node, *args):
    out = args[0]
    for a in args[1:]:
        out = jnp.minimum(out, a)
    return out


# ---------------------------------------------------------------- boolean
# Kleene three-valued logic (reference: impl/src/scalar/conjunction.rs)

@kernel("and", type_rule=_BOOL, input_kinds=("bool", "bool"))
def _and(node, cols):
    a, b = cols
    av, bv = a.valid_mask(), b.valid_mask()
    data = a.data & b.data
    # NULL unless: any FALSE operand (result FALSE) or both valid
    false_a = av & ~a.data
    false_b = bv & ~b.data
    valid = false_a | false_b | (av & bv)
    if a.valid is None and b.valid is None:
        valid = None
    return Column(data, valid)


@kernel("or", type_rule=_BOOL, input_kinds=("bool", "bool"))
def _or(node, cols):
    a, b = cols
    av, bv = a.valid_mask(), b.valid_mask()
    data = a.data | b.data
    true_a = av & a.data
    true_b = bv & b.data
    valid = true_a | true_b | (av & bv)
    if a.valid is None and b.valid is None:
        valid = None
    return Column(data, valid)


@kernel("not", type_rule=_BOOL, input_kinds=("bool",))
@strict
def _not(node, a):
    return ~a


@kernel("is_null", type_rule=_BOOL, input_kinds=("any",))
def _is_null(node, cols):
    (a,) = cols
    return Column(~a.valid_mask(), None)


@kernel("is_not_null", type_rule=_BOOL, input_kinds=("any",))
def _is_not_null(node, cols):
    (a,) = cols
    return Column(a.valid_mask(), None)


# ------------------------------------------------------------ conditional

@kernel("case", type_rule=case_rule, input_kinds=("bool", "any"), variadic=True)
def _case(node, cols):
    """case(cond1, val1, cond2, val2, ..., [else]) — first-match wins."""
    n = len(cols)
    has_else = n % 2 == 1
    pairs = (n - 1) // 2 if has_else else n // 2
    if has_else:
        out, valid = cols[-1].data.astype(node.ret_type.jnp_dtype), cols[-1].valid_mask()
    else:
        out = jnp.zeros_like(cols[1].data, dtype=node.ret_type.jnp_dtype)
        valid = jnp.zeros(cols[1].capacity, dtype=bool)
    for i in reversed(range(pairs)):
        cond, val = cols[2 * i], cols[2 * i + 1]
        hit = cond.valid_mask() & cond.data
        out = jnp.where(hit, val.data.astype(node.ret_type.jnp_dtype), out)
        valid = jnp.where(hit, val.valid_mask(), valid)
    return Column(out, valid)


@kernel("hll_estimate", type_rule=_I64, input_kinds=("num",), variadic=True)
def _hll_estimate(node, cols):
    from .hll import estimate_from_words_jnp
    out = estimate_from_words_jnp([c.data for c in cols])
    valid = cols[0].valid_mask()
    for c in cols[1:]:
        valid = valid & c.valid_mask()
    return Column(out, valid)


@kernel("coalesce", input_kinds=("any",), variadic=True)
def _coalesce(node, cols):
    out = cols[-1].data.astype(node.ret_type.jnp_dtype)
    valid = cols[-1].valid_mask()
    for c in reversed(cols[:-1]):
        cv = c.valid_mask()
        out = jnp.where(cv, c.data.astype(node.ret_type.jnp_dtype), out)
        valid = cv | valid
    return Column(out, valid)


# ------------------------------------------------------------------- cast

@kernel("cast", input_kinds=("any",))
def _cast(node, cols):
    (a,) = cols
    src = a.data
    dst = node.ret_type
    if dst is DataType.BOOLEAN:
        out = src != 0
    else:
        out = src.astype(dst.jnp_dtype)
    return Column(out, a.valid)


# --------------------------------------------------------------- datetime
# Timestamps are int64 microseconds; intervals are int64 microseconds.

@kernel("tumble_start", type_rule=_TS, input_kinds=("ts", "interval"))
@strict
def _tumble_start(node, ts, interval):
    return ts - ts % interval


@kernel("tumble_end", type_rule=_TS, input_kinds=("ts", "interval"))
@strict
def _tumble_end(node, ts, interval):
    return ts - ts % interval + interval


@kernel("extract_epoch", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_epoch(node, ts):
    return ts // 1_000_000


# ------------------------------------------------- numeric breadth
# (reference impl/src/scalar/{arithmetic_op,round,exp,pow,trigonometric}.rs)

@kernel("floor", input_kinds=("num",))
@strict
def _floor(node, a):
    return jnp.floor(a).astype(node.ret_type.jnp_dtype)


@kernel("ceil", input_kinds=("num",))
@strict
def _ceil(node, a):
    return jnp.ceil(a).astype(node.ret_type.jnp_dtype)


@kernel("round", input_kinds=("num",))
@strict
def _round(node, a):
    # PG/reference round halves AWAY from zero (round.rs); jnp.round is
    # banker's half-to-even. Integers round to themselves (a float64
    # round-trip would corrupt values above 2^53).
    if jnp.issubdtype(a.dtype, jnp.integer):
        return a.astype(node.ret_type.jnp_dtype)
    return jnp.trunc(a + jnp.where(a >= 0, 0.5, -0.5)).astype(
        node.ret_type.jnp_dtype)


@kernel("trunc", input_kinds=("num",))
@strict
def _trunc(node, a):
    return jnp.trunc(a).astype(node.ret_type.jnp_dtype)


@kernel("sign", input_kinds=("num",))
@strict
def _sign(node, a):
    return jnp.sign(a).astype(node.ret_type.jnp_dtype)


@kernel("pow", type_rule=_F64, input_kinds=("num", "num"))
@strict
def _pow(node, a, b):
    return jnp.power(a.astype(jnp.float64), b).astype(node.ret_type.jnp_dtype)


@kernel("sqrt", type_rule=_F64, input_kinds=("num",))
@strict
def _sqrt(node, a):
    return jnp.sqrt(a.astype(jnp.float64))


@kernel("cbrt", type_rule=_F64, input_kinds=("num",))
@strict
def _cbrt(node, a):
    return jnp.cbrt(a.astype(jnp.float64))


@kernel("exp", type_rule=_F64, input_kinds=("num",))
@strict
def _exp(node, a):
    return jnp.exp(a.astype(jnp.float64))


@kernel("ln", type_rule=_F64, input_kinds=("num",))
@strict
def _ln(node, a):
    return jnp.log(a.astype(jnp.float64))


@kernel("log10", type_rule=_F64, input_kinds=("num",))
@strict
def _log10(node, a):
    return jnp.log10(a.astype(jnp.float64))


@kernel("sin", type_rule=_F64, input_kinds=("num",))
@strict
def _sin(node, a):
    return jnp.sin(a.astype(jnp.float64))


@kernel("cos", type_rule=_F64, input_kinds=("num",))
@strict
def _cos(node, a):
    return jnp.cos(a.astype(jnp.float64))


@kernel("tan", type_rule=_F64, input_kinds=("num",))
@strict
def _tan(node, a):
    return jnp.tan(a.astype(jnp.float64))


@kernel("atan", type_rule=_F64, input_kinds=("num",))
@strict
def _atan(node, a):
    return jnp.arctan(a.astype(jnp.float64))


@kernel("bitwise_and", input_kinds=("num", "num"))
@strict
def _bit_and(node, a, b):
    return a & b


@kernel("bitwise_or", input_kinds=("num", "num"))
@strict
def _bit_or(node, a, b):
    return a | b


@kernel("bitwise_xor", input_kinds=("num", "num"))
@strict
def _bit_xor(node, a, b):
    return a ^ b


@kernel("bitwise_not", input_kinds=("num",))
@strict
def _bit_not(node, a):
    return jnp.invert(a)


@kernel("bitwise_shift_left", input_kinds=("num", "num"))
@strict
def _shl(node, a, b):
    return jnp.left_shift(a, b)


@kernel("bitwise_shift_right", input_kinds=("num", "num"))
@strict
def _shr(node, a, b):
    return jnp.right_shift(a, b)


# ------------------------------------------------- datetime breadth
# Timestamps are int64 microseconds since the unix epoch (common/types.py);
# calendar fields use the branchless civil-from-days algorithm (Howard
# Hinnant's date algorithms — pure integer arithmetic, vectorizes on TPU).
# Reference: impl/src/scalar/{extract,date_trunc,tumble}.rs.

_US_PER_DAY = 86_400_000_000


def _civil_from_days(z):
    """days since epoch -> (year, month, day), vectorized int math."""
    z = z + 719_468
    # floor_divide already floors toward -inf; Hinnant's (z - 146096)
    # adjustment is only for TRUNCATING division and would double-correct
    era = jnp.floor_divide(z, 146_097)
    doe = z - era * 146_097
    yoe = jnp.floor_divide(
        doe - jnp.floor_divide(doe, 1460) + jnp.floor_divide(doe, 36_524)
        - jnp.floor_divide(doe, 146_096), 365)
    y = yoe + era * 400
    doy = doe - (365 * yoe + jnp.floor_divide(yoe, 4)
                 - jnp.floor_divide(yoe, 100))
    mp = jnp.floor_divide(5 * doy + 2, 153)
    d = doy - jnp.floor_divide(153 * mp + 2, 5) + 1
    m = mp + jnp.where(mp < 10, 3, -9)
    y = y + (m <= 2)
    return y, m, d


def _days_and_us(ts):
    days = jnp.floor_divide(ts, _US_PER_DAY)
    return days, ts - days * _US_PER_DAY


@kernel("extract_year", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_year(node, ts):
    y, _, _ = _civil_from_days(_days_and_us(ts)[0])
    return y.astype(jnp.int64)


@kernel("extract_month", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_month(node, ts):
    _, m, _ = _civil_from_days(_days_and_us(ts)[0])
    return m.astype(jnp.int64)


@kernel("extract_day", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_day(node, ts):
    _, _, d = _civil_from_days(_days_and_us(ts)[0])
    return d.astype(jnp.int64)


@kernel("extract_hour", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_hour(node, ts):
    return jnp.floor_divide(_days_and_us(ts)[1],
                            3_600_000_000).astype(jnp.int64)


@kernel("extract_minute", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_minute(node, ts):
    return jnp.mod(jnp.floor_divide(_days_and_us(ts)[1], 60_000_000),
                   60).astype(jnp.int64)


@kernel("extract_second", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_second(node, ts):
    return jnp.mod(jnp.floor_divide(_days_and_us(ts)[1], 1_000_000),
                   60).astype(jnp.int64)


@kernel("extract_dow", type_rule=_I64, input_kinds=("ts",))
@strict
def _extract_dow(node, ts):
    # 1970-01-01 was a Thursday (dow 4, Sunday = 0)
    days = _days_and_us(ts)[0]
    return jnp.mod(days + 4, 7).astype(jnp.int64)


_TRUNC_US = {
    "second": 1_000_000,
    "minute": 60_000_000,
    "hour": 3_600_000_000,
    "day": _US_PER_DAY,
    "week": 7 * _US_PER_DAY,
}


@kernel("date_trunc_second", "date_trunc_minute", "date_trunc_hour",
        "date_trunc_day", "date_trunc_week", type_rule=_TS,
        input_kinds=("ts",))
def _date_trunc(node, cols):
    unit = node.name.rsplit("_", 1)[1]
    us = _TRUNC_US[unit]
    ts = cols[0]
    off = 3 * _US_PER_DAY if unit == "week" else 0  # weeks start Monday
    data = (jnp.floor_divide(ts.data + off, us)) * us - off
    return Column(data.astype(node.ret_type.jnp_dtype), ts.valid)

"""Aggregate functions as device reduction specs.

Reference: `AggregateFunction{update(state, StreamChunk), get_result}`
(src/expr/core/src/aggregate/mod.rs:34-55) with retractable builds for
streaming. The TPU re-design splits an aggregate into three pure pieces that
compose with segment-reduction and hash-table scatter:

  partial(values, signs, seg_ids, num_segments) -> per-segment partial states
  combine(state, partial) -> state               (associative merge)
  emit(state) -> output value

Linear aggs (count/sum) are fully retractable — a Delete row contributes with
sign -1, exactly the reference's retractable build. min/max are retractable
only with materialized input state (reference `minput`,
executor/aggregation/minput.rs); on append-only inputs (Nexmark sources) the
cheap combine form is valid and is what `append_only=True` selects. The
materialized-input path for retractable min/max lives in the hash-agg
executor, not here.

`avg` is lowered by the planner to sum/count + a projection divide (the
reference does the same in the frontend).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp

from ..common.types import DataType


class AggKind(enum.Enum):
    COUNT = "count"
    SUM = "sum"
    MIN = "min"
    MAX = "max"
    # one packed 8-byte register word of a 64-register HLL sketch
    # (expr/hll.py) — approx_count_distinct lowers to 8 of these
    HLL_REG = "hll_reg"


@dataclass(frozen=True)
class AggCall:
    kind: AggKind
    arg: Optional[int]          # input column index (None for count(*))
    ret_type: DataType
    append_only: bool = False   # input stream has no deletes
    lane: int = 0               # HLL_REG word index (buckets [8L, 8L+8))

    def spec(self) -> "AggSpec":
        return make_spec(self)


_I64_MIN = jnp.iinfo(jnp.int64).min
_I64_MAX = jnp.iinfo(jnp.int64).max


@dataclass(frozen=True)
class AggSpec:
    call: AggCall
    state_dtype: object
    init: object  # identity element

    def init_state(self, shape) -> jnp.ndarray:
        return jnp.full(shape, self.init, dtype=self.state_dtype)

    # values: [N] input column data (garbage where sign==0)
    # signs:  [N] int32 in {-1, 0, +1} (0 = masked/invisible/null)
    # seg_ids:[N] int32 segment per row; num_segments static
    def partial(self, values, signs, seg_ids, num_segments) -> jnp.ndarray:
        k = self.call.kind
        if k is AggKind.HLL_REG:
            from .hll import lane_partial
            return lane_partial(values, signs, seg_ids, num_segments,
                                self.call.lane)
        if k is AggKind.COUNT:
            return jax.ops.segment_sum(signs.astype(jnp.int64), seg_ids, num_segments)
        if k is AggKind.SUM:
            v = values.astype(self.state_dtype) * signs.astype(self.state_dtype)
            return jax.ops.segment_sum(v, seg_ids, num_segments)
        if k is AggKind.MIN:
            v = jnp.where(signs > 0, values.astype(self.state_dtype), self.init)
            return jax.ops.segment_min(v, seg_ids, num_segments)
        if k is AggKind.MAX:
            v = jnp.where(signs > 0, values.astype(self.state_dtype), self.init)
            return jax.ops.segment_max(v, seg_ids, num_segments)
        raise NotImplementedError(k)

    def combine(self, state, partial) -> jnp.ndarray:
        k = self.call.kind
        if k is AggKind.HLL_REG:
            from .hll import lane_combine
            return lane_combine(state, partial)
        if k in (AggKind.COUNT, AggKind.SUM):
            return state + partial
        if k is AggKind.MIN:
            return jnp.minimum(state, partial)
        if k is AggKind.MAX:
            return jnp.maximum(state, partial)
        raise NotImplementedError(k)

    def emit(self, state) -> jnp.ndarray:
        return state.astype(self.call.ret_type.jnp_dtype)


def make_spec(call: AggCall) -> AggSpec:
    k = call.kind
    if k is AggKind.HLL_REG:
        if not call.append_only:
            raise NotImplementedError(
                "approx_count_distinct needs an append-only input "
                "(register max cannot retract)")
        return AggSpec(call, jnp.int64, 0)
    if k is AggKind.COUNT:
        return AggSpec(call, jnp.int64, 0)
    if k is AggKind.SUM:
        dt = jnp.float64 if call.ret_type.is_float else jnp.int64
        return AggSpec(call, dt, 0 if dt == jnp.int64 else 0.0)
    if k in (AggKind.MIN, AggKind.MAX):
        if not call.append_only:
            # retractable min/max needs the materialized-input state path
            # (handled by the executor); the combine-form spec is still used
            # for within-chunk partials of insert rows.
            pass
        if call.ret_type.is_float:
            dt, ident = jnp.float64, (jnp.inf if k is AggKind.MIN else -jnp.inf)
        else:
            dt, ident = jnp.int64, (_I64_MAX if k is AggKind.MIN else _I64_MIN)
        return AggSpec(call, dt, ident)
    raise NotImplementedError(k)


def count_star(append_only: bool = False) -> AggCall:
    return AggCall(AggKind.COUNT, None, DataType.INT64, append_only)


def agg_max(col: int, ret_type: DataType = DataType.INT64, append_only: bool = False) -> AggCall:
    return AggCall(AggKind.MAX, col, ret_type, append_only)


def agg_min(col: int, ret_type: DataType = DataType.INT64, append_only: bool = False) -> AggCall:
    return AggCall(AggKind.MIN, col, ret_type, append_only)


def agg_sum(col: int, ret_type: DataType = DataType.INT64, append_only: bool = False) -> AggCall:
    return AggCall(AggKind.SUM, col, ret_type, append_only)

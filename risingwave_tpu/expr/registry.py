"""Declarative JIT kernel registry — ONE table: name -> kernel + type rule.

The reference engine scales its ~600-kernel scalar library through a
single `#[function("add(int, int) -> auto")]` registry (src/expr/macro/,
SURVEY §2.4): a kernel is declared ONCE with its signature and every
consumer — batch eval, stream eval, codegen — goes through the table.
Here the same idea lands as a declarative python table: a `KernelEntry`
carries the pure jax kernel, its TYPE RULE, and its input-kind signature,
and every consumer is a table lookup:

  * the batch Column evaluator (`FuncCall.eval` in ir.py),
  * return-type inference at plan time (`call()` -> `infer_ret_type`),
  * the mesh prelude / fused-program builder — hollowed Project/HopWindow
    stages trace the SAME kernels inside the consumer's `shard_map`
    program, so a registered kernel fuses into the mesh plane for free.

A new scalar function is one `@kernel(...)` registration (kernel body +
type rule + input kinds); no per-function lowering exists anywhere else.

Null discipline: `strict` lifts a data-only kernel to AND-of-valids
propagation (reference strict eval, expr/mod.rs:167); non-strict kernels
(bool ops, case, coalesce, is_null) manage validity themselves with
Kleene semantics.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Sequence

from ..common.chunk import Column
from ..common.types import DataType

# ------------------------------------------------------------- type rules
# A type rule is (name, args) -> DataType where args are Expr nodes with
# a .ret_type. Combinators below cover the whole built-in library; a
# bespoke callable is fine for anything irregular (see `case_rule`).

_NUMERIC_ORDER = [
    DataType.BOOLEAN, DataType.INT16, DataType.INT32, DataType.INT64,
    DataType.DECIMAL, DataType.FLOAT32, DataType.FLOAT64,
]


def _promote(types) -> DataType:
    best = DataType.INT16
    for t in types:
        if t in (DataType.TIMESTAMP, DataType.TIMESTAMPTZ, DataType.DATE,
                 DataType.TIME, DataType.INTERVAL):
            return t
        if t not in _NUMERIC_ORDER:
            return t
        if _NUMERIC_ORDER.index(t) > _NUMERIC_ORDER.index(best):
            best = t
    return best


def promote(name: str, args) -> DataType:
    """Default rule: numeric promotion over the argument types."""
    return _promote([a.ret_type for a in args])


def fixed(dt: DataType):
    """Rule: the function always returns `dt`."""
    def rule(name: str, args) -> DataType:
        return dt
    return rule


def case_rule(name: str, args) -> DataType:
    """case(c1, v1, ..., [else]) — common type of the VALUE branches."""
    n = len(args)
    vals = [args[2 * i + 1] for i in range(n // 2)]
    if n % 2 == 1:
        vals.append(args[-1])
    ts = [a.ret_type for a in vals]
    if all(t == ts[0] for t in ts):
        return ts[0]     # _promote would degrade BOOLEAN to INT16
    return _promote(ts)


# ------------------------------------------------------------- the table

@dataclass(frozen=True)
class KernelEntry:
    name: str
    kernel: Callable        # (node, cols: Sequence[Column]) -> Column
    type_rule: Callable     # (name, args) -> DataType
    input_kinds: tuple      # ("num", "num"), ("str", "lit"), ... or ()
    variadic: bool = False


_TABLE: dict[str, KernelEntry] = {}
_loaded = False


def kernel(*names: str, type_rule: Optional[Callable] = None,
           input_kinds: Sequence[str] = (), variadic: bool = False):
    """Register a kernel under one or more names.

    The decorated callable has the evaluator signature
    `(node, cols: Sequence[Column]) -> Column`; wrap a data-only body
    with `strict` for AND-of-valids null propagation."""
    rule = type_rule if type_rule is not None else promote

    def deco(fn):
        for nm in names:
            _TABLE[nm] = KernelEntry(nm, fn, rule, tuple(input_kinds),
                                     variadic)
        return fn
    return deco


def _ensure_loaded() -> None:
    # registrations live in functions.py / strings.py as import side
    # effects; lazy so `registry` itself has no import cycle
    global _loaded
    if not _loaded:
        _loaded = True
        from . import functions, strings  # noqa: F401


def lookup(name: str) -> Callable:
    _ensure_loaded()
    try:
        return _TABLE[name].kernel
    except KeyError:
        raise NotImplementedError(
            f"scalar function {name!r} not registered") from None


def entry(name: str) -> KernelEntry:
    _ensure_loaded()
    return _TABLE[name]


def entries() -> list:
    """All registered entries — the sweep surface for differential tests
    and the mesh program builder's capability listing."""
    _ensure_loaded()
    return [_TABLE[k] for k in sorted(_TABLE)]


def registered_functions() -> list:
    _ensure_loaded()
    return sorted(_TABLE)


def infer_ret_type(name: str, args) -> DataType:
    _ensure_loaded()
    e = _TABLE.get(name)
    if e is not None:
        return e.type_rule(name, args)
    return promote(name, args)


# ---------------------------------------------------------- null helpers

def _and_valid(cols: Sequence[Column]):
    valid = None
    for c in cols:
        if c.valid is not None:
            valid = c.valid if valid is None else (valid & c.valid)
    return valid


def strict(fn):
    """Lift a data-only kernel to null-propagating (strict) semantics."""
    def wrapped(node, cols: Sequence[Column]) -> Column:
        data = fn(node, *[c.data for c in cols])
        return Column(data, _and_valid(cols))
    return wrapped

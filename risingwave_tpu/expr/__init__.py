from .ir import Expr, InputRef, Literal, FuncCall, call, col, lit
from .agg import AggCall, AggKind, AggSpec, count_star, agg_max, agg_min, agg_sum
from .registry import KernelEntry, entries, kernel, registered_functions

__all__ = [
    "Expr", "InputRef", "Literal", "FuncCall", "call", "col", "lit",
    "AggCall", "AggKind", "AggSpec", "count_star", "agg_max", "agg_min",
    "agg_sum", "registered_functions", "KernelEntry", "entries", "kernel",
]

from . import strings as _strings  # registers string kernels

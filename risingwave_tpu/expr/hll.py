"""HyperLogLog for approx_count_distinct — 64 registers, byte-packed.

Reference: src/expr/impl/src/aggregate/approx_count_distinct/ (the
reference keeps per-bucket structures; the streaming variant there adds
retraction counts). TPU re-design: m = 64 registers packed as 8 int64
words of 8 bytes each, so the whole sketch is EIGHT scalar agg states
per group — the planner lowers approx_count_distinct into 8 hidden
register-word calls (one per word lane) plus an `hll_estimate` post
projection, exactly the way avg lowers to sum+count. Register update
is bytewise max, which each lane computes with 8 segment_max
reductions (a row contributes to exactly one byte of one lane).

Append-only inputs only (register max cannot retract) — the planner
refuses otherwise, like the reference's append-only agg variants.

The SAME hash / bucket / rank / estimator runs in numpy for the batch
engine (hll_estimate_numpy), so streaming and batch agree bit-for-bit
— which keeps the differential fuzzer usable over this aggregate.

Relative error ~ 1.04/sqrt(64) ~ 13%.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

M = 64               # registers
LANES = 8            # int64 words per sketch
ALPHA_M = 0.709      # alpha for m = 64


# ------------------------------------------------------------------ hash
def _splitmix64_np(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = (x + np.uint64(0x9E3779B97F4A7C15))
        x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return x ^ (x >> np.uint64(31))


def _splitmix64_jnp(x: jnp.ndarray) -> jnp.ndarray:
    x = x.astype(jnp.uint64)
    x = x + jnp.uint64(0x9E3779B97F4A7C15)
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


# rank = index of lowest set bit of the post-bucket hash bits, 1-based,
# 59 when they are all zero (58 usable bits after the 6 bucket bits).
# PURE INTEGER math (SWAR popcount of low-1): a float log2 of an exact
# power of two came back 2.999... under a cross-machine XLA AOT cache,
# flooring ranks off by one — bit positions must never route through
# floating point.
_MAX_RANK = 59


def _popcount_np(x: np.ndarray) -> np.ndarray:
    x = x.astype(np.uint64)
    with np.errstate(over="ignore"):
        x = x - ((x >> np.uint64(1)) & np.uint64(0x5555555555555555))
        x = ((x & np.uint64(0x3333333333333333))
             + ((x >> np.uint64(2)) & np.uint64(0x3333333333333333)))
        x = (x + (x >> np.uint64(4))) & np.uint64(0x0F0F0F0F0F0F0F0F)
        return ((x * np.uint64(0x0101010101010101))
                >> np.uint64(56)).astype(np.int64)


def _popcount_jnp(x: jnp.ndarray) -> jnp.ndarray:
    x = x.astype(jnp.uint64)
    x = x - ((x >> jnp.uint64(1)) & jnp.uint64(0x5555555555555555))
    x = ((x & jnp.uint64(0x3333333333333333))
         + ((x >> jnp.uint64(2)) & jnp.uint64(0x3333333333333333)))
    x = (x + (x >> jnp.uint64(4))) & jnp.uint64(0x0F0F0F0F0F0F0F0F)
    return ((x * jnp.uint64(0x0101010101010101))
            >> jnp.uint64(56)).astype(jnp.int64)


def _to_bits_np(vals: np.ndarray) -> np.ndarray:
    """Distinct VALUES must map to distinct BIT patterns: floats bitcast
    (a value-cast would collapse every float sharing an integer part)."""
    if vals.dtype == np.uint64:
        return vals
    if np.issubdtype(vals.dtype, np.floating):
        return vals.astype(np.float64).view(np.uint64)
    return vals.astype(np.int64).view(np.uint64)


def _bucket_rank_np(vals: np.ndarray):
    h = _splitmix64_np(_to_bits_np(vals))
    bucket = (h & np.uint64(M - 1)).astype(np.int64)
    rest = (h >> np.uint64(6)).astype(np.uint64)
    with np.errstate(over="ignore"):
        low = rest & (~rest + np.uint64(1))
        tz = _popcount_np(low - np.uint64(1))
    rank = np.where(rest == 0, _MAX_RANK, tz + 1)
    return bucket, rank.astype(np.int64)


def _to_bits_jnp(vals: jnp.ndarray) -> jnp.ndarray:
    if jnp.issubdtype(vals.dtype, jnp.floating):
        return jax.lax.bitcast_convert_type(
            vals.astype(jnp.float64), jnp.uint64)
    return vals.astype(jnp.int64).view(jnp.uint64)


def _bucket_rank_jnp(vals: jnp.ndarray):
    h = _splitmix64_jnp(_to_bits_jnp(vals))
    bucket = (h & jnp.uint64(M - 1)).astype(jnp.int64)
    rest = (h >> jnp.uint64(6))
    low = rest & (~rest + jnp.uint64(1))
    tz = _popcount_jnp(low - jnp.uint64(1))
    rank = jnp.where(rest == 0, _MAX_RANK, tz + 1)
    return bucket, rank.astype(jnp.int64)


# ------------------------------------------------------- streaming (jnp)
def lane_partial(values: jnp.ndarray, signs: jnp.ndarray,
                 seg_ids: jnp.ndarray, num_segments: int,
                 lane: int) -> jnp.ndarray:
    """Per-segment packed register word for `lane` (buckets
    [8*lane, 8*lane+8))."""
    bucket, rank = _bucket_rank_jnp(values)
    live = signs > 0
    in_lane = (bucket >> 3) == lane
    out = jnp.zeros(num_segments, dtype=jnp.int64)
    for b in range(8):
        v = jnp.where(live & in_lane & ((bucket & 7) == b), rank, 0)
        mx = jax.ops.segment_max(v, seg_ids, num_segments)
        out = out | (jnp.maximum(mx, 0) << (8 * b))
    return out


def lane_combine(state: jnp.ndarray, partial: jnp.ndarray) -> jnp.ndarray:
    out = jnp.zeros_like(state)
    for b in range(8):
        sh = 8 * b
        a = (state >> sh) & 255
        c = (partial >> sh) & 255
        out = out | (jnp.maximum(a, c) << sh)
    return out


def estimate_from_words_jnp(words) -> jnp.ndarray:
    """8 packed int64 word columns [G] -> per-group estimate int64."""
    regs = []
    for w in words:
        for b in range(8):
            regs.append(((w >> (8 * b)) & 255).astype(jnp.float64))
    regs = jnp.stack(regs, axis=-1)            # [G, 64]
    inv = jnp.sum(jnp.exp2(-regs), axis=-1)
    est = ALPHA_M * M * M / inv
    zeros = jnp.sum(regs == 0, axis=-1)
    small = est <= 2.5 * M
    lc = M * jnp.log(jnp.maximum(M / jnp.maximum(zeros, 1), 1.0))
    est = jnp.where(small & (zeros > 0), lc, est)
    return jnp.round(est).astype(jnp.int64)


# ----------------------------------------------------------- batch (np)
def hll_estimate_numpy(vals: np.ndarray, valid: np.ndarray,
                       seg_id: np.ndarray, n_groups: int):
    """-> (estimate int64 [n_groups], out_valid) — identical math to
    the streaming lanes (count of zero rows per group -> NULL)."""
    regs = np.zeros((n_groups, M), dtype=np.int64)
    if len(vals):
        bucket, rank = _bucket_rank_np(np.asarray(vals))
        keep = np.asarray(valid, dtype=bool)
        np.maximum.at(regs, (seg_id[keep], bucket[keep]), rank[keep])
    rf = regs.astype(np.float64)
    inv = np.sum(np.exp2(-rf), axis=-1)
    est = ALPHA_M * M * M / inv
    zeros = np.sum(regs == 0, axis=-1)
    small = est <= 2.5 * M
    lc = M * np.log(np.maximum(M / np.maximum(zeros, 1), 1.0))
    est = np.where(small & (zeros > 0), lc, est)
    cnt = np.bincount(seg_id, weights=np.asarray(valid, np.float64),
                      minlength=n_groups)
    return np.round(est).astype(np.int64), cnt > 0

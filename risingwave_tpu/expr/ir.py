"""Expression IR — the tree the planner emits and the device evaluates.

Re-design of the reference's expression engine (src/expr/core/src/expr/mod.rs:
66-94: `Expression::eval(&DataChunk) -> ArrayRef`): an `Expr` tree evaluates
vectorized over a chunk's columns with jnp ops, so a whole executor step —
expressions included — traces into one XLA computation. There is no separate
"compile" step: tracing under `jax.jit` *is* the lowering (the reference's
build-from-proto + dyn-dispatch eval becomes trace-time recursion that
disappears at runtime).

Null semantics (reference `Datum = Option<ScalarImpl>`): every value carries
an optional validity mask; strict functions propagate nulls elementwise
(mod.rs:167-182 strict/non-strict split). Non-strict evaluation maps errors to
NULL per-row instead of failing the chunk — on device, error conditions
(div-by-zero, overflow-free semantics of jnp) are masked the same way.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax.numpy as jnp

from ..common.chunk import Column
from ..common.types import DataType, GLOBAL_DICT


class Expr:
    """Base expression node. `ret_type` is static; `eval` is traced."""

    ret_type: DataType

    def eval(self, columns: Sequence[Column]) -> Column:
        raise NotImplementedError

    # convenience builders ------------------------------------------------
    def __add__(self, o): return call("add", self, _lit(o))
    def __sub__(self, o): return call("subtract", self, _lit(o))
    def __mul__(self, o): return call("multiply", self, _lit(o))
    def __ge__(self, o): return call("greater_than_or_equal", self, _lit(o))
    def __gt__(self, o): return call("greater_than", self, _lit(o))
    def __le__(self, o): return call("less_than_or_equal", self, _lit(o))
    def __lt__(self, o): return call("less_than", self, _lit(o))
    def eq(self, o): return call("equal", self, _lit(o))


@dataclass
class InputRef(Expr):
    """Column reference (reference: expr/expr_input_ref.rs)."""

    index: int
    ret_type: DataType = DataType.INT64

    def eval(self, columns):
        return columns[self.index]

    def __repr__(self):
        return f"${self.index}"


@dataclass
class Literal(Expr):
    """Constant (reference: expr/expr_literal.rs). A string literal is
    dict-encoded at plan time."""

    value: Any
    ret_type: DataType = DataType.INT64

    def eval(self, columns):
        cap = columns[0].capacity if columns else 1
        if self.value is None:
            data = jnp.zeros(cap, dtype=self.ret_type.jnp_dtype)
            return Column(data, jnp.zeros(cap, dtype=bool))
        v = self.value
        if isinstance(v, str):
            v = GLOBAL_DICT.get_or_insert(v)
        data = jnp.full(cap, v, dtype=self.ret_type.jnp_dtype)
        return Column(data, None)

    def __repr__(self):
        return f"lit({self.value})"


@dataclass
class FuncCall(Expr):
    """Scalar function application; impl looked up in the registry at trace
    time (reference: the `#[function]` sig registry, src/expr/core/src/sig/)."""

    name: str
    args: tuple
    ret_type: DataType

    def eval(self, columns):
        from .registry import lookup
        arg_cols = [a.eval(columns) for a in self.args]
        return lookup(self.name)(self, arg_cols)

    def __repr__(self):
        return f"{self.name}({', '.join(map(repr, self.args))})"


def _lit(v) -> Expr:
    if isinstance(v, Expr):
        return v
    if v is None:
        return Literal(None, DataType.INT64)   # typeless SQL NULL
    if isinstance(v, bool):
        return Literal(v, DataType.BOOLEAN)
    if isinstance(v, int):
        return Literal(v, DataType.INT64)
    if isinstance(v, float):
        return Literal(v, DataType.FLOAT64)
    if isinstance(v, str):
        return Literal(v, DataType.VARCHAR)
    raise TypeError(f"cannot lift {v!r} to a literal")


def call(name: str, *args) -> FuncCall:
    """Build a FuncCall with inferred return type."""
    from .registry import infer_ret_type
    args = tuple(_lit(a) for a in args)
    return FuncCall(name, args, infer_ret_type(name, args))


def col(index: int, dtype: DataType = DataType.INT64) -> InputRef:
    return InputRef(index, dtype)


def lit(value, dtype: Optional[DataType] = None) -> Literal:
    e = _lit(value)
    if dtype is not None:
        e.ret_type = dtype
    return e


def input_refs(e: Expr) -> set:
    """All InputRef indices in an expression tree (optimizer analysis)."""
    if isinstance(e, InputRef):
        return {e.index}
    if isinstance(e, FuncCall):
        out = set()
        for a in e.args:
            out |= input_refs(a)
        return out
    return set()


def remap_inputs(e: Expr, mapping: dict) -> Expr:
    """Rewrite InputRef indices through `mapping` (projection pruning)."""
    if isinstance(e, InputRef):
        return InputRef(mapping[e.index], e.ret_type)
    if isinstance(e, FuncCall):
        return FuncCall(e.name, tuple(remap_inputs(a, mapping)
                                      for a in e.args), e.ret_type)
    return e

"""String functions over dict-encoded VARCHAR columns.

Reference: src/expr/impl/src/scalar/{lower,upper,length,like,...}.rs —
the reference evaluates string kernels over UTF-8 payloads per row. Here
VARCHAR columns are GLOBAL_DICT int32 ids, so ANY pure string function
becomes a DEVICE GATHER through a host-built mapping table over the
dictionary: `out[i] = map[ids[i]]` where `map[k] = f(dict[k])`. One
mapping covers every row ever — O(|dict|) host work per (function,
dict-version), O(1) gathers per chunk, no per-row host string code on
the streaming path.

Mappings are cached per (key, dict length) and rebuilt when the dict
grows (a retrace; dictionaries are near-static after vocab
registration). Ids minted AFTER the mapping was traced gather the
clipped last entry — callers that mint ids mid-stream (none of the
built-in connectors do) must flush jit caches; documented limitation.

String-RESULT functions (lower/upper/...) insert their outputs into the
dict on the host at mapping-build time, so emitted ids always decode.
"""

from __future__ import annotations

import re

import jax.numpy as jnp
import numpy as np

from ..common.chunk import Column
from ..common.types import GLOBAL_DICT, DataType
from .registry import _and_valid, fixed, kernel, strict

_VARCHAR = fixed(DataType.VARCHAR)
_BOOL = fixed(DataType.BOOLEAN)
_I64 = fixed(DataType.INT64)

# (key, dict_len) -> device mapping array
_MAP_CACHE: dict = {}


def _mapping(key, fn, np_dtype):
    d = GLOBAL_DICT
    snapshot = list(d._strings)          # fn may insert (string results)
    n = len(snapshot)
    cached = _MAP_CACHE.get(key)
    if cached is not None and cached[0] == n:
        return cached[1]
    vals = np.asarray([fn(s) for s in snapshot], dtype=np_dtype)
    if n == 0:
        vals = np.zeros(1, dtype=np_dtype)
    # cache NUMPY, never device values: _mapping may run inside a jit
    # trace, and a cached traced constant would escape its trace
    _MAP_CACHE[key] = (n, vals)
    return vals


def _gather(arr, ids):
    arr = jnp.asarray(arr)
    return arr[jnp.clip(ids, 0, arr.shape[0] - 1)]


def _str_to_str(name, py_fn):
    @kernel(name, type_rule=_VARCHAR, input_kinds=("str",))
    @strict
    def _impl(node, ids, _name=name, _fn=py_fn):
        m = _mapping(("s2s", _name),
                     lambda s: GLOBAL_DICT.get_or_insert(_fn(s)),
                     np.int32)
        return _gather(m, ids)
    return _impl


_str_to_str("lower", str.lower)
_str_to_str("upper", str.upper)
_str_to_str("trim", str.strip)
_str_to_str("ltrim", str.lstrip)
_str_to_str("rtrim", str.rstrip)
_str_to_str("reverse", lambda s: s[::-1])
_str_to_str("md5", lambda s: __import__("hashlib").md5(
    s.encode()).hexdigest())


@kernel("length", "char_length", type_rule=_I64, input_kinds=("str",))
@strict
def _length(node, ids):
    m = _mapping(("len",), len, np.int64)
    return _gather(m, ids)


@kernel("ascii", type_rule=_I64, input_kinds=("str",))
@strict
def _ascii(node, ids):
    m = _mapping(("ascii",), lambda s: ord(s[0]) if s else 0, np.int64)
    return _gather(m, ids)


def _literal_arg(node, pos: int, what: str) -> str:
    from .ir import Literal
    a = node.args[pos]
    if not isinstance(a, Literal) or not isinstance(a.value, str):
        raise NotImplementedError(
            f"{node.name} needs a string literal {what} (got {a!r})")
    return a.value


def _str_pred(name, build_pred):
    """String predicate with a LITERAL second argument -> bool mapping."""
    @kernel(name, type_rule=_BOOL, input_kinds=("str", "lit"))
    def _impl(node, cols, _name=name, _build=build_pred):
        pat = _literal_arg(node, 1, "pattern")
        pred = _build(pat)
        m = _mapping((_name, pat), lambda s: bool(pred(s)), np.bool_)
        data = _gather(m, cols[0].data)
        return Column(data, _and_valid(cols[:1]))
    return _impl


def _like_matcher(pattern: str):
    rx = re.compile("".join(
        ".*" if ch == "%" else "." if ch == "_" else re.escape(ch)
        for ch in pattern) + r"\Z", re.S)
    return lambda s: rx.match(s) is not None


_str_pred("like", _like_matcher)
_str_pred("starts_with", lambda p: (lambda s: s.startswith(p)))
_str_pred("ends_with", lambda p: (lambda s: s.endswith(p)))
_str_pred("contains", lambda p: (lambda s: p in s))


@kernel("substr", type_rule=_VARCHAR, input_kinds=("str", "lit"),
        variadic=True)
@strict
def _substr(node, ids, *_rest):
    """substr(s, start[, count]) with LITERAL positions (1-based, PG)."""
    from .ir import Literal
    start = node.args[1]
    if not isinstance(start, Literal):
        raise NotImplementedError("substr needs literal positions")
    s0 = int(start.value)
    cnt = None
    if len(node.args) > 2:
        c = node.args[2]
        if not isinstance(c, Literal):
            raise NotImplementedError("substr needs literal positions")
        cnt = int(c.value)

    def f(s):
        begin = max(0, s0 - 1)
        out = s[begin:begin + cnt] if cnt is not None else s[begin:]
        return GLOBAL_DICT.get_or_insert(out)
    m = _mapping(("substr", s0, cnt), f, np.int32)
    return _gather(m, ids)


STRING_FNS = ("lower", "upper", "trim", "ltrim", "rtrim", "reverse",
              "md5", "substr")
STRING_PREDS = ("like", "starts_with", "ends_with", "contains")


def numpy_string_eval(node, ids: np.ndarray) -> np.ndarray:
    """Serving-path evaluation: the SAME mappings, gathered in numpy."""
    name = node.name
    if name in ("length", "char_length"):
        m = _mapping(("len",), len, np.int64)
    elif name == "ascii":
        m = _mapping(("ascii",), lambda s: ord(s[0]) if s else 0, np.int64)
    elif name in STRING_PREDS:
        pat = _literal_arg(node, 1, "pattern")
        builders = {"like": _like_matcher,
                    "starts_with": lambda p: (lambda s: s.startswith(p)),
                    "ends_with": lambda p: (lambda s: s.endswith(p)),
                    "contains": lambda p: (lambda s: p in s)}
        pred = builders[name](pat)
        m = _mapping((name, pat), lambda s: bool(pred(s)), np.bool_)
    elif name == "substr":
        from .ir import Literal
        s0 = int(node.args[1].value)
        cnt = int(node.args[2].value) if len(node.args) > 2 else None

        def f(s):
            begin = max(0, s0 - 1)
            out = s[begin:begin + cnt] if cnt is not None else s[begin:]
            return GLOBAL_DICT.get_or_insert(out)
        m = _mapping(("substr", s0, cnt), f, np.int32)
    else:
        fns = {"lower": str.lower, "upper": str.upper, "trim": str.strip,
               "ltrim": str.lstrip, "rtrim": str.rstrip,
               "reverse": lambda s: s[::-1],
               "md5": lambda s: __import__("hashlib").md5(
                   s.encode()).hexdigest()}
        m = _mapping(("s2s", name),
                     lambda s, _f=fns[name]: GLOBAL_DICT.get_or_insert(
                         _f(s)), np.int32)
    return m[np.clip(ids, 0, len(m) - 1)]

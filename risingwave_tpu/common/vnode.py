"""Consistent-hash virtual nodes.

Reference: src/common/src/hash/consistent_hash/vnode.rs:34-157 — 256 vnodes,
`vnode = crc32(dist_key) % 256`, computed vectorized per chunk
(`VirtualNode::compute_chunk`). Here the crc32 runs *on device* as a
byte-table-lookup kernel over the key columns' little-endian bytes, so routing
never leaves HBM. Data-distribution decisions (vnode -> shard) all key off
this single function.
"""

from __future__ import annotations

from functools import lru_cache
from typing import Sequence

import jax.numpy as jnp
import numpy as np

VNODE_BITS = 8
VNODE_COUNT = 1 << VNODE_BITS  # 256


@lru_cache(maxsize=1)
def _crc32_table_np() -> np.ndarray:
    poly = np.uint32(0xEDB88320)
    table = np.zeros(256, dtype=np.uint32)
    for i in range(256):
        c = np.uint32(i)
        for _ in range(8):
            c = np.where(c & 1, (c >> np.uint32(1)) ^ poly, c >> np.uint32(1))
        table[i] = c
    return table


def crc32_columns(columns: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Vectorized crc32 over the little-endian bytes of fixed-width columns.

    columns: arrays of identical leading shape [N]; each element contributes
    its dtype's width in bytes, column-major in argument order (a stable,
    injective-enough serialization standing in for the reference's
    value-encoding bytes).
    Returns uint32 [N].
    """
    table = jnp.asarray(_crc32_table_np())
    crc = jnp.full(columns[0].shape[0], 0xFFFFFFFF, dtype=jnp.uint32)
    for col in columns:
        nbytes = col.dtype.itemsize
        # reinterpret to unsigned of same width, then peel bytes LE
        u = col.view(jnp.dtype(f"uint{8 * nbytes}")) if col.dtype != jnp.bool_ else col.astype(jnp.uint8)
        u = u.astype(jnp.uint64)
        for b in range(nbytes):
            byte = ((u >> jnp.uint64(8 * b)) & jnp.uint64(0xFF)).astype(jnp.uint32)
            idx = (crc ^ byte) & jnp.uint32(0xFF)
            crc = (crc >> jnp.uint32(8)) ^ jnp.take(table, idx.astype(jnp.int32))
    return crc ^ jnp.uint32(0xFFFFFFFF)


def compute_vnodes(key_columns: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """vnode per row = splitmix64(key columns) % 256  (int32 [N]).

    Reference semantics at vnode.rs:126 (`compute_chunk`): one consistent
    hash over the distribution-key columns, modulo VNODE_COUNT. The
    reference hashes with crc32; here the mixer is a splitmix64 chain —
    measured on TPU, the table-driven crc's 8 byte-gathers cost ~13ms per
    131k-row chunk (small-table gathers do not vectorize on the VPU) and
    even a branchless bitwise crc32 costs 6.6ms from its 64-step serial
    dependency chain, while the splitmix chain is pure wide ALU ops at
    microseconds. Any consistent hash preserves the vnode contract; crc32
    itself remains (crc32_columns) for value-serialization golden tests.
    """
    h = jnp.full(key_columns[0].shape[0], 0x243F6A8885A308D3,
                 dtype=jnp.uint64)
    for col in key_columns:
        nbytes = col.dtype.itemsize
        u = (col.view(jnp.dtype(f"uint{8 * nbytes}"))
             if col.dtype != jnp.bool_ else col.astype(jnp.uint8))
        x = h ^ (u.astype(jnp.uint64) * jnp.uint64(0x9E3779B97F4A7C15))
        x = x + jnp.uint64(0x9E3779B97F4A7C15)
        x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
        x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
        h = x ^ (x >> jnp.uint64(31))
    return (h & jnp.uint64(VNODE_COUNT - 1)).astype(jnp.int32)


def crc32_numpy(columns: Sequence[np.ndarray]) -> np.ndarray:
    """Host mirror of crc32_columns (golden tests, meta-side placement)."""
    table = _crc32_table_np()
    crc = np.full(len(columns[0]), 0xFFFFFFFF, dtype=np.uint32)
    for col in columns:
        col = np.asarray(col)
        if col.dtype == np.bool_:
            col = col.astype(np.uint8)
        nbytes = col.dtype.itemsize
        u = col.view(f"uint{8 * nbytes}").astype(np.uint64)
        for b in range(nbytes):
            byte = ((u >> np.uint64(8 * b)) & np.uint64(0xFF)).astype(np.uint32)
            idx = (crc ^ byte) & np.uint32(0xFF)
            crc = (crc >> np.uint32(8)) ^ table[idx]
    return crc ^ np.uint32(0xFFFFFFFF)


def compute_vnodes_numpy(key_columns: Sequence[np.ndarray]) -> np.ndarray:
    """Host mirror of compute_vnodes — MUST produce identical vnodes (the
    meta side places state by the same hash the device routes by)."""
    with np.errstate(over="ignore"):
        h = np.full(len(key_columns[0]), 0x243F6A8885A308D3, dtype=np.uint64)
        for col in key_columns:
            col = np.asarray(col)
            if col.dtype == np.bool_:
                col = col.astype(np.uint8)
            u = col.view(f"uint{8 * col.dtype.itemsize}").astype(np.uint64)
            x = h ^ (u * np.uint64(0x9E3779B97F4A7C15))
            x = x + np.uint64(0x9E3779B97F4A7C15)
            x = (x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
            x = (x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
            h = x ^ (x >> np.uint64(31))
    return (h & np.uint64(VNODE_COUNT - 1)).astype(np.int32)

"""Configuration system — typed sections, TOML files, env overrides.

Reference: three tiers (src/common/src/config.rs `RwConfig` TOML with
server/streaming/storage sections; `ALTER SYSTEM` mutable system params in
system_param/mod.rs with `barrier_interval_ms=1000`,
`checkpoint_frequency=1`; per-session vars). Collapsed here to the two
tiers the engine uses: `RwConfig` (TOML/dict + `RW_`-prefixed env
overrides) and `SystemParams` (runtime-mutable, the ALTER SYSTEM
analogue).
"""

from __future__ import annotations

import os

try:                                    # stdlib from 3.11; 3.10 images may
    import tomllib                      # carry the identical `tomli` instead
except ImportError:                     # pragma: no cover
    try:
        import tomli as tomllib
    except ImportError:
        tomllib = None
from dataclasses import dataclass, field, fields
from typing import Optional


def _coerce(current, raw):
    """Coerce a dict/env value to the field's type; bools parse strings
    ('false' must not be truthy)."""
    if isinstance(current, bool):
        if isinstance(raw, bool):
            return raw
        return str(raw).lower() in ("1", "true", "t", "on", "yes")
    return type(current)(raw)


@dataclass
class StreamingConfig:
    barrier_interval_ms: int = 1000
    checkpoint_frequency: int = 1
    # bounded window of sealed-but-uncommitted checkpoint epochs the
    # background uploader may hold (meta/barrier_manager.py); 0 = inline
    # sync on the barrier path (the pre-pipeline behavior)
    checkpoint_max_inflight: int = 2
    chunk_size: int = 8192
    channel_capacity: int = 64
    max_inflight_chunks: int = 16
    # HBM budget for device-resident executor state (memory/manager.py):
    # 0 = accounting only (today's grow-or-fail behavior); > 0 = the
    # memory manager evicts cold key groups to host at barriers to keep
    # the accounted total under budget
    hbm_budget_bytes: int = 0
    # 'lru' (default) = epoch-stamped coldest-first eviction when a
    # budget is set; 'none' = never evict even when over budget
    memory_eviction_policy: str = "lru"
    # serving layer (serving/): bounded worker-thread pool for batch
    # queries over pinned snapshot caches — at most this many queries
    # execute concurrently, excess callers queue at admission
    serving_max_concurrency: int = 4
    # per-query serving timeout; 0 = unbounded (the worker thread is
    # abandoned on timeout, the client gets the error immediately)
    serving_query_timeout_ms: int = 0
    # 1 = maintain per-MV snapshot caches incrementally from the
    # changelog (queries pin an epoch); 0 = every SELECT re-scans the
    # committed LSM snapshot (the pre-serving behavior)
    serving_cache: int = 1
    # observability (stream/monitor.py): 'off' = no per-actor
    # instrumentation, 'info' = trace phase splits only (default),
    # 'debug' = full per-actor/per-channel labelled series (the
    # reference MetricLevel knob)
    metric_level: str = "info"
    # monitor HTTP endpoint (meta/monitor_service.py): /metrics,
    # /healthz, /debug/traces, /debug/await_tree; 0 = disabled
    monitor_port: int = 0
    # stuck-barrier watchdog: an in-flight epoch older than this logs
    # one diagnosis and bumps barrier_stalls_total; 0 disables
    barrier_stall_threshold_ms: int = 60000


@dataclass
class StorageConfig:
    l0_compact_threshold: int = 8
    object_store_root: str = "./state"


@dataclass
class ServerConfig:
    metrics_enabled: bool = True


@dataclass
class RwConfig:
    streaming: StreamingConfig = field(default_factory=StreamingConfig)
    storage: StorageConfig = field(default_factory=StorageConfig)
    server: ServerConfig = field(default_factory=ServerConfig)

    @classmethod
    def from_dict(cls, d: dict) -> "RwConfig":
        cfg = cls()
        for section_field in fields(cls):
            sec = getattr(cfg, section_field.name)
            for k, v in d.get(section_field.name, {}).items():
                if not hasattr(sec, k):
                    raise ValueError(
                        f"unknown config key {section_field.name}.{k}")
                cur = getattr(sec, k)
                setattr(sec, k, _coerce(cur, v))
        return cfg

    @classmethod
    def from_toml(cls, path: str) -> "RwConfig":
        if tomllib is None:
            raise RuntimeError(
                "TOML config files need Python >= 3.11 (tomllib) or the "
                "tomli package; use RwConfig.from_dict / env overrides")
        with open(path, "rb") as f:
            return cls.from_dict(tomllib.load(f))

    def apply_env(self, environ=None) -> "RwConfig":
        """RW_<SECTION>_<KEY>=value overrides (highest precedence)."""
        environ = environ if environ is not None else os.environ
        for section_field in fields(type(self)):
            sec = getattr(self, section_field.name)
            for f in fields(type(sec)):
                env_key = f"RW_{section_field.name.upper()}_{f.name.upper()}"
                if env_key in environ:
                    setattr(sec, f.name,
                            _coerce(getattr(sec, f.name), environ[env_key]))
        return self


class SystemParams:
    """Cluster-wide runtime-mutable params (ALTER SYSTEM analogue);
    observers are notified on change (the notification-service shape)."""

    MUTABLE = {"barrier_interval_ms", "checkpoint_frequency",
               "checkpoint_max_inflight", "hbm_budget_bytes",
               "memory_eviction_policy", "serving_max_concurrency",
               "serving_query_timeout_ms", "serving_cache",
               "metric_level", "monitor_port",
               "barrier_stall_threshold_ms"}

    def __init__(self, config: Optional[RwConfig] = None):
        cfg = config or RwConfig()
        self._values = {
            "barrier_interval_ms": cfg.streaming.barrier_interval_ms,
            "checkpoint_frequency": cfg.streaming.checkpoint_frequency,
            "checkpoint_max_inflight":
                cfg.streaming.checkpoint_max_inflight,
            "hbm_budget_bytes": cfg.streaming.hbm_budget_bytes,
            "memory_eviction_policy":
                cfg.streaming.memory_eviction_policy,
            "serving_max_concurrency":
                cfg.streaming.serving_max_concurrency,
            "serving_query_timeout_ms":
                cfg.streaming.serving_query_timeout_ms,
            "serving_cache": cfg.streaming.serving_cache,
            "metric_level": cfg.streaming.metric_level,
            "monitor_port": cfg.streaming.monitor_port,
            "barrier_stall_threshold_ms":
                cfg.streaming.barrier_stall_threshold_ms,
        }
        self._observers = []

    def get(self, name: str):
        return self._values[name]

    def set(self, name: str, value) -> None:
        if name not in self.MUTABLE:
            raise ValueError(f"system param {name!r} is not mutable")
        self._values[name] = value
        for fn in self._observers:
            fn(name, value)

    def subscribe(self, fn) -> None:
        self._observers.append(fn)

"""Epochs — the global logical clock of the barrier protocol.

Reference: src/common/src/util/epoch.rs:30-39,118-120 — a 64-bit epoch is
physical milliseconds since an engine epoch origin shifted left 16 bits; the
low 16 bits are a sequence for intra-epoch spills. `EpochPair{curr, prev}`
rides every barrier.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

EPOCH_PHYSICAL_SHIFT = 16
# 2022-01-01T00:00:00Z, an arbitrary engine origin (reference uses its own).
EPOCH_ORIGIN_MS = 1_640_995_200_000

INVALID_EPOCH = 0


def physical_now_ms() -> int:
    return int(time.time() * 1000) - EPOCH_ORIGIN_MS


def from_physical(ms: int) -> int:
    return ms << EPOCH_PHYSICAL_SHIFT


def to_physical(epoch: int) -> int:
    return epoch >> EPOCH_PHYSICAL_SHIFT


def next_epoch(prev: int) -> int:
    """Strictly-increasing epoch from the wall clock (or prev+1 if the clock
    has not advanced a full millisecond)."""
    cand = from_physical(physical_now_ms())
    return cand if cand > prev else prev + 1


@dataclass(frozen=True)
class EpochPair:
    curr: int
    prev: int

    @staticmethod
    def new_initial(curr: int) -> "EpochPair":
        return EpochPair(curr, INVALID_EPOCH)

    def bump(self, new_curr: int) -> "EpochPair":
        return EpochPair(new_curr, self.curr)

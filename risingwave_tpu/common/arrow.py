"""Arrow interop — the zero-copy on/off ramp for chunks.

Reference: src/common/src/array/arrow/arrow_impl.rs:55 (Array <-> arrow
conversions powering UDFs, sinks and the iceberg path). SURVEY calls this
"the DLPack on-ramp for TPU": fixed-width columns convert without copying
(numpy view -> arrow buffer and back), and the engine's dict-encoded
VARCHAR maps 1:1 onto Arrow dictionary arrays — the dictionary IS
GLOBAL_DICT's decode table, so string payloads never materialize per row.
"""

from __future__ import annotations

from typing import Optional

import numpy as np
import pyarrow as pa

from .chunk import StreamChunk
from .types import DataType, Field, GLOBAL_DICT, Schema

_ARROW_TYPES = {
    DataType.BOOLEAN: pa.bool_(),
    DataType.INT16: pa.int16(),
    DataType.INT32: pa.int32(),
    DataType.INT64: pa.int64(),
    DataType.SERIAL: pa.int64(),
    DataType.FLOAT32: pa.float32(),
    DataType.FLOAT64: pa.float64(),
    DataType.DECIMAL: pa.int64(),          # scaled int (engine encoding)
    DataType.TIMESTAMP: pa.timestamp("us"),
}


def arrow_schema(schema: Schema) -> pa.Schema:
    fields = []
    for f in schema:
        if f.data_type is DataType.VARCHAR:
            t = pa.dictionary(pa.int32(), pa.string())
        else:
            t = _ARROW_TYPES[f.data_type]
        fields.append(pa.field(f.name, t))
    return pa.schema(fields)


def chunk_to_arrow(chunk: StreamChunk) -> pa.RecordBatch:
    """Visible rows -> RecordBatch. Fixed-width columns transfer as one
    buffer each (no per-row python); VARCHAR becomes a DictionaryArray
    whose dictionary is the prefix of GLOBAL_DICT covering the ids."""
    vis = np.asarray(chunk.vis)
    arrays = []
    for f, col in zip(chunk.schema, chunk.columns):
        data = np.asarray(col.data)[vis]
        valid = np.asarray(col.valid_mask())[vis]
        mask = ~valid if not valid.all() else None
        if f.data_type is DataType.VARCHAR:
            ids = data.astype(np.int32)
            hi = int(ids.max(initial=-1))
            dictionary = pa.array(
                GLOBAL_DICT.decode_many(np.arange(hi + 1)),
                type=pa.string())
            idx = pa.array(ids, type=pa.int32(), mask=mask)
            arrays.append(pa.DictionaryArray.from_arrays(idx, dictionary))
        elif f.data_type is DataType.TIMESTAMP:
            arrays.append(pa.array(data, type=pa.timestamp("us"),
                                   mask=mask))
        else:
            arrays.append(pa.array(data, type=_ARROW_TYPES[f.data_type],
                                   mask=mask))
    return pa.RecordBatch.from_arrays(arrays, schema=arrow_schema(
        chunk.schema))


def batch_to_chunk(batch: pa.RecordBatch, schema: Schema,
                   capacity: Optional[int] = None) -> StreamChunk:
    """RecordBatch -> StreamChunk (all rows visible, op Insert). String
    and dictionary columns intern through GLOBAL_DICT; fixed-width
    columns convert as whole buffers."""
    n = batch.num_rows
    arrays, valids = [], []
    for f, col in zip(schema, batch.columns):
        if isinstance(col, pa.ChunkedArray):
            col = col.combine_chunks()
        valid = np.asarray(col.is_valid())
        if f.data_type is DataType.VARCHAR:
            if pa.types.is_dictionary(col.type):
                dic = col.dictionary.to_pylist()
                remap = np.asarray(
                    [GLOBAL_DICT.get_or_insert(s if s is not None else "")
                     for s in dic], dtype=np.int32)
                idx = np.asarray(col.indices.fill_null(0))
                arrays.append(remap[idx])
            else:
                arrays.append(np.asarray(
                    [GLOBAL_DICT.get_or_insert(s) if s is not None else 0
                     for s in col.to_pylist()], dtype=np.int32))
        elif f.data_type is DataType.TIMESTAMP:
            arrays.append(np.asarray(col.cast(pa.int64()).fill_null(0),
                                     dtype=np.int64))
        else:
            arrays.append(np.asarray(
                col.fill_null(0).cast(_ARROW_TYPES[f.data_type]),
                dtype=f.data_type.np_dtype))
        valids.append(None if valid.all() else valid)
    cap = capacity or max(1, 1 << max(0, (n - 1).bit_length()))
    return StreamChunk.from_numpy(schema, arrays, capacity=cap,
                                  valids=valids)


def schema_from_arrow(aschema: pa.Schema) -> Schema:
    fields = []
    for f in aschema:
        if pa.types.is_dictionary(f.type) or pa.types.is_string(f.type) \
                or pa.types.is_large_string(f.type):
            t = DataType.VARCHAR
        elif pa.types.is_timestamp(f.type):
            t = DataType.TIMESTAMP
        elif pa.types.is_boolean(f.type):
            t = DataType.BOOLEAN
        elif pa.types.is_float32(f.type):
            t = DataType.FLOAT32
        elif pa.types.is_floating(f.type):
            t = DataType.FLOAT64
        elif pa.types.is_int16(f.type):
            t = DataType.INT16
        elif pa.types.is_int32(f.type):
            t = DataType.INT32
        else:
            t = DataType.INT64
        fields.append(Field(f.name, t))
    return Schema(tuple(fields))

"""Data types of the engine.

Re-design of the reference's `DataType` enum (src/common/src/types/mod.rs:110-165)
for a TPU columnar engine: every type has a fixed-width device representation
(jnp dtype); variable-width types (Varchar/Bytea/Jsonb) are dictionary-encoded
on the host and appear on device as int32 ids. Decimal is a scaled int64
(fixed-point) — TPU has no decimal unit, and Nexmark/TPC-H money columns fit.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass

import jax.numpy as jnp
import numpy as np


class DataType(enum.Enum):
    BOOLEAN = "boolean"
    INT16 = "int16"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT32 = "float32"
    FLOAT64 = "float64"
    # Fixed-point decimal: int64 mantissa with per-column scale (digits after
    # the point). Matches reference Decimal semantics for the benchmark
    # workloads; scale is carried in the Field, not the array.
    DECIMAL = "decimal"
    DATE = "date"            # int32 days since unix epoch
    TIME = "time"            # int64 microseconds since midnight
    TIMESTAMP = "timestamp"  # int64 microseconds since unix epoch (naive)
    TIMESTAMPTZ = "timestamptz"  # int64 microseconds since unix epoch (UTC)
    INTERVAL = "interval"    # int64 microseconds (months/days folded; subset)
    VARCHAR = "varchar"      # int32 dictionary id (host-side StringDictionary)
    BYTEA = "bytea"          # int32 dictionary id
    JSONB = "jsonb"          # int32 dictionary id
    SERIAL = "serial"        # int64 (vnode-prefixed row ids)

    # ------------------------------------------------------------------
    @property
    def np_dtype(self) -> np.dtype:
        return np.dtype(_NP_DTYPE[self])

    @property
    def jnp_dtype(self):
        return _NP_DTYPE[self]

    @property
    def is_dict_encoded(self) -> bool:
        return self in (DataType.VARCHAR, DataType.BYTEA, DataType.JSONB)

    @property
    def is_float(self) -> bool:
        return self in (DataType.FLOAT32, DataType.FLOAT64)

    @property
    def is_integral(self) -> bool:
        return self in (
            DataType.INT16, DataType.INT32, DataType.INT64, DataType.SERIAL,
            DataType.DECIMAL, DataType.DATE, DataType.TIME, DataType.TIMESTAMP,
            DataType.TIMESTAMPTZ, DataType.INTERVAL,
        )

    def zero_value(self):
        if self is DataType.BOOLEAN:
            return False
        if self.is_float:
            return 0.0
        return 0


_NP_DTYPE = {
    DataType.BOOLEAN: np.bool_,
    DataType.INT16: np.int16,
    DataType.INT32: np.int32,
    DataType.INT64: np.int64,
    DataType.FLOAT32: np.float32,
    DataType.FLOAT64: np.float64,
    DataType.DECIMAL: np.int64,
    DataType.DATE: np.int32,
    DataType.TIME: np.int64,
    DataType.TIMESTAMP: np.int64,
    DataType.TIMESTAMPTZ: np.int64,
    DataType.INTERVAL: np.int64,
    DataType.VARCHAR: np.int32,
    DataType.BYTEA: np.int32,
    DataType.JSONB: np.int32,
    DataType.SERIAL: np.int64,
}


@dataclass(frozen=True)
class Field:
    """A named, typed column of a schema (reference: catalog Field)."""

    name: str
    data_type: DataType
    # decimal scale (digits after the point) when data_type == DECIMAL
    scale: int = 0


@dataclass(frozen=True)
class Schema:
    fields: tuple[Field, ...]

    def __post_init__(self):
        object.__setattr__(self, "fields", tuple(self.fields))

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)

    def __getitem__(self, i: int) -> Field:
        return self.fields[i]

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(f.name for f in self.fields)

    @property
    def data_types(self) -> tuple[DataType, ...]:
        return tuple(f.data_type for f in self.fields)

    def index_of(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def select(self, indices) -> "Schema":
        return Schema(tuple(self.fields[i] for i in indices))

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.fields + other.fields)


def schema(*pairs) -> Schema:
    """schema(("a", DataType.INT64), ("b", DataType.FLOAT64))"""
    return Schema(tuple(Field(n, t) for n, t in pairs))


class StringDictionary:
    """Host-side append-only string<->id mapping for dict-encoded columns.

    The device only ever sees int32 ids; equality/group-by/join on strings is
    exact on ids. Ordering on dict-encoded columns is NOT id order — ordered
    ops on strings must go through the host path.
    """

    __slots__ = ("_strings", "_ids", "_mint_lock")

    def __init__(self):
        import threading
        self._strings: list[str] = []
        self._ids: dict[str, int] = {}
        # serving queries bind literals on worker threads; minting must
        # be atomic or two threads can hand out the same id for two
        # different strings. Reads (decode, the hit path below) stay
        # lock-free — the structures are append-only.
        self._mint_lock = threading.Lock()

    def __len__(self):
        return len(self._strings)

    def get_or_insert(self, s: str) -> int:
        i = self._ids.get(s)
        if i is None:
            with self._mint_lock:
                i = self._ids.get(s)
                if i is None:
                    i = len(self._strings)
                    self._strings.append(s)
                    self._ids[s] = i
        return i

    def encode_many(self, strings) -> np.ndarray:
        return np.asarray([self.get_or_insert(s) for s in strings], dtype=np.int32)

    def decode(self, i: int) -> str:
        return self._strings[i]

    def decode_many(self, ids) -> list[str]:
        return [self._strings[int(i)] for i in np.asarray(ids).ravel()]


# A process-global dictionary: ids are consistent across all columns, which
# lets dict-encoded values flow between operators without re-encoding.
GLOBAL_DICT = StringDictionary()


# ------------------------------------------------------- dict durability
# Open-vocabulary sources (connectors/file_source.py) mint dict ids at
# parse time; MV state then stores those ids. The dictionary is
# append-only with stable ids, so durability is an append-only DELTA LOG
# in the object store: each checkpoint persists the strings minted since
# the last one (meta/barrier_manager.py calls persist_dict_delta before
# the epoch's manifest commit), and recovery replays the log IN ORDER
# before anything re-encodes (frontend/session.py calls load_dict_log at
# store-open). Reference: the dictionary the reference never needs —
# its VARCHAR cells are inline bytes; dict encoding is the TPU design's
# device representation, so its durability is a TPU-design obligation.

_DICT_LOG_PREFIX = "dict/"


def persist_dict_delta(objects, cursor: int) -> int:
    """Append strings [cursor, len) to the log; returns the new cursor."""
    import json as _json
    n = len(GLOBAL_DICT)
    if n > cursor:
        blob = _json.dumps(GLOBAL_DICT._strings[cursor:n]).encode()
        objects.upload(f"{_DICT_LOG_PREFIX}{cursor:012d}-{n:012d}", blob)
        cursor = n
    return cursor


def load_dict_log(objects) -> int:
    """Replay the delta log into GLOBAL_DICT; returns the restored
    length. Tolerates overlapping ranges (re-persisted prefixes) but
    REQUIRES content agreement — a mismatch means two incompatible
    dictionaries and must fail loudly, not decode garbage."""
    import json as _json
    paths = sorted(objects.list(_DICT_LOG_PREFIX))
    covered = 0      # ids the LOG covers — pre-existing in-process
    #                  strings beyond it still need a first delta
    for p in paths:
        name = p[len(_DICT_LOG_PREFIX):] if p.startswith(_DICT_LOG_PREFIX) \
            else p.rsplit("/", 1)[-1]
        start = int(name.split("-")[0])
        covered = max(covered, int(name.split("-")[1]))
        strings = _json.loads(objects.read(p))
        have = len(GLOBAL_DICT)
        if start > have:
            raise RuntimeError(
                f"dict log gap: segment starts at {start}, have {have}")
        for k, s in enumerate(strings):
            i = start + k
            if i < have:
                if GLOBAL_DICT._strings[i] != s:
                    raise RuntimeError(
                        f"dict log mismatch at id {i}: "
                        f"{GLOBAL_DICT._strings[i]!r} != {s!r}")
            else:
                got = GLOBAL_DICT.get_or_insert(s)
                assert got == i, f"dict id drift: {got} != {i}"
                have = got + 1
    return covered

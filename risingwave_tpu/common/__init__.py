from .types import DataType, Field, Schema, StringDictionary, GLOBAL_DICT, schema
from .chunk import (
    Column,
    StreamChunk,
    StreamChunkBuilder,
    empty_chunk,
    op_sign,
    OP_INSERT,
    OP_DELETE,
    OP_UPDATE_DELETE,
    OP_UPDATE_INSERT,
    DEFAULT_CHUNK_CAPACITY,
)
from .vnode import VNODE_COUNT, compute_vnodes, compute_vnodes_numpy, crc32_columns
from .epoch import EpochPair, next_epoch, INVALID_EPOCH

__all__ = [
    "DataType", "Field", "Schema", "StringDictionary", "GLOBAL_DICT", "schema",
    "Column", "StreamChunk", "StreamChunkBuilder", "empty_chunk", "op_sign",
    "OP_INSERT", "OP_DELETE", "OP_UPDATE_DELETE", "OP_UPDATE_INSERT",
    "DEFAULT_CHUNK_CAPACITY",
    "VNODE_COUNT", "compute_vnodes", "compute_vnodes_numpy", "crc32_columns",
    "EpochPair", "next_epoch", "INVALID_EPOCH",
]
from .config import RwConfig, StreamingConfig, StorageConfig, SystemParams

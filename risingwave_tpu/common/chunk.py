"""Columnar chunks — the data quantum flowing between executors.

Re-design of the reference's DataChunk/StreamChunk
(src/common/src/array/data_chunk.rs:66, array/stream_chunk.rs:44-92) for XLA:
a chunk is a *fixed-capacity* struct-of-arrays pytree. Row count is dynamic
only through the visibility mask — shapes are static so every executor step
compiles once. The reference already carries a visibility bitmap on every
chunk; here it is load-bearing for padding as well.

Ops follow reference `Op` (stream_chunk.rs:44-49):
  INSERT=0  DELETE=1  UPDATE_DELETE=2  UPDATE_INSERT=3
`op_sign` maps insert-like ops to +1 and delete-like to -1 — the sign of a
row's contribution to any linear aggregate, which is how changelog semantics
stay branch-free on device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .types import DataType, Schema

# Op encoding (int8 on device)
OP_INSERT = 0
OP_DELETE = 1
OP_UPDATE_DELETE = 2
OP_UPDATE_INSERT = 3

DEFAULT_CHUNK_CAPACITY = 4096


def op_sign(ops: jnp.ndarray) -> jnp.ndarray:
    """+1 for Insert/UpdateInsert, -1 for Delete/UpdateDelete."""
    is_insert = (ops == OP_INSERT) | (ops == OP_UPDATE_INSERT)
    return jnp.where(is_insert, jnp.int32(1), jnp.int32(-1))


@jax.tree_util.register_pytree_node_class
@dataclass
class Column:
    """One column: fixed-width data + optional validity (None = all valid)."""

    data: jnp.ndarray
    valid: Optional[jnp.ndarray] = None  # bool mask, True = non-null

    def tree_flatten(self):
        return (self.data, self.valid), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def valid_mask(self) -> jnp.ndarray:
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.valid

    def take(self, idx: jnp.ndarray) -> "Column":
        return Column(
            jnp.take(self.data, idx, axis=0),
            None if self.valid is None else jnp.take(self.valid, idx, axis=0),
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class StreamChunk:
    """ops + columns + visibility. A DataChunk is a StreamChunk with all-INSERT
    ops (the reference keeps two types; one suffices here — batch executors
    simply ignore `ops`)."""

    columns: tuple[Column, ...]
    ops: jnp.ndarray       # int8 [CAP]
    vis: jnp.ndarray       # bool [CAP]
    schema: Schema         # static aux

    def tree_flatten(self):
        return (self.columns, self.ops, self.vis), self.schema

    @classmethod
    def tree_unflatten(cls, schema, children):
        columns, ops, vis = children
        return cls(tuple(columns), ops, vis, schema)

    # -- shape ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.ops.shape[0]

    def cardinality(self) -> jnp.ndarray:
        """Number of visible rows (device scalar)."""
        return jnp.sum(self.vis.astype(jnp.int32))

    def num_rows_host(self) -> int:
        return int(np.asarray(self.cardinality()))

    # -- transforms ----------------------------------------------------
    def with_vis(self, vis: jnp.ndarray) -> "StreamChunk":
        return StreamChunk(self.columns, self.ops, vis, self.schema)

    def mask(self, keep: jnp.ndarray) -> "StreamChunk":
        return self.with_vis(self.vis & keep)

    def project(self, indices: Sequence[int]) -> "StreamChunk":
        return StreamChunk(
            tuple(self.columns[i] for i in indices),
            self.ops, self.vis, self.schema.select(indices),
        )

    def take(self, idx: jnp.ndarray, vis: jnp.ndarray) -> "StreamChunk":
        """Row gather (used by compaction / dispatch routing)."""
        return StreamChunk(
            tuple(c.take(idx) for c in self.columns),
            jnp.take(self.ops, idx, axis=0), vis, self.schema,
        )

    def compact(self) -> "StreamChunk":
        """Move visible rows to the front (stable). Keeps capacity."""
        cap = self.capacity
        order = jnp.argsort(~self.vis, stable=True)
        n = self.cardinality()
        new_vis = jnp.arange(cap) < n
        return self.take(order, new_vis)

    # -- host I/O ------------------------------------------------------
    @staticmethod
    def from_numpy(
        schema: Schema,
        arrays: Sequence[np.ndarray],
        ops: Optional[np.ndarray] = None,
        capacity: Optional[int] = None,
        valids: Optional[Sequence[Optional[np.ndarray]]] = None,
    ) -> "StreamChunk":
        n = len(arrays[0]) if arrays else 0
        cap = capacity or max(DEFAULT_CHUNK_CAPACITY, n)
        assert n <= cap, f"{n} rows > capacity {cap}"
        cols = []
        for i, (arr, f) in enumerate(zip(arrays, schema)):
            arr = np.asarray(arr, dtype=f.data_type.np_dtype)
            pad = np.zeros(cap, dtype=f.data_type.np_dtype)
            pad[:n] = arr
            valid = None
            if valids is not None and valids[i] is not None:
                v = np.zeros(cap, dtype=bool)
                v[:n] = valids[i]
                valid = jnp.asarray(v)
            cols.append(Column(jnp.asarray(pad), valid))
        ops_arr = np.zeros(cap, dtype=np.int8)
        if ops is not None:
            ops_arr[:n] = np.asarray(ops, dtype=np.int8)
        vis = np.zeros(cap, dtype=bool)
        vis[:n] = True
        return StreamChunk(tuple(cols), jnp.asarray(ops_arr), jnp.asarray(vis), schema)

    def to_numpy(self) -> tuple[list[np.ndarray], np.ndarray]:
        """Visible rows only -> (columns, ops). Device->host sync."""
        vis = np.asarray(self.vis)
        cols = [np.asarray(c.data)[vis] for c in self.columns]
        ops = np.asarray(self.ops)[vis]
        return cols, ops

    def to_rows(self) -> list[tuple]:
        """Visible rows as python tuples (op, values...), NULL lanes as
        None. For materialize/sinks/tests — NULL-ness must survive the
        host boundary or outer-join padding rows materialize as zeros."""
        vis = np.asarray(self.vis)
        ops = np.asarray(self.ops)[vis]
        cols = [np.asarray(c.data)[vis] for c in self.columns]
        valids = [None if c.valid is None else np.asarray(c.valid)[vis]
                  for c in self.columns]
        out = []
        for r in range(len(ops)):
            out.append((int(ops[r]), tuple(
                c[r].item() if v is None or v[r] else None
                for c, v in zip(cols, valids))))
        return out


def empty_chunk(schema: Schema, capacity: int = DEFAULT_CHUNK_CAPACITY) -> StreamChunk:
    return StreamChunk.from_numpy(schema, [np.zeros(0, f.data_type.np_dtype) for f in schema], capacity=capacity)


# ------------------------------------------------------------- coalescing

def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1).bit_length())


def _pad_chunk_impl(chunk: StreamChunk, out_capacity: int) -> StreamChunk:
    """Grow a chunk to `out_capacity` with invisible rows (row order and
    update-pair adjacency preserved — padding is strictly at the tail)."""
    pad = out_capacity - chunk.capacity

    def ext(x):
        return jnp.concatenate([x, jnp.zeros(pad, dtype=x.dtype)])

    cols = tuple(
        Column(ext(c.data), None if c.valid is None else ext(c.valid))
        for c in chunk.columns)
    return StreamChunk(cols, ext(chunk.ops), ext(chunk.vis), chunk.schema)


def _concat2_impl(a: StreamChunk, b: StreamChunk) -> StreamChunk:
    """Concatenate two equal-schema chunks (a's rows first)."""
    def cat(x, y):
        return jnp.concatenate([x, y])

    def cat_valid(ca: Column, cb: Column):
        if ca.valid is None and cb.valid is None:
            return None
        va = ca.valid if ca.valid is not None else \
            jnp.ones(ca.capacity, dtype=bool)
        vb = cb.valid if cb.valid is not None else \
            jnp.ones(cb.capacity, dtype=bool)
        return cat(va, vb)

    cols = tuple(Column(cat(ca.data, cb.data), cat_valid(ca, cb))
                 for ca, cb in zip(a.columns, b.columns))
    return StreamChunk(cols, cat(a.ops, b.ops), cat(a.vis, b.vis), a.schema)


# Shared pack programs (lazy: jit_state imports jax utils; chunk.py is
# imported by host-only code paths too). Capacities are bucketed to powers
# of two, so the static-shape set is {pad: (2^i -> 2^j), concat: (2^j,
# 2^j)} — O(log^2 max_capacity) programs TOTAL across all coalescers, and
# zero recompiles once a pipeline's buckets are warm. The inputs are NOT
# donated: dispatchers fan chunks out zero-copy (same arrays, different
# visibility), so a pack input may be aliased by a sibling consumer.
_PACK_PROGRAMS: dict = {}


def _pack_programs():
    if not _PACK_PROGRAMS:
        from ..ops.jit_state import jit_state
        _PACK_PROGRAMS["pad"] = jit_state(
            _pad_chunk_impl, static_argnums=(1,), name="chunk_pad")
        _PACK_PROGRAMS["concat2"] = jit_state(
            _concat2_impl, name="chunk_concat2")
    return _PACK_PROGRAMS


class ChunkCoalescer:
    """Packs consecutive small chunks between barriers into fewer, fuller
    chunks — the host-loop half of making per-barrier-interval device work
    O(1) dispatches.

    Every chunk an executor sees costs one device dispatch per jitted step
    regardless of how few visible rows it carries; sources and exchanges
    frequently emit runs of small chunks inside one barrier interval.  The
    coalescer buffers a run (receiver side, after the channel — it never
    interacts with backpressure), then folds it pairwise into one chunk
    whose capacity is the power-of-two bucket of the run's total capacity.
    Row order is preserved (stable tail-concat), so changelog update pairs
    stay adjacent; visibility masks carry over untouched.

    The pack programs compile once per (capacity-bucket) pair and are
    shared process-wide, so coalescing adds ZERO steady-state recompiles
    while removing k-1 downstream dispatches per k-chunk run — per
    stateful executor in the chain below.

    Protocol: `push(chunk)` returns chunks ready to emit now (a full run,
    or a passthrough); `flush()` drains the pending run — callers MUST
    flush before forwarding a barrier or watermark so cross-message
    ordering is exactly the uncoalesced stream's.
    """

    def __init__(self, max_capacity: int = 4 * DEFAULT_CHUNK_CAPACITY):
        self.max_capacity = max(1, int(max_capacity))
        self._pending: list[StreamChunk] = []
        self._pending_cap = 0
        self.packed = 0          # chunks absorbed into a merge
        self.emitted = 0         # chunks emitted (after packing)

    def push(self, chunk: StreamChunk) -> list[StreamChunk]:
        out: list[StreamChunk] = []
        cap = chunk.capacity
        if cap >= self.max_capacity:
            # too big to pack with anything: drain, then pass through
            out.extend(self.flush())
            out.append(chunk)
            self.emitted += 1
            return out
        if self._pending:
            head = self._pending[0]
            schema_differs = (head.schema is not chunk.schema
                              and head.schema != chunk.schema)
            if (self._pending_cap + cap > self.max_capacity
                    or schema_differs):
                out.extend(self.flush())
        self._pending.append(chunk)
        self._pending_cap += cap
        return out

    def flush(self) -> list[StreamChunk]:
        if not self._pending:
            return []
        run, self._pending, self._pending_cap = self._pending, [], 0
        if len(run) == 1:
            self.emitted += 1
            return run
        progs = _pack_programs()
        merged = run[0]
        for nxt in run[1:]:
            # equalize to the larger power-of-two bucket, then concat —
            # keeps every program signature inside the bucketed set
            target = _next_pow2(max(merged.capacity, nxt.capacity))
            if merged.capacity < target:
                merged = progs["pad"](merged, target)
            if nxt.capacity < target:
                nxt = progs["pad"](nxt, target)
            merged = progs["concat2"](merged, nxt)
        self.packed += len(run)
        self.emitted += 1
        return [merged]


class StreamChunkBuilder:
    """Host-side row accumulator emitting fixed-capacity chunks
    (reference: StreamChunkBuilder, array/stream_chunk_builder.rs).
    Update pairs are kept within a single chunk."""

    def __init__(self, schema: Schema, capacity: int = DEFAULT_CHUNK_CAPACITY):
        self.schema = schema
        self.capacity = capacity
        self._rows: list[tuple[int, tuple]] = []

    def __len__(self):
        return len(self._rows)

    def append_row(self, op: int, values: tuple) -> Optional[StreamChunk]:
        self._rows.append((op, values))
        if len(self._rows) >= self.capacity:
            # Never split an UpdateDelete/UpdateInsert pair across chunks —
            # downstream op-fixup kernels rely on pair adjacency within one
            # chunk (the reference builder reserves a slot the same way).
            held = None
            if len(self._rows) > 1 and self._rows[-1][0] == OP_UPDATE_DELETE:
                held = self._rows.pop()
            chunk = self.take()
            if held is not None:
                self._rows.append(held)
            return chunk
        return None

    def take(self) -> Optional[StreamChunk]:
        if not self._rows:
            return None
        ops = np.asarray([r[0] for r in self._rows], dtype=np.int8)
        arrays = []
        for i, f in enumerate(self.schema):
            arrays.append(np.asarray([r[1][i] for r in self._rows], dtype=f.data_type.np_dtype))
        self._rows = []
        return StreamChunk.from_numpy(self.schema, arrays, ops=ops, capacity=self.capacity)

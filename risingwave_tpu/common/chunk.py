"""Columnar chunks — the data quantum flowing between executors.

Re-design of the reference's DataChunk/StreamChunk
(src/common/src/array/data_chunk.rs:66, array/stream_chunk.rs:44-92) for XLA:
a chunk is a *fixed-capacity* struct-of-arrays pytree. Row count is dynamic
only through the visibility mask — shapes are static so every executor step
compiles once. The reference already carries a visibility bitmap on every
chunk; here it is load-bearing for padding as well.

Ops follow reference `Op` (stream_chunk.rs:44-49):
  INSERT=0  DELETE=1  UPDATE_DELETE=2  UPDATE_INSERT=3
`op_sign` maps insert-like ops to +1 and delete-like to -1 — the sign of a
row's contribution to any linear aggregate, which is how changelog semantics
stay branch-free on device.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .types import DataType, Schema

# Op encoding (int8 on device)
OP_INSERT = 0
OP_DELETE = 1
OP_UPDATE_DELETE = 2
OP_UPDATE_INSERT = 3

DEFAULT_CHUNK_CAPACITY = 4096


def op_sign(ops: jnp.ndarray) -> jnp.ndarray:
    """+1 for Insert/UpdateInsert, -1 for Delete/UpdateDelete."""
    is_insert = (ops == OP_INSERT) | (ops == OP_UPDATE_INSERT)
    return jnp.where(is_insert, jnp.int32(1), jnp.int32(-1))


@jax.tree_util.register_pytree_node_class
@dataclass
class Column:
    """One column: fixed-width data + optional validity (None = all valid)."""

    data: jnp.ndarray
    valid: Optional[jnp.ndarray] = None  # bool mask, True = non-null

    def tree_flatten(self):
        return (self.data, self.valid), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)

    @property
    def capacity(self) -> int:
        return self.data.shape[0]

    def valid_mask(self) -> jnp.ndarray:
        if self.valid is None:
            return jnp.ones(self.data.shape[0], dtype=bool)
        return self.valid

    def take(self, idx: jnp.ndarray) -> "Column":
        return Column(
            jnp.take(self.data, idx, axis=0),
            None if self.valid is None else jnp.take(self.valid, idx, axis=0),
        )


@jax.tree_util.register_pytree_node_class
@dataclass
class StreamChunk:
    """ops + columns + visibility. A DataChunk is a StreamChunk with all-INSERT
    ops (the reference keeps two types; one suffices here — batch executors
    simply ignore `ops`)."""

    columns: tuple[Column, ...]
    ops: jnp.ndarray       # int8 [CAP]
    vis: jnp.ndarray       # bool [CAP]
    schema: Schema         # static aux

    def tree_flatten(self):
        return (self.columns, self.ops, self.vis), self.schema

    @classmethod
    def tree_unflatten(cls, schema, children):
        columns, ops, vis = children
        return cls(tuple(columns), ops, vis, schema)

    # -- shape ---------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.ops.shape[0]

    def cardinality(self) -> jnp.ndarray:
        """Number of visible rows (device scalar)."""
        return jnp.sum(self.vis.astype(jnp.int32))

    def num_rows_host(self) -> int:
        return int(np.asarray(self.cardinality()))

    # -- transforms ----------------------------------------------------
    def with_vis(self, vis: jnp.ndarray) -> "StreamChunk":
        return StreamChunk(self.columns, self.ops, vis, self.schema)

    def mask(self, keep: jnp.ndarray) -> "StreamChunk":
        return self.with_vis(self.vis & keep)

    def project(self, indices: Sequence[int]) -> "StreamChunk":
        return StreamChunk(
            tuple(self.columns[i] for i in indices),
            self.ops, self.vis, self.schema.select(indices),
        )

    def take(self, idx: jnp.ndarray, vis: jnp.ndarray) -> "StreamChunk":
        """Row gather (used by compaction / dispatch routing)."""
        return StreamChunk(
            tuple(c.take(idx) for c in self.columns),
            jnp.take(self.ops, idx, axis=0), vis, self.schema,
        )

    def compact(self) -> "StreamChunk":
        """Move visible rows to the front (stable). Keeps capacity."""
        cap = self.capacity
        order = jnp.argsort(~self.vis, stable=True)
        n = self.cardinality()
        new_vis = jnp.arange(cap) < n
        return self.take(order, new_vis)

    # -- host I/O ------------------------------------------------------
    @staticmethod
    def from_numpy(
        schema: Schema,
        arrays: Sequence[np.ndarray],
        ops: Optional[np.ndarray] = None,
        capacity: Optional[int] = None,
        valids: Optional[Sequence[Optional[np.ndarray]]] = None,
    ) -> "StreamChunk":
        n = len(arrays[0]) if arrays else 0
        cap = capacity or max(DEFAULT_CHUNK_CAPACITY, n)
        assert n <= cap, f"{n} rows > capacity {cap}"
        cols = []
        for i, (arr, f) in enumerate(zip(arrays, schema)):
            arr = np.asarray(arr, dtype=f.data_type.np_dtype)
            pad = np.zeros(cap, dtype=f.data_type.np_dtype)
            pad[:n] = arr
            valid = None
            if valids is not None and valids[i] is not None:
                v = np.zeros(cap, dtype=bool)
                v[:n] = valids[i]
                valid = jnp.asarray(v)
            cols.append(Column(jnp.asarray(pad), valid))
        ops_arr = np.zeros(cap, dtype=np.int8)
        if ops is not None:
            ops_arr[:n] = np.asarray(ops, dtype=np.int8)
        vis = np.zeros(cap, dtype=bool)
        vis[:n] = True
        return StreamChunk(tuple(cols), jnp.asarray(ops_arr), jnp.asarray(vis), schema)

    def to_numpy(self) -> tuple[list[np.ndarray], np.ndarray]:
        """Visible rows only -> (columns, ops). Device->host sync."""
        vis = np.asarray(self.vis)
        cols = [np.asarray(c.data)[vis] for c in self.columns]
        ops = np.asarray(self.ops)[vis]
        return cols, ops

    def to_rows(self) -> list[tuple]:
        """Visible rows as python tuples (op, values...), NULL lanes as
        None. For materialize/sinks/tests — NULL-ness must survive the
        host boundary or outer-join padding rows materialize as zeros."""
        vis = np.asarray(self.vis)
        ops = np.asarray(self.ops)[vis]
        cols = [np.asarray(c.data)[vis] for c in self.columns]
        valids = [None if c.valid is None else np.asarray(c.valid)[vis]
                  for c in self.columns]
        out = []
        for r in range(len(ops)):
            out.append((int(ops[r]), tuple(
                c[r].item() if v is None or v[r] else None
                for c, v in zip(cols, valids))))
        return out


def empty_chunk(schema: Schema, capacity: int = DEFAULT_CHUNK_CAPACITY) -> StreamChunk:
    return StreamChunk.from_numpy(schema, [np.zeros(0, f.data_type.np_dtype) for f in schema], capacity=capacity)


class StreamChunkBuilder:
    """Host-side row accumulator emitting fixed-capacity chunks
    (reference: StreamChunkBuilder, array/stream_chunk_builder.rs).
    Update pairs are kept within a single chunk."""

    def __init__(self, schema: Schema, capacity: int = DEFAULT_CHUNK_CAPACITY):
        self.schema = schema
        self.capacity = capacity
        self._rows: list[tuple[int, tuple]] = []

    def __len__(self):
        return len(self._rows)

    def append_row(self, op: int, values: tuple) -> Optional[StreamChunk]:
        self._rows.append((op, values))
        if len(self._rows) >= self.capacity:
            # Never split an UpdateDelete/UpdateInsert pair across chunks —
            # downstream op-fixup kernels rely on pair adjacency within one
            # chunk (the reference builder reserves a slot the same way).
            held = None
            if len(self._rows) > 1 and self._rows[-1][0] == OP_UPDATE_DELETE:
                held = self._rows.pop()
            chunk = self.take()
            if held is not None:
                self._rows.append(held)
            return chunk
        return None

    def take(self) -> Optional[StreamChunk]:
        if not self._rows:
            return None
        ops = np.asarray([r[0] for r in self._rows], dtype=np.int8)
        arrays = []
        for i, f in enumerate(self.schema):
            arrays.append(np.asarray([r[1][i] for r in self._rows], dtype=f.data_type.np_dtype))
        self._rows = []
        return StreamChunk.from_numpy(self.schema, arrays, ops=ops, capacity=self.capacity)

"""In-mesh shuffle: HashDispatcher + Merge as one XLA all_to_all.

Reference: the hash exchange (src/stream/src/executor/dispatch.rs:679 routes
rows by vnode to downstream actors over channels/gRPC; merge.rs:109 fans in).
Inside a TPU mesh that whole path collapses to a single collective: each
shard buckets its local rows by destination shard (vnode routing table),
then `lax.all_to_all` swaps buckets over ICI. No host hop, no serialization,
no per-row control flow — the shuffle is one fused device op per chunk.

All functions here run INSIDE shard_map (they use axis collectives); shapes
are per-shard. Rows are (columns..., vis) with fixed capacity; destination
overflow beyond `cap_out` rows per (src,dst) pair is counted and surfaced so
callers size capacities (the host pipeline applies backpressure long before
overflow in practice — chunk capacity bounds per-dest rows).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.vnode import compute_vnodes


def shuffle_cap_out(local_rows: int, n_shards: int, slack: int = 0) -> int:
    """Per-(src, dst) send capacity for `shuffle_rows`.

    slack = 0 (the default) is ZERO-DROP sizing: a source shard holds at
    most `local_rows` rows, so `cap_out = local_rows` can never overflow
    regardless of key skew (a chunk whose rows all share one hot vnode —
    e.g. a tumble-window group key inside one barrier interval — routes
    everything to a single shard). The receive buffer is then
    n_shards * local_rows = the global chunk capacity, i.e. the fused
    path costs no more compute than the replicated-and-masked path while
    still moving the data over ICI instead of the host.

    slack = k > 0 sizes for BALANCED routing with k× headroom:
    cap_out = k * ceil(local_rows / n_shards), so each shard's receive
    buffer shrinks to ~k/n_shards of the chunk — the near-linear-compute
    regime for well-distributed keys (q5's (auction, window) groups).
    Overflow is counted on device and FAIL-STOPS the epoch at the next
    barrier watchdog fetch (mesh_shuffle_dropped_rows_total), so an
    undersized slack surfaces loudly instead of dropping rows."""
    if slack <= 0:
        return local_rows
    per_pair = -(-local_rows // n_shards)
    return min(local_rows, max(64, slack * per_pair))


def bucket_by_dest(columns: Sequence[jnp.ndarray], vis: jnp.ndarray,
                   dest: jnp.ndarray, n_dest: int, cap_out: int):
    """Scatter local rows into per-destination send buffers.

    columns: [N] arrays; vis: bool [N]; dest: int32 [N] in [0, n_dest).
    Returns (send_cols: list of [n_dest, cap_out], send_vis: [n_dest, cap_out],
    n_dropped: int32 scalar, max_fill: int32 scalar — the largest
    per-destination demand BEFORE capping, for adaptive bucket sizing).
    """
    onehot = (dest[:, None] == jnp.arange(n_dest, dtype=dest.dtype)[None, :]) & vis[:, None]
    pos = (jnp.cumsum(onehot, axis=0) - onehot).astype(jnp.int32)  # rank within dest
    pos_of_row = jnp.sum(pos * onehot, axis=1)
    ok = vis & (pos_of_row < cap_out)
    n_dropped = jnp.sum(vis & ~ok, dtype=jnp.int32)
    # demand (pre-cap) per destination bucket — the adaptive slack
    # signal: the largest send bucket this shard WANTED this chunk
    max_fill = jnp.max(jnp.sum(onehot, axis=0, dtype=jnp.int32))
    flat = jnp.where(ok, dest * cap_out + pos_of_row, n_dest * cap_out)
    send_cols = []
    for col in columns:
        buf = jnp.zeros(n_dest * cap_out + 1, dtype=col.dtype)
        send_cols.append(buf.at[flat].set(col, mode="drop")[:-1].reshape(n_dest, cap_out))
    vbuf = jnp.zeros(n_dest * cap_out + 1, dtype=bool)
    send_vis = vbuf.at[flat].set(ok, mode="drop")[:-1].reshape(n_dest, cap_out)
    return send_cols, send_vis, n_dropped, max_fill


def shuffle_rows(columns: Sequence[jnp.ndarray], vis: jnp.ndarray,
                 dest: jnp.ndarray, axis_name: str, n_shards: int,
                 cap_out: int):
    """Route rows to their destination shard (call inside shard_map).

    Returns (recv_cols: list of [n_shards*cap_out], recv_vis, n_dropped,
    max_fill): the rows this shard owns, gathered from every source shard.
    """
    send_cols, send_vis, n_dropped, max_fill = bucket_by_dest(
        columns, vis, dest, n_shards, cap_out)
    recv_cols = [
        jax.lax.all_to_all(c, axis_name, split_axis=0, concat_axis=0,
                           tiled=True).reshape(n_shards * cap_out)
        for c in send_cols
    ]
    recv_vis = jax.lax.all_to_all(send_vis, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True).reshape(n_shards * cap_out)
    return recv_cols, recv_vis, n_dropped, max_fill


def shuffle_by_vnode(columns: Sequence[jnp.ndarray], vis: jnp.ndarray,
                     key_columns: Sequence[jnp.ndarray],
                     vnode_to_shard_table: jnp.ndarray,
                     axis_name: str, n_shards: int, cap_out: int):
    """The full HashDispatcher semantics: vnode = crc32(dist_key) % 256
    (vnode.rs:126), shard = routing_table[vnode], then all_to_all."""
    vnodes = compute_vnodes(key_columns)
    dest = jnp.take(vnode_to_shard_table, vnodes)
    return shuffle_rows(columns, vis, dest, axis_name, n_shards, cap_out)


def mesh_ingest_chunk(chunk: StreamChunk, key_indices, vnode_to_shard_table,
                      axis_name: str, n_shards: int, cap_out: int):
    """The fused exchange ingest (call INSIDE shard_map): this shard's
    LOCAL row slice of a chunk is routed to the shards owning each row's
    vnode — ops, every column (data + validity) and visibility ride one
    all_to_all. Returns (local_chunk, n_dropped, max_fill) where
    `local_chunk` has capacity n_shards * cap_out and holds exactly the
    rows this shard owns, in source-shard-major order. Because the host
    chunk is sliced CONTIGUOUSLY over the mesh axis, source-shard-major
    order IS the original chunk order restricted to the owned rows — the
    same relative order the replicated-and-masked path sees, so per-shard
    executor semantics (pk-run netting, extrema updates) are unchanged.

    key_indices=None is the mesh-to-mesh NoShuffle leg: the upstream
    shards already own their rows under the downstream distribution, so
    the local slice passes through untouched — ZERO transfer, no
    collective, n_dropped == 0, max_fill = this shard's visible rows."""
    if key_indices is None:
        zero = jnp.zeros((), dtype=jnp.int32)
        occ = jnp.sum(chunk.vis, dtype=jnp.int32)
        return chunk, zero, occ
    payload = [chunk.ops]
    for c in chunk.columns:
        payload.append(c.data)
        if c.valid is not None:
            payload.append(c.valid)
    key_cols = [chunk.columns[i].data for i in key_indices]
    recv, recv_vis, n_dropped, max_fill = shuffle_by_vnode(
        payload, chunk.vis, key_cols, vnode_to_shard_table, axis_name,
        n_shards, cap_out)
    it = iter(recv)
    ops = next(it)
    cols = []
    for c in chunk.columns:
        data = next(it)
        valid = next(it) if c.valid is not None else None
        cols.append(Column(data, valid))
    return StreamChunk(tuple(cols), ops, recv_vis, chunk.schema), n_dropped, max_fill

"""In-mesh shuffle: HashDispatcher + Merge as one XLA all_to_all.

Reference: the hash exchange (src/stream/src/executor/dispatch.rs:679 routes
rows by vnode to downstream actors over channels/gRPC; merge.rs:109 fans in).
Inside a TPU mesh that whole path collapses to a single collective: each
shard buckets its local rows by destination shard (vnode routing table),
then `lax.all_to_all` swaps buckets over ICI. No host hop, no serialization,
no per-row control flow — the shuffle is one fused device op per chunk.

All functions here run INSIDE shard_map (they use axis collectives); shapes
are per-shard. Rows are (columns..., vis) with fixed capacity; destination
overflow beyond `cap_out` rows per (src,dst) pair is counted and surfaced so
callers size capacities (the host pipeline applies backpressure long before
overflow in practice — chunk capacity bounds per-dest rows).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

from ..common.vnode import compute_vnodes


def bucket_by_dest(columns: Sequence[jnp.ndarray], vis: jnp.ndarray,
                   dest: jnp.ndarray, n_dest: int, cap_out: int):
    """Scatter local rows into per-destination send buffers.

    columns: [N] arrays; vis: bool [N]; dest: int32 [N] in [0, n_dest).
    Returns (send_cols: list of [n_dest, cap_out], send_vis: [n_dest, cap_out],
    n_dropped: int32 scalar).
    """
    onehot = (dest[:, None] == jnp.arange(n_dest, dtype=dest.dtype)[None, :]) & vis[:, None]
    pos = (jnp.cumsum(onehot, axis=0) - onehot).astype(jnp.int32)  # rank within dest
    pos_of_row = jnp.sum(pos * onehot, axis=1)
    ok = vis & (pos_of_row < cap_out)
    n_dropped = jnp.sum(vis & ~ok, dtype=jnp.int32)
    flat = jnp.where(ok, dest * cap_out + pos_of_row, n_dest * cap_out)
    send_cols = []
    for col in columns:
        buf = jnp.zeros(n_dest * cap_out + 1, dtype=col.dtype)
        send_cols.append(buf.at[flat].set(col, mode="drop")[:-1].reshape(n_dest, cap_out))
    vbuf = jnp.zeros(n_dest * cap_out + 1, dtype=bool)
    send_vis = vbuf.at[flat].set(ok, mode="drop")[:-1].reshape(n_dest, cap_out)
    return send_cols, send_vis, n_dropped


def shuffle_rows(columns: Sequence[jnp.ndarray], vis: jnp.ndarray,
                 dest: jnp.ndarray, axis_name: str, n_shards: int,
                 cap_out: int):
    """Route rows to their destination shard (call inside shard_map).

    Returns (recv_cols: list of [n_shards*cap_out], recv_vis, n_dropped):
    the rows this shard owns, gathered from every source shard.
    """
    send_cols, send_vis, n_dropped = bucket_by_dest(columns, vis, dest, n_shards, cap_out)
    recv_cols = [
        jax.lax.all_to_all(c, axis_name, split_axis=0, concat_axis=0,
                           tiled=True).reshape(n_shards * cap_out)
        for c in send_cols
    ]
    recv_vis = jax.lax.all_to_all(send_vis, axis_name, split_axis=0,
                                  concat_axis=0, tiled=True).reshape(n_shards * cap_out)
    return recv_cols, recv_vis, n_dropped


def shuffle_by_vnode(columns: Sequence[jnp.ndarray], vis: jnp.ndarray,
                     key_columns: Sequence[jnp.ndarray],
                     vnode_to_shard_table: jnp.ndarray,
                     axis_name: str, n_shards: int, cap_out: int):
    """The full HashDispatcher semantics: vnode = crc32(dist_key) % 256
    (vnode.rs:126), shard = routing_table[vnode], then all_to_all."""
    vnodes = compute_vnodes(key_columns)
    dest = jnp.take(vnode_to_shard_table, vnodes)
    return shuffle_rows(columns, vis, dest, axis_name, n_shards, cap_out)

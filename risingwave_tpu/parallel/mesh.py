"""Device mesh + vnode placement.

Reference analogue: meta's parallel-unit scheduling (`ParallelUnitMapping`,
src/common/src/hash/consistent_hash/mapping.rs:200-266) assigns the 256
vnodes to parallel units; here vnodes map to *mesh shards*. The mapping is
contiguous ranges (minimal-movement rebalance on scale, like the reference's
rebalancer) and lives on host as a [256] int array, shipped to device as a
routing table for the all_to_all exchange.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

try:                                      # jax >= 0.5 exports it top-level
    from jax import shard_map
except ImportError:                       # 0.4.x: experimental namespace
    from jax.experimental.shard_map import shard_map as _shard_map_04

    def shard_map(f, *, mesh, in_specs, out_specs):
        # 0.4.x's replication checker crashes on nested pjit equations
        # ('NoneType' is not iterable in _check_rep) that the executor
        # step bodies routinely contain; the check is an optimization
        # validator, not a correctness requirement — disable it
        return _shard_map_04(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_rep=False)

from ..common.vnode import VNODE_COUNT

VNODE_AXIS = "vnode"

__all__ = ["VNODE_AXIS", "make_mesh", "shard_map", "shard_vnode_bitmaps",
           "vnode_to_shard"]


def make_mesh(n_devices: Optional[int] = None, devices: Optional[Sequence] = None,
              axis: str = VNODE_AXIS) -> Mesh:
    """1-D mesh over the vnode (data-parallel) axis. Higher-D meshes (e.g.
    separating ICI rings) reshape here without touching executors."""
    if devices is None:
        devices = jax.devices()
        if n_devices is not None:
            assert len(devices) >= n_devices, \
                f"need {n_devices} devices, default platform has {len(devices)}; " \
                f"pass devices= explicitly (e.g. jax.devices('cpu') with " \
                f"xla_force_host_platform_device_count) for a virtual mesh"
            devices = devices[:n_devices]
    elif n_devices is not None:
        assert len(devices) >= n_devices, \
            f"need {n_devices} devices, given {len(devices)}"
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def vnode_to_shard(n_shards: int) -> np.ndarray:
    """Contiguous range placement: vnode v -> shard v * n / 256 (int32 [256]).

    Contiguity means scaling from n to n' moves only boundary ranges —
    the same minimal-movement property the reference's rebalancer targets
    (src/meta/src/stream/scale.rs).
    """
    return ((np.arange(VNODE_COUNT, dtype=np.int64) * n_shards) // VNODE_COUNT).astype(np.int32)


def shard_vnode_bitmaps(n_shards: int) -> list[np.ndarray]:
    """Per-shard ownership bitmaps (reference StreamActor.vnode_bitmap)."""
    owner = vnode_to_shard(n_shards)
    return [(owner == s) for s in range(n_shards)]

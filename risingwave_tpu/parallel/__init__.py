"""Multi-device parallelism: mesh placement + in-mesh collective exchange.

Reference mapping (SURVEY.md §2.3): data-parallel actors with vnode bitmaps
become mesh shards; HashDispatcher+Merge inside a mesh becomes
`lax.all_to_all` (exchange.py); global/singleton aggs become `psum`;
rescheduling is a routing-table + state reshard update.
"""

from .mesh import VNODE_AXIS, make_mesh, shard_vnode_bitmaps, vnode_to_shard
from .exchange import (bucket_by_dest, mesh_ingest_chunk, shuffle_by_vnode,
                       shuffle_cap_out, shuffle_rows)

__all__ = [
    "VNODE_AXIS", "make_mesh", "shard_vnode_bitmaps", "vnode_to_shard",
    "bucket_by_dest", "mesh_ingest_chunk", "shuffle_by_vnode",
    "shuffle_cap_out", "shuffle_rows",
]

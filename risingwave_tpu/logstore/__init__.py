"""Changelog log store — exactly-once sinks, durable epoch-indexed
egress, and changelog subscriptions for serving replicas.

Layers:
  * log.py          — durable per-table logs riding the checkpoint
                      (`SinkChangelog` seq-keyed delivery log,
                      `MvChangelog` epoch-keyed subscription log) and
                      the per-coordinator `LogStoreHub` driving
                      background delivery off the commit pulse;
  * subscription.py — backfill-then-tail subscription protocol, local
                      (`ChangelogSubscription`) and over the cluster
                      control-plane wire (`SubscriptionServer`);
  * replica.py      — `ServingReplica`: a read-only SnapshotCache fed
                      by the subscription, answering point lookups
                      bit-identical to the meta-side serving cache.
"""

from .log import (
    LogStoreHub, MvChangelog, MvChangelogWriter, SinkChangelog,
    SinkDelivery,
)
from .replica import ServingReplica
from .subscription import (
    ChangelogSubscription, SubscribeError, SubscriptionServer,
)

__all__ = [
    "LogStoreHub", "MvChangelog", "MvChangelogWriter", "SinkChangelog",
    "SinkDelivery", "ServingReplica", "ChangelogSubscription",
    "SubscribeError", "SubscriptionServer",
]

"""Changelog subscriptions — backfill-then-tail over the MV log.

Reference: the subscription surface of the reference's log store (
`CREATE SUBSCRIPTION`, subscription cursors over the table change log)
collapsed to the primitive the serving tier needs: a consumer asks for
one MV's changelog and receives

  1. a BACKFILL: the full committed snapshot of the MV's state table at
     exactly `store.committed_epoch()` (call it E0), with store keys so
     the consumer reproduces the scan order bit-identically, then
  2. a TAIL: every committed log entry with epoch > E0, pushed in epoch
     order as the checkpoint commits land.

The no-gap/no-overlap handoff is by construction: the MV log activates
at a collected barrier (everything <= that sealed epoch lives in table
state, everything after is logged), the subscribe call waits until the
commit point passes the activation floor, and the snapshot + cursor
are taken in one synchronous step on the event loop — no commit can
interleave between "snapshot at E0" and "tail from > E0".

Two transports share the server-side pump:

  * `ChangelogSubscription` — in-process (the local endpoint): batches
    land in an asyncio queue, `next_batch()` pops them.
  * `SubscriptionServer` — the cluster-tier endpoint: an RPC listener
    (cluster/rpc.py frames) where `subscribe` returns the backfill and
    `changelog` pushes carry the tail; serving replicas
    (logstore/replica.py) connect here from other processes.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..utils.metrics import GLOBAL_METRICS
from .log import LogStoreHub, MvChangelog


class SubscribeError(RuntimeError):
    pass


class _SubscriptionPump:
    """Server-side tail for one subscription: wakes at every checkpoint
    commit, reads committed log entries past its cursor, hands each
    batch to the transport sink in epoch order."""

    def __init__(self, hub: LogStoreHub, mv: str, log: MvChangelog,
                 cursor_epoch: int, sink, sub_id: str,
                 cursor_name: Optional[str] = None):
        self.hub = hub
        self.mv = mv
        self.log = log
        self.cursor_epoch = cursor_epoch
        self.sink = sink                  # async (epoch, rows) -> None
        self.sub_id = sub_id
        # durable cursor: a NAMED subscription persists its delivered-
        # through epoch with each checkpoint, keeps the log active (and
        # retention pinned) while disconnected, and resumes the tail
        # from the cursor on reconnect instead of re-backfilling
        self.cursor_name = cursor_name
        self.delivered_batches = 0
        self.closing = False
        self.task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._lag = GLOBAL_METRICS.gauge(
            "logstore_subscription_lag_epochs",
            subscription=f"{mv}/{sub_id}")

    def spawn(self) -> "_SubscriptionPump":
        self.task = asyncio.get_running_loop().create_task(
            self._run(), name=f"changelog-sub-{self.mv}-{self.sub_id}")
        return self

    async def _run(self) -> None:
        seen = self.hub.commit_seq
        while not self.closing:
            try:
                await self.pump_pending()
            except asyncio.CancelledError:
                raise
            except (ConnectionResetError, BrokenPipeError, OSError):
                self.stop()               # subscriber went away
                return
            seen = await self.hub.wait_commit(seen)
            if self.hub.aborted:
                return

    async def pump_pending(self) -> None:
        async with self._lock:
            pending = list(self.log.read_committed(self.cursor_epoch))
            self._lag.set(float(len(pending)))
            for epoch, rows in pending:
                if self.closing:
                    return
                await self.sink(epoch, rows)
                self.cursor_epoch = epoch
                self.delivered_batches += 1
                self._lag.dec()
            if self.cursor_name is not None and pending:
                # stage the durable cursor; it rides the next checkpoint
                self.log.persist_sub_cursor(
                    self.cursor_name, self.cursor_epoch,
                    self.hub.collected_epoch)

    def stop(self) -> None:
        self.closing = True
        if self.task is not None and not self.task.done():
            self.task.cancel()
        if self in self.hub.subscriptions:
            self.hub.subscriptions.remove(self)
        GLOBAL_METRICS.remove("logstore_subscription_lag_epochs",
                              subscription=f"{self.mv}/{self.sub_id}")
        # last LIVE consumer gone -> stop paying the log writes — unless
        # a durable named cursor still pins the log (lease not lapsed,
        # hub.pinning_sub_cursors): the whole point of the cursor is
        # that a reconnect resumes the tail, which needs the log to
        # keep accumulating while nobody is connected
        if not any(p.mv == self.mv for p in self.hub.subscriptions) \
                and not self.hub.pinning_sub_cursors(self.mv, self.log):
            self.log.deactivate()


async def open_subscription(hub: LogStoreHub, mv: str, sink,
                            sub_id: str,
                            cursor_name: Optional[str] = None,
                            allow_resume: bool = True) -> tuple:
    """Shared server-side subscribe: activate the MV's log, wait for the
    commit point to pass the activation floor, take the committed
    backfill snapshot, register the tail pump — snapshot epoch and
    pump cursor are assigned in ONE synchronous step, which is the
    whole no-gap/no-overlap argument.

    `cursor_name` names a DURABLE cursor: the pump persists its
    delivered-through epoch with each checkpoint, and a later subscribe
    under the same name RESUMES the tail from the committed cursor —
    no backfill rows ship (`backfill["resume"]` is True) when the log
    has stayed active and retention has not passed the cursor; the
    consumer keeps the snapshot it already has and continues applying
    epochs > cursor. Otherwise the normal backfill runs.

    Returns (pump, backfill dict)."""
    from ..state.storage_table import StorageTable
    log = hub.mv_logs.get(mv)
    if log is None:
        raise SubscribeError(f"unknown changelog source {mv!r}")
    if log.state_table is None:
        raise SubscribeError(
            f"{mv!r} has no subscribable state table (cluster MVs keep "
            "their changelog in the workers — v1 subscriptions serve "
            "meta-local MVs)")
    if cursor_name is not None and allow_resume and log.active:
        cur = log.read_sub_cursor(cursor_name)
        if cur is not None and cur >= log.active_from \
                and cur >= log.truncated_below \
                and cursor_name in hub.pinning_sub_cursors(mv, log):
            # resume: entries > cur are all retained (retention floors
            # at the minimum cursor, which includes this one) and the
            # log has been active since before the cursor — the tail
            # from cur is gapless by the same argument as a fresh
            # backfill handoff
            pump = _SubscriptionPump(hub, mv, log, cur, sink, sub_id,
                                     cursor_name=cursor_name)
            hub.subscriptions.append(pump)
            pump.spawn()
            return pump, {
                "sub_id": sub_id,
                "table_id": log.state_table.table_id,
                "schema": log.schema,
                "pk_indices": tuple(log.pk_indices),
                "epoch": cur,
                "resume": True,
            }
    log.activate(hub.collected_epoch)
    floor = log.active_from
    seen = hub.commit_seq
    while hub.store.committed_epoch() < floor:
        if hub.aborted:
            raise SubscribeError("coordinator recovering; retry subscribe")
        hub.check_failure()
        seen = await hub.wait_commit(seen)
    # ---- synchronous from here to pump registration ----
    e0 = hub.store.committed_epoch()
    storage = StorageTable.for_state_table(log.state_table)
    rows, keys = storage.snapshot_with_keys(committed_only=True)
    pump = _SubscriptionPump(hub, mv, log, e0, sink, sub_id,
                             cursor_name=cursor_name)
    hub.subscriptions.append(pump)
    pump.spawn()
    backfill = {
        "sub_id": sub_id,
        "table_id": log.state_table.table_id,
        "schema": log.schema,
        "pk_indices": tuple(log.pk_indices),
        "epoch": e0,
        "rows": rows,
        "keys": keys,
    }
    return pump, backfill


class ChangelogSubscription:
    """The local endpoint: `start()` returns the backfill, then
    `next_batch()` pops (epoch, rows) tail batches in epoch order.
    `cursor_name` makes the subscription durable (see
    `open_subscription`): a later incarnation under the same name
    resumes the tail from the committed cursor instead of
    re-backfilling."""

    def __init__(self, hub: LogStoreHub, mv: str,
                 cursor_name: Optional[str] = None):
        self.hub = hub
        self.mv = mv
        self.cursor_name = cursor_name
        self.queue: asyncio.Queue = asyncio.Queue()
        self.pump: Optional[_SubscriptionPump] = None
        self.backfill: Optional[dict] = None

    async def start(self) -> dict:
        async def sink(epoch, rows):
            await self.queue.put((epoch, rows))

        self.pump, self.backfill = await open_subscription(
            self.hub, self.mv, sink,
            sub_id=f"local{id(self) & 0xffff:04x}",
            cursor_name=self.cursor_name)
        return self.backfill

    async def next_batch(self, timeout: Optional[float] = None):
        if timeout is None:
            return await self.queue.get()
        return await asyncio.wait_for(self.queue.get(), timeout)

    def close(self) -> None:
        if self.pump is not None:
            self.pump.stop()


class SubscriptionServer:
    """The cluster-tier endpoint: serves `subscribe` requests over the
    control-plane wire (length-prefixed pickle frames between trusted
    processes, cluster/rpc.py) and pushes `changelog` batches per
    committed epoch. One server per session; serving replicas connect
    here (`SET subscription_port = N`, 0 = off)."""

    def __init__(self, session, port: int = 0, host: str = "127.0.0.1"):
        self.session = session
        self.host = host
        self.port = port
        self._server = None
        self._conns: list = []

    @property
    def hub(self) -> LogStoreHub:
        # read live: auto-recovery swaps the coordinator (and its hub)
        return self.session.coord.logstore

    async def start(self) -> "SubscriptionServer":
        from ..cluster.rpc import start_rpc_server

        def handler_factory(conn):
            pumps: list = []
            next_sub = [1]
            self._conns.append(conn)

            async def handler(method, args):
                if method == "subscribe":
                    sub_id = f"c{id(conn) & 0xffff:04x}.{next_sub[0]}"
                    next_sub[0] += 1

                    async def sink(epoch, rows, _sid=sub_id):
                        await conn.push("changelog", sub_id=_sid,
                                        epoch=epoch, rows=rows)

                    pump, backfill = await open_subscription(
                        self.hub, args["mv"], sink, sub_id,
                        cursor_name=args.get("cursor_name"),
                        allow_resume=bool(args.get("allow_resume",
                                                   True)))
                    pumps.append(pump)
                    return backfill
                if method == "unsubscribe":
                    for p in pumps:
                        if p.sub_id == args["sub_id"]:
                            p.stop()
                    return {}
                if method == "ping":
                    return {}
                raise ValueError(f"unknown subscription method {method!r}")

            def on_closed(exc):
                for p in pumps:
                    p.stop()
                if conn in self._conns:
                    self._conns.remove(conn)

            return handler, on_closed

        self._server = await start_rpc_server(handler_factory,
                                              host=self.host,
                                              port=self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        for conn in list(self._conns):
            await conn.close()
        self._conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

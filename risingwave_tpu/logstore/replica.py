"""Serving replica — a read-only process fed by the MV changelog.

Reference: ROADMAP item 3(d) / the reference's serving-node split: query
traffic scales independently of the stream engine when read-only
replicas subscribe to the MV change stream instead of sharing the
stream cluster's process. The replica here is the first consumer of the
changelog subscription protocol (logstore/subscription.py):

  1. connect to the session's SubscriptionServer over the control-plane
     wire (cluster/rpc.py frames);
  2. `subscribe` returns the committed backfill — rows plus their store
     keys and the MV's state-table id, so the replica constructs the
     SAME key layout and its `SnapshotCache` compaction order is
     bit-identical to the meta-side cache;
  3. every `changelog` push (one committed epoch's effective changelog)
     advances the cache exactly like the meta-side ServingManager does
     at barrier collection.

Point lookups answer from the replica's own epoch-pinned snapshot —
the same `pk_index` probe the meta serving path uses — while barriers
keep flowing upstream. Run in-process (tests, embedded read pools) or
as a standalone process:

    python -m risingwave_tpu.logstore.replica --connect HOST:PORT \
        --mv NAME [--serve-port N]

which additionally serves `lookup`/`rows`/`epoch` RPCs on its own port.
"""

from __future__ import annotations

import asyncio
from typing import Optional

from ..serving.cache import SnapshotCache


class ServingReplica:
    """One MV's read-only replica: a SnapshotCache maintained from the
    changelog subscription.

    `cursor_name` makes the subscription DURABLE (logstore cursor
    keyspace): the server persists the replica's delivered-through
    epoch with each checkpoint and keeps the changelog retained while
    the replica is away, so `resubscribe()` after a connection loss
    resumes the tail from the cursor — the snapshot the replica
    already holds stays valid and no backfill rows ship."""

    def __init__(self, mv: str, cursor_name: Optional[str] = None):
        self.mv = mv
        self.cursor_name = cursor_name
        self.cache: Optional[SnapshotCache] = None
        self.sub_id: Optional[str] = None
        self.conn = None
        self._epoch_advanced = asyncio.Event()
        self.batches_applied = 0
        self.resumed = False              # last (re)subscribe skipped backfill
        self.closed = False

    # ---------------------------------------------------------- connect
    @classmethod
    async def connect(cls, host: str, port: int, mv: str,
                      cursor_name: Optional[str] = None
                      ) -> "ServingReplica":
        self = cls(mv, cursor_name=cursor_name)
        await self._subscribe(host, port)
        return self

    async def _subscribe(self, host: str, port: int) -> None:
        from ..cluster.rpc import RpcConn
        reader, writer = await asyncio.open_connection(host, port)
        self.conn = RpcConn(reader, writer, handler=self._on_push,
                            on_closed=self._on_closed)
        self.conn.start()
        # resume only when this process still HOLDS a snapshot to resume
        # onto — a fresh replica must backfill even if a durable cursor
        # survives from a previous incarnation
        backfill = await self.conn.call(
            "subscribe", mv=self.mv, cursor_name=self.cursor_name,
            allow_resume=self.cache is not None)
        self.closed = False
        if backfill.get("resume"):
            # keep the local snapshot; the tail continues past the
            # durable cursor (epochs already applied dedupe in _on_push)
            self.sub_id = backfill["sub_id"]
            self.resumed = True
        else:
            self.resumed = False
            self._install_backfill(backfill)

    async def resubscribe(self, host: str, port: int) -> None:
        """Reconnect after a dropped subscription. With a `cursor_name`
        the server resumes the tail from the durable cursor (no
        backfill, no cache rebuild); without one this is a fresh
        backfill subscribe."""
        if self.conn is not None and not self.conn.closed:
            await self.conn.close()
        await self._subscribe(host, port)

    def _install_backfill(self, backfill: dict) -> None:
        from ..state.state_table import StateTable
        self.sub_id = backfill["sub_id"]
        schema = backfill["schema"]
        pk_indices = tuple(backfill["pk_indices"])
        # store=None: the layout is pure key math (vnode hash +
        # memcomparable pk) — the replica never touches a state store
        layout = StateTable(None, table_id=backfill["table_id"],
                            schema=schema, pk_indices=pk_indices)
        self.cache = SnapshotCache(self.mv, schema, pk_indices, layout)
        self.cache.build(backfill["rows"], backfill["keys"],
                         backfill["epoch"])

    async def _on_push(self, method: str, args: dict) -> None:
        if method != "changelog" or args.get("sub_id") != self.sub_id:
            return
        if args["epoch"] <= self.epoch:
            # re-delivery inside the cursor-persistence window (the
            # durable cursor lags applied epochs by at most one
            # checkpoint): the snapshot already reflects this epoch
            return
        # one committed epoch's effective changelog, in epoch order
        # (the pump pushes ascending; TCP preserves it)
        self.cache.advance([(args["epoch"], args["rows"])], args["epoch"])
        self.batches_applied += 1
        self._epoch_advanced.set()

    def _on_closed(self, exc) -> None:
        self.closed = True
        self._epoch_advanced.set()

    # ------------------------------------------------------------ reads
    @property
    def epoch(self) -> int:
        return self.cache.snapshot.epoch if self.cache else 0

    def lookup(self, pk: tuple) -> Optional[tuple]:
        """Point lookup from the replica's pinned snapshot — the same
        pk-index probe the meta serving cache answers with."""
        snap = self.cache.snapshot
        pos = snap.lookup(tuple(
            self.cache._canon(v, i)
            for v, i in zip(pk, self.cache.pk_indices)))
        if pos is None:
            return None
        cols, valids = snap.point_rel(pos)
        return tuple(
            None if not bool(v[0]) else c[0].item()
            for c, v in zip(cols, valids))

    def rows(self):
        """(cols, valids) of the live rows in store-key order —
        bit-identical to the meta cache's `Snapshot.compact()` at the
        same epoch."""
        return self.cache.snapshot.compact()

    async def wait_epoch(self, epoch: int, timeout: float = 30.0) -> int:
        """Block until the replica has applied every batch <= `epoch`
        (or the log reports no entry for it — epochs with no changes
        are not pushed, so callers wait on the last CHANGED epoch)."""
        deadline = asyncio.get_running_loop().time() + timeout
        while self.epoch < epoch and not self.closed:
            remaining = deadline - asyncio.get_running_loop().time()
            if remaining <= 0:
                raise TimeoutError(
                    f"replica stuck at epoch {self.epoch} < {epoch}")
            self._epoch_advanced.clear()
            try:
                await asyncio.wait_for(self._epoch_advanced.wait(),
                                       remaining)
            except asyncio.TimeoutError:
                pass
        return self.epoch

    async def close(self) -> None:
        if self.conn is not None and not self.conn.closed:
            try:
                await self.conn.call("unsubscribe", sub_id=self.sub_id,
                                     timeout=5)
            except Exception:  # noqa: BLE001 — server may be gone
                pass
            await self.conn.close()
        self.closed = True


async def serve_replica(host: str, port: int, mv: str,
                        serve_port: int = 0):
    """Process mode: maintain the replica and answer `lookup`/`rows`/
    `epoch` RPCs on `serve_port` (0 = ephemeral). Returns (replica,
    server)."""
    from ..cluster.rpc import start_rpc_server
    replica = await ServingReplica.connect(host, port, mv)

    def handler_factory(conn):
        async def handler(method, args):
            if method == "lookup":
                return replica.lookup(tuple(args["pk"]))
            if method == "epoch":
                return replica.epoch
            if method == "rows":
                cols, valids = replica.rows()
                return {"cols": [c.tolist() for c in cols],
                        "valids": [v.tolist() for v in valids]}
            raise ValueError(f"unknown replica method {method!r}")

        return handler, None

    server = await start_rpc_server(handler_factory, port=serve_port)
    return replica, server


def main(argv=None) -> None:
    import argparse
    p = argparse.ArgumentParser(description="read-only serving replica")
    p.add_argument("--connect", required=True,
                   help="subscription server host:port")
    p.add_argument("--mv", required=True, help="materialized view name")
    p.add_argument("--serve-port", type=int, default=0)
    args = p.parse_args(argv)
    host, _, port = args.connect.rpartition(":")

    async def run():
        replica, server = await serve_replica(host, int(port), args.mv,
                                              args.serve_port)
        sp = server.sockets[0].getsockname()[1]
        print(f"replica serving {args.mv} on 127.0.0.1:{sp} "
              f"(epoch {replica.epoch})", flush=True)
        async with server:
            await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()

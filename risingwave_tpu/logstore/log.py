"""Changelog log store — durable, epoch-indexed egress decoupling.

Reference: `src/stream/src/common/log_store_impl/` — the sink executor
does not deliver to the external target directly; it appends each epoch's
changelog to a KV log whose writes are persisted WITH the checkpoint, a
background reader delivers committed epochs to the target after the
commit, and target-side sequence dedupe absorbs the one-epoch redelivery
window around a crash. That decoupling is what turns the documented
at-least-once crash window of direct at-barrier delivery into
exactly-once without falling into the at-most-once trap (deliver-after-
commit alone drops the epoch if the process dies between commit and
delivery — recovery does not replay committed epochs; the log does).

Two log layouts over the session's one StateStore:

  * `SinkChangelog` — the per-sink delivery log, keyed by a dense
    SEQUENCE number (`table_id ++ 0x00 ++ seq_be8`). The sequence is
    what targets dedupe on: it is minted at append time, becomes
    durable only when the checkpoint commits (append stages the entry
    at the SEALED epoch, so it rides the exact `seal -> upload_sealed
    -> commit_sealed` path the rest of the epoch's state takes), and a
    replay after a crash re-mints the SAME numbers for the re-computed
    epochs — cross-restart dedupe finally works, unlike the wall-clock
    epoch ids the old direct path handed targets. A delivery CURSOR
    (`table_id ++ 0x01`) and log truncation below it ride the same
    checkpoint, so the log stays bounded by the delivery lag.
  * `MvChangelog` — the per-MV subscription log, keyed by the sealed
    EPOCH (`table_id ++ 0x00 ++ epoch_be8`): subscribers hand-off from
    a committed snapshot at epoch E0 to tailing entries with epoch >
    E0 (subscription.py). Activation is lazy (an MV nobody subscribes
    to logs nothing), mirroring the serving cache's changelog hook.

`LogStoreHub` is the per-coordinator authority (owned by the
BarrierCoordinator exactly like the Memory/Serving managers): it is
pulsed at every checkpoint COMMIT, owns the per-sink background
delivery tasks and the per-subscription pumps, and fail-stops the
coordinator when a delivery raises (recovery then replays from the
last committed epoch, exactly like an upload failure).
"""

from __future__ import annotations

import asyncio
from typing import Iterator, Optional

from ..state.serde import RowSerde
from ..state.store import StateStore, WriteBatch
from ..utils.metrics import (
    GLOBAL_METRICS, LOGSTORE_APPEND_BYTES, SINK_DELIVERED_EPOCHS,
    SINK_DELIVERED_ROWS,
)

# key-space layout under one log table id
_ENTRIES = 0x00        # log entries: tid ++ 0x00 ++ index_be8
_CURSOR = 0x01         # delivery cursor: tid ++ 0x01
_SUBCUR = 0x02         # durable subscription cursors: tid ++ 0x02 ++ name


def _entry_key(table_id: int, index: int) -> bytes:
    return table_id.to_bytes(4, "big") + bytes([_ENTRIES]) \
        + index.to_bytes(8, "big")


def _cursor_key(table_id: int) -> bytes:
    return table_id.to_bytes(4, "big") + bytes([_CURSOR])


def _sub_cursor_key(table_id: int, name: str) -> bytes:
    return table_id.to_bytes(4, "big") + bytes([_SUBCUR]) \
        + name.encode("utf-8")


def _sub_cursor_range(table_id: int) -> tuple[bytes, bytes]:
    return (table_id.to_bytes(4, "big") + bytes([_SUBCUR]),
            table_id.to_bytes(4, "big") + bytes([_SUBCUR + 1]))


def _entry_range(table_id: int, after_index: int) -> tuple[bytes, bytes]:
    """[start, end) covering entries with index > after_index."""
    return (_entry_key(table_id, after_index + 1),
            _cursor_key(table_id))


class _LogCodec:
    """Value codec for one log entry: u32 row count, then per row one op
    byte + u32 length + RowSerde bytes. The epoch the entry belongs to
    is prefixed (sink entries are seq-keyed but targets still receive
    the epoch id for observability)."""

    def __init__(self, schema):
        self.schema = schema
        self._serde = RowSerde(schema)

    def encode(self, epoch: int, rows: list) -> bytes:
        out = bytearray()
        out += epoch.to_bytes(8, "big")
        out += len(rows).to_bytes(4, "big")
        for op, vals in rows:
            enc = self._serde.encode(vals)
            out += bytes([op & 0xFF])
            out += len(enc).to_bytes(4, "big")
            out += enc
        return bytes(out)

    def decode(self, blob: bytes) -> tuple[int, list]:
        epoch = int.from_bytes(blob[:8], "big")
        n = int.from_bytes(blob[8:12], "big")
        pos = 12
        rows = []
        for _ in range(n):
            op = blob[pos]
            if op >= 128:                 # signed ops (OP_DEL = -1)
                op -= 256
            ln = int.from_bytes(blob[pos + 1:pos + 5], "big")
            pos += 5
            rows.append((op, self._serde.decode(blob[pos:pos + ln])))
            pos += ln
        return epoch, rows


class SinkChangelog:
    """The per-sink delivery log (seq-keyed). All writes stage into the
    store's shared buffer at the SEALED epoch of the checkpoint barrier
    that produced them, so the log entry, the delivery cursor and the
    truncation tombstones commit atomically with the rest of the epoch —
    a crash replays neither more nor less than the stream state does."""

    def __init__(self, store: StateStore, table_id: int, schema):
        self.store = store
        self.table_id = table_id
        self.codec = _LogCodec(schema)
        # next sequence number to mint: resume from the COMMITTED state
        # (a crash discards staged entries AND the in-memory counter
        # dies with the process — both sides restart from the same
        # committed prefix, so re-minted numbers match re-computed
        # epochs exactly)
        self._next_seq = max(self.committed_max_seq(),
                             self.read_cursor()) + 1

    # ------------------------------------------------------------ writes
    def append(self, epoch: int, rows: list) -> int:
        """Stage one epoch's changelog under the next sequence number at
        `epoch` (the sealed epoch — the write rides its checkpoint).
        Returns the sequence number minted."""
        seq = self._next_seq
        self._next_seq += 1
        blob = self.codec.encode(epoch, rows)
        self.store.ingest_batch(WriteBatch(
            self.table_id, epoch, {_entry_key(self.table_id, seq): blob}))
        LOGSTORE_APPEND_BYTES.inc(len(blob))
        return seq

    def persist_cursor(self, epoch: int, delivered_seq: int) -> None:
        """Stage the delivery cursor + truncate entries <= it, riding the
        same checkpoint as this barrier's append. After a crash the
        durable cursor is exactly what delivery resumes after; entries
        at or below it are never read again, so tombstoning them in the
        SAME atomic commit keeps the log bounded by delivery lag."""
        puts: dict[bytes, Optional[bytes]] = {
            _cursor_key(self.table_id): delivered_seq.to_bytes(8, "big")}
        start, end = _entry_range(self.table_id, 0)
        for k, _v in self.store.iter_range(start, end):
            if int.from_bytes(k[5:13], "big") <= delivered_seq:
                puts[k] = None
            else:
                break
        self.store.ingest_batch(WriteBatch(self.table_id, epoch, puts))

    # ------------------------------------------------------------- reads
    def read_cursor(self) -> int:
        """The durable delivery cursor from the COMMITTED view: staged
        (uncommitted) cursor writes vanish in a crash, so startup must
        resume from what actually committed."""
        v = self.store.get_committed(_cursor_key(self.table_id))
        return int.from_bytes(v, "big") if v is not None else 0

    def committed_max_seq(self) -> int:
        last = 0
        start, end = _entry_range(self.table_id, 0)
        for k, _v in self.store.iter_range(start, end,
                                           committed_only=True):
            last = int.from_bytes(k[5:13], "big")
        return last

    def read_committed(self, after_seq: int
                       ) -> Iterator[tuple[int, int, list]]:
        """(seq, epoch, rows) for committed entries with seq >
        after_seq, ascending — the delivery read. Only the committed
        view: a sealed-but-uncommitted epoch must never reach the
        target (delivering it and then crashing before the commit would
        replay the epoch under a fresh sequence number = a duplicate)."""
        start, end = _entry_range(self.table_id, after_seq)
        for k, v in self.store.iter_range(start, end, committed_only=True):
            epoch, rows = self.codec.decode(v)
            yield int.from_bytes(k[5:13], "big"), epoch, rows


class MvChangelog:
    """The per-MV subscription log (epoch-keyed). One writer per
    materialize actor; a parallel materialize's writers share the log
    table and stage disjoint row sets at the same epochs (vnode-
    partitioned state ⇒ disjoint pks), under per-writer sub-keys so
    concurrent actors never clobber one entry."""

    def __init__(self, store: StateStore, table_id: int, schema,
                 pk_indices, state_table=None, n_writers: int = 1):
        self.store = store
        self.table_id = table_id
        self.schema = schema
        self.pk_indices = tuple(pk_indices)
        # the MV's state table (subscription backfills scan its
        # committed snapshot; its id/layout ship to replicas so their
        # row keys — and thus scan order — match bit-identically)
        self.state_table = state_table
        self.codec = _LogCodec(schema)
        self.writers = [MvChangelogWriter(self, i)
                        for i in range(n_writers)]
        # sealed epoch at/below which nothing is logged (set at
        # activation — everything <= it is covered by the snapshot a
        # subscriber backfills from)
        self.active_from: Optional[int] = None
        # retention floor this incarnation truncated to (the durable
        # truth is the committed tombstones; this just avoids rescanning
        # when nothing advanced)
        self.truncated_below = 0

    @property
    def active(self) -> bool:
        return self.active_from is not None

    def activate(self, last_collected_epoch: int) -> None:
        """Start logging. Every sealed epoch AFTER `last_collected_epoch`
        lands in the log (writers preserve their open-interval buffer,
        mirroring MvChangelogHook.activate), so a subscriber that
        snapshots at any committed E0 >= last_collected_epoch tails
        entries > E0 with no gap and no overlap."""
        if self.active_from is None:
            self.active_from = last_collected_epoch

    def deactivate(self) -> None:
        self.active_from = None

    # ------------------------------------- durable subscription cursors
    def persist_sub_cursor(self, name: str, cursor_epoch: int,
                           stage_epoch: int) -> None:
        """Stage a named subscription's delivered-through epoch; it
        commits with the next checkpoint, so after a reconnect the
        durable cursor is at or (by at most the delivery-to-checkpoint
        window) behind what the subscriber actually applied — resuming
        the tail from it re-delivers at most that window, which
        epoch-keyed application dedupes."""
        self.store.ingest_batch(WriteBatch(
            self.table_id, stage_epoch,
            {_sub_cursor_key(self.table_id, name):
             cursor_epoch.to_bytes(8, "big")}))

    def read_sub_cursor(self, name: str) -> Optional[int]:
        v = self.store.get_committed(_sub_cursor_key(self.table_id, name))
        return int.from_bytes(v, "big") if v is not None else None

    def committed_sub_cursors(self) -> dict[str, int]:
        start, end = _sub_cursor_range(self.table_id)
        out = {}
        for k, v in self.store.iter_range(start, end, committed_only=True):
            out[k[5:].decode("utf-8")] = int.from_bytes(v, "big")
        return out

    def drop_sub_cursor(self, name: str, stage_epoch: int) -> None:
        """Forget a named subscription (tombstone its durable cursor) —
        without this an abandoned replica pins retention forever."""
        self.store.ingest_batch(WriteBatch(
            self.table_id, stage_epoch,
            {_sub_cursor_key(self.table_id, name): None}))

    # --------------------------------------------------------- retention
    def truncate_below(self, floor_epoch: int, stage_epoch: int) -> None:
        """Tombstone committed entries with epoch <= floor_epoch (the
        minimum subscriber cursor): every subscriber — live pump or
        durable named cursor — has already consumed them, so they ride
        the next checkpoint out, exactly like the sink log's delivery-
        cursor truncation. The log stays bounded by subscriber lag
        instead of growing for the MV's lifetime."""
        start, end = _entry_range(self.table_id, 0)
        puts: dict[bytes, Optional[bytes]] = {}
        for k, _v in self.store.iter_range(start, end,
                                           committed_only=True):
            if int.from_bytes(k[5:13], "big") <= floor_epoch:
                puts[k] = None
            else:
                break
        if puts:
            self.store.ingest_batch(WriteBatch(
                self.table_id, stage_epoch, puts))
        self.truncated_below = max(self.truncated_below, floor_epoch)

    # ------------------------------------------------------------- reads
    def read_committed(self, after_epoch: int
                       ) -> Iterator[tuple[int, list]]:
        """(epoch, merged rows) for committed entries with epoch >
        after_epoch, ascending. Per-writer sub-entries of one epoch are
        merged in writer order (their pk sets are disjoint, so the
        order never changes the applied result)."""
        start, end = _entry_range(self.table_id, 0)
        start = self.table_id.to_bytes(4, "big") + bytes([_ENTRIES]) \
            + (after_epoch + 1).to_bytes(8, "big")
        cur_epoch = None
        cur_rows: list = []
        for k, v in self.store.iter_range(start, end, committed_only=True):
            epoch = int.from_bytes(k[5:13], "big")
            _e, rows = self.codec.decode(v)
            if epoch != cur_epoch:
                if cur_epoch is not None:
                    yield cur_epoch, cur_rows
                cur_epoch, cur_rows = epoch, []
            cur_rows.extend(rows)
        if cur_epoch is not None:
            yield cur_epoch, cur_rows


class MvChangelogWriter:
    """Attached to one MaterializeExecutor as `changelog_log`: buffers
    the interval's effective changelog (the same post-conflict rows the
    serving hook carries) and stages it under the sealed epoch at each
    barrier while the log is active."""

    __slots__ = ("log", "writer_idx", "_pending")

    def __init__(self, log: MvChangelog, writer_idx: int):
        self.log = log
        self.writer_idx = writer_idx
        self._pending: list = []

    def on_rows(self, rows: list) -> None:
        self._pending.extend(rows)

    def on_barrier(self, sealed_epoch: int) -> None:
        rows = self._pending
        self._pending = []
        if not self.log.active or not rows:
            return
        key = self.log.table_id.to_bytes(4, "big") + bytes([_ENTRIES]) \
            + sealed_epoch.to_bytes(8, "big") \
            + self.writer_idx.to_bytes(2, "big")
        blob = self.log.codec.encode(sealed_epoch, rows)
        self.log.store.ingest_batch(WriteBatch(
            self.log.table_id, sealed_epoch, {key: blob}))
        LOGSTORE_APPEND_BYTES.inc(len(blob))


class SinkDelivery:
    """Background delivery for one sink: reads the COMMITTED log past
    the cursor and writes each entry to the target exactly once per
    sequence number, waking on every checkpoint commit. Failures park on
    the hub and fail-stop the coordinator at the next injection (the
    upload-failure discipline), so recovery owns retries."""

    def __init__(self, hub: "LogStoreHub", name: str, log: SinkChangelog,
                 target):
        self.hub = hub
        self.name = name
        self.log = log
        self.target = target
        self.delivered_seq = max(log.read_cursor(), target.committed_seq())
        self.delivered_epochs = 0
        self.closing = False
        self.task: Optional[asyncio.Task] = None
        self._lock = asyncio.Lock()
        self._lag = GLOBAL_METRICS.gauge("logstore_subscription_lag_epochs",
                                         subscription=f"sink/{name}")

    def spawn(self) -> None:
        if self.task is None or self.task.done():
            self.task = asyncio.get_running_loop().create_task(
                self._run(), name=f"sink-delivery-{self.name}")

    async def _run(self) -> None:
        seen = self.hub.commit_seq
        while not self.closing:
            try:
                await self.deliver_pending()
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 — park for injection
                self.hub.fail(self.name, e)
                return
            seen = await self.hub.wait_commit(seen)

    async def deliver_pending(self) -> None:
        """Deliver every committed entry past the cursor, in order. The
        lock serializes the background loop against an explicit
        `drain()` — double delivery of one seq to a deduping target is
        harmless but to a callback target it would not be."""
        async with self._lock:
            while True:
                batch = list(self.log.read_committed(self.delivered_seq))
                self._lag.set(float(len(batch)))
                if not batch:
                    break
                for seq, epoch, rows in batch:
                    if seq > self.target.committed_seq():
                        await asyncio.to_thread(
                            self.target.write, seq, epoch, rows)
                        SINK_DELIVERED_ROWS.inc(len(rows))
                    self.delivered_seq = seq
                    self.delivered_epochs += 1
                    SINK_DELIVERED_EPOCHS.inc()
                    self._lag.dec()

    def pending(self) -> bool:
        for _ in self.log.read_committed(self.delivered_seq):
            return True
        return False

    def stop(self) -> None:
        self.closing = True
        if self.task is not None and not self.task.done():
            self.task.cancel()
        GLOBAL_METRICS.remove("logstore_subscription_lag_epochs",
                              subscription=f"sink/{self.name}")


class LogStoreHub:
    """Per-coordinator log-store authority (meta/barrier_manager.py owns
    one like the Memory/Serving managers). Commit pulses drive delivery
    and subscription pumps; `drain()` is the quiesce point run by
    `run_rounds`/`stop_all` so callers observe delivered targets the
    same way they observe committed state."""

    def __init__(self, store: StateStore):
        self.store = store
        self.sinks: dict[str, SinkDelivery] = {}
        self.mv_logs: dict[str, MvChangelog] = {}
        self.subscriptions: list = []     # live _SubscriptionPump objects
        self.collected_epoch = 0
        self.commit_seq = 0
        self._commit_event = asyncio.Event()
        self.failure: Optional[tuple[str, BaseException]] = None
        self.aborted = False
        # durable event log (meta/event_log.py), attached by the
        # session: a sink parking on delivery failure leaves a record
        self.event_log = None
        # durable-cursor lease (SET subscription_cursor_ttl_ms): a named
        # cursor with NO live pump renewing its lease for this long
        # stops pinning changelog retention — the abandoned-replica
        # escape hatch. 0 = never expire. `_cursor_seen` is the lease
        # clock: (mv, cursor) -> monotonic time last renewed (a live
        # pump renews; an orphan's clock starts at first observation).
        self.sub_cursor_ttl_ms = 0
        self._cursor_seen: dict[tuple[str, str], float] = {}

    # ------------------------------------------------------ registration
    def register_sink(self, name: str, log: SinkChangelog,
                      target) -> SinkDelivery:
        """Called by the sink executor at its first barrier; replaces a
        previous incarnation's task (re-create after drop, recovery
        rebuilds on a fresh hub so collisions are same-session only)."""
        old = self.sinks.pop(name, None)
        if old is not None:
            old.stop()
        d = SinkDelivery(self, name, log, target)
        self.sinks[name] = d
        d.spawn()
        return d

    def unregister_sink(self, name: str) -> None:
        d = self.sinks.pop(name, None)
        if d is not None:
            d.stop()

    def register_mv(self, name: str, table_id: int, schema, pk_indices,
                    state_table=None, n_writers: int = 1) -> MvChangelog:
        log = MvChangelog(self.store, table_id, schema, pk_indices,
                          state_table=state_table, n_writers=n_writers)
        cursors = log.committed_sub_cursors()
        if cursors:
            # durable named cursors survive a restart: re-activate
            # immediately so the rebuilt writers log every post-recovery
            # epoch — entries in (min cursor, committed] are already
            # durable in the log (retention floors at the min cursor),
            # so a reconnecting subscriber's resume stays gapless across
            # the crash
            log.activate(min(cursors.values()))
        self.mv_logs[name] = log
        return log

    def unregister_mv(self, name: str) -> None:
        self.mv_logs.pop(name, None)
        # live subscriptions of a dropped MV can never see another
        # entry; stop their pumps instead of leaving them parked on the
        # commit pulse forever
        for pump in [p for p in self.subscriptions if p.mv == name]:
            pump.stop()

    # ----------------------------------------------------------- commits
    def pinning_sub_cursors(self, name: str, log: MvChangelog) -> dict:
        """The durable named cursors still HOLDING `log`'s retention: a
        cursor whose lease lapsed (no live pump under that name within
        `sub_cursor_ttl_ms`) is excluded — retention advances past it,
        and a later resubscribe under the name falls back to
        backfill-then-tail instead of resuming. Renewals happen here:
        every call stamps cursors with a live pump, so the TTL clock
        only runs while the subscriber is actually away."""
        import time
        durable = log.committed_sub_cursors()
        if not durable:
            return {}
        now = time.monotonic()
        live = {p.cursor_name for p in self.subscriptions
                if p.mv == name and p.cursor_name is not None}
        ttl_s = self.sub_cursor_ttl_ms / 1e3
        out = {}
        for cname, cur in durable.items():
            key = (name, cname)
            if cname in live:
                self._cursor_seen[key] = now
            seen = self._cursor_seen.setdefault(key, now)
            if ttl_s <= 0 or cname in live or (now - seen) < ttl_s:
                out[cname] = cur
        return out

    def on_commit(self, epoch: int) -> None:
        """Pulsed by the coordinator at every checkpoint commit (inline
        sync, background uploader, and cluster commit_remote paths).
        Also the MV-changelog retention point: entries below every
        subscriber's cursor (live pumps AND durable named cursors whose
        lease has not lapsed) are tombstoned, staged at the current open
        epoch so the truncation rides the next checkpoint."""
        self.commit_seq += 1
        self._commit_event.set()
        for name, log in self.mv_logs.items():
            if not log.active:
                continue
            durable = log.committed_sub_cursors()
            pinning = self.pinning_sub_cursors(name, log)
            live_names = {p.cursor_name for p in self.subscriptions
                          if p.mv == name}
            # a lapsed lease is released DURABLY: the cursor tombstone
            # rides the next checkpoint, so expiry survives restart
            # (register_mv would otherwise resurrect retention from the
            # stale cursor) and a later resubscribe under the name
            # deterministically backfills instead of resuming
            for cname in set(durable) - set(pinning) - live_names:
                log.drop_sub_cursor(cname, self.collected_epoch)
                self._cursor_seen.pop((name, cname), None)
            cursors = [p.cursor_epoch for p in self.subscriptions
                       if p.mv == name]
            cursors.extend(pinning.values())
            if not cursors:
                if durable:
                    # every holder was an expired cursor: stop paying
                    # the log entirely — truncate to the sealed floor
                    # and deactivate (a resubscribe re-activates with a
                    # fresh backfill handoff)
                    log.truncate_below(self.collected_epoch,
                                       self.collected_epoch)
                    log.deactivate()
                continue
            floor = min(cursors)
            if floor > log.truncated_below:
                log.truncate_below(floor, self.collected_epoch)

    def on_barrier(self, barrier) -> None:
        """Collected-barrier hook: remember the sealed epoch — the
        activation floor for MV logs (everything <= it is in table
        state, everything after will be logged once active)."""
        self.collected_epoch = barrier.epoch.prev

    async def wait_commit(self, seen: int) -> int:
        while self.commit_seq == seen:
            self._commit_event.clear()
            await self._commit_event.wait()
        return self.commit_seq

    def fail(self, name: str, exc: BaseException) -> None:
        if self.failure is None:
            self.failure = (name, exc)
            if self.event_log is not None:
                self.event_log.emit("sink_park", sink=name,
                                    error=repr(exc))
        self.commit_seq += 1
        self._commit_event.set()          # wake waiters so they observe it

    def check_failure(self) -> None:
        if self.failure is not None:
            name, exc = self.failure
            raise RuntimeError(
                f"sink delivery {name!r} failed; recovery must replay "
                f"from the last committed epoch") from exc

    # ------------------------------------------------------------- drain
    async def drain(self) -> None:
        """Deliver everything committed (quiesce point; NOT part of the
        barrier path). Raises a parked delivery failure like
        drain_uploads raises an upload failure — a failure DURING this
        drain parks the same way (wrapped in the standard fail-stop
        RuntimeError), so tick's auto-recovery owns the retry instead
        of a raw connector error escaping to the caller."""
        self.check_failure()
        for d in list(self.sinks.values()):
            try:
                await d.deliver_pending()
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # noqa: BLE001 — park it
                self.fail(d.name, e)
                break
        for pump in list(self.subscriptions):
            try:
                await pump.pump_pending()
            except (ConnectionResetError, BrokenPipeError, OSError):
                # a vanished subscriber is the subscriber's problem —
                # sink failures fail-stop, subscription failures just
                # close the subscription
                pump.stop()
        self.check_failure()

    def abort(self) -> None:
        """Crash/recovery entry: cancel every background task. Durable
        cursors are already exact (they commit with checkpoints), so
        the rebuilt topology's fresh tasks resume exactly-once."""
        self.aborted = True
        for d in self.sinks.values():
            d.stop()
        self.sinks.clear()
        for pump in list(self.subscriptions):
            pump.stop()
        self.subscriptions.clear()
        self.commit_seq += 1
        self._commit_event.set()          # release parked subscribe waits

    # --------------------------------------------------------- reporting
    def report(self) -> list[tuple]:
        """SHOW subscriptions rows: (name, kind, cursor, delivered,
        active)."""
        rows = []
        for name in sorted(self.sinks):
            d = self.sinks[name]
            rows.append((f"sink/{name}", "delivery",
                         str(d.delivered_seq), str(d.delivered_epochs),
                         "failed" if self.failure
                         and self.failure[0] == name else "live"))
        for pump in self.subscriptions:
            rows.append((f"{pump.mv}/{pump.sub_id}", "changelog",
                         str(pump.cursor_epoch),
                         str(pump.delivered_batches),
                         "live" if not pump.closing else "closed"))
        return rows

"""Synchronous broker client — the engine side of the broker wire.

Connectors call the broker from two very different contexts: source
fetches run ON the event loop (the connector protocol is synchronous,
like the jsonl file reads) and sink appends run on the log-store
delivery WORKER THREAD. A small blocking client serves both: requests
are the same length-prefixed pickle frames `cluster/rpc.py` speaks
(`{"id": n, "method": m, "args": {...}}` -> `{"id": -n, "ok": ...}`),
issued strictly sequentially per client, so no multiplexing machinery
is needed. One transparent reconnect absorbs a broker restart between
calls; a failure during a call raises to the caller (the source's
fail-stop -> auto-recovery path, or the sink delivery's park).

Address forms:
    "host:port"        TCP to a `BrokerServer`
    "inproc://name"    direct calls on a registered in-process `Broker`
    a `Broker` object  direct calls (engine-level tests)
"""

from __future__ import annotations

import pickle
import socket
import struct
from typing import Optional

from .server import Broker, resolve_inproc


class BrokerClient:
    def __init__(self, brokers, timeout: float = 10.0):
        self.addr = brokers
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._next_id = 1

    # ---------------------------------------------------------- transport
    def _direct(self) -> Optional[Broker]:
        if isinstance(self.addr, Broker):
            return self.addr
        if isinstance(self.addr, str) and self.addr.startswith("inproc://"):
            return resolve_inproc(self.addr[len("inproc://"):])
        return None

    def _connect(self) -> socket.socket:
        if self._sock is None:
            host, _, port = self.addr.rpartition(":")
            s = socket.create_connection((host or "127.0.0.1", int(port)),
                                         timeout=self.timeout)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
        return self._sock

    def _recv_exact(self, s: socket.socket, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            part = s.recv(n - len(buf))
            if not part:
                raise ConnectionResetError("broker closed the connection")
            buf += part
        return buf

    def _roundtrip(self, method: str, args: dict):
        s = self._connect()
        rid = self._next_id
        self._next_id += 1
        blob = pickle.dumps({"id": rid, "method": method, "args": args})
        s.sendall(struct.pack("!i", len(blob)) + blob)
        while True:
            ln = struct.unpack("!i", self._recv_exact(s, 4))[0]
            msg = pickle.loads(self._recv_exact(s, ln))
            if msg.get("id") == -rid:
                if msg.get("ok"):
                    return msg.get("result")
                raise RuntimeError(
                    f"broker {method} failed: {msg.get('error')}")
            # the broker server never pushes; any other id is protocol
            # noise from a half-closed previous call — skip it

    def call(self, method: str, **args):
        direct = self._direct()
        if direct is not None:
            return getattr(direct, method)(**args)
        try:
            return self._roundtrip(method, args)
        except (OSError, ConnectionError, EOFError):
            # one reconnect: a restarted broker (durable log, same
            # address) is indistinguishable from a dropped idle socket
            self.close()
            return self._roundtrip(method, args)

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    # ------------------------------------------------------------ methods
    def create_topic(self, topic: str, partitions: int = 1) -> int:
        return self.call("create_topic", topic=topic, partitions=partitions)

    def add_partitions(self, topic: str, total: int) -> int:
        return self.call("add_partitions", topic=topic, total=total)

    def list_partitions(self, topic: str) -> int:
        return self.call("list_partitions", topic=topic)

    def topics(self) -> dict:
        return self.call("topics")

    def append(self, topic: str, partition: int, records: list,
               meta: Optional[dict] = None) -> int:
        return self.call("append", topic=topic, partition=partition,
                         records=records, meta=meta)

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 256) -> dict:
        return self.call("fetch", topic=topic, partition=partition,
                         offset=offset, max_records=max_records)

    def high_watermark(self, topic: str, partition: int) -> int:
        return self.call("high_watermark", topic=topic,
                         partition=partition)

    def last_meta(self, topic: str, partition: int) -> Optional[dict]:
        return self.call("last_meta", topic=topic, partition=partition)

    def ping(self) -> dict:
        return self.call("ping")

    def set_compaction(self, topic: str, keys: list) -> None:
        return self.call("set_compaction", topic=topic, keys=list(keys))

    def set_retention_floor(self, topic: str, partition: int,
                            offset: int) -> dict:
        return self.call("set_retention_floor", topic=topic,
                         partition=partition, offset=offset)

    def earliest_offset(self, topic: str, partition: int) -> int:
        return self.call("earliest_offset", topic=topic,
                         partition=partition)

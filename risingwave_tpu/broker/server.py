"""Broker — partitioned topics over `PartitionLog`, plus the RPC server.

`Broker` is the storage/control authority (usable in-process by tests);
`BrokerServer` exposes it over the cluster control-plane wire
(cluster/rpc.py length-prefixed pickle frames) so engines in other
processes reach it at `host:port`. An in-process REGISTRY lets tests run
the whole engine↔broker pipeline on one event loop with zero sockets:
`register_inproc('x', broker)` + `brokers='inproc://x'`.

Topic layout on disk:  <root>/<topic>/p<00000>/<base_offset>.seg —
partition membership IS the directory listing, so a broker restart
recovers topics, partition counts, offsets and batch metadata by scan
(torn trailing frames dropped, log.py)."""

from __future__ import annotations

import asyncio
import json
import os
import re
import threading
from typing import Optional

from .log import PartitionLog

_NAME_RE = re.compile(r"^[A-Za-z0-9_.\-]+$")


class Broker:
    """Topic/partition authority. All methods are synchronous and
    thread-safe (the RPC server calls them via worker threads; in-proc
    clients call them from the loop AND from sink delivery threads)."""

    def __init__(self, root: str, segment_bytes: int = 64 << 20,
                 fsync: bool = True):
        self.root = root
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        self._lock = threading.Lock()
        self._parts: dict[tuple[str, int], PartitionLog] = {}
        # topic -> key field names for key-compacted (changelog) topics;
        # durable in <topic>/_compact.json so a restart keeps compacting
        self._compact_keys: dict[str, list[str]] = {}
        os.makedirs(root, exist_ok=True)
        for topic in sorted(os.listdir(root)):
            tdir = os.path.join(root, topic)
            if not os.path.isdir(tdir):
                continue
            cpath = os.path.join(tdir, "_compact.json")
            if os.path.exists(cpath):
                try:
                    with open(cpath) as f:
                        self._compact_keys[topic] = list(json.load(f))
                except (OSError, ValueError):
                    pass
            for p in sorted(os.listdir(tdir)):
                if p.startswith("p") and p[1:].isdigit():
                    self._open(topic, int(p[1:]))

    def _open(self, topic: str, partition: int) -> PartitionLog:
        key = (topic, partition)
        if key not in self._parts:
            self._parts[key] = PartitionLog(
                os.path.join(self.root, topic, f"p{partition:05d}"),
                segment_bytes=self.segment_bytes, fsync=self.fsync)
        return self._parts[key]

    def _part(self, topic: str, partition: int) -> PartitionLog:
        log = self._parts.get((topic, partition))
        if log is None:
            raise KeyError(
                f"unknown topic/partition {topic!r}/{partition}")
        return log

    # ------------------------------------------------------------ control
    def create_topic(self, topic: str, partitions: int = 1) -> int:
        """Idempotent: an existing topic keeps its (possibly larger)
        partition count — partitions only ever grow. Returns the live
        partition count."""
        if not _NAME_RE.match(topic or ""):
            raise ValueError(f"bad topic name {topic!r}")
        with self._lock:
            have = self._n_partitions(topic)
            for p in range(have, max(int(partitions), have, 1)):
                self._open(topic, p)
            return self._n_partitions(topic)

    def add_partitions(self, topic: str, total: int) -> int:
        """Grow a topic to `total` partitions (never shrinks) — the live
        split-discovery trigger: source enumerators poll
        `list_partitions` and assign the new splits at a barrier."""
        with self._lock:
            have = self._n_partitions(topic)
            if have == 0:
                raise KeyError(f"unknown topic {topic!r}")
            for p in range(have, max(int(total), have)):
                self._open(topic, p)
            return self._n_partitions(topic)

    def _n_partitions(self, topic: str) -> int:
        return sum(1 for t, _p in self._parts if t == topic)

    def list_partitions(self, topic: str) -> int:
        with self._lock:
            return self._n_partitions(topic)

    def topics(self) -> dict:
        """topic -> {partitions, high_watermarks: [per partition]}."""
        with self._lock:
            out: dict = {}
            for (t, p), log in sorted(self._parts.items()):
                ent = out.setdefault(t, {"partitions": 0,
                                         "high_watermarks": []})
                ent["partitions"] += 1
                ent["high_watermarks"].append(log.high_watermark)
            return out

    # --------------------------------------------------------------- data
    def append(self, topic: str, partition: int, records: list,
               meta: Optional[dict] = None) -> int:
        return self._part(topic, partition).append(
            [bytes(r) for r in records], meta=meta)

    def fetch(self, topic: str, partition: int, offset: int,
              max_records: int = 256) -> dict:
        log = self._part(topic, partition)
        offset = int(offset)
        if offset < log.start_offset:
            # below the retention floor: a key-compacted partition
            # serves its latest-per-key snapshot in ONE batch (net
            # state, then the tail from start_offset); a plain one
            # clamps forward — either way the consumer backfills from
            # the floor instead of offset 0
            snap = log.snapshot_records()
            if snap is not None:
                return {"records": snap,
                        "next_offset": log.start_offset,
                        "high_watermark": log.high_watermark,
                        "log_start_offset": log.start_offset,
                        "compacted": True}
            offset = log.start_offset
        recs = log.fetch(offset, int(max_records))
        return {"records": recs,
                "next_offset": offset + len(recs),
                "high_watermark": log.high_watermark,
                "log_start_offset": log.start_offset,
                # producer-stamped batch metadata overlapping the range
                # (sink seq + cross-engine trace context): consumers
                # use it for ingest-span links, everyone else ignores it
                "metas": log.fetch_metas(offset, len(recs))}

    def high_watermark(self, topic: str, partition: int) -> int:
        return self._part(topic, partition).high_watermark

    # ---------------------------------------------------------- retention
    def set_compaction(self, topic: str, keys: list) -> None:
        """Mark `topic` key-compacted: retention folds dropped segments
        into a latest-record-per-key snapshot instead of discarding
        them. Durable per topic (_compact.json)."""
        with self._lock:
            if self._n_partitions(topic) == 0:
                raise KeyError(f"unknown topic {topic!r}")
            self._compact_keys[topic] = [str(k) for k in keys]
            with open(os.path.join(self.root, topic, "_compact.json"),
                      "w") as f:
                json.dump(self._compact_keys[topic], f)

    def set_retention_floor(self, topic: str, partition: int,
                            offset: int) -> dict:
        """The engine's durable-consumer floor for one partition: drop
        whole sealed segments entirely below it (key-compacting them
        first on a compacted topic). Idempotent; a floor above the high
        watermark is clamped by the whole-segment rule itself."""
        log = self._part(topic, partition)
        dropped = log.drop_segments_below(
            int(offset), self._compact_keys.get(topic))
        return {"segments_dropped": dropped,
                "log_start_offset": log.start_offset}

    def earliest_offset(self, topic: str, partition: int) -> int:
        return self._part(topic, partition).start_offset

    def last_meta(self, topic: str, partition: int) -> Optional[dict]:
        """Metadata of the last durable batch that carried one — where a
        `BrokerSink` finds its committed delivery sequence after either
        side restarts."""
        return self._part(topic, partition).last_meta

    def ping(self) -> dict:
        return {"ok": True}


# --------------------------------------------------------------- in-proc
# name -> Broker: `brokers='inproc://name'` resolves here at CALL time,
# so a test can wipe and re-register a broker (restart simulation) while
# connectors hold the address.
_INPROC: dict[str, Broker] = {}


def register_inproc(name: str, broker: Broker) -> None:
    _INPROC[name] = broker


def unregister_inproc(name: str) -> None:
    _INPROC.pop(name, None)


def resolve_inproc(name: str) -> Broker:
    b = _INPROC.get(name)
    if b is None:
        raise ConnectionRefusedError(
            f"no in-process broker registered as {name!r}")
    return b


# ---------------------------------------------------------------- server
class BrokerServer:
    """RPC front: every request maps 1:1 onto a `Broker` method; disk
    work runs via `asyncio.to_thread` so one slow fsync never blocks
    other clients' frames."""

    _METHODS = ("create_topic", "add_partitions", "list_partitions",
                "topics", "append", "fetch", "high_watermark",
                "last_meta", "ping", "set_compaction",
                "set_retention_floor", "earliest_offset")

    def __init__(self, broker: Broker, host: str = "127.0.0.1",
                 port: int = 0):
        self.broker = broker
        self.host = host
        self.port = port
        self._server = None
        self._conns: list = []

    async def start(self) -> "BrokerServer":
        from ..cluster.rpc import start_rpc_server

        def handler_factory(conn):
            self._conns.append(conn)

            async def handler(method, args):
                if method not in self._METHODS:
                    raise ValueError(f"unknown broker method {method!r}")
                return await asyncio.to_thread(
                    getattr(self.broker, method), **args)

            def on_closed(exc):
                if conn in self._conns:
                    self._conns.remove(conn)

            return handler, on_closed

        self._server = await start_rpc_server(
            handler_factory, host=self.host, port=self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        for conn in list(self._conns):
            await conn.close()
        self._conns.clear()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
            self._server = None

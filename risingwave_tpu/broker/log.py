"""Partition log — append-only segment files with dense record offsets.

Reference: a Kafka partition (log segments + the high watermark) reduced
to the invariants the engine's exactly-once contracts actually consume:

  * records are opaque bytes with DENSE offsets 0,1,2,... per partition —
    the offset is the exactly-once resume point a source commits in
    barrier state;
  * appends are BATCH-atomic: one `append()` call writes one framed
    batch (`u32 len ++ u32 crc32 ++ body`) with a single write+fsync. A
    crash mid-append leaves a torn trailing frame whose length or crc
    check fails on reopen — the whole batch never existed, exactly like
    `FileSink`'s torn trailing JSON line. That atomicity is what lets a
    sink persist its delivery sequence number IN the batch metadata: the
    last readable batch's meta is always a sequence whose rows are all
    durable.
  * segments roll at a size threshold; a segment file is named by the
    base offset of its first record, so a reader locates any offset from
    directory listing alone.
  * retention drops WHOLE sealed segments below a floor offset pushed by
    the engine (the minimum offset every consumer has durably
    checkpointed) — `start_offset` is then the earliest retained record
    and a reopen seeds itself from the first surviving batch. On a
    key-compacted topic the dropped range folds into a latest-record-
    per-key snapshot (`COMPACT.snap`, written atomically BEFORE the
    segment files go) that fetches below the floor serve in one batch —
    a new changelog consumer gets net state + tail instead of history
    from offset 0.

Batch body layout (all big-endian):

    u64 base_offset | u32 n_records | u32 meta_len | meta (json bytes)
    then per record: u32 len | bytes
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Optional

_FRAME = struct.Struct("!II")          # body_len, crc32(body)
_HDR = struct.Struct("!QII")           # base_offset, n_records, meta_len
_REC = struct.Struct("!I")


class PartitionLog:
    """One partition directory of `*.seg` files. Thread-safe: appends
    serialize on a lock; fetches read immutable prefixes (a batch is
    visible only after its index entry is published under the lock)."""

    def __init__(self, path: str, segment_bytes: int = 64 << 20,
                 fsync: bool = True):
        self.path = path
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        self._lock = threading.Lock()
        # batch index: (base_offset, n_records, seg_path, file_pos)
        self._index: list[tuple[int, int, str, int]] = []
        self.next_offset = 0
        # earliest retained record (> 0 once retention dropped segments)
        self.start_offset = 0
        # metadata of the last readable batch that carried one (the
        # sink's durable sequence number lives here)
        self.last_meta: Optional[dict] = None
        os.makedirs(path, exist_ok=True)
        self._scan()

    # --------------------------------------------------------------- open
    def _segments(self) -> list[str]:
        return sorted(f for f in os.listdir(self.path)
                      if f.endswith(".seg"))

    def _scan(self) -> None:
        """Rebuild the batch index from disk. A torn/corrupt trailing
        frame (crash mid-append) is truncated away — the batch never
        happened; everything before it is intact by construction
        (batches are written strictly sequentially)."""
        for seg in self._segments():
            seg_path = os.path.join(self.path, seg)
            size = os.path.getsize(seg_path)
            with open(seg_path, "rb") as f:
                pos = 0
                while pos + _FRAME.size <= size:
                    body_len, crc = _FRAME.unpack(f.read(_FRAME.size))
                    body = f.read(body_len)
                    if len(body) != body_len \
                            or zlib.crc32(body) != crc:
                        # torn tail: drop the frame AND anything the
                        # crashed writer managed to queue after it
                        with open(seg_path, "ab") as t:
                            t.truncate(pos)
                        break
                    base, n, meta_len = _HDR.unpack_from(body)
                    meta = (json.loads(body[_HDR.size:
                                            _HDR.size + meta_len])
                            if meta_len else None)
                    if not self._index:
                        # first surviving batch seeds the offset space:
                        # retention may have dropped a whole segment
                        # prefix, so the log no longer starts at 0
                        self.start_offset = base
                        self.next_offset = base
                    if base != self.next_offset:
                        break               # gap: a lost segment prefix
                    self._index.append((base, n, seg_path, pos))
                    self.next_offset = base + n
                    if meta is not None:
                        self.last_meta = meta
                    pos += _FRAME.size + body_len
        if not self._index:
            # no surviving batch (fresh dir, or a torn tail emptied the
            # only segment): the segment NAME still carries the base
            # offset, so appends continue the dense offset space instead
            # of restarting at 0 under committed consumer cursors
            segs = self._segments()
            if segs:
                base = int(segs[-1].split(".")[0])
                self.start_offset = base
                self.next_offset = base

    # ------------------------------------------------------------- append
    def append(self, records: list[bytes],
               meta: Optional[dict] = None) -> int:
        """Atomically append one batch; returns its base offset. The
        frame is assembled host-side and lands with ONE write + fsync,
        so the torn-tail tolerance above makes it all-or-nothing."""
        with self._lock:
            base = self.next_offset
            meta_b = json.dumps(meta).encode() if meta is not None else b""
            body = bytearray(_HDR.pack(base, len(records), len(meta_b)))
            body += meta_b
            for r in records:
                body += _REC.pack(len(r))
                body += r
            frame = _FRAME.pack(len(body), zlib.crc32(bytes(body))) \
                + bytes(body)
            seg_path = self._active_segment()
            pos = os.path.getsize(seg_path)
            with open(seg_path, "ab") as f:
                f.write(frame)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            self._index.append((base, len(records), seg_path, pos))
            self.next_offset = base + len(records)
            if meta is not None:
                self.last_meta = meta
            return base

    def _active_segment(self) -> str:
        segs = self._segments()
        if segs:
            p = os.path.join(self.path, segs[-1])
            if os.path.getsize(p) < self.segment_bytes:
                return p
        p = os.path.join(self.path, f"{self.next_offset:020d}.seg")
        if not os.path.exists(p):
            open(p, "wb").close()
        return p

    # -------------------------------------------------------------- fetch
    def fetch(self, offset: int, max_records: int) -> list[bytes]:
        """Records [offset, offset + max_records) ∩ [0, high watermark),
        in offset order."""
        if offset >= self.next_offset or max_records <= 0:
            return []
        # binary search the batch covering `offset`
        lo, hi = 0, len(self._index)
        while lo < hi:
            mid = (lo + hi) // 2
            base, n, _, _ = self._index[mid]
            if base + n <= offset:
                lo = mid + 1
            else:
                hi = mid
        out: list[bytes] = []
        for base, n, seg_path, pos in self._index[lo:]:
            if len(out) >= max_records:
                break
            try:
                with open(seg_path, "rb") as f:
                    f.seek(pos)
                    body_len, _crc = _FRAME.unpack(f.read(_FRAME.size))
                    body = f.read(body_len)
            except FileNotFoundError:
                # a racing retention drop removed this (sub-floor)
                # segment; stop here so the returned records stay
                # offset-contiguous — the caller refetches above the
                # new start_offset
                break
            _base, _n, meta_len = _HDR.unpack_from(body)
            p = _HDR.size + meta_len
            for i in range(n):
                (ln,) = _REC.unpack_from(body, p)
                p += _REC.size
                if base + i >= offset and len(out) < max_records:
                    out.append(body[p:p + ln])
                p += ln
        return out

    def fetch_metas(self, offset: int, max_records: int) -> list:
        """`[[base_offset, meta], ...]` for every meta-carrying batch
        overlapping [offset, offset + max_records) — the side channel a
        consumer reads producer-stamped batch metadata (sink sequence
        numbers, cross-engine trace context) from without touching the
        record bytes. Separate from `fetch` so the record path keeps
        its exact shape."""
        if offset >= self.next_offset or max_records <= 0:
            return []
        end = offset + max_records
        out = []
        for base, n, seg_path, pos in self._index:
            if base + n <= offset:
                continue
            if base >= end:
                break
            try:
                with open(seg_path, "rb") as f:
                    f.seek(pos)
                    body_len, _crc = _FRAME.unpack(f.read(_FRAME.size))
                    body = f.read(body_len)
            except FileNotFoundError:
                break                       # racing retention drop
            _base, _n, meta_len = _HDR.unpack_from(body)
            if meta_len:
                try:
                    meta = json.loads(
                        body[_HDR.size:_HDR.size + meta_len])
                except ValueError:
                    continue
                out.append([base, meta])
        return out

    @property
    def high_watermark(self) -> int:
        return self.next_offset

    # ---------------------------------------------------------- retention
    _SNAP = "COMPACT.snap"

    def _read_batch_records(self, seg_path: str, pos: int) -> list[bytes]:
        with open(seg_path, "rb") as f:
            f.seek(pos)
            body_len, _crc = _FRAME.unpack(f.read(_FRAME.size))
            body = f.read(body_len)
        _base, n, meta_len = _HDR.unpack_from(body)
        out: list[bytes] = []
        p = _HDR.size + meta_len
        for _ in range(n):
            (ln,) = _REC.unpack_from(body, p)
            p += _REC.size
            out.append(body[p:p + ln])
            p += ln
        return out

    def drop_segments_below(self, floor: int,
                            compact_keys: Optional[list] = None) -> int:
        """Drop the longest PREFIX of whole sealed segments whose every
        record sits below `floor` (the engine's durable-consumer floor).
        The active segment never drops; a partially-covered segment
        blocks the prefix (offsets stay dense). With `compact_keys` the
        dropped range first folds into the latest-per-key snapshot —
        written atomically BEFORE any file is removed, so a crash
        between the two at worst re-folds the same records (idempotent:
        latest-per-key). Returns the number of segments dropped."""
        with self._lock:
            segs = self._segments()
            if len(segs) <= 1:
                return 0
            ends: dict[str, int] = {}
            for base, n, seg_path, _pos in self._index:
                name = os.path.basename(seg_path)
                ends[name] = max(ends.get(name, 0), base + n)
            drop: list[str] = []
            for name in segs[:-1]:          # never the active segment
                end = ends.get(name)
                if end is not None and end <= floor:
                    drop.append(name)
                else:
                    break
            if not drop:
                return 0
            if compact_keys:
                self._merge_snapshot(drop, list(compact_keys))
            dropped = {os.path.join(self.path, n) for n in drop}
            for p in sorted(dropped):
                os.remove(p)
            self._index = [e for e in self._index if e[2] not in dropped]
            self.start_offset = (self._index[0][0] if self._index
                                 else self.next_offset)
            return len(drop)

    def _merge_snapshot(self, drop_names: list[str],
                        keys: list[str]) -> None:
        """Fold every record of the to-be-dropped segments into the
        compacted snapshot: latest JSON record per key tuple wins, a
        record carrying `__op` (the changelog delete marker —
        connectors/broker.py encode_row) removes its key. Non-JSON
        records have no key and are dropped with the history."""
        snap = self._load_snapshot() or {}
        dropped = {os.path.join(self.path, n) for n in drop_names}
        for base, n, seg_path, pos in self._index:
            if seg_path not in dropped:
                continue
            for rec in self._read_batch_records(seg_path, pos):
                try:
                    obj = json.loads(rec)
                except ValueError:
                    continue
                if not isinstance(obj, dict):
                    continue
                key = json.dumps([obj.get(k) for k in keys])
                if "__op" in obj:
                    snap.pop(key, None)
                else:
                    snap[key] = rec.decode()   # json => valid utf-8
        tmp = os.path.join(self.path, self._SNAP + ".tmp")
        with open(tmp, "w") as f:
            json.dump(snap, f)
            f.flush()
            if self.fsync:
                os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.path, self._SNAP))

    def _load_snapshot(self) -> Optional[dict]:
        path = os.path.join(self.path, self._SNAP)
        if not os.path.exists(path):
            return None
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def snapshot_records(self) -> Optional[list[bytes]]:
        """The compacted prefix as record bytes (net state below
        `start_offset`), or None when this partition was never
        key-compacted. Served whole to fetches below the floor."""
        snap = self._load_snapshot()
        if snap is None:
            return None
        return [s.encode() for s in snap.values()]

"""Partition log — append-only segment files with dense record offsets.

Reference: a Kafka partition (log segments + the high watermark) reduced
to the invariants the engine's exactly-once contracts actually consume:

  * records are opaque bytes with DENSE offsets 0,1,2,... per partition —
    the offset is the exactly-once resume point a source commits in
    barrier state;
  * appends are BATCH-atomic: one `append()` call writes one framed
    batch (`u32 len ++ u32 crc32 ++ body`) with a single write+fsync. A
    crash mid-append leaves a torn trailing frame whose length or crc
    check fails on reopen — the whole batch never existed, exactly like
    `FileSink`'s torn trailing JSON line. That atomicity is what lets a
    sink persist its delivery sequence number IN the batch metadata: the
    last readable batch's meta is always a sequence whose rows are all
    durable.
  * segments roll at a size threshold; a segment file is named by the
    base offset of its first record, so a reader locates any offset from
    directory listing alone.

Batch body layout (all big-endian):

    u64 base_offset | u32 n_records | u32 meta_len | meta (json bytes)
    then per record: u32 len | bytes
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from typing import Optional

_FRAME = struct.Struct("!II")          # body_len, crc32(body)
_HDR = struct.Struct("!QII")           # base_offset, n_records, meta_len
_REC = struct.Struct("!I")


class PartitionLog:
    """One partition directory of `*.seg` files. Thread-safe: appends
    serialize on a lock; fetches read immutable prefixes (a batch is
    visible only after its index entry is published under the lock)."""

    def __init__(self, path: str, segment_bytes: int = 64 << 20,
                 fsync: bool = True):
        self.path = path
        self.segment_bytes = segment_bytes
        self.fsync = fsync
        self._lock = threading.Lock()
        # batch index: (base_offset, n_records, seg_path, file_pos)
        self._index: list[tuple[int, int, str, int]] = []
        self.next_offset = 0
        # metadata of the last readable batch that carried one (the
        # sink's durable sequence number lives here)
        self.last_meta: Optional[dict] = None
        os.makedirs(path, exist_ok=True)
        self._scan()

    # --------------------------------------------------------------- open
    def _segments(self) -> list[str]:
        return sorted(f for f in os.listdir(self.path)
                      if f.endswith(".seg"))

    def _scan(self) -> None:
        """Rebuild the batch index from disk. A torn/corrupt trailing
        frame (crash mid-append) is truncated away — the batch never
        happened; everything before it is intact by construction
        (batches are written strictly sequentially)."""
        for seg in self._segments():
            seg_path = os.path.join(self.path, seg)
            size = os.path.getsize(seg_path)
            with open(seg_path, "rb") as f:
                pos = 0
                while pos + _FRAME.size <= size:
                    body_len, crc = _FRAME.unpack(f.read(_FRAME.size))
                    body = f.read(body_len)
                    if len(body) != body_len \
                            or zlib.crc32(body) != crc:
                        # torn tail: drop the frame AND anything the
                        # crashed writer managed to queue after it
                        with open(seg_path, "ab") as t:
                            t.truncate(pos)
                        break
                    base, n, meta_len = _HDR.unpack_from(body)
                    meta = (json.loads(body[_HDR.size:
                                            _HDR.size + meta_len])
                            if meta_len else None)
                    if base != self.next_offset:
                        break               # gap: a lost segment prefix
                    self._index.append((base, n, seg_path, pos))
                    self.next_offset = base + n
                    if meta is not None:
                        self.last_meta = meta
                    pos += _FRAME.size + body_len

    # ------------------------------------------------------------- append
    def append(self, records: list[bytes],
               meta: Optional[dict] = None) -> int:
        """Atomically append one batch; returns its base offset. The
        frame is assembled host-side and lands with ONE write + fsync,
        so the torn-tail tolerance above makes it all-or-nothing."""
        with self._lock:
            base = self.next_offset
            meta_b = json.dumps(meta).encode() if meta is not None else b""
            body = bytearray(_HDR.pack(base, len(records), len(meta_b)))
            body += meta_b
            for r in records:
                body += _REC.pack(len(r))
                body += r
            frame = _FRAME.pack(len(body), zlib.crc32(bytes(body))) \
                + bytes(body)
            seg_path = self._active_segment()
            pos = os.path.getsize(seg_path)
            with open(seg_path, "ab") as f:
                f.write(frame)
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
            self._index.append((base, len(records), seg_path, pos))
            self.next_offset = base + len(records)
            if meta is not None:
                self.last_meta = meta
            return base

    def _active_segment(self) -> str:
        segs = self._segments()
        if segs:
            p = os.path.join(self.path, segs[-1])
            if os.path.getsize(p) < self.segment_bytes:
                return p
        p = os.path.join(self.path, f"{self.next_offset:020d}.seg")
        if not os.path.exists(p):
            open(p, "wb").close()
        return p

    # -------------------------------------------------------------- fetch
    def fetch(self, offset: int, max_records: int) -> list[bytes]:
        """Records [offset, offset + max_records) ∩ [0, high watermark),
        in offset order."""
        if offset >= self.next_offset or max_records <= 0:
            return []
        # binary search the batch covering `offset`
        lo, hi = 0, len(self._index)
        while lo < hi:
            mid = (lo + hi) // 2
            base, n, _, _ = self._index[mid]
            if base + n <= offset:
                lo = mid + 1
            else:
                hi = mid
        out: list[bytes] = []
        for base, n, seg_path, pos in self._index[lo:]:
            if len(out) >= max_records:
                break
            with open(seg_path, "rb") as f:
                f.seek(pos)
                body_len, _crc = _FRAME.unpack(f.read(_FRAME.size))
                body = f.read(body_len)
            _base, _n, meta_len = _HDR.unpack_from(body)
            p = _HDR.size + meta_len
            for i in range(n):
                (ln,) = _REC.unpack_from(body, p)
                p += _REC.size
                if base + i >= offset and len(out) < max_records:
                    out.append(body[p:p + ln])
                p += ln
        return out

    @property
    def high_watermark(self) -> int:
        return self.next_offset

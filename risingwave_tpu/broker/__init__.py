"""Standalone kafka-alike broker — durable partitioned topics.

The external-streaming half of ROADMAP item 4: a broker process (or
in-process object, for tests) owning TOPICS of append-only PARTITIONS
with dense per-partition record offsets, served over the control-plane
wire (cluster/rpc.py length-prefixed frames). The engine talks to it
through two connectors:

  * ingress — `connector='broker'` sources (connectors/broker.py):
    splits ARE broker partitions, per-split offsets checkpoint in
    barrier state exactly like the generator splits, and a meta-side
    enumerator picks up newly-added partitions at a barrier
    (reference: src/meta/src/stream/source_manager.rs).
  * egress — `BrokerSink` implementing the log-store delivery contract
    `write(seq, epoch, rows)` / `committed_seq()`, with the sequence
    number persisted IN the topic (batch metadata), so delivery dedupes
    across engine crash AND broker restart.

Run standalone:  python -m risingwave_tpu.broker --data DIR --port N
"""

from .log import PartitionLog
from .server import Broker, BrokerServer, register_inproc, unregister_inproc
from .client import BrokerClient

__all__ = ["PartitionLog", "Broker", "BrokerServer", "BrokerClient",
           "register_inproc", "unregister_inproc"]

"""Standalone broker process:

    python -m risingwave_tpu.broker --data DIR [--port N] [--host H]

Prints one JSON line `{"broker": "host:port", "data": DIR}` to stdout
once listening (scripts parse it to learn the ephemeral port), then
serves until killed. Durable state lives entirely in --data; restarting
on the same directory recovers every topic, partition, offset and batch
metadata (torn trailing frames from a kill mid-append are dropped)."""

from __future__ import annotations

import argparse
import asyncio
import json
import sys

from .server import Broker, BrokerServer


async def _main() -> int:
    ap = argparse.ArgumentParser(prog="risingwave_tpu.broker")
    ap.add_argument("--data", required=True,
                    help="topic/segment root directory")
    ap.add_argument("--port", type=int, default=0,
                    help="listen port (0 = ephemeral)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--no-fsync", action="store_true",
                    help="skip per-append fsync (tests only)")
    args = ap.parse_args()

    broker = Broker(args.data, fsync=not args.no_fsync)
    server = await BrokerServer(broker, host=args.host,
                                port=args.port).start()
    print(json.dumps({"broker": f"{args.host}:{server.port}",
                      "data": args.data}), flush=True)
    try:
        await asyncio.Event().wait()
    except (KeyboardInterrupt, asyncio.CancelledError):
        pass
    finally:
        await server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(_main()))

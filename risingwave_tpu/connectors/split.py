"""Split readers — partitioning one logical stream across source actors.

Reference: src/connector/src/source/base.rs (SplitEnumerator/SplitReader)
+ src/meta/src/stream/source_manager.rs (split discovery & assignment).
Kafka-style sources have broker-defined partitions; the deterministic
generators here are partitioned by BLOCK INTERLEAVING instead: split k of
S owns every chunk-sized block b with b % S == k. The union over splits
is the whole stream, disjoint, and each split is independently seekable —
the per-split offset (rows consumed BY THIS SPLIT) is the exactly-once
state, exactly like a Kafka partition offset.
"""

from __future__ import annotations


class BlockSplitConnector:
    """Wrap a seekable contiguous connector as split k of S."""

    def __init__(self, inner, split_id: int, n_splits: int):
        assert 0 <= split_id < n_splits
        self.inner = inner
        self.split_id = split_id
        self.n_splits = n_splits
        self.schema = inner.schema
        self.chunk_size = inner.chunk_size
        self.offset = 0                  # rows consumed by THIS split
        self.table = getattr(inner, "table", None)

    def _global_offset(self) -> int:
        block = self.offset // self.chunk_size
        return (block * self.n_splits + self.split_id) * self.chunk_size

    def next_chunk(self):
        self.inner.seek(self._global_offset())
        chunk = self.inner.next_chunk()
        self.offset += self.chunk_size
        return chunk

    def seek(self, offset: int) -> None:
        assert offset % self.chunk_size == 0, \
            "split offsets advance in whole blocks"
        self.offset = offset

    @property
    def exhausted(self) -> bool:
        # a split-wrapped FINITE source (ArrowSource, jsonl tail at EOF)
        # must surface exhaustion at THIS split's next global position,
        # or the source executor busy-spins empty chunks (ADVICE r4 #3).
        # The positioning seek is cached per global offset (the source
        # loop polls this before every read) and a vanished backing file
        # reads as exhausted, matching the inner connectors' own
        # contract.
        if not hasattr(self.inner, "exhausted"):
            return False
        go = self._global_offset()
        if getattr(self, "_probed_at", None) != go:
            try:
                self.inner.seek(go)
            except OSError:
                return True
            self._probed_at = go
        return self.inner.exhausted

    @property
    def watermark_col(self) -> int:
        return self.inner.watermark_col

    def current_watermark(self) -> int:
        # the inner connector sits right after this split's last block —
        # its frontier is exact for the rows THIS split emitted; the
        # source takes the min across splits
        return self.inner.current_watermark()

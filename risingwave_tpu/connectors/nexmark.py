"""Nexmark event generator — device-native datagen source.

Reference: src/connector/src/source/nexmark/ (wraps the public `nexmark`
crate); workloads defined by ci/scripts/sql/nexmark/q*.sql. This is a
re-implementation of the *public Nexmark benchmark generator model* (person/
auction/bid event interleaving 1:3:46 per 50 events, hot-key skew ratios
from the spec) as a pure function `event_index -> row`, vectorized in jnp so
a whole chunk is generated on device per call — the source never bottlenecks
the TPU executors it feeds.

Randomness is a counter-based splitmix64 of the event id: deterministic,
seekable (exactly-once source recovery = remember the next event index,
reference source offsets in state_table_handler.rs), and identical across
hosts.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from ..common.chunk import StreamChunk, Column
from ..common.types import DataType, GLOBAL_DICT, Schema, schema

# Event interleaving per 50 events (Nexmark spec)
PERSON_PROPORTION = 1
AUCTION_PROPORTION = 3
BID_PROPORTION = 46
TOTAL_PROPORTION = 50

HOT_AUCTION_RATIO = 100
HOT_BIDDER_RATIO = 100
HOT_SELLER_RATIO = 4

FIRST_PERSON_ID = 1000
FIRST_AUCTION_ID = 1000
FIRST_CATEGORY_ID = 10

BID_SCHEMA = schema(
    ("auction", DataType.INT64),
    ("bidder", DataType.INT64),
    ("price", DataType.INT64),
    ("channel", DataType.VARCHAR),
    ("url", DataType.VARCHAR),
    ("date_time", DataType.TIMESTAMP),
    ("extra", DataType.VARCHAR),
)

PERSON_SCHEMA = schema(
    ("id", DataType.INT64),
    ("name", DataType.VARCHAR),
    ("email_address", DataType.VARCHAR),
    ("credit_card", DataType.VARCHAR),
    ("city", DataType.VARCHAR),
    ("state", DataType.VARCHAR),
    ("date_time", DataType.TIMESTAMP),
    ("extra", DataType.VARCHAR),
)

AUCTION_SCHEMA = schema(
    ("id", DataType.INT64),
    ("item_name", DataType.VARCHAR),
    ("description", DataType.VARCHAR),
    ("initial_bid", DataType.INT64),
    ("reserve", DataType.INT64),
    ("date_time", DataType.TIMESTAMP),
    ("expires", DataType.TIMESTAMP),
    ("seller", DataType.INT64),
    ("category", DataType.INT64),
    ("extra", DataType.VARCHAR),
)

_CHANNELS = ["apple", "google", "baidu", "facebook"]
_STATES = ["AZ", "CA", "ID", "OR", "WA", "WY"]
_CITIES = ["Phoenix", "Los Angeles", "San Francisco", "Boise", "Portland",
           "Bend", "Redmond", "Seattle", "Kent", "Cheyenne"]

# Dict-encoded vocabularies: every VARCHAR column draws ids from a
# contiguous range [base, base+size) registered in GLOBAL_DICT, so device
# ids always decode to real strings.
_VOCABS: dict[str, tuple[int, ...]] = {}


def _register_vocab(name: str, strings: list[str]) -> tuple:
    # ids need NOT be contiguous: any of these strings may already be in
    # GLOBAL_DICT (e.g. inserted by a bound SQL literal before the first
    # generator was constructed), so vocab picks gather from an explicit
    # id table instead of doing base+offset arithmetic
    if name not in _VOCABS:
        _VOCABS[name] = tuple(GLOBAL_DICT.get_or_insert(s)
                              for s in strings)
    return _VOCABS[name]


def _ensure_vocabs() -> dict[str, tuple[int, ...]]:
    _register_vocab("channel", _CHANNELS)
    _register_vocab("state", _STATES)
    _register_vocab("city", _CITIES)
    _register_vocab("name", [f"person_{i}" for i in range(1000)])
    _register_vocab("email", [f"user_{i}@example.com" for i in range(1000)])
    _register_vocab("card", [f"{i:04d} {i:04d} {i:04d} {i:04d}" for i in range(1000)])
    _register_vocab("url", [f"https://b.example.com/item/{i}" for i in range(1000)])
    _register_vocab("item", [f"item_{i}" for i in range(1000)])
    _register_vocab("desc", [f"description_{i}" for i in range(100)])
    _register_vocab("extra", [f"extra_{i}" for i in range(100)])
    return dict(_VOCABS)


def _vocab_pick(vocab: tuple, eid: jnp.ndarray, salt: int) -> jnp.ndarray:
    ids = jnp.asarray(vocab, dtype=jnp.int32)
    return ids[_rand(eid, salt, len(vocab))]


def _splitmix64(x: jnp.ndarray) -> jnp.ndarray:
    """Counter-based hash, uint64 -> uint64 (public splitmix64 constants)."""
    x = x.astype(jnp.uint64)
    x = (x + jnp.uint64(0x9E3779B97F4A7C15))
    x = (x ^ (x >> jnp.uint64(30))) * jnp.uint64(0xBF58476D1CE4E5B9)
    x = (x ^ (x >> jnp.uint64(27))) * jnp.uint64(0x94D049BB133111EB)
    return x ^ (x >> jnp.uint64(31))


def _rand(eid: jnp.ndarray, salt: int, mod: int) -> jnp.ndarray:
    """Deterministic uniform int64 in [0, mod)."""
    h = _splitmix64(eid.astype(jnp.uint64) * jnp.uint64(2654435761) + jnp.uint64(salt))
    return (h % jnp.uint64(mod)).astype(jnp.int64)


@dataclass(frozen=True)
class NexmarkConfig:
    base_time_us: int = 1_500_000_000_000_000  # event-time origin (us)
    inter_event_us: int = 10                   # logical event spacing
    num_active_people: int = 1000
    in_flight_auctions: int = 100


def _ids_so_far(global_id):
    """Counts of persons/auctions emitted up to global event id (exclusive)."""
    group = global_id // TOTAL_PROPORTION
    off = global_id % TOTAL_PROPORTION
    n_persons = group * PERSON_PROPORTION + jnp.minimum(off, PERSON_PROPORTION)
    n_auctions = group * AUCTION_PROPORTION + jnp.clip(
        off - PERSON_PROPORTION, 0, AUCTION_PROPORTION)
    return n_persons, n_auctions


def _event_time(global_id, cfg: NexmarkConfig):
    return cfg.base_time_us + global_id * cfg.inter_event_us


@partial(jax.jit, static_argnums=(1, 2, 3))
def gen_bid_columns(start_index: jnp.ndarray, n: int, cfg: NexmarkConfig,
                    vocabs: tuple = ()):
    """Bid events k = start_index .. start_index+n-1 (bid-local indices)."""
    V = dict(vocabs)
    k = start_index + jnp.arange(n, dtype=jnp.int64)
    group = k // BID_PROPORTION
    off = k % BID_PROPORTION
    global_id = group * TOTAL_PROPORTION + PERSON_PROPORTION + AUCTION_PROPORTION + off
    n_persons, n_auctions = _ids_so_far(global_id)

    # auction: hot (1 per HOT_AUCTION_RATIO chance of cold) -> recent hot id
    hot = _rand(global_id, 1, HOT_AUCTION_RATIO) > 0
    hot_auction = ((n_auctions - 1) // HOT_AUCTION_RATIO) * HOT_AUCTION_RATIO
    cold_auction = n_auctions - 1 - _rand(global_id, 2, cfg.in_flight_auctions)
    auction = FIRST_AUCTION_ID + jnp.where(hot, hot_auction, jnp.maximum(cold_auction, 0))

    hot_b = _rand(global_id, 3, HOT_BIDDER_RATIO) > 0
    hot_bidder = ((n_persons - 1) // HOT_BIDDER_RATIO) * HOT_BIDDER_RATIO + 1
    cold_bidder = n_persons - 1 - _rand(global_id, 4, cfg.num_active_people)
    bidder = FIRST_PERSON_ID + jnp.where(hot_b, hot_bidder, jnp.maximum(cold_bidder, 0))

    # price: roughly log-uniform in [100, 10^7] (spec's price model shape)
    lg = _rand(global_id, 5, 5)  # decade
    mant = _rand(global_id, 6, 900) + 100
    price = mant * (10 ** lg).astype(jnp.int64)

    channel = _vocab_pick(V["channel"], global_id, 7)
    url = _vocab_pick(V["url"], global_id, 8)
    date_time = _event_time(global_id, cfg)
    extra = _vocab_pick(V["extra"], global_id, 9)
    return (auction, bidder, price, channel, url, date_time, extra)


@partial(jax.jit, static_argnums=(1, 2, 3))
def gen_person_columns(start_index: jnp.ndarray, n: int, cfg: NexmarkConfig,
                       vocabs: tuple = ()):
    V = dict(vocabs)
    k = start_index + jnp.arange(n, dtype=jnp.int64)
    global_id = k * TOTAL_PROPORTION  # persons sit at offset 0 of each group
    pid = FIRST_PERSON_ID + k
    name_ids = jnp.asarray(V["name"], dtype=jnp.int32)
    name = name_ids[pid % len(V["name"])]
    email = _vocab_pick(V["email"], global_id, 11)
    card = _vocab_pick(V["card"], global_id, 12)
    city = _vocab_pick(V["city"], global_id, 13)
    state = _vocab_pick(V["state"], global_id, 14)
    date_time = _event_time(global_id, cfg)
    extra = _vocab_pick(V["extra"], global_id, 15)
    return (pid, name, email, card, city, state, date_time, extra)


@partial(jax.jit, static_argnums=(1, 2, 3))
def gen_auction_columns(start_index: jnp.ndarray, n: int, cfg: NexmarkConfig,
                        vocabs: tuple = ()):
    V = dict(vocabs)
    k = start_index + jnp.arange(n, dtype=jnp.int64)
    group = k // AUCTION_PROPORTION
    off = k % AUCTION_PROPORTION
    global_id = group * TOTAL_PROPORTION + PERSON_PROPORTION + off
    n_persons, _ = _ids_so_far(global_id)
    aid = FIRST_AUCTION_ID + k
    item = _vocab_pick(V["item"], global_id, 21)
    desc = _vocab_pick(V["desc"], global_id, 22)
    initial_bid = _rand(global_id, 23, 1000) * 100 + 100
    reserve = initial_bid + _rand(global_id, 24, 1000) * 100
    date_time = _event_time(global_id, cfg)
    expires = date_time + (_rand(global_id, 25, 100) + 1) * 1_000_000
    hot = _rand(global_id, 26, HOT_SELLER_RATIO) > 0
    hot_seller = ((n_persons - 1) // HOT_SELLER_RATIO) * HOT_SELLER_RATIO
    cold_seller = n_persons - 1 - _rand(global_id, 27, cfg.num_active_people)
    seller = FIRST_PERSON_ID + jnp.where(hot, hot_seller, jnp.maximum(cold_seller, 0))
    category = FIRST_CATEGORY_ID + _rand(global_id, 28, 5)
    return (aid, item, desc, initial_bid, reserve, date_time, expires,
            seller, category, _vocab_pick(V["extra"], global_id, 29))


_TABLES = {
    "bid": (BID_SCHEMA, gen_bid_columns),
    "person": (PERSON_SCHEMA, gen_person_columns),
    "auction": (AUCTION_SCHEMA, gen_auction_columns),
}


class NexmarkGenerator:
    """Split reader for one Nexmark table (reference SplitReader,
    connector/src/source/base.rs). Offset = next event index of this table —
    the exactly-once source state."""

    def __init__(self, table: str, chunk_size: int = 4096,
                 cfg: NexmarkConfig = NexmarkConfig(), start_offset: int = 0):
        self.table = table
        self.schema, self._gen = _TABLES[table]
        self.chunk_size = chunk_size
        self.cfg = cfg
        self.offset = start_offset
        self._vocabs = tuple(sorted(_ensure_vocabs().items()))
        self._vis = jnp.ones(chunk_size, dtype=bool)
        self._ops = jnp.zeros(chunk_size, dtype=jnp.int8)

    def seek(self, offset: int) -> None:
        self.offset = offset

    def next_chunk(self) -> StreamChunk:
        cols = self._gen(jnp.int64(self.offset), self.chunk_size, self.cfg, self._vocabs)
        self.offset += self.chunk_size
        columns = tuple(Column(c) for c in cols)
        return StreamChunk(columns, self._ops, self._vis, self.schema)

    @property
    def watermark_col(self) -> int:
        """Index of date_time in this table's schema."""
        return {"bid": 5, "person": 6, "auction": 5}[self.table]

    def current_watermark(self) -> int:
        """Event-time watermark after the last emitted chunk, computed on the
        HOST from pure offset arithmetic (the generator's event time is
        deterministic in the event id) — no device readback on the hot path.
        Nexmark event time is monotone in the id, so this is exact."""
        if self.offset == 0:
            return self.cfg.base_time_us
        k = self.offset - 1
        if self.table == "bid":
            group, off = divmod(k, BID_PROPORTION)
            gid = group * TOTAL_PROPORTION + PERSON_PROPORTION + AUCTION_PROPORTION + off
        elif self.table == "person":
            gid = k * TOTAL_PROPORTION
        else:
            group, off = divmod(k, AUCTION_PROPORTION)
            gid = group * TOTAL_PROPORTION + PERSON_PROPORTION + off
        return self.cfg.base_time_us + gid * self.cfg.inter_event_us

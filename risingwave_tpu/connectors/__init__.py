from .nexmark import (
    NexmarkGenerator, NexmarkConfig, BID_SCHEMA, PERSON_SCHEMA, AUCTION_SCHEMA,
)
from .datagen import ColumnSpec, DatagenConnector
from .tpch import TpchGenerator, TPCH_SCHEMAS  # noqa: E402,F401
from .arrow_source import ArrowSource  # noqa: E402,F401

"""Broker connectors — the engine side of the external-streaming wire.

Reference: src/connector/src/source/base.rs (`SplitEnumerator` /
`SplitReader`) + src/meta/src/stream/source_manager.rs (split discovery
and assignment) + src/connector/src/sink/kafka.rs, over the local
kafka-alike broker (risingwave_tpu/broker/).

  * `BrokerPartitionConnector` — a SplitReader: one split IS one broker
    partition; `offset` is the dense partition record offset, which is
    exactly the per-split state the source executor commits in barrier
    state (exactly-once resume across crash/recovery, same machinery as
    the generator splits).
  * `BrokerSplitEnumerator` — the meta-side enumerator: polls partition
    membership (throttled) from the barrier-injection path; a topic that
    grew partitions yields an `AddSplitsMutation` so the new splits are
    assigned to source actors AT a barrier — totally ordered with data,
    offsets committed from the same barrier on.
  * `BrokerSink` — the log-store delivery target (`write(seq, epoch,
    rows)` / `committed_seq()`, stream/sink.py contract): each committed
    log entry appends as ONE atomic batch whose metadata carries the
    sequence number. `committed_seq()` recovers from the topic itself
    (last durable batch meta), so delivery dedupes across engine crash
    AND broker restart — a torn batch from a kill mid-append reports the
    previous sequence and re-delivers whole.

Record format: one JSON object per record, column-name keyed with dict-
encoded VARCHARs DECODED to strings (two engines chained through a
topic do not share a string dictionary); `__op` carries non-insert
changelog ops (update pairs are normalized to delete+insert so a batch
split across fetch chunks never strands half a pair)."""

from __future__ import annotations

import json
import time
from typing import Optional

import numpy as np

from ..broker.client import BrokerClient
from ..common.chunk import (OP_DELETE, OP_INSERT, OP_UPDATE_DELETE,
                            OP_UPDATE_INSERT, StreamChunk)
from ..common.types import DataType, GLOBAL_DICT, Schema
from ..utils.faults import FAULTS, FaultInjected


def _parse_records(schema: Schema, records: list, chunk_size: int
                   ) -> StreamChunk:
    """JSON record bytes -> typed StreamChunk (the jsonl parser's rules:
    malformed record -> all-NULL row so offsets stay record-aligned,
    type-mismatched cell -> NULL), plus changelog ops via `__op`."""
    n = len(records)
    objs = []
    ops = np.zeros(n, dtype=np.int8)
    for i, rec in enumerate(records):
        try:
            obj = json.loads(rec)
            if not isinstance(obj, dict):
                obj = None
        except ValueError:
            obj = None
        objs.append(obj)
        if obj is not None:
            op = obj.get("__op", OP_INSERT)
            if op in (OP_DELETE, OP_UPDATE_DELETE):
                ops[i] = OP_DELETE
            elif op == OP_UPDATE_INSERT:
                ops[i] = OP_INSERT
    cols: list[np.ndarray] = []
    valids: list[Optional[np.ndarray]] = []
    for f in schema:
        vals = np.zeros(n, dtype=f.data_type.np_dtype)
        valid = np.zeros(n, dtype=bool)
        for i, obj in enumerate(objs):
            v = None if obj is None else obj.get(f.name)
            if v is None:
                continue
            try:
                if f.data_type is DataType.VARCHAR:
                    vals[i] = GLOBAL_DICT.get_or_insert(str(v))
                elif f.data_type in (DataType.FLOAT32, DataType.FLOAT64):
                    vals[i] = float(v)
                elif f.data_type is DataType.BOOLEAN:
                    vals[i] = bool(v)
                else:
                    vals[i] = int(v)
                valid[i] = True
            except (TypeError, ValueError, OverflowError):
                continue
        cols.append(vals)
        valids.append(valid)
    return StreamChunk.from_numpy(schema, cols, ops=ops,
                                  capacity=max(chunk_size, n),
                                  valids=valids)


def encode_row(schema: Schema, op: int, vals) -> bytes:
    """One changelog row -> one JSON record (the BrokerSink writer and
    test producers share it). Update ops normalize to delete/insert."""
    obj = {}
    for f, v in zip(schema, vals):
        if v is None:
            continue
        if f.data_type is DataType.VARCHAR:
            obj[f.name] = GLOBAL_DICT.decode(int(v))
        elif f.data_type in (DataType.FLOAT32, DataType.FLOAT64):
            obj[f.name] = float(v)
        elif f.data_type is DataType.BOOLEAN:
            obj[f.name] = bool(v)
        else:
            obj[f.name] = int(v)
    if op in (OP_DELETE, OP_UPDATE_DELETE):
        obj["__op"] = OP_DELETE
    return json.dumps(obj).encode()


class BrokerPartitionConnector:
    """Connector protocol (stream/source.py): next_chunk / seek /
    offset / exhausted, over one broker partition."""

    def __init__(self, brokers, topic: str, partition: int,
                 schema: Schema, chunk_size: int = 256):
        self.brokers = brokers
        self.topic = topic
        self.partition = partition
        self.schema = schema
        self.chunk_size = chunk_size
        self.client = BrokerClient(brokers)
        self.offset = 0
        self._hwm = 0                 # cached high watermark
        self._last_rows = 0
        # upstream trace contexts read from fetched batch metas: staged
        # here, drained by the barrier coordinator into the epoch trace
        # as "in" links (utils/trace.py cross-engine stitching)
        self._trace_links: list = []

    @property
    def last_chunk_rows(self) -> int:
        return self._last_rows

    def seek(self, offset: int) -> None:
        self.offset = int(offset)

    @property
    def exhausted(self) -> bool:
        """Caught-up check. Cheap against the cached high watermark
        (every fetch refreshes it); one RPC only when the cache says
        caught-up. A vanished broker reads as exhausted — the source
        then blocks at barrier cadence (no busy-spin, no crash) and
        resumes when the broker is back, mirroring the jsonl
        connector's vanished-file contract."""
        if self.offset < self._hwm:
            return False
        try:
            self._hwm = self.client.high_watermark(
                topic=self.topic, partition=self.partition)
        except (OSError, ConnectionError, RuntimeError):
            return True
        return self.offset >= self._hwm

    def lag_rows(self) -> int:
        """Broker high watermark minus consumed offset (the
        source_lag_rows gauge; cached — no RPC)."""
        return max(0, self._hwm - self.offset)

    def next_chunk(self) -> StreamChunk:
        if FAULTS.active and FAULTS.hit(
                "broker_fetch_fail", topic=self.topic,
                partition=self.partition) is not None:
            raise FaultInjected(
                f"injected broker_fetch_fail {self.topic}/"
                f"p{self.partition} at offset {self.offset}")
        res = self.client.fetch(topic=self.topic,
                                partition=self.partition,
                                offset=self.offset,
                                max_records=self.chunk_size)
        records = res["records"]
        self._hwm = res["high_watermark"]
        self.offset = res["next_offset"]
        self._last_rows = len(records)
        for base, meta in res.get("metas") or ():
            ctx = meta.get("trace") if isinstance(meta, dict) else None
            if ctx and len(self._trace_links) < 256:
                self._trace_links.append({
                    "dir": "in", "topic": self.topic,
                    "partition": self.partition, "offset": int(base),
                    "peer": ctx.get("span"),
                    "peer_engine": ctx.get("engine"),
                    "peer_epoch": ctx.get("epoch")})
        return _parse_records(self.schema, records, self.chunk_size)

    def drain_trace_links(self) -> list:
        """Ingest-span link records staged since the last drain (the
        coordinator attaches them to the closing epoch's trace)."""
        out, self._trace_links = self._trace_links, []
        return out


class BrokerSplitEnumerator:
    """Meta-side split discovery for one broker-source fragment. The
    barrier coordinator polls every registered enumerator at injection
    (throttled per `poll_interval_s`); growth comes back as
    {source actor id: ((split_id, connector), ...)} and rides the
    barrier as an `AddSplitsMutation` — split k goes to actor (k % P),
    the same deterministic rule the initial build uses."""

    def __init__(self, brokers, topic: str, schema: Schema,
                 chunk_size: int, parallelism: int,
                 known_partitions: int, poll_interval_s: float = 1.0):
        self.brokers = brokers
        self.topic = topic
        self.schema = schema
        self.chunk_size = chunk_size
        self.parallelism = max(1, int(parallelism))
        self.known = int(known_partitions)
        self.poll_interval_s = poll_interval_s
        self.client = BrokerClient(brokers)
        self.frag_key = None          # set by the builder (teardown key)
        self._actors: dict[int, int] = {}    # actor_idx -> source id
        self._last_poll = 0.0

    def register_actor(self, actor_idx: int, source_id: int) -> None:
        self._actors[actor_idx] = source_id

    def observe_build(self, n_partitions: int) -> None:
        """A (re)build constructed connectors for every partition it saw
        — never re-announce those."""
        self.known = max(self.known, int(n_partitions))

    def poll(self) -> Optional[dict]:
        now = time.monotonic()
        if self.poll_interval_s > 0 \
                and now - self._last_poll < self.poll_interval_s:
            return None
        self._last_poll = now
        try:
            n = self.client.list_partitions(topic=self.topic)
        except (OSError, ConnectionError, RuntimeError):
            return None               # broker away: retry next barrier
        if n <= self.known:
            return None
        assignments: dict[int, list] = {}
        for k in range(self.known, n):
            sid = self._actors.get(k % self.parallelism)
            if sid is None:
                continue
            conn = BrokerPartitionConnector(
                self.brokers, self.topic, k, self.schema,
                chunk_size=self.chunk_size)
            assignments.setdefault(sid, []).append((k, conn))
        self.known = n
        if not assignments:
            return None
        return {sid: tuple(v) for sid, v in assignments.items()}


class BrokerSink:
    """Log-store delivery target (stream/sink.py SinkTarget contract).
    One committed log entry = one atomic broker batch (partition
    `seq % partitions`, metadata `{"seq", "epoch"}`). Sequence numbers
    are ascending, so the max last-batch meta across partitions is
    always the last COMPLETE delivery — the recovery read for
    `committed_seq()` whichever side restarted."""

    def __init__(self, brokers, topic: str, schema=None,
                 partitions: int = 1):
        self.brokers = brokers
        self.topic = topic
        self.schema = schema
        # cross-engine trace stamping (plan/build.py attaches both):
        # every delivered batch's meta carries (engine_id, epoch, span)
        # so the consuming engine can link its ingest span back here
        self.engine_id = None
        self.tracer = None
        self.client = BrokerClient(brokers)
        self.n_partitions = self.client.create_topic(
            topic=topic, partitions=partitions)
        self._committed = 0
        self.rows_appended = 0
        for p in range(self.n_partitions):
            m = self.client.last_meta(topic=topic, partition=p)
            if m and "seq" in m:
                self._committed = max(self._committed, int(m["seq"]))

    def write(self, seq: int, epoch: int, rows: list) -> None:
        if FAULTS.active and FAULTS.hit(
                "broker_append_fail", topic=self.topic,
                seq=seq) is not None:
            raise FaultInjected(
                f"injected broker_append_fail {self.topic} seq {seq}")
        records = [encode_row(self.schema, op, vals)
                   if self.schema is not None
                   else json.dumps({"__op": op, "vals": list(vals)}).encode()
                   for op, vals in rows]
        meta = {"seq": seq, "epoch": epoch}
        span = None
        if self.engine_id is not None:
            span = f"{self.engine_id}/e{int(epoch)}/s{int(seq)}"
            meta["trace"] = {"engine": str(self.engine_id),
                             "epoch": int(epoch), "span": span}
        partition = seq % self.n_partitions
        base = self.client.append(self.topic, partition, records,
                                  meta=meta)
        self._committed = seq
        self.rows_appended += len(records)
        if span is not None and self.tracer is not None:
            try:
                self.tracer.add_links(int(epoch), [{
                    "dir": "out", "topic": self.topic,
                    "partition": partition,
                    "offset": int(base) if base is not None else None,
                    "span": span, "engine": str(self.engine_id)}])
            except Exception:
                pass

    def committed_seq(self) -> int:
        return self._committed

"""TPC-H datagen connector — deterministic, seekable part/lineitem
streams for the q17 workload (BASELINE staged config 5).

Reference workload: /root/reference/e2e_test/tpch/ and the ci q17 SQL.
The reference feeds TPC-H through Kafka from dbgen files; here the rows
are generated on device from the offset counter (counter-based
splitmix64, same scheme as nexmark.py) so the stream is deterministic,
seekable for exactly-once replay, and needs no external system.

Simplifications vs dbgen (documented, not hidden): a fixed part universe
of NUM_PARTS keys that lineitems draw from uniformly; brand/container
derived from the partkey hash so any prefix of both streams agrees with
a host oracle; prices are integers (the engine's decimal = scaled int).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, schema
from .nexmark import _register_vocab, _splitmix64

NUM_PARTS = 1000
BRANDS = [f"Brand#{i}{j}" for i in range(1, 6) for j in range(1, 6)]
CONTAINERS = [f"{s} {t}" for s in ("SM", "MED", "LG", "JUMBO")
              for t in ("CASE", "BOX", "PACK", "DRUM")]

PART_SCHEMA = schema(
    ("p_partkey", DataType.INT64),
    ("p_brand", DataType.VARCHAR),
    ("p_container", DataType.VARCHAR),
    ("p_retailprice", DataType.INT64),
)

LINEITEM_SCHEMA = schema(
    ("l_orderkey", DataType.INT64),
    ("l_partkey", DataType.INT64),
    ("l_quantity", DataType.INT64),
    ("l_extendedprice", DataType.INT64),
)

TPCH_SCHEMAS = {"part": PART_SCHEMA, "lineitem": LINEITEM_SCHEMA}


def _part_cols(keys: jnp.ndarray, brand_ids, container_ids):
    """Columns for part rows keyed by `keys` (shared by both tables'
    derivations so lineitem oracles can recompute brand/container)."""
    h = _splitmix64(keys.astype(jnp.uint64) ^ jnp.uint64(0xA5A5))
    brand = jnp.take(brand_ids, (h % len(BRANDS)).astype(jnp.int32))
    h2 = _splitmix64(keys.astype(jnp.uint64) ^ jnp.uint64(0x5A5A))
    container = jnp.take(container_ids,
                         (h2 % len(CONTAINERS)).astype(jnp.int32))
    price = 900 + (h % jnp.uint64(200)).astype(jnp.int64)
    return brand.astype(jnp.int64), container.astype(jnp.int64), price


class TpchGenerator:
    """Connector protocol: next_chunk() / seek(offset) / offset."""

    def __init__(self, table: str, chunk_size: int = 4096,
                 start_offset: int = 0):
        assert table in TPCH_SCHEMAS, table
        self.table = table
        self.chunk_size = chunk_size
        self.offset = start_offset
        self.schema = TPCH_SCHEMAS[table]
        self._brand_ids = jnp.asarray(
            _register_vocab("tpch_brand", BRANDS), dtype=jnp.int64)
        self._container_ids = jnp.asarray(
            _register_vocab("tpch_container", CONTAINERS), dtype=jnp.int64)
        self._vis = jnp.ones(chunk_size, dtype=bool)
        self._ops = jnp.zeros(chunk_size, dtype=jnp.int8)
        self._gen = jax.jit(self._gen_impl, static_argnums=(1,))

    def _gen_impl(self, offset, n, brand_ids, container_ids):
        rid = offset + jnp.arange(n, dtype=jnp.int64)
        if self.table == "part":
            keys = rid + 1
            brand, container, price = _part_cols(keys, brand_ids,
                                                 container_ids)
            return keys, brand, container, price
        h = _splitmix64(rid.astype(jnp.uint64) ^ jnp.uint64(0x71F3))
        partkey = 1 + (h % jnp.uint64(NUM_PARTS)).astype(jnp.int64)
        hq = _splitmix64(rid.astype(jnp.uint64) ^ jnp.uint64(0x9D2C))
        quantity = 1 + (hq % jnp.uint64(50)).astype(jnp.int64)
        _, _, price = _part_cols(partkey, brand_ids, container_ids)
        extended = quantity * price
        orderkey = rid // 4 + 1
        return orderkey, partkey, quantity, extended

    def next_chunk(self) -> StreamChunk:
        cols = self._gen(jnp.int64(self.offset), self.chunk_size,
                         self._brand_ids, self._container_ids)
        self.offset += self.chunk_size
        return StreamChunk(tuple(Column(c) for c in cols), self._ops,
                           self._vis, self.schema)

    def seek(self, offset: int) -> None:
        self.offset = offset

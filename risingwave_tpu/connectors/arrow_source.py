"""ArrowSource — a source connector over in-memory Arrow data.

Reference: the reference ingests Arrow through its UDF/iceberg surfaces
(arrow_impl.rs); here any pyarrow Table / RecordBatch list becomes a
seekable stream (the offset is the row index), so external systems that
speak Arrow can feed the engine with one conversion at the boundary.
"""

from __future__ import annotations

import numpy as np
import pyarrow as pa

from ..common.arrow import batch_to_chunk, schema_from_arrow
from ..common.chunk import StreamChunk


class ArrowSource:
    def __init__(self, data, chunk_size: int = 4096):
        if isinstance(data, pa.RecordBatch):
            data = pa.Table.from_batches([data])
        elif isinstance(data, list):
            data = pa.Table.from_batches(data)
        self.table: pa.Table = data.combine_chunks()
        self.chunk_size = chunk_size
        self.schema = schema_from_arrow(self.table.schema)
        self.offset = 0

    def seek(self, offset: int) -> None:
        self.offset = offset

    @property
    def last_chunk_rows(self) -> int:
        return getattr(self, "_last_rows", 0)

    @property
    def exhausted(self) -> bool:
        return self.offset >= self.table.num_rows

    def next_chunk(self) -> StreamChunk:
        n = self.table.num_rows
        lo = min(self.offset, n)
        hi = min(lo + self.chunk_size, n)
        self.offset = hi
        self._last_rows = hi - lo
        if hi > lo:
            batch = (self.table.slice(lo, hi - lo).combine_chunks()
                     .to_batches()[0])
        else:       # exhausted: an empty (all-invisible) chunk
            batch = pa.RecordBatch.from_pylist(
                [], schema=self.table.schema)
        return batch_to_chunk(batch, self.schema,
                              capacity=self.chunk_size)

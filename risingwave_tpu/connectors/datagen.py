"""Datagen connector — schema-driven synthetic source.

Reference: src/connector/src/source/datagen/ — per-column generator specs
(sequence or random with min/max) driving a rate-controlled stream; used
everywhere in tests/demos where Kafka would be.

TPU build: one jitted program per chunk computes every column from the
row-id counter (counter-based splitmix64 like the Nexmark generator, so
the stream is deterministic and seekable for exactly-once replay)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from ..common.chunk import Column, StreamChunk
from ..common.types import DataType, Field, Schema
from .nexmark import _splitmix64


@dataclass(frozen=True)
class ColumnSpec:
    """sequence: start + row_id; random: splitmix64(seed, row_id) in
    [min, max]; timestamp: base + row_id * interval_us."""

    name: str
    kind: str                      # "sequence" | "random" | "timestamp"
    # random spans [min, max] INCLUSIVE (reference datagen treats max as
    # inclusive)
    dtype: DataType = DataType.INT64
    start: int = 0                 # sequence
    min: int = 0                   # random
    max: int = 1 << 31
    base_us: int = 1_500_000_000_000_000   # timestamp
    interval_us: int = 1000


class DatagenConnector:
    """Deterministic, seekable generator over ColumnSpecs (the Connector
    protocol SourceExecutor expects)."""

    def __init__(self, columns: Sequence[ColumnSpec], chunk_size: int = 4096,
                 seed: int = 42, start_offset: int = 0):
        self.columns = tuple(columns)
        self.chunk_size = chunk_size
        self.seed = seed
        self.offset = start_offset
        self.schema = Schema(tuple(Field(c.name, c.dtype)
                                   for c in self.columns))
        self._vis = jnp.ones(chunk_size, dtype=bool)
        self._ops = jnp.zeros(chunk_size, dtype=jnp.int8)
        self._gen = jax.jit(self._gen_impl)
        # watermark support when a timestamp column exists
        self._ts_spec = next(
            (i for i, c in enumerate(self.columns)
             if c.kind == "timestamp"), None)

    def _gen_impl(self, offset):
        ids = offset + jnp.arange(self.chunk_size, dtype=jnp.int64)
        cols = []
        for i, c in enumerate(self.columns):
            if c.kind == "sequence":
                data = (c.start + ids).astype(c.dtype.jnp_dtype)
            elif c.kind == "timestamp":
                data = (c.base_us + ids * c.interval_us).astype(
                    c.dtype.jnp_dtype)
            else:
                h = _splitmix64(ids.astype(jnp.uint64)
                                ^ jnp.uint64(self.seed * 0x9E37 + i))
                span = jnp.uint64(max(1, c.max - c.min + 1))
                data = (c.min + (h % span).astype(jnp.int64)).astype(
                    c.dtype.jnp_dtype)
            cols.append(data)
        return tuple(cols)

    def next_chunk(self) -> StreamChunk:
        cols = self._gen(jnp.int64(self.offset))
        self.offset += self.chunk_size
        return StreamChunk(tuple(Column(c) for c in cols), self._ops,
                           self._vis, self.schema)

    def seek(self, offset: int) -> None:
        self.offset = offset

    @property
    def watermark_col(self) -> int:
        assert self._ts_spec is not None, "no timestamp column"
        return self._ts_spec

    def current_watermark(self) -> int:
        assert self._ts_spec is not None, \
            "datagen watermarks need a timestamp column"
        c = self.columns[self._ts_spec]
        return c.base_us + max(0, self.offset - 1) * c.interval_us

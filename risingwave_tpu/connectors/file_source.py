"""File-tailing JSONL source — the external-connector path.

Reference: src/connector/src/source/kafka/source/reader.rs:40-50 (a
SplitReader pulling an append-only partition from a committed offset)
+ parser/json_parser.rs (JSON bytes -> typed rows). The faithful local
stand-in for a Kafka partition is an append-only JSONL file: a split is
one file, the offset is the LINE number, the reader tails the file and
re-seeks on recovery, and writers append whole lines (a partial last
line — a write caught mid-append — is left for the next poll, the same
way a partial Kafka record never surfaces).

Unlike the deterministic generators, this source has an OPEN string
vocabulary: VARCHAR cells dict-encode through GLOBAL_DICT at parse
time, which is exactly what forces the dictionary to be part of the
checkpoint (common/types.py persist_dict_delta / load_dict_log —
recovery must restore id->string before any MV row can decode).
"""

from __future__ import annotations

import json
import os
from typing import Optional

import numpy as np

from ..common.chunk import StreamChunk
from ..common.types import DataType, GLOBAL_DICT, Schema


def parse_columns(spec: str) -> Schema:
    """'name type, name type, ...' -> Schema (the CREATE SOURCE
    `columns` option; external files carry no schema of their own)."""
    from ..common.types import Field
    fields = []
    for part in spec.split(","):
        nm, _, ty = part.strip().partition(" ")
        if not nm or not ty:
            raise ValueError(
                f"columns entry {part.strip()!r} is not 'name type'")
        try:
            dt = DataType(ty.strip().lower())
        except ValueError:
            raise ValueError(f"unknown column type {ty.strip()!r}")
        fields.append(Field(nm.strip(), dt))
    return Schema(tuple(fields))


class JsonlFileConnector:
    """Connector protocol (stream/source.py): next_chunk / seek / offset.

    `offset` is the number of CONSUMED lines; `exhausted` flips whenever
    the tail is reached and clears when the file grows (the source
    executor re-checks it at every barrier, so appended data is picked
    up at barrier cadence without busy-spinning)."""

    def __init__(self, path: str, schema: Schema, chunk_size: int = 256):
        self.path = path
        self.schema = schema
        self.chunk_size = chunk_size
        self.offset = 0
        self._byte_pos = 0
        self._last_rows = 0

    @property
    def last_chunk_rows(self) -> int:
        return self._last_rows

    @property
    def exhausted(self) -> bool:
        try:
            return os.path.getsize(self.path) <= self._byte_pos
        except OSError:
            return True

    def seek(self, offset: int) -> None:
        """Re-position to line `offset`. A forward seek scans from the
        CURRENT position (split readers advance monotonically — a
        from-zero rescan per block would be quadratic in file size);
        only a backward seek restarts from byte 0 (recovery)."""
        if offset < self.offset:
            self.offset = 0
            self._byte_pos = 0
        if offset <= self.offset:
            return
        with open(self.path, "rb") as f:
            f.seek(self._byte_pos)
            for _ in range(offset - self.offset):
                line = f.readline()
                if not line or not line.endswith(b"\n"):
                    break
                self.offset += 1
                self._byte_pos += len(line)

    def _read_lines(self) -> list[bytes]:
        out = []
        try:
            with open(self.path, "rb") as f:
                f.seek(self._byte_pos)
                while len(out) < self.chunk_size:
                    line = f.readline()
                    if not line or not line.endswith(b"\n"):
                        break   # EOF or partial append: retry next poll
                    out.append(line)
                    self._byte_pos += len(line)
        except OSError:
            pass
        return out

    def next_chunk(self) -> StreamChunk:
        lines = self._read_lines()
        n = len(lines)
        self.offset += n
        self._last_rows = n
        cols: list[np.ndarray] = []
        valids: list[Optional[np.ndarray]] = []
        rows = []
        for ln in lines:
            try:
                obj = json.loads(ln)
                if not isinstance(obj, dict):
                    obj = None
            except ValueError:
                obj = None   # malformed line -> all-NULL row (the
                #              reference's json parser skips bad records;
                #              a NULL row keeps offsets line-aligned)
            rows.append(obj)
        for f in self.schema:
            vals = np.zeros(n, dtype=f.data_type.np_dtype)
            valid = np.zeros(n, dtype=bool)
            for i, obj in enumerate(rows):
                v = None if obj is None else obj.get(f.name)
                if v is None:
                    continue
                try:
                    if f.data_type is DataType.VARCHAR:
                        vals[i] = GLOBAL_DICT.get_or_insert(str(v))
                    elif f.data_type in (DataType.FLOAT32,
                                         DataType.FLOAT64):
                        vals[i] = float(v)
                    elif f.data_type is DataType.BOOLEAN:
                        vals[i] = bool(v)
                    else:
                        vals[i] = int(v)
                    valid[i] = True
                except (TypeError, ValueError, OverflowError):
                    continue   # type-mismatched cell -> NULL
            cols.append(vals)
            valids.append(valid)
        return StreamChunk.from_numpy(
            self.schema, cols, capacity=self.chunk_size, valids=valids)

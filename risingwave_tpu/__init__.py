"""risingwave_tpu — a TPU-native streaming-dataflow framework.

A from-scratch re-design of RisingWave's streaming engine (reference:
/root/reference, Rust) for TPU hardware: SQL-defined incrementally-maintained
materialized views over unbounded streams, with

- changelog chunk processing (Insert/Delete/UpdateDelete/UpdateInsert ops)
  on fixed-capacity columnar device chunks with visibility masks,
- epoch-aligned barrier checkpoints (Chandy-Lamport), exactly-once state
  commit to an LSM state store,
- consistent-hash (vnode) partitioned operator state held in HBM as
  jax-sharded arrays over a device mesh, shuffles as XLA collectives,
- a jax.jit-lowered vectorized expression engine.

Layer map (mirrors SURVEY.md §1 of the reference):
  frontend/   SQL -> bound plan -> stream fragment graph
  meta/       barrier manager, catalog, cluster, recovery
  stream/     executors (source, project, filter, hash_agg, hash_join,
              hop_window, top_n, materialize, dispatch/merge), actors
  expr/       expression IR + vectorized jnp evaluation + aggregates
  state/      StateTable facade, memory & LSM (hummock-lite) state stores
  parallel/   vnode<->mesh mapping, all_to_all exchange
  ops/        device kernels: hashing, open-addressing tables, segments
  common/     chunk/type/row/vnode/epoch data kernel
  connectors/ sources (nexmark, datagen) and sinks
"""

import jax

# The reference's type system is 64-bit first (Int64 ids, Timestamp micros,
# Epoch = ms<<16; src/common/src/types/mod.rs:110). Enable x64 once, at
# import, before any tracing happens.
jax.config.update("jax_enable_x64", True)

# Persistent compilation cache: executor kernels (hash-table while_loops,
# flush compaction) compile in 5-45s through the remote-TPU tunnel; caching
# makes every process after the first start warm.
import os as _os

_cache_dir = _os.environ.get("RWTPU_COMPILE_CACHE",
                             _os.path.expanduser("~/.cache/rwtpu_xla"))
try:
    _os.makedirs(_cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", _cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
except Exception:
    pass  # cache is an optimization, never a requirement

__version__ = "0.1.0"

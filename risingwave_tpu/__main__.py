"""`python -m risingwave_tpu` — the playground (reference: the multicall
binary's `playground` mode, src/cmd_all/src/bin/risingwave.rs:126): an
all-in-one single-process deployment with an interactive SQL shell.

    $ python -m risingwave_tpu [--data DIR] [--tick-ms 1000]

DDL and queries run immediately; materialized views advance continuously
on the barrier interval in the background. With --data, state lives in a
durable Hummock store under DIR and survives restarts. With
--monitor-port, an HTTP observability endpoint serves /metrics (full
Prometheus exposition — point a real Prometheus at it), /healthz,
/debug/traces and /debug/await_tree (also `SET monitor_port = N` at
runtime). Meta commands:
    \\tick [n]    advance n barrier rounds now
    \\mvs         list materialized views
    \\metrics     dump the metrics registry (+ per-MV HBM accounting)
    \\metrics prom   full Prometheus text exposition (# TYPE metadata)
    \\trace       recent per-epoch barrier spans (with per-actor
                 apply/persist/align phase splits at metric_level>=info)
    \\stacks      await-tree dump of every live task
    \\q           quit
"""

from __future__ import annotations

import argparse
import asyncio
import sys


async def repl(args) -> None:
    from risingwave_tpu.frontend import Session, SqlError
    from risingwave_tpu.frontend.binder import BindError
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    from risingwave_tpu.utils.metrics import GLOBAL_METRICS

    store = None
    if args.data:
        store = HummockStateStore(LocalFsObjectStore(args.data))
        print(f"durable state: {args.data} "
              f"(committed epoch {store.committed_epoch()})")
    session = Session(store=store)
    if store is not None:
        await session.recover()
        if session.catalog.mvs:
            print(f"recovered {len(session.catalog.sources)} source(s), "
                  f"{len(session.catalog.mvs)} MV(s) from catalog")

    stop = asyncio.Event()

    async def ticker():
        while not stop.is_set():
            try:
                await asyncio.wait_for(stop.wait(), args.tick_ms / 1000)
            except asyncio.TimeoutError:
                pass
            if stop.is_set():
                return
            try:
                await session.tick(1)
            except Exception as e:  # surfaced failures stop the clock
                print(f"barrier loop error: {e}", file=sys.stderr)
                return

    tick_task = asyncio.create_task(ticker())
    if args.monitor_port:
        mon = await session.start_monitor(args.monitor_port)
        print(f"monitor endpoint on http://127.0.0.1:{mon.port} "
              f"(/metrics /healthz /debug/traces /debug/await_tree)")
    pg = None
    if args.pgwire:
        from .frontend.pgwire import PgServer
        pg = await PgServer(session, port=args.pgwire).start()
        print(f"pgwire listening on {pg.addr[0]}:{pg.addr[1]} "
              f"(psql -h {pg.addr[0]} -p {pg.addr[1]})")
    print("risingwave_tpu playground — SQL statements end with ';', "
          "\\q quits")
    loop = asyncio.get_event_loop()
    buf = ""
    while True:
        try:
            line = await loop.run_in_executor(
                None, lambda: input("rw> " if not buf else "  > "))
        except (EOFError, KeyboardInterrupt):
            break
        cmd = line.strip()
        if not buf and cmd.startswith("\\"):
            parts = cmd.split()
            if parts[0] == "\\q":
                break
            if parts[0] == "\\tick":
                n = int(parts[1]) if len(parts) > 1 else 1
                await session.tick(n)
                print(f"advanced {n} round(s)")
            elif parts[0] == "\\mvs":
                for name, mv in session.catalog.mvs.items():
                    print(f"  {name}: {', '.join(mv.schema.names)}")
            elif parts[0] == "\\metrics":
                if len(parts) > 1 and parts[1] == "prom":
                    print(GLOBAL_METRICS.render_prometheus())
                else:
                    print(GLOBAL_METRICS.render())
                    for ln in session.coord.memory.render():
                        print(ln)
                    for ln in session.coord.serving.render():
                        print(ln)
            elif parts[0] == "\\trace":
                for t in session.coord.tracer.recent():
                    print(t.render())
            elif parts[0] == "\\stacks":
                from risingwave_tpu.utils.trace import dump_task_tree
                print(dump_task_tree())
            else:
                print(f"unknown meta command {parts[0]}")
            continue
        buf += (" " if buf else "") + line
        while ";" in buf:                     # drain ALL complete statements
            stmt, buf = buf.split(";", 1)
            buf = buf.strip()
            if not stmt.strip():
                continue
            try:
                result = await session.execute(stmt)
            except Exception as e:            # a shell survives any error
                print(f"error: {e}")
                continue
            if isinstance(result, list):
                for row in result:
                    print("  " + " | ".join(str(v) for v in row))
                print(f"({len(result)} rows)")
            elif result is not None:
                kind = type(result).__name__.replace("Def", "").upper()
                print(f"CREATE {kind} ok")
    stop.set()
    await tick_task
    if pg is not None:
        await pg.stop()
    await (session.shutdown() if args.data else session.drop_all())
    # the stdin executor thread may still be blocked in input(); a normal
    # interpreter exit would wait for it until the user presses Enter
    import os
    sys.stdout.flush()
    os._exit(0)


def main() -> None:
    p = argparse.ArgumentParser(prog="risingwave_tpu")
    p.add_argument("--data", default=None,
                   help="durable state directory (default: in-memory)")
    p.add_argument("--tick-ms", type=int, default=1000,
                   help="barrier interval (reference barrier_interval_ms)")
    p.add_argument("--pgwire", type=int, default=None, metavar="PORT",
                   help="serve the PostgreSQL wire protocol on PORT "
                        "(reference default: 4566)")
    p.add_argument("--monitor-port", type=int, default=None,
                   metavar="PORT",
                   help="serve the HTTP observability endpoint on PORT "
                        "(/metrics Prometheus exposition, /healthz, "
                        "/debug/traces, /debug/await_tree)")
    asyncio.run(repl(p.parse_args()))


if __name__ == "__main__":
    main()

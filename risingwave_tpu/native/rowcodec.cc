// Native host kernels for the state-persistence hot path.
//
// The reference implements row serde / hashing in Rust (src/common/src/
// row/, util/memcmp_encoding.rs, hash/); the TPU build keeps device
// compute in XLA and gives the HOST runtime the same native treatment:
// batch memcomparable key encoding, value-row encoding, and the crc32
// vnode hash, each vectorized over whole column batches instead of
// per-row Python. Byte formats are bit-identical to state/serde.py and
// common/vnode.py (golden-tested from tests/test_native.py).

#include <cstdint>
#include <cstring>

extern "C" {

// memcomparable for non-null ascending int64 fields:
// field = 0x01 ++ bigendian(v XOR sign-flip). out stride = k * 9 bytes.
void mc_encode_i64(const int64_t* vals, int64_t n, int64_t k,
                   uint8_t* out) {
    for (int64_t r = 0; r < n; ++r) {
        uint8_t* p = out + r * k * 9;
        for (int64_t c = 0; c < k; ++c) {
            uint64_t u = (uint64_t)vals[r * k + c] ^ 0x8000000000000000ull;
            *p++ = 0x01;
            for (int b = 7; b >= 0; --b) *p++ = (uint8_t)(u >> (8 * b));
        }
    }
}

// value encoding for all-int64 rows with no nulls:
// row = null bitmap (nb bytes, zero) ++ k * int64 little-endian
void row_encode_i64(const int64_t* vals, int64_t n, int64_t k,
                    int64_t nb, uint8_t* out) {
    const int64_t stride = nb + 8 * k;
    for (int64_t r = 0; r < n; ++r) {
        uint8_t* p = out + r * stride;
        std::memset(p, 0, (size_t)nb);
        std::memcpy(p + nb, vals + r * k, (size_t)(8 * k));
    }
}

// crc32 (poly 0xEDB88320) over the LE bytes of k int64 columns per row,
// column-major in argument order — bit-identical to vnode.crc32_numpy
void crc32_i64_cols(const int64_t* vals /* n*k row-major */, int64_t n,
                    int64_t k, uint32_t* out) {
    static uint32_t table[256];
    static bool init = false;
    if (!init) {
        for (uint32_t i = 0; i < 256; ++i) {
            uint32_t c = i;
            for (int j = 0; j < 8; ++j)
                c = (c & 1) ? (c >> 1) ^ 0xEDB88320u : c >> 1;
            table[i] = c;
        }
        init = true;
    }
    for (int64_t r = 0; r < n; ++r) {
        uint32_t crc = 0xFFFFFFFFu;
        for (int64_t c = 0; c < k; ++c) {
            uint64_t u = (uint64_t)vals[r * k + c];
            for (int b = 0; b < 8; ++b) {
                uint32_t byte = (uint32_t)((u >> (8 * b)) & 0xFF);
                crc = (crc >> 8) ^ table[(crc ^ byte) & 0xFF];
            }
        }
        out[r] = crc ^ 0xFFFFFFFFu;
    }
}

}  // extern "C"

"""Native host runtime kernels (C++), compiled on first use.

The reference's host runtime is native Rust end to end; here the pieces
with real per-row Python overhead — batch key/value serde and vnode
hashing on the persistence path — are C++ behind ctypes, with a pure-
Python fallback when no toolchain is available. `lib()` returns None in
that case and callers fall back transparently.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import tempfile
from functools import lru_cache
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "rowcodec.cc")


@lru_cache(maxsize=1)
def lib() -> Optional[ctypes.CDLL]:
    so = os.path.join(os.path.dirname(__file__), "_rowcodec.so")

    def build() -> None:
        with tempfile.TemporaryDirectory() as td:
            tmp = os.path.join(td, "rowcodec.so")
            subprocess.run(
                ["g++", "-O3", "-shared", "-fPIC", "-o", tmp, _SRC],
                check=True, capture_output=True)
            os.replace(tmp, so)

    try:
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(_SRC)):
            build()
        try:
            l = ctypes.CDLL(so)
        except OSError:
            # stale or foreign-arch artifact: rebuild for THIS machine
            build()
            l = ctypes.CDLL(so)
        l.mc_encode_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
        l.row_encode_i64.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p]
        l.crc32_i64_cols.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p]
        return l
    except Exception:
        return None


def mc_encode_i64_batch(vals: np.ndarray) -> Optional[np.ndarray]:
    """vals [n, k] int64 -> [n, 9k] uint8 memcomparable keys (asc, no
    nulls); None if the native lib is unavailable."""
    l = lib()
    if l is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    n, k = vals.shape
    out = np.empty((n, 9 * k), dtype=np.uint8)
    l.mc_encode_i64(vals.ctypes.data, n, k, out.ctypes.data)
    return out


def row_encode_i64_batch(vals: np.ndarray, nb: int) -> Optional[np.ndarray]:
    """vals [n, k] int64 -> [n, nb + 8k] uint8 value rows (no nulls)."""
    l = lib()
    if l is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    n, k = vals.shape
    out = np.empty((n, nb + 8 * k), dtype=np.uint8)
    l.row_encode_i64(vals.ctypes.data, n, k, nb, out.ctypes.data)
    return out


def crc32_i64_batch(vals: np.ndarray) -> Optional[np.ndarray]:
    """vals [n, k] int64 -> uint32 [n] crc32 (vnode hash)."""
    l = lib()
    if l is None:
        return None
    vals = np.ascontiguousarray(vals, dtype=np.int64)
    n, k = vals.shape
    out = np.empty(n, dtype=np.uint32)
    l.crc32_i64_cols(vals.ctypes.data, n, k, out.ctypes.data)
    return out

"""Persistent XLA compilation cache — repeat runs start hot.

The engine's jitted programs are keyed on SHAPE (power-of-two chunk
buckets, fixed state capacities — the whole dispatch discipline exists
so steady state never recompiles), which makes them ideal persistent-
cache citizens: a bench/CI/profile re-run of the same query shape skips
the 2-6s (CPU) to 60-120s (tunneled-TPU) compile entirely.

`enable_persistent_cache()` is idempotent and safe before OR after jax
import: it prefers `jax.config.update` (wins over env-var readers and
sitecustomize overrides) and falls back to the environment for
subprocesses that import jax later. Every entry point that re-runs
canned shapes calls it: bench.py, the scripts/*_profile.py CI gates,
and the cluster worker (a compute node restarted by recovery recompiles
nothing it compiled in a previous life).
"""

from __future__ import annotations

import os

DEFAULT_MIN_COMPILE_SECS = 2.0


def default_cache_dir() -> str:
    """Repo-local cache dir (shared by bench, CI gates, and workers on
    one machine; the content hash includes backend + compiler version,
    so mixed cpu/tpu use is safe)."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")


def enable_persistent_cache(cache_dir: str | None = None,
                            min_compile_secs: float =
                            DEFAULT_MIN_COMPILE_SECS) -> str:
    """Point jax's persistent compilation cache at `cache_dir` (default:
    <repo>/.jax_cache). Returns the directory in effect. Environment
    variables are ALSO set so child processes (bench query subprocesses,
    cluster workers) inherit the cache without their own call."""
    d = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR") \
        or default_cache_dir()
    os.makedirs(d, exist_ok=True)
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", d)
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          str(min_compile_secs))
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(os.environ[
                    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
        except (AttributeError, KeyError):
            pass                    # older jax: env var alone suffices
    except Exception:  # noqa: BLE001 — env vars still cover the child
        pass
    return d

"""Persistent XLA compilation cache — repeat runs start hot.

The engine's jitted programs are keyed on SHAPE (power-of-two chunk
buckets, fixed state capacities — the whole dispatch discipline exists
so steady state never recompiles), which makes them ideal persistent-
cache citizens: a bench/CI/profile re-run of the same query shape skips
the 2-6s (CPU) to 60-120s (tunneled-TPU) compile entirely.

The cache directory is NAMESPACED by backend + host machine fingerprint:
XLA:CPU AOT artifacts embed the COMPILE machine's CPU feature set, and
jax's cache key does not include the host's — loading an artifact
compiled on a different machine spams `cpu_aot_loader` "machine type
doesn't match" warnings and risks SIGILL (MULTICHIP_r05's tail is full
of exactly that: a cache directory shared between the tunnel host and
the bench host). `<base>/<backend>-<fingerprint>/` keeps each
(backend, machine) pair's artifacts to itself while still sharing one
base directory across bench, CI gates and workers on the same host.

`enable_persistent_cache()` is idempotent and safe before OR after jax
import: it prefers `jax.config.update` (wins over env-var readers and
sitecustomize overrides) and falls back to the environment for
subprocesses that import jax later. The environment variable is set to
the NAMESPACED directory, so children on the same machine inherit it
without re-deriving (re-application detects an already-namespaced path
and leaves it alone). Every entry point that re-runs canned shapes
calls it: bench.py, the scripts/*_profile.py CI gates, and the cluster
worker (a compute node restarted by recovery recompiles nothing it
compiled in a previous life).
"""

from __future__ import annotations

import hashlib
import os
import platform

DEFAULT_MIN_COMPILE_SECS = 2.0


def default_cache_dir() -> str:
    """Repo-local cache BASE dir (namespaced per backend + machine
    below; see module docstring)."""
    return os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")


def machine_fingerprint() -> str:
    """Stable per-host fingerprint of the CPU feature set — the exact
    axis the XLA:CPU AOT loader validates (`cpu_aot_loader.cc` compares
    compile-machine features against the executing host's)."""
    bits = [platform.machine(), platform.system()]
    try:
        # x86 exposes `flags`, aarch64 `Features` — either line is the
        # feature set AOT artifacts are specialized to
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith(("flags", "Features")):
                    bits.append(line.split(":", 1)[1].strip())
                    break
    except OSError:
        bits.append(platform.processor() or "")
    return hashlib.sha256(" ".join(bits).encode()).hexdigest()[:12]


def cache_namespace() -> str:
    """`<backend>-<machine fingerprint>` leaf directory name."""
    backend = (os.environ.get("JAX_PLATFORMS") or "default"
               ).split(",")[0].strip() or "default"
    return f"{backend}-{machine_fingerprint()}"


def enable_persistent_cache(cache_dir: str | None = None,
                            min_compile_secs: float =
                            DEFAULT_MIN_COMPILE_SECS) -> str:
    """Point jax's persistent compilation cache at the namespaced
    directory under `cache_dir` (default: <repo>/.jax_cache, or an
    externally-provided JAX_COMPILATION_CACHE_DIR treated as the base).
    Returns the directory in effect. The environment variable is set to
    the NAMESPACED directory so child processes (bench query
    subprocesses, cluster workers) inherit it as-is."""
    base = cache_dir or os.environ.get("JAX_COMPILATION_CACHE_DIR") \
        or default_cache_dir()
    ns = cache_namespace()
    # idempotent under re-application (the env round-trip hands children
    # the already-namespaced path)
    d = base if os.path.basename(base) == ns else os.path.join(base, ns)
    os.makedirs(d, exist_ok=True)
    os.environ["JAX_COMPILATION_CACHE_DIR"] = d
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS",
                          str(min_compile_secs))
    try:
        import jax
        jax.config.update("jax_compilation_cache_dir", d)
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs",
                float(os.environ[
                    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]))
        except (AttributeError, KeyError):
            pass                    # older jax: env var alone suffices
    except Exception:  # noqa: BLE001 — env vars still cover the child
        pass
    return d

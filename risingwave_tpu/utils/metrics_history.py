"""Barrier-paced metrics history — the time-series substrate behind
`rw_metrics` and the autoscaling signals ROADMAP item 1 needs.

The live `MetricsRegistry` is a point-in-time surface: a scrape sees
NOW and nothing else. Control loops (and post-mortems) need *history* —
`stream_exchange_blocked_put_seconds` over the last minute, per-worker
HBM as a series, `source_lag_rows` trend — so the coordinator samples a
configurable allowlist of series once per barrier interval into bounded
per-series rings. Two tiers per series:

  * fine ring: the newest `retention` samples at barrier cadence;
  * coarse ring: every `downsample`-th sample evicted from the fine
    ring, so a series keeps `retention` recent points at full
    resolution plus `retention` older points at 1/downsample
    resolution before history falls off entirely.

Optionally the sampler also appends one crc-framed record per pulse to
a durable log next to the event log (same torn-tail framing via
`meta/event_log.py`, subdir "metrics"): a restart replays the tail so
`rw_metrics` spans the crash. Sampling never raises into the barrier
path — a broken history store must not stall the pipeline.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Optional

from .metrics import GLOBAL_METRICS

# series the autoscaler / stall autopsies care about out of the box;
# `metrics_history_series` (frontend/session.py) overrides the list.
DEFAULT_SERIES = (
    "meta_barrier_latency_seconds",
    "checkpoint_inflight_epochs",
    "stream_exchange_queue_depth",
    "stream_exchange_blocked_put_seconds_total",
    "stream_actor_busy_seconds_total",
    "stream_actor_row_count",
    "source_lag_rows",
    "source_split_offset",
    "hbm_state_bytes",
    "hbm_budget_bytes",
    "hbm_spilled_rows",
    "serving_cache_rows",
    "barrier_stalls_total",
)

# stall-relevant subset dumped by bench.py deadline-abort autopsies
STALL_SERIES = (
    "meta_barrier_latency_seconds",
    "checkpoint_inflight_epochs",
    "stream_exchange_queue_depth",
    "stream_exchange_blocked_put_seconds_total",
    "source_lag_rows",
    "hbm_state_bytes",
)


_UNSET = object()


class _Series:
    __slots__ = ("fine", "coarse", "evicted")

    def __init__(self, retention: int):
        self.fine: deque = deque(maxlen=retention)
        self.coarse: deque = deque(maxlen=retention)
        self.evicted = 0

    def append(self, sample, downsample: int) -> None:
        if len(self.fine) == self.fine.maxlen:
            old = self.fine[0]
            if self.evicted % max(1, downsample) == 0:
                self.coarse.append(old)
            self.evicted += 1
        self.fine.append(sample)

    def samples(self) -> list:
        return list(self.coarse) + list(self.fine)


class MetricsHistory:
    """Bounded per-series sample rings fed by `on_barrier(epoch)`.

    Samples are `(ts, epoch, value)` tuples keyed by
    `(name, sorted-label-items)`. Histogram families expand into
    `<name>_p50` / `<name>_p99` / `<name>_count` scalar series so the
    ring only ever holds numbers.
    """

    def __init__(self, registry=None, interval: int = 1,
                 retention: int = 512, downsample: int = 8,
                 series=None, root=None):
        self.registry = registry if registry is not None else GLOBAL_METRICS
        self._lock = threading.Lock()
        self._series: dict = {}
        self._log = None
        self.interval = 1
        self.retention = 512
        self.downsample = 8
        self.allow: tuple = tuple(DEFAULT_SERIES)
        self._pulses = 0
        self.configure(interval=interval, retention=retention,
                       downsample=downsample, series=series, root=root)

    # -------------------------------------------------------- configure
    def configure(self, interval=None, retention=None, downsample=None,
                  series=None, root=_UNSET) -> None:
        """Re-apply knobs; a retention change re-rings existing series
        (keeping the newest samples), a `root` change re-opens (or
        drops) the durable log and replays its tail."""
        with self._lock:
            if interval is not None:
                self.interval = max(0, int(interval))
            if downsample is not None:
                self.downsample = max(1, int(downsample))
            if series is not None:
                names = [s.strip() for s in series.split(",")] \
                    if isinstance(series, str) else list(series)
                names = [s for s in names if s]
                self.allow = tuple(names) if names else tuple(DEFAULT_SERIES)
            if retention is not None and int(retention) != self.retention:
                self.retention = max(2, int(retention))
                for key, ser in list(self._series.items()):
                    fresh = _Series(self.retention)
                    for s in ser.samples()[-self.retention:]:
                        fresh.fine.append(s)
                    fresh.evicted = ser.evicted
                    self._series[key] = fresh
        if root is not _UNSET:
            self._attach_log(root)

    def _attach_log(self, root) -> None:
        from ..meta.event_log import EventLog
        with self._lock:
            if self._log is not None:
                self._log.close()
                self._log = None
            if not root:
                return
            self._log = EventLog(root, keep=2048, subdir="metrics")
            # replay the durable tail so history spans the restart
            for rec in self._log.records(kind="sample"):
                for name, labels, value in rec.get("series", ()):
                    key = (name, tuple(sorted(
                        (str(k), str(v)) for k, v in labels.items())))
                    ser = self._series.get(key)
                    if ser is None:
                        ser = self._series[key] = _Series(self.retention)
                    ser.append((rec.get("ts", 0.0), rec.get("epoch", 0),
                                float(value)), self.downsample)

    # ----------------------------------------------------------- sample
    def on_barrier(self, epoch: int) -> None:
        """One pulse per completed barrier (coordinator's between-epochs
        window). Never raises."""
        try:
            if self.interval <= 0:
                return
            self._pulses += 1
            if (self._pulses - 1) % self.interval != 0:
                return
            self._sample(int(epoch))
        except Exception:
            pass

    def _sample(self, epoch: int) -> None:
        snap = self.registry.snapshot()
        ts = time.time()
        batch = []
        with self._lock:
            for name in self.allow:
                for row in snap.get(name, ()):
                    labels = row.get("labels", {})
                    if "value" in row:
                        pairs = [(name, row["value"])]
                    else:           # histogram family -> scalar series
                        pairs = [(name + "_p50", row.get("p50", 0.0)),
                                 (name + "_p99", row.get("p99", 0.0)),
                                 (name + "_count", row.get("count", 0))]
                    for sname, value in pairs:
                        try:
                            value = float(value)
                        except (TypeError, ValueError):
                            continue
                        key = (sname, tuple(sorted(
                            (str(k), str(v)) for k, v in labels.items())))
                        ser = self._series.get(key)
                        if ser is None:
                            ser = self._series[key] = _Series(self.retention)
                        ser.append((ts, epoch, value), self.downsample)
                        batch.append((sname, labels, value))
            log = self._log
        if log is not None and batch:
            log.emit("sample", epoch=epoch,
                     series=[[n, dict(l), v] for n, l, v in batch])

    # ------------------------------------------------------------ reads
    def series_names(self) -> list:
        with self._lock:
            return sorted({name for name, _ in self._series})

    def samples(self, name: str, **labels) -> list:
        """All retained `(ts, epoch, value)` for one series (coarse tier
        first, then fine), oldest first. Labels must match exactly."""
        key = (name, tuple(sorted(
            (str(k), str(v)) for k, v in labels.items())))
        with self._lock:
            ser = self._series.get(key)
            return ser.samples() if ser is not None else []

    def rows(self) -> list:
        """Flat `{name, labels, ts, epoch, value}` dicts — the relation
        `rw_metrics` scans (frontend/system_tables.py)."""
        with self._lock:
            items = [(name, dict(lbls), ser.samples())
                     for (name, lbls), ser in self._series.items()]
        out = []
        for name, labels, samples in items:
            for ts, epoch, value in samples:
                out.append({"name": name, "labels": labels, "ts": ts,
                            "epoch": epoch, "value": value})
        return out

    def dump_tail(self, names=STALL_SERIES, k: int = 8) -> str:
        """Human-readable last-K-samples digest of the stall-relevant
        series — bench.py deadline-abort autopsies print this."""
        lines = []
        with self._lock:
            items = sorted(self._series.items())
        for (name, lbls), ser in items:
            if names is not None and not any(
                    name == n or name.startswith(n) for n in names):
                continue
            tail = ser.samples()[-int(k):]
            if not tail:
                continue
            lab = ",".join(f"{k_}={v}" for k_, v in lbls)
            vals = " ".join(f"e{int(e)}:{v:.6g}" for _, e, v in tail)
            lines.append(f"  {name}{{{lab}}} {vals}")
        return "\n".join(lines)

    def __len__(self) -> int:
        with self._lock:
            return len(self._series)

    def close(self) -> None:
        with self._lock:
            if self._log is not None:
                self._log.close()
                self._log = None

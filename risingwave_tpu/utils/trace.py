"""Epoch spans + async stack dumps — the tracing/await-tree analogue.

Reference: (a) barriers carry a TracingContext so each epoch is a
distributed trace spanning meta -> CN actors (common/src/util/tracing.rs,
executor/mod.rs:267, actor.rs:195-240); (b) every actor future is
await-tree-instrumented and dumpable via the MonitorService for
stuck-barrier debugging (stream_manager.rs:66).

Single-process TPU analogue:
  * EpochTrace — per-epoch spans recorded by the barrier coordinator:
    inject time, per-actor collect times, sync duration. A slow epoch's
    trace shows WHICH actor held the barrier.
  * dump_task_tree() — the await-tree: every asyncio task's current
    await stack, so a stuck barrier shows exactly which executor
    coroutine is parked where (channel recv, credit wait, device fence).

Cluster (distributed) traces: each ComputeNode's local coordinator
records its OWN EpochTrace (inject_remote starts it with the epoch
re-based to the worker's clock), and the closed span bundle ships to
meta piggybacked on the sealed-report push (cluster/compute_node.py ->
cluster/meta_service.py -> `EpochTracer.ingest_worker`). Meta stitches
them into ONE per-epoch timeline: worker offsets are RELATIVE TO THE
INJECT PUSH (offset 0 on worker wN = the moment wN received meta's
inject), so per-worker sub-blocks line up under meta's span without
any cross-host clock agreement. `traces_to_json` / `traces_to_chrome`
export the same stitched data machine-readably (the chrome form loads
in Perfetto: one pid per worker, one tid per actor).
"""

from __future__ import annotations

import asyncio
import time
import traceback
from collections import deque
from dataclasses import dataclass, field


@dataclass
class EpochTrace:
    epoch: int
    inject_ns: int
    collects: list = field(default_factory=list)   # (actor_id, ns_after)
    # actor_id -> {"apply_ns", "persist_ns", "align_ns"} — the interval's
    # phase split reported by the actor at its collect (stream/actor.py):
    # apply = chunk compute+dispatch, persist = barrier-time flush/commit
    # work in the chain, align = input-channel + fence waiting. A slow
    # epoch's trace shows WHO held the barrier and DOING WHAT.
    phases: dict = field(default_factory=dict)
    sync_ns: int = 0        # inline store sync duration (pipelining off)
    # checkpoint-pipeline phases (annotated AFTER the span closes — the
    # uploader commits in the background, off the barrier critical path)
    seal_ns: int = 0
    upload_ns: int = 0
    commit_ns: int = 0
    total_ns: int = 0
    # cluster stitching (meta side only): worker_id -> that worker's
    # span dict (an EpochTrace.to_dict() shipped on the sealed push).
    # Worker offsets are relative to the worker's inject RECEIPT, which
    # stitching anchors at meta's inject push — no cross-host clocks.
    worker_spans: dict = field(default_factory=dict)
    # cross-engine broker links: `dir="out"` = a BrokerSink delivery
    # this epoch (carries OUR span id, stamped into the batch meta);
    # `dir="in"` = a BrokerPartitionConnector ingest (carries the
    # UPSTREAM engine's span id read back from that meta). The pair
    # meets again in `stitch_chrome_traces` via matching span ids.
    links: list = field(default_factory=list)

    def to_dict(self) -> dict:
        """Wire form of the span (sealed-push piggyback + format=json):
        every time is an OFFSET from inject_ns, so the dict is
        meaningful on any host."""
        return {
            "epoch": self.epoch,
            "collects": [[a, int(dt)] for a, dt in self.collects],
            "phases": {str(a): dict(ph)
                       for a, ph in self.phases.items()},
            "sync_ns": int(self.sync_ns),
            "seal_ns": int(self.seal_ns),
            "upload_ns": int(self.upload_ns),
            "commit_ns": int(self.commit_ns),
            "total_ns": int(self.total_ns),
            "links": [dict(ln) for ln in self.links],
        }

    @classmethod
    def from_dict(cls, d: dict) -> "EpochTrace":
        t = cls(int(d["epoch"]), 0)
        t.collects = [(int(a), int(dt))
                      for a, dt in d.get("collects", ())]
        t.phases = {int(a): dict(ph)
                    for a, ph in d.get("phases", {}).items()}
        t.sync_ns = int(d.get("sync_ns", 0))
        t.seal_ns = int(d.get("seal_ns", 0))
        t.upload_ns = int(d.get("upload_ns", 0))
        t.commit_ns = int(d.get("commit_ns", 0))
        t.total_ns = int(d.get("total_ns", 0))
        t.links = [dict(ln) for ln in d.get("links", ())]
        return t

    @staticmethod
    def _actor_line(actor_id, dt, ph, prefix="") -> str:
        line = (f"  {prefix}actor {actor_id} collected at "
                f"+{dt / 1e6:.1f}ms")
        if ph:
            line += (f" (apply {ph.get('apply_ns', 0) / 1e6:.1f}ms, "
                     f"persist {ph.get('persist_ns', 0) / 1e6:.1f}ms, "
                     f"align {ph.get('align_ns', 0) / 1e6:.1f}ms)")
        return line

    def render(self) -> str:
        head = (f"epoch {self.epoch}: total {self.total_ns / 1e6:.1f}ms, "
                f"sync {self.sync_ns / 1e6:.1f}ms")
        if self.seal_ns or self.upload_ns or self.commit_ns:
            head += (f" [bg seal {self.seal_ns / 1e6:.1f}ms, "
                     f"upload {self.upload_ns / 1e6:.1f}ms, "
                     f"commit {self.commit_ns / 1e6:.1f}ms]")
        lines = [head]
        for actor_id, dt in sorted(self.collects, key=lambda x: x[1]):
            lines.append(self._actor_line(
                actor_id, dt, self.phases.get(actor_id)))
        # stitched per-worker sub-blocks: one timeline, offsets
        # anchored at each worker's inject receipt (= meta's push)
        for wid in sorted(self.worker_spans):
            w = self.worker_spans[wid]
            lines.append(
                f"  -- w{wid} (offsets from inject receipt): "
                f"total {w.get('total_ns', 0) / 1e6:.1f}ms"
                + (f", seal {w['seal_ns'] / 1e6:.1f}ms"
                   f" upload {w['upload_ns'] / 1e6:.1f}ms"
                   f" commit {w['commit_ns'] / 1e6:.1f}ms"
                   if w.get("seal_ns") or w.get("upload_ns")
                   or w.get("commit_ns") else ""))
            phases = w.get("phases", {})
            for actor_id, dt in sorted(w.get("collects", ()),
                                       key=lambda x: x[1]):
                lines.append(self._actor_line(
                    actor_id, dt, phases.get(str(actor_id)),
                    prefix=f"w{wid}/"))
        return "\n".join(lines)


class EpochTracer:
    """Ring of recent epoch traces (the Grafana trace panel stand-in)."""

    def __init__(self, keep: int = 64):
        self._ring: deque[EpochTrace] = deque(maxlen=keep)
        self._open: dict[int, EpochTrace] = {}
        # recovery spans (frontend/session.py notes one per auto-
        # recovery): rendered by /debug/traces next to the epoch spans
        # so a post-mortem shows WHEN recovery ran, at what scope, for
        # how long, and which actors were rebuilt
        self.recoveries: deque[dict] = deque(maxlen=keep)

    def note_recovery(self, scope: str, cause: str, duration_ns: int,
                      actors=()) -> None:
        self.recoveries.append({
            "scope": scope, "cause": cause,
            "duration_ns": int(duration_ns),
            "actors": list(actors),
            "at_ns": time.monotonic_ns()})

    def render_recoveries(self) -> list[str]:
        return [
            (f"recovery scope={r['scope']} cause={r['cause']} "
             f"{r['duration_ns'] / 1e6:.1f}ms "
             f"rebuilt_actors={r['actors']}")
            for r in self.recoveries]

    def begin(self, epoch: int) -> None:
        self._open[epoch] = EpochTrace(epoch, time.monotonic_ns())

    def collect(self, epoch: int, actor_id: int) -> None:
        t = self._open.get(epoch)
        if t is not None:
            t.collects.append(
                (actor_id, time.monotonic_ns() - t.inject_ns))

    def collect_phases(self, epoch: int, actor_id: int,
                       phases: dict) -> None:
        """Attach an actor's interval phase split (apply / persist /
        align, in ns) to the open epoch span (reported by the actor just
        before it collects the barrier)."""
        t = self._open.get(epoch)
        if t is not None:
            t.phases[actor_id] = phases

    def end(self, epoch: int, sync_ns: int = 0) -> None:
        t = self._open.pop(epoch, None)
        if t is not None:
            t.total_ns = time.monotonic_ns() - t.inject_ns
            t.sync_ns = sync_ns
            self._ring.append(t)

    def annotate(self, epoch: int, *, seal_ns: int = 0, upload_ns: int = 0,
                 commit_ns: int = 0) -> None:
        """Attach checkpoint-pipeline phase durations to an epoch whose
        span already closed — the background uploader reports these after
        the barrier completed (which is the whole point of the pipeline)."""
        t = self._open.get(epoch)
        if t is None:
            for cand in reversed(self._ring):
                if cand.epoch == epoch:
                    t = cand
                    break
        if t is not None:
            t.seal_ns, t.upload_ns, t.commit_ns = seal_ns, upload_ns, commit_ns

    def add_links(self, epoch: int, links) -> None:
        """Attach cross-engine broker link records to an epoch span —
        open first, then the ring (sink deliveries on the exactly-once
        path land after the epoch's span closed, like annotate)."""
        t = self._open.get(epoch)
        if t is None:
            for cand in reversed(self._ring):
                if cand.epoch == epoch:
                    t = cand
                    break
        if t is not None:
            t.links.extend(dict(ln) for ln in links)

    def ingest_worker(self, worker_id: int, spans) -> None:
        """Meta-side stitch point: attach a worker's shipped span
        bundle (list of EpochTrace.to_dict()) to the matching meta
        epoch spans — open first, then the ring (the sealed report that
        carries a bundle usually lands AFTER the epoch's span closed,
        exactly like the background uploader's annotate)."""
        for d in spans or ():
            try:
                canon = EpochTrace.from_dict(d).to_dict()
            except (KeyError, TypeError, ValueError):
                continue            # a malformed bundle never wedges meta
            epoch = canon["epoch"]
            t = self._open.get(epoch)
            if t is None:
                for cand in reversed(self._ring):
                    if cand.epoch == epoch:
                        t = cand
                        break
            if t is not None:
                t.worker_spans[int(worker_id)] = canon

    def unshipped(self, shipped: set) -> list[EpochTrace]:
        """Worker-side: closed spans not yet piggybacked on a sealed
        report (the caller records what it shipped)."""
        return [t for t in self._ring if t.epoch not in shipped]

    def recent(self, n: int = 8) -> list[EpochTrace]:
        return list(self._ring)[-n:]

    def open_traces(self) -> list[EpochTrace]:
        """In-flight (uncollected) epochs — THE data for a stuck
        barrier: which actors already collected, and when."""
        out = []
        now = time.monotonic_ns()
        for t in self._open.values():
            t.total_ns = now - t.inject_ns
            out.append(t)
        return sorted(out, key=lambda t: t.epoch)

    def slowest(self, n: int = 3) -> list[EpochTrace]:
        return sorted(self._ring, key=lambda t: -t.total_ns)[:n]


def dump_task_tree(limit_frames: int = 6) -> str:
    """Await stacks of every live asyncio task (await-tree analogue:
    risectl's stack dump for stuck-barrier debugging). Safe to call from
    inside the loop; excludes the calling task's own dump frames."""
    out = []
    try:
        current = asyncio.current_task()
        tasks = asyncio.all_tasks()
    except RuntimeError:
        return "(no running event loop)"
    for task in sorted(tasks,
                       key=lambda t: t.get_name()):
        if task is current:
            continue
        out.append(f"task {task.get_name()}"
                   f"{' <cancelled>' if task.cancelled() else ''}:")
        frames = task.get_stack(limit=limit_frames)
        if not frames:
            out.append("  (no frames: done or not started)")
            continue
        for f in frames:
            code = f.f_code
            out.append(f"  {code.co_filename.rsplit('/', 1)[-1]}"
                       f":{f.f_lineno} {code.co_name}")
    return "\n".join(out)


class RecoveryRing:
    """Recovery post-mortem spans, owned by the SESSION (not the
    coordinator): a full recovery swaps the coordinator — and with it
    the EpochTracer — so a ring living there died with the very
    recovery it was describing. The session survives the swap; the
    ring survives with it. EpochTracer keeps a back-compat mirror."""

    def __init__(self, keep: int = 64):
        self.recoveries: deque[dict] = deque(maxlen=keep)

    def note_recovery(self, scope: str, cause: str, duration_ns: int,
                      actors=()) -> None:
        self.recoveries.append({
            "scope": scope, "cause": cause,
            "duration_ns": int(duration_ns),
            "actors": list(actors),
            "at_ns": time.monotonic_ns()})

    def render_recoveries(self) -> list[str]:
        return [
            (f"recovery scope={r['scope']} cause={r['cause']} "
             f"{r['duration_ns'] / 1e6:.1f}ms "
             f"rebuilt_actors={r['actors']}")
            for r in self.recoveries]


def traces_to_json(traces, recoveries=()) -> dict:
    """format=json: the stitched spans + recovery ring, verbatim."""
    return {
        "traces": [
            {**t.to_dict(),
             "worker_spans": {str(w): dict(s)
                              for w, s in t.worker_spans.items()}}
            for t in traces],
        "recoveries": [dict(r) for r in recoveries],
    }


# tid of the per-engine "broker i/o" track holding cross-engine link
# slices (far above any real actor id)
BROKER_TID = 9_999_999


def _flow_id(span: str) -> int:
    """Stable chrome flow-event id for a span id string: the SAME id on
    the producer's "s" and the consumer's "f" is what ties a sink
    delivery to the downstream ingest across two engines' exports."""
    import zlib
    return zlib.crc32(str(span).encode()) & 0x7FFFFFFF


def traces_to_chrome(traces) -> list:
    """format=chrome: Chrome trace-event array (Perfetto-loadable).
    One pid per worker (pid 0 = meta), one tid per actor (tid 0 = the
    epoch-level span). All timestamps are µs offsets from the OLDEST
    exported epoch's inject, each epoch anchored at its inject time;
    worker events anchor at the inject push, i.e. the same origin.
    Cross-engine broker links add a "broker i/o" track per epoch plus
    chrome flow events ("s"/"f" with matching ids) so Perfetto draws an
    arrow from a sink delivery to the downstream engine's ingest once
    two exports are stitched (`stitch_chrome_traces`)."""
    events = []
    base = 0
    for i, t in enumerate(sorted(traces, key=lambda t: t.epoch)):
        def ev(name, pid, tid, ts_ns, dur_ns, **args):
            events.append({
                "name": name, "ph": "X", "cat": "epoch",
                "pid": pid, "tid": tid,
                "ts": round((base + ts_ns) / 1e3, 3),
                "dur": round(max(dur_ns, 0) / 1e3, 3),
                "args": {"epoch": t.epoch, **args}})

        ev(f"epoch {t.epoch}", 0, 0, 0, t.total_ns,
           sync_ms=t.sync_ns / 1e6)
        if t.seal_ns or t.upload_ns or t.commit_ns:
            off = t.total_ns
            for nm, dur in (("seal", t.seal_ns),
                            ("upload", t.upload_ns),
                            ("commit", t.commit_ns)):
                ev(f"{nm} {t.epoch}", 0, 0, off, dur)
                off += dur
        for actor_id, dt in t.collects:
            ph = t.phases.get(actor_id, {})
            ev(f"collect actor {actor_id}", 0, actor_id, 0, dt,
               **{k: v / 1e6 for k, v in ph.items()})
        for wid in sorted(t.worker_spans):
            w = t.worker_spans[wid]
            ev(f"w{wid} epoch {t.epoch}", wid, 0, 0,
               w.get("total_ns", 0))
            phases = w.get("phases", {})
            for actor_id, dt in w.get("collects", ()):
                ph = phases.get(str(actor_id), {})
                ev(f"w{wid} collect actor {actor_id}", wid,
                   actor_id, 0, dt,
                   **{k: v / 1e6 for k, v in ph.items()})
        # cross-engine links: one slice per delivery/ingest on the
        # broker i/o track + a flow event INSIDE it (flow events bind
        # to their enclosing slice by pid/tid/ts)
        span_ns = max(t.total_ns, 1_000_000)
        for ln in t.links:
            where = (f"{ln.get('topic')}[{ln.get('partition')}]"
                     f"@{ln.get('offset')}")
            out = ln.get("dir") == "out"
            name = ("sink deliver " if out else "source ingest ") + where
            span = ln.get("span") if out else ln.get("peer")
            ev(name, 0, BROKER_TID, 0, span_ns, **{
                k: v for k, v in ln.items() if v is not None})
            if span:
                events.append({
                    "name": "xengine", "cat": "broker",
                    "ph": "s" if out else "f", **({} if out
                                                  else {"bp": "e"}),
                    "id": _flow_id(span), "pid": 0, "tid": BROKER_TID,
                    "ts": round((base + span_ns / 2) / 1e3, 3)})
        # epochs laid end to end: each epoch's window begins where the
        # previous one's longest span ended (monotonic offsets without
        # trusting any wall clock)
        base += max(t.total_ns + t.seal_ns + t.upload_ns + t.commit_ns,
                    max((w.get("total_ns", 0)
                         for w in t.worker_spans.values()), default=0),
                    1_000_000)
    return events


def stitch_chrome_traces(a_events, b_events, a_name: str = "engine-a",
                         b_name: str = "engine-b"):
    """Merge two engines' chrome exports into ONE Perfetto timeline.

    Engine B's pids are re-based (pid + 100 per worker) so the two
    engines render as separate process groups, process_name metadata
    labels them, and engine B's clock is shifted so every matched
    delivery→ingest flow pair is causal (ingest at-or-after delivery —
    the only cross-engine ordering the broker offsets guarantee).
    Returns `(merged_events, n_links)` where n_links counts flow ids
    present as BOTH an "s" (delivery) and an "f" (ingest)."""
    PID_STRIDE = 100
    b_events = [dict(e) for e in b_events]
    for e in b_events:
        e["pid"] = int(e.get("pid", 0)) + PID_STRIDE
    out_ids = {e["id"]: e["ts"] for e in a_events
               if e.get("ph") == "s" and "id" in e}
    in_ids = {e["id"]: e["ts"] for e in b_events
              if e.get("ph") == "f" and "id" in e}
    # reverse direction too (B sinks into A)
    out_ids.update({e["id"]: e["ts"] for e in b_events
                    if e.get("ph") == "s" and "id" in e})
    in_ids.update({e["id"]: e["ts"] for e in a_events
                   if e.get("ph") == "f" and "id" in e})
    matched = sorted(set(out_ids) & set(in_ids))
    # causality shift: push B late enough that no matched ingest
    # precedes its delivery (both exports start at their own t=0)
    delta = 0.0
    for fid in matched:
        a_ts = out_ids[fid]
        b_ts = in_ids[fid]
        delta = max(delta, a_ts - b_ts + 1.0)
    if delta:
        for e in b_events:
            e["ts"] = round(e.get("ts", 0) + delta, 3)
    merged = []
    for pid_base, name, evs in ((0, a_name, a_events),
                                (PID_STRIDE, b_name, b_events)):
        pids = sorted({int(e.get("pid", 0)) for e in evs})
        for pid in pids:
            wid = pid - pid_base
            label = name if wid == 0 else f"{name}/w{wid}"
            merged.append({"name": "process_name", "ph": "M",
                           "pid": pid, "tid": 0,
                           "args": {"name": label}})
    merged.extend(a_events)
    merged.extend(b_events)
    return merged, len(matched)


def format_stuck_barrier_report(coord, worker_reports=None) -> str:
    """One-call diagnosis: the STUCK epochs' partial spans (who already
    collected, and when), recent completed spans, and the await tree.
    In cluster mode the watchdog passes `worker_reports` (worker_id ->
    that worker's own report text pulled over rpc.py) so a wedged epoch
    names the worker, actor, AND parked await frame.
    (What the reference gets from `risectl trace` + await-tree dump.)"""
    tracer = getattr(coord, "tracer", None)
    lines = []
    if tracer is not None:
        stuck = tracer.open_traces()
        if stuck:
            lines.append("== in-flight (stuck) epochs ==")
            for t in stuck:
                lines.append(t.render())
        lines.append("== recent completed epochs ==")
        for t in tracer.recent():
            lines.append(t.render())
    lines.append("== await tree ==")
    lines.append(dump_task_tree())
    for wid in sorted(worker_reports or ()):
        lines.append(f"== worker w{wid} ==")
        lines.append(str(worker_reports[wid]))
    return "\n".join(lines)

"""Epoch spans + async stack dumps — the tracing/await-tree analogue.

Reference: (a) barriers carry a TracingContext so each epoch is a
distributed trace spanning meta -> CN actors (common/src/util/tracing.rs,
executor/mod.rs:267, actor.rs:195-240); (b) every actor future is
await-tree-instrumented and dumpable via the MonitorService for
stuck-barrier debugging (stream_manager.rs:66).

Single-process TPU analogue:
  * EpochTrace — per-epoch spans recorded by the barrier coordinator:
    inject time, per-actor collect times, sync duration. A slow epoch's
    trace shows WHICH actor held the barrier.
  * dump_task_tree() — the await-tree: every asyncio task's current
    await stack, so a stuck barrier shows exactly which executor
    coroutine is parked where (channel recv, credit wait, device fence).
"""

from __future__ import annotations

import asyncio
import time
import traceback
from collections import deque
from dataclasses import dataclass, field


@dataclass
class EpochTrace:
    epoch: int
    inject_ns: int
    collects: list = field(default_factory=list)   # (actor_id, ns_after)
    # actor_id -> {"apply_ns", "persist_ns", "align_ns"} — the interval's
    # phase split reported by the actor at its collect (stream/actor.py):
    # apply = chunk compute+dispatch, persist = barrier-time flush/commit
    # work in the chain, align = input-channel + fence waiting. A slow
    # epoch's trace shows WHO held the barrier and DOING WHAT.
    phases: dict = field(default_factory=dict)
    sync_ns: int = 0        # inline store sync duration (pipelining off)
    # checkpoint-pipeline phases (annotated AFTER the span closes — the
    # uploader commits in the background, off the barrier critical path)
    seal_ns: int = 0
    upload_ns: int = 0
    commit_ns: int = 0
    total_ns: int = 0

    def render(self) -> str:
        head = (f"epoch {self.epoch}: total {self.total_ns / 1e6:.1f}ms, "
                f"sync {self.sync_ns / 1e6:.1f}ms")
        if self.seal_ns or self.upload_ns or self.commit_ns:
            head += (f" [bg seal {self.seal_ns / 1e6:.1f}ms, "
                     f"upload {self.upload_ns / 1e6:.1f}ms, "
                     f"commit {self.commit_ns / 1e6:.1f}ms]")
        lines = [head]
        for actor_id, dt in sorted(self.collects, key=lambda x: x[1]):
            line = f"  actor {actor_id} collected at +{dt / 1e6:.1f}ms"
            ph = self.phases.get(actor_id)
            if ph:
                line += (f" (apply {ph.get('apply_ns', 0) / 1e6:.1f}ms, "
                         f"persist {ph.get('persist_ns', 0) / 1e6:.1f}ms, "
                         f"align {ph.get('align_ns', 0) / 1e6:.1f}ms)")
            lines.append(line)
        return "\n".join(lines)


class EpochTracer:
    """Ring of recent epoch traces (the Grafana trace panel stand-in)."""

    def __init__(self, keep: int = 64):
        self._ring: deque[EpochTrace] = deque(maxlen=keep)
        self._open: dict[int, EpochTrace] = {}
        # recovery spans (frontend/session.py notes one per auto-
        # recovery): rendered by /debug/traces next to the epoch spans
        # so a post-mortem shows WHEN recovery ran, at what scope, for
        # how long, and which actors were rebuilt
        self.recoveries: deque[dict] = deque(maxlen=keep)

    def note_recovery(self, scope: str, cause: str, duration_ns: int,
                      actors=()) -> None:
        self.recoveries.append({
            "scope": scope, "cause": cause,
            "duration_ns": int(duration_ns),
            "actors": list(actors),
            "at_ns": time.monotonic_ns()})

    def render_recoveries(self) -> list[str]:
        return [
            (f"recovery scope={r['scope']} cause={r['cause']} "
             f"{r['duration_ns'] / 1e6:.1f}ms "
             f"rebuilt_actors={r['actors']}")
            for r in self.recoveries]

    def begin(self, epoch: int) -> None:
        self._open[epoch] = EpochTrace(epoch, time.monotonic_ns())

    def collect(self, epoch: int, actor_id: int) -> None:
        t = self._open.get(epoch)
        if t is not None:
            t.collects.append(
                (actor_id, time.monotonic_ns() - t.inject_ns))

    def collect_phases(self, epoch: int, actor_id: int,
                       phases: dict) -> None:
        """Attach an actor's interval phase split (apply / persist /
        align, in ns) to the open epoch span (reported by the actor just
        before it collects the barrier)."""
        t = self._open.get(epoch)
        if t is not None:
            t.phases[actor_id] = phases

    def end(self, epoch: int, sync_ns: int = 0) -> None:
        t = self._open.pop(epoch, None)
        if t is not None:
            t.total_ns = time.monotonic_ns() - t.inject_ns
            t.sync_ns = sync_ns
            self._ring.append(t)

    def annotate(self, epoch: int, *, seal_ns: int = 0, upload_ns: int = 0,
                 commit_ns: int = 0) -> None:
        """Attach checkpoint-pipeline phase durations to an epoch whose
        span already closed — the background uploader reports these after
        the barrier completed (which is the whole point of the pipeline)."""
        t = self._open.get(epoch)
        if t is None:
            for cand in reversed(self._ring):
                if cand.epoch == epoch:
                    t = cand
                    break
        if t is not None:
            t.seal_ns, t.upload_ns, t.commit_ns = seal_ns, upload_ns, commit_ns

    def recent(self, n: int = 8) -> list[EpochTrace]:
        return list(self._ring)[-n:]

    def open_traces(self) -> list[EpochTrace]:
        """In-flight (uncollected) epochs — THE data for a stuck
        barrier: which actors already collected, and when."""
        out = []
        now = time.monotonic_ns()
        for t in self._open.values():
            t.total_ns = now - t.inject_ns
            out.append(t)
        return sorted(out, key=lambda t: t.epoch)

    def slowest(self, n: int = 3) -> list[EpochTrace]:
        return sorted(self._ring, key=lambda t: -t.total_ns)[:n]


def dump_task_tree(limit_frames: int = 6) -> str:
    """Await stacks of every live asyncio task (await-tree analogue:
    risectl's stack dump for stuck-barrier debugging). Safe to call from
    inside the loop; excludes the calling task's own dump frames."""
    out = []
    try:
        current = asyncio.current_task()
        tasks = asyncio.all_tasks()
    except RuntimeError:
        return "(no running event loop)"
    for task in sorted(tasks,
                       key=lambda t: t.get_name()):
        if task is current:
            continue
        out.append(f"task {task.get_name()}"
                   f"{' <cancelled>' if task.cancelled() else ''}:")
        frames = task.get_stack(limit=limit_frames)
        if not frames:
            out.append("  (no frames: done or not started)")
            continue
        for f in frames:
            code = f.f_code
            out.append(f"  {code.co_filename.rsplit('/', 1)[-1]}"
                       f":{f.f_lineno} {code.co_name}")
    return "\n".join(out)


def format_stuck_barrier_report(coord) -> str:
    """One-call diagnosis: the STUCK epochs' partial spans (who already
    collected, and when), recent completed spans, and the await tree.
    (What the reference gets from `risectl trace` + await-tree dump.)"""
    tracer = getattr(coord, "tracer", None)
    lines = []
    if tracer is not None:
        stuck = tracer.open_traces()
        if stuck:
            lines.append("== in-flight (stuck) epochs ==")
            for t in stuck:
                lines.append(t.render())
        lines.append("== recent completed epochs ==")
        for t in tracer.recent():
            lines.append(t.render())
    lines.append("== await tree ==")
    lines.append(dump_task_tree())
    return "\n".join(lines)

"""On-demand profiling — the MonitorService's heap/cpu/device triggers.

Reference: the reference's MonitorService exposes on-demand profiling
RPCs (StackTrace / Profiling / HeapProfiling — stream_manager.rs:66,
monitor_service.proto): an operator hits an endpoint on a LIVE node and
gets a profile back, no restart, no always-on overhead. Same shape
here, stdlib-only:

  * profile_cpu(seconds)   — a helper thread samples every Python
    thread's current frame stack (`sys._current_frames`) at ~100Hz and
    emits COLLAPSED-STACK lines ("thread;frameA;frameB N") so standard
    flamegraph tooling consumes the output directly.
  * profile_heap(seconds)  — tracemalloc enable -> snapshot -> wait ->
    snapshot -> top-N allocation diff by source line (enable/disable is
    scoped to the call when tracing was off, so idle cost stays zero).
  * profile_device(coord)  — per-executor HBM from the coordinator's
    MemoryManager accounting plus jax live-buffer totals when a device
    runtime is importable (gated: works CPU-only too).

Both timed profilers BLOCK for `seconds` — callers on the event loop
run them via `asyncio.to_thread` (meta/monitor_service.py does; the
worker RPC path in cluster/compute_node.py does too).
"""

from __future__ import annotations

import sys
import threading
import time

# sampling cadence for the cpu profiler: ~100Hz is the flamegraph
# convention — coarse enough to stay invisible next to device steps,
# fine enough that a hot loop dominates the sample counts
DEFAULT_HZ = 100.0


def _frame_name(frame) -> str:
    """One collapsed-stack frame token: file.py:func:line with the
    separator characters (';' and whitespace) sanitized so the line
    splits cleanly back into frames."""
    code = frame.f_code
    fname = code.co_filename.rsplit("/", 1)[-1]
    tok = f"{fname}:{code.co_name}:{frame.f_lineno}"
    return tok.replace(";", ",").replace(" ", "_")


def _thread_names() -> dict:
    return {t.ident: t.name for t in threading.enumerate()}


def profile_cpu(seconds: float, hz: float = DEFAULT_HZ,
                max_seconds: float = 60.0) -> str:
    """Sample every live thread's stack for `seconds`, return collapsed
    stacks: one line per unique (thread, root-first frame chain), the
    trailing integer its sample count. Blocking — run off-loop."""
    seconds = max(0.05, min(float(seconds), max_seconds))
    interval = 1.0 / max(1.0, float(hz))
    counts: dict = {}
    samples = 0
    me = threading.get_ident()
    deadline = time.monotonic() + seconds
    while time.monotonic() < deadline:
        names = _thread_names()
        for ident, frame in sys._current_frames().items():
            if ident == me:
                continue   # the sampler's own busy-loop is noise
            stack = []
            f = frame
            while f is not None:
                stack.append(_frame_name(f))
                f = f.f_back
            stack.reverse()   # root-first, the collapsed-stack order
            tname = names.get(ident, f"thread-{ident}")
            key = ";".join(
                [tname.replace(";", ",").replace(" ", "_")] + stack)
            counts[key] = counts.get(key, 0) + 1
        samples += 1
        time.sleep(interval)
    head = (f"# cpu profile: {samples} samples over {seconds:.2f}s "
            f"at {hz:.0f}Hz")
    lines = [head]
    for key, n in sorted(counts.items(), key=lambda kv: -kv[1]):
        lines.append(f"{key} {n}")
    return "\n".join(lines) + "\n"


def parse_collapsed(text: str) -> list:
    """Parse collapsed-stack text back into [(frames, count)] — the
    profiler's own round-trip check (tests + gate use it)."""
    out = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        stack, _, count = line.rpartition(" ")
        if not stack or not count.isdigit():
            raise ValueError(f"bad collapsed-stack line: {line!r}")
        out.append((stack.split(";"), int(count)))
    return out


def profile_heap(seconds: float, top: int = 30,
                 max_seconds: float = 60.0) -> str:
    """Allocation growth over a window: tracemalloc snapshot at start
    and end, top-N source lines by net new bytes. Enables tracemalloc
    for the call when it was off (and disables it after), so the idle
    process pays nothing. Blocking — run off-loop."""
    import tracemalloc
    seconds = max(0.05, min(float(seconds), max_seconds))
    was_tracing = tracemalloc.is_tracing()
    if not was_tracing:
        tracemalloc.start()
    try:
        before = tracemalloc.take_snapshot()
        time.sleep(seconds)
        after = tracemalloc.take_snapshot()
        current, peak = tracemalloc.get_traced_memory()
    finally:
        if not was_tracing:
            tracemalloc.stop()
    stats = after.compare_to(before, "lineno")
    lines = [f"# heap profile: {seconds:.2f}s window, "
             f"traced current={current} peak={peak}",
             "# size_diff_b count_diff source"]
    for st in stats[:max(1, int(top))]:
        frame = st.traceback[0] if st.traceback else None
        where = (f"{frame.filename.rsplit('/', 1)[-1]}:{frame.lineno}"
                 if frame is not None else "?")
        lines.append(f"{st.size_diff:+d} {st.count_diff:+d} {where}")
    return "\n".join(lines) + "\n"


def profile_device(coord) -> str:
    """Device-memory report: per-executor HBM accounting rows from the
    coordinator's MemoryManager (always available — it is pure
    bookkeeping) plus the jax live-buffer totals per device when a
    runtime is importable."""
    lines = ["# device profile"]
    memory = getattr(coord, "memory", None)
    rows = memory.report() if memory is not None else []
    lines.append("# executor state_bytes evicted_bytes reload_count "
                 "spilled_rows")
    for r in rows:
        lines.append(f"{r['executor']} {r['state_bytes']} "
                     f"{r['evicted_bytes']} {r['reload_count']} "
                     f"{r['spilled_rows']}")
    if not rows:
        lines.append("(no accounted executors)")
    try:
        import jax
        lines.append("# jax live arrays per device")
        per_dev = {}
        for arr in jax.live_arrays():
            try:
                for shard in arr.addressable_shards:
                    dev = shard.device
                    n, nbytes = per_dev.get(dev, (0, 0))
                    per_dev[dev] = (n + 1,
                                    nbytes + getattr(shard.data,
                                                     "nbytes", 0))
            except Exception:  # noqa: BLE001 — backend-dependent API
                continue
        for dev in jax.devices():
            n, nbytes = per_dev.get(dev, (0, 0))
            lines.append(f"{dev.platform}:{dev.id} buffers={n} "
                         f"bytes={nbytes}")
    except Exception:  # noqa: BLE001 — no jax runtime: accounting only
        lines.append("# jax runtime unavailable")
    return "\n".join(lines) + "\n"

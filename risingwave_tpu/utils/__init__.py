from .metrics import GLOBAL_METRICS, MetricsRegistry

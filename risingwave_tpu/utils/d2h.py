"""Packed device→host fetches.

The tunneled TPU charges ~0.15-0.3s PER FETCH CALL regardless of size
(measured round 5; bandwidth after the fixed cost is fine). Every
persist path therefore ships its whole payload in at most TWO calls:
one for the host-needed counts, then one packed int64 buffer holding
all columns (floats bitcast, narrower ints widened). These helpers keep
the pack/unpack rule in one place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pack_for_fetch(arrays):
    """1-D device arrays (host-known lengths) -> (flat int64 device
    array, metas). Fetch the flat array with ONE np.asarray, then
    unpack_fetched."""
    parts, metas = [], []
    for a in arrays:
        dt = np.dtype(a.dtype)
        if dt == np.float64:
            x = jax.lax.bitcast_convert_type(a, jnp.int64)
        elif dt == np.float32:
            x = jax.lax.bitcast_convert_type(
                a.astype(jnp.float64), jnp.int64)
        else:
            x = a.astype(jnp.int64)
        parts.append(x)
        metas.append((int(a.shape[0]), dt))
    flat = (jnp.concatenate(parts) if parts
            else jnp.zeros(0, dtype=jnp.int64))
    return flat, metas


def unpack_fetched(flat: np.ndarray, metas) -> list[np.ndarray]:
    out, off = [], 0
    for n, dt in metas:
        seg = flat[off:off + n]
        off += n
        if dt == np.float64 or dt == np.float32:
            out.append(seg.view(np.float64).astype(dt, copy=False))
        elif dt == np.int64:
            out.append(seg)
        else:
            out.append(seg.astype(dt))
    return out


def fetch_columns(arrays) -> list[np.ndarray]:
    """Pack + single fetch + unpack."""
    flat, metas = pack_for_fetch(arrays)
    return unpack_fetched(np.asarray(flat), metas)

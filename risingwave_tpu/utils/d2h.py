"""Packed device→host fetches.

The tunneled TPU charges ~0.15-0.3s PER FETCH CALL regardless of size
(measured round 5; bandwidth after the fixed cost is fine). Every
persist path therefore ships its whole payload in at most TWO calls:
one for the host-needed counts, then one packed int64 buffer holding
all columns (floats bitcast, narrower ints widened). These helpers keep
the pack/unpack rule in one place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pack_for_fetch(arrays):
    """1-D device arrays (host-known lengths) -> (flat int64 device
    array, metas). Fetch the flat array with ONE np.asarray, then
    unpack_fetched."""
    parts, metas = [], []
    for a in arrays:
        dt = np.dtype(a.dtype)
        if dt == np.float64:
            x = jax.lax.bitcast_convert_type(a, jnp.int64)
        elif dt == np.float32:
            x = jax.lax.bitcast_convert_type(
                a.astype(jnp.float64), jnp.int64)
        else:
            x = a.astype(jnp.int64)
        parts.append(x)
        metas.append((int(a.shape[0]), dt))
    flat = (jnp.concatenate(parts) if parts
            else jnp.zeros(0, dtype=jnp.int64))
    return flat, metas


def unpack_fetched(flat: np.ndarray, metas) -> list[np.ndarray]:
    out, off = [], 0
    for n, dt in metas:
        seg = flat[off:off + n]
        off += n
        if dt == np.float64 or dt == np.float32:
            out.append(seg.view(np.float64).astype(dt, copy=False))
        elif dt == np.int64:
            out.append(seg)
        else:
            out.append(seg.astype(dt))
    return out


def fetch_flat(flat) -> np.ndarray:
    """Blocking d2h of an already-packed flat device buffer — a PURE
    WAIT (`np.asarray` on a concrete array; no op dispatch), so it is
    the ONE d2h primitive safe to run on a worker thread while the
    event-loop thread keeps dispatching. Dispatching eager jax ops from
    two threads concurrently deadlocks (observed: a background slice
    gather vs. the loop blocked in `_value`); every deferred-flush wait
    phase must therefore bottom out here or in a bare np.asarray of a
    dispatched buffer."""
    from .metrics import D2H_BYTES, D2H_FETCHES
    host = np.asarray(flat)
    D2H_FETCHES.inc()
    D2H_BYTES.inc(host.nbytes)
    return host


def fetch_columns(arrays) -> list[np.ndarray]:
    """Pack + single fetch + unpack."""
    flat, metas = pack_for_fetch(arrays)
    return unpack_fetched(fetch_flat(flat), metas)


def _bucket(n: int, cap: int) -> int:
    if n <= 0:
        return 0
    return min(1 << (n - 1).bit_length(), cap)


def prepare_prefix_groups(groups):
    """Dispatch-only half of fetch_prefix_groups: slice each group's
    arrays to the pow2 bucket of its host-known prefix length and pack
    everything into ONE flat int64 device buffer. Returns
    (flat, metas, group_meta) for `finish_prefix_groups`. MUST run on
    the event-loop thread — it dispatches device ops (see fetch_flat)."""
    sliced, meta = [], []
    for arrays, n in groups:
        cap = int(arrays[0].shape[0]) if arrays else 0
        b = _bucket(int(n), cap)
        for a in arrays:
            sliced.append(a[:b])
        meta.append((len(arrays), int(n)))
    flat, metas = pack_for_fetch(sliced)
    return flat, metas, meta


def finish_prefix_groups(host_flat: np.ndarray, metas, group_meta) -> list:
    """Host-only half: unpack the fetched flat buffer and trim each
    group to its exact prefix length. No device work — safe anywhere."""
    host = unpack_fetched(host_flat, metas)
    out, i = [], 0
    for cnt, n in group_meta:
        out.append([h[:n] for h in host[i:i + cnt]])
        i += cnt
    return out


def fetch_prefix_groups(groups) -> list:
    """groups: [(full_arrays, n_prefix)] -> list of lists of np arrays
    trimmed to n_prefix, via ONE packed fetch. Slice lengths bucket to
    powers of two so the eager slice/concat SHAPES repeat across
    barriers — every fresh shape signature costs a compile round trip
    (~1-3s on the tunneled link), which exact per-epoch lengths would
    pay at every single barrier."""
    flat, metas, meta = prepare_prefix_groups(groups)
    return finish_prefix_groups(fetch_flat(flat), metas, meta)

"""Packed device→host fetches.

The tunneled TPU charges ~0.15-0.3s PER FETCH CALL regardless of size
(measured round 5; bandwidth after the fixed cost is fine). Every
persist path therefore ships its whole payload in at most TWO calls:
one for the host-needed counts, then one packed int64 buffer holding
all columns (floats bitcast, narrower ints widened). These helpers keep
the pack/unpack rule in one place.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def pack_for_fetch(arrays):
    """1-D device arrays (host-known lengths) -> (flat int64 device
    array, metas). Fetch the flat array with ONE np.asarray, then
    unpack_fetched."""
    parts, metas = [], []
    for a in arrays:
        dt = np.dtype(a.dtype)
        if dt == np.float64:
            x = jax.lax.bitcast_convert_type(a, jnp.int64)
        elif dt == np.float32:
            x = jax.lax.bitcast_convert_type(
                a.astype(jnp.float64), jnp.int64)
        else:
            x = a.astype(jnp.int64)
        parts.append(x)
        metas.append((int(a.shape[0]), dt))
    flat = (jnp.concatenate(parts) if parts
            else jnp.zeros(0, dtype=jnp.int64))
    return flat, metas


def unpack_fetched(flat: np.ndarray, metas) -> list[np.ndarray]:
    out, off = [], 0
    for n, dt in metas:
        seg = flat[off:off + n]
        off += n
        if dt == np.float64 or dt == np.float32:
            out.append(seg.view(np.float64).astype(dt, copy=False))
        elif dt == np.int64:
            out.append(seg)
        else:
            out.append(seg.astype(dt))
    return out


def fetch_columns(arrays) -> list[np.ndarray]:
    """Pack + single fetch + unpack."""
    flat, metas = pack_for_fetch(arrays)
    return unpack_fetched(np.asarray(flat), metas)


def _bucket(n: int, cap: int) -> int:
    if n <= 0:
        return 0
    return min(1 << (n - 1).bit_length(), cap)


def fetch_prefix_groups(groups) -> list:
    """groups: [(full_arrays, n_prefix)] -> list of lists of np arrays
    trimmed to n_prefix, via ONE packed fetch. Slice lengths bucket to
    powers of two so the eager slice/concat SHAPES repeat across
    barriers — every fresh shape signature costs a compile round trip
    (~1-3s on the tunneled link), which exact per-epoch lengths would
    pay at every single barrier."""
    sliced, meta = [], []
    for arrays, n in groups:
        cap = int(arrays[0].shape[0]) if arrays else 0
        b = _bucket(int(n), cap)
        for a in arrays:
            sliced.append(a[:b])
        meta.append((len(arrays), int(n)))
    host = fetch_columns(sliced)
    out, i = [], 0
    for cnt, n in meta:
        out.append([h[:n] for h in host[i:i + cnt]])
        i += cnt
    return out

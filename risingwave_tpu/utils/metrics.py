"""Metrics registry — counters, gauges, histograms with labels.

Reference: Prometheus metrics everywhere (`StreamingMetrics` ~150 series,
src/stream/src/executor/monitor/streaming_stats.rs; `MetricLevel` gating;
docs/metrics.md defines barrier latency as THE health metric). This is the
same shape without a Prometheus dependency: a process-local registry whose
`snapshot()`/`render()` can feed any scraper, plus the headline series
pre-registered (source throughput, barrier latency histogram, actor rows).
"""

from __future__ import annotations

import bisect
import threading
import time
from dataclasses import dataclass, field
from typing import Optional, Sequence


class Counter:
    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    @property
    def value(self) -> float:
        return self._v


class Gauge:
    """Thread-safe gauge with set/inc/dec. Worker threads mutate gauges
    too (serving/pool.py admission accounting runs from done-callbacks
    racing the loop), so the read-modify-write of inc/dec must hold a
    lock — a bare `self.value += x` from two threads loses updates."""

    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._v = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v += amount

    def dec(self, amount: float = 1.0) -> None:
        with self._lock:
            self._v -= amount

    @property
    def value(self) -> float:
        return self._v


class Histogram:
    """Fixed-bucket histogram (Prometheus semantics: cumulative buckets)."""

    DEFAULT_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0,
                       2.5, 5.0, 10.0)

    def __init__(self, buckets: Optional[Sequence[float]] = None):
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self.counts = [0] * (len(self.buckets) + 1)
        self.sum = 0.0
        self.n = 0
        # largest observation ever seen: quantiles that land in the
        # +Inf overflow bucket report this instead of silently clamping
        # to buckets[-1] (which under-reported every outlier)
        self.max = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            i = bisect.bisect_left(self.buckets, v)
            self.counts[i] += 1
            self.sum += v
            self.n += 1
            if v > self.max:
                self.max = v

    def percentile(self, p: float) -> float:
        """Approximate percentile from bucket boundaries; quantiles that
        fall in the overflow (+Inf) bucket return the observed max."""
        if self.n == 0:
            return 0.0
        target = p * self.n
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return (self.buckets[i] if i < len(self.buckets)
                        else self.max)
        return self.max


def escape_label_value(v) -> str:
    """Prometheus exposition label-value escaping: backslash, double
    quote and newline must be escaped or the line is unparseable."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _fmt_labels(labels) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{escape_label_value(v)}"' for k, v in labels)
    return "{" + inner + "}"


def _histogram_lines(name: str, labels, h: "Histogram") -> list[str]:
    """Cumulative bucket/sum/count lines for one histogram series — the
    ONE place the exposition bucket format lives (render and
    render_prometheus both consume it)."""
    lines = []
    acc = 0
    for b, cnt in zip(h.buckets, h.counts):
        acc += cnt
        lab = dict(labels)
        lab["le"] = b
        lines.append(f"{name}_bucket{_fmt_labels(sorted(lab.items()))} {acc}")
    lab = dict(labels)
    lab["le"] = "+Inf"   # required by histogram_quantile
    lines.append(f"{name}_bucket{_fmt_labels(sorted(lab.items()))} {h.n}")
    lines.append(f"{name}_sum{_fmt_labels(labels)} {h.sum}")
    lines.append(f"{name}_count{_fmt_labels(labels)} {h.n}")
    return lines


@dataclass
class MetricsRegistry:
    counters: dict = field(default_factory=dict)
    gauges: dict = field(default_factory=dict)
    histograms: dict = field(default_factory=dict)

    def counter(self, name: str, **labels) -> Counter:
        key = (name, tuple(sorted(labels.items())))
        if key not in self.counters:
            self.counters[key] = Counter()
        return self.counters[key]

    def gauge(self, name: str, **labels) -> Gauge:
        key = (name, tuple(sorted(labels.items())))
        if key not in self.gauges:
            self.gauges[key] = Gauge()
        return self.gauges[key]

    def histogram(self, name: str,
                  buckets: Optional[Sequence[float]] = None,
                  **labels) -> Histogram:
        key = (name, tuple(sorted(labels.items())))
        if key not in self.histograms:
            self.histograms[key] = Histogram(buckets)
        return self.histograms[key]

    def labelled_series(self, prefix: str = "",
                        kinds=("counter", "gauge", "histogram")) -> set:
        """Every (name, labels) key with a NON-empty label set, as
        `(name, (("k","v"), ...))` tuples. The teardown-audit surface:
        tests snapshot this before a create/…/drop cycle and diff after
        — anything new is a series some teardown path forgot to
        `remove()` and /metrics would grow by forever. `kinds` narrows
        the audit: cumulative counters conventionally outlive their
        emitter (totals stay meaningful after a drop), so leak checks
        usually pass kinds=("gauge", "histogram")."""
        by_kind = {"counter": self.counters, "gauge": self.gauges,
                   "histogram": self.histograms}
        out = set()
        for k in kinds:
            for name, labels in by_kind[k]:
                if labels and name.startswith(prefix):
                    out.add((name, labels))
        return out

    def remove(self, name: str, **labels) -> None:
        """Drop one series (all kinds) — dead actors must not linger in
        scrapes forever (stream/monitor.py unregisters through here)."""
        key = (name, tuple(sorted(labels.items())))
        self.counters.pop(key, None)
        self.gauges.pop(key, None)
        self.histograms.pop(key, None)

    # ------------------------------------------------------------ export
    def snapshot(self) -> dict:
        out = {}
        for (name, labels), c in self.counters.items():
            out.setdefault(name, []).append(
                {"labels": dict(labels), "value": c.value})
        for (name, labels), g in self.gauges.items():
            out.setdefault(name, []).append(
                {"labels": dict(labels), "value": g.value})
        for (name, labels), h in self.histograms.items():
            out.setdefault(name, []).append(
                {"labels": dict(labels), "count": h.n, "sum": h.sum,
                 "p50": h.percentile(0.5), "p99": h.percentile(0.99)})
        return out

    def render(self) -> str:
        """Prometheus text exposition (scraper-compatible)."""
        lines = []
        for (name, labels), c in sorted(self.counters.items()):
            lines.append(f"{name}{_fmt_labels(labels)} {c.value}")
        for (name, labels), g in sorted(self.gauges.items()):
            lines.append(f"{name}{_fmt_labels(labels)} {g.value}")
        for (name, labels), h in sorted(self.histograms.items()):
            lines.extend(_histogram_lines(name, labels, h))
        return "\n".join(lines) + "\n"

    def render_prometheus(self) -> str:
        """Full Prometheus text format WITH `# TYPE` metadata, one family
        block per metric name — the exposition a real scrape endpoint (or
        `curl | promtool check metrics`) expects. `render()` stays the
        terse label-value dump for the REPL; this is the export surface
        (the `\\metrics prom` verb and the monitor HTTP `/metrics`)."""
        by_family: dict[str, tuple[str, list[str]]] = {}

        def family(name: str, typ: str) -> list[str]:
            if name not in by_family:
                by_family[name] = (typ, [])
            return by_family[name][1]

        for (name, labels), c in sorted(self.counters.items()):
            family(name, "counter").append(
                f"{name}{_fmt_labels(labels)} {c.value}")
        for (name, labels), g in sorted(self.gauges.items()):
            family(name, "gauge").append(
                f"{name}{_fmt_labels(labels)} {g.value}")
        for (name, labels), h in sorted(self.histograms.items()):
            family(name, "histogram").extend(
                _histogram_lines(name, labels, h))
        lines = []
        for name, (typ, rows) in sorted(by_family.items()):
            lines.append(f"# TYPE {name} {typ}")
            lines.extend(rows)
        return "\n".join(lines) + "\n"


# the process-default registry (reference GLOBAL_METRICS_REGISTRY)
GLOBAL_METRICS = MetricsRegistry()

# Pre-registered process totals for the jitted step programs (incremented
# by ops/jit_state.py — one compile per traced signature, one dispatch per
# program invocation; per-program labelled series ride alongside). The
# north-star queries are host-dispatch-bound, so dispatches per barrier
# interval and recompiles after warmup are headline health series: they
# always render in `\metrics` / scrapes, even at zero.
JIT_COMPILES = GLOBAL_METRICS.counter("jit_compile_count")
DEVICE_DISPATCHES = GLOBAL_METRICS.counter("device_dispatch_count")

# Checkpoint pipeline phases (meta/barrier_manager.py): the old opaque
# `sync_ns` splits into seal (deferred executor flushes + shared-buffer
# seal), upload (SST build + object PUT, runs in background) and commit
# (manifest swap). Always rendered so `\metrics` shows the split even
# before the first checkpoint.
CHECKPOINT_SEAL_SECONDS = GLOBAL_METRICS.histogram(
    "checkpoint_seal_seconds")
CHECKPOINT_UPLOAD_SECONDS = GLOBAL_METRICS.histogram(
    "checkpoint_upload_seconds")
CHECKPOINT_COMMIT_SECONDS = GLOBAL_METRICS.histogram(
    "checkpoint_commit_seconds")
# sealed-but-uncommitted epochs currently in the background uploader
CHECKPOINT_INFLIGHT = GLOBAL_METRICS.gauge("checkpoint_inflight_epochs")
# time barrier injection spent waiting for a free in-flight slot
CHECKPOINT_BACKPRESSURE_SECONDS = GLOBAL_METRICS.counter(
    "checkpoint_backpressure_seconds_total")

# Device->host transfer accounting (utils/d2h.py packs every persist
# payload through fetch_columns): bytes moved and fetch calls made — the
# durable bench's d2h_bytes_per_s comes from here.
D2H_BYTES = GLOBAL_METRICS.counter("d2h_bytes_total")
D2H_FETCHES = GLOBAL_METRICS.counter("d2h_fetch_count")

# HBM memory manager (memory/manager.py): exact accounted device-state
# bytes vs. the configured budget, plus eviction/reload activity. The
# global series always render; per-executor `hbm_state_bytes{executor=..}`
# gauges ride alongside once flows register.
HBM_STATE_BYTES = GLOBAL_METRICS.gauge("hbm_state_bytes")
HBM_BUDGET_BYTES = GLOBAL_METRICS.gauge("hbm_budget_bytes")
HBM_EVICTED_BYTES = GLOBAL_METRICS.counter("hbm_evicted_bytes_total")
HBM_EVICTIONS = GLOBAL_METRICS.counter("hbm_evictions_total")
HBM_RELOADS = GLOBAL_METRICS.counter("hbm_reloads_total")
HBM_SPILLED_ROWS = GLOBAL_METRICS.gauge("hbm_spilled_rows")
# keys the reload-LFU guard kept device-resident through an eviction
# round (memory/manager.py ReloadGuard: reloaded >= 2x within the
# barrier window -> exempt from the next eviction)
HBM_GUARD_PROTECTED = GLOBAL_METRICS.counter("hbm_guard_protected_total")

# Serving layer (serving/): the read path's health series. Queries are
# host-side numpy over pinned snapshots, so latency buckets reach well
# below the default 1ms floor — point lookups are tens of microseconds.
SERVING_LATENCY_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025,
                           0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                           1.0, 2.5, 5.0)
SERVING_QUERIES = GLOBAL_METRICS.counter("serving_queries_total")
SERVING_LATENCY = GLOBAL_METRICS.histogram(
    "serving_latency_seconds", buckets=SERVING_LATENCY_BUCKETS)
SERVING_CACHE_HITS = GLOBAL_METRICS.counter("serving_cache_hits_total")
SERVING_CACHE_MISSES = GLOBAL_METRICS.counter(
    "serving_cache_misses_total")
SERVING_POINT_LOOKUPS = GLOBAL_METRICS.counter(
    "serving_point_lookups_total")
SERVING_INFLIGHT = GLOBAL_METRICS.gauge("serving_inflight_queries")
SERVING_ADMISSION_WAIT = GLOBAL_METRICS.counter(
    "serving_admission_wait_seconds_total")
SERVING_TIMEOUTS = GLOBAL_METRICS.counter("serving_timeouts_total")

# Stuck-barrier watchdog (meta/barrier_manager.py): incremented once per
# stalled epoch when an in-flight barrier exceeds
# barrier_stall_threshold_ms; the one-shot report rides stdout/logs.
BARRIER_STALLS = GLOBAL_METRICS.counter("barrier_stalls_total")

# Mesh-parallel fragment execution (parallel/exchange.py +
# stream/sharded_*.py): rows the in-mesh all_to_all shuffle dropped
# because a (src, dst) send bucket overflowed its per-pair capacity
# (streaming_mesh_shuffle_slack sized it too tight for the key skew).
# Nonzero is a FAIL-STOP: the owning executor raises at the barrier
# watchdog fetch before the epoch's checkpoint commits, so a dropped
# row is never silently absent from durable state.
# `mesh_fragment_shards{actor=...}` gauges ride alongside once fused
# mesh fragments register with the barrier coordinator.
MESH_SHUFFLE_DROPPED = GLOBAL_METRICS.counter(
    "mesh_shuffle_dropped_rows_total")

# Recovery plane (frontend/session.py): every auto-recovery increments
# `recovery_total{scope=fragment|cone|mesh|worker|full,cause=...}`
# (labelled series ride alongside these process totals) and observes
# its wall-clock duration; tick's exponential backoff between attempts
# accumulates into the backoff counter. Buckets reach low because a
# per-fragment rebuild on a warm process is milliseconds while a full
# DDL replay is seconds. `recovery_flapping{cause}` flips to 1 when a
# cause recovers more than `recovery_flap_threshold` times within the
# trailing window below — the rate then escalates the backoff base and
# /healthz reports `degraded`.
RECOVERY_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                    5.0, 10.0, 30.0)
RECOVERY_FLAP_WINDOW_S = 30.0
RECOVERY_TOTAL = GLOBAL_METRICS.counter("recovery_total")
RECOVERY_DURATION = GLOBAL_METRICS.histogram(
    "recovery_duration_seconds", buckets=RECOVERY_BUCKETS)
RECOVERY_BACKOFF = GLOBAL_METRICS.counter(
    "recovery_backoff_seconds_total")

# Changelog log store (logstore/): exactly-once egress + subscriptions.
# Bytes staged into the durable per-table logs (sink delivery logs + MV
# changelog logs), epochs/rows the background delivery handed to sink
# targets after commit, and per-subscription lag gauges
# (`logstore_subscription_lag_epochs{subscription=...}`) ride alongside
# once flows register.
LOGSTORE_APPEND_BYTES = GLOBAL_METRICS.counter("logstore_append_bytes_total")

# Fault-tolerant storage plane (state/object_store.py ResilientObjectStore,
# state/hummock.py read-path hardening, state/scrub.py): transient object
# faults are absorbed BELOW the recovery machinery. Per-op labelled series
# `object_store_retries_total{op}` / `object_store_op_seconds{op}` ride
# alongside the process totals; crc-retry counts the read-path's one
# re-read of a checksum-mismatched object before it is declared durably
# corrupt, quarantined and (when a backup is attached) restored.
OBJECT_RETRIES = GLOBAL_METRICS.counter("object_store_retries_total")
OBJECT_TMP_SWEPT = GLOBAL_METRICS.counter("object_store_tmp_swept_total")
STORAGE_CRC_RETRIES = GLOBAL_METRICS.counter(
    "storage_crc_retries_total")
STORAGE_QUARANTINED = GLOBAL_METRICS.gauge("storage_quarantined_objects")
STORAGE_RESTORED = GLOBAL_METRICS.counter(
    "storage_restored_from_backup_total")
# Background scrubber (state/scrub.py, barrier-paced by the coordinator):
# objects verified, corruptions found, orphan SSTs currently visible
# (uploaded by a crashed/aborted checkpoint, referenced by no manifest)
# and orphans actually swept after the two-sighting grace.
STORAGE_SCRUB_PASSES = GLOBAL_METRICS.counter("storage_scrub_passes_total")
STORAGE_SCRUB_OBJECTS = GLOBAL_METRICS.counter(
    "storage_scrub_objects_total")
STORAGE_SCRUB_CORRUPTIONS = GLOBAL_METRICS.counter(
    "storage_scrub_corruptions_total")
STORAGE_ORPHAN_OBJECTS = GLOBAL_METRICS.gauge("storage_orphan_objects")
STORAGE_ORPHANS_SWEPT = GLOBAL_METRICS.counter(
    "storage_orphan_swept_total")
# Backup plane (state/backup.py): generation-stamped incremental backups;
# objects copied vs skipped-as-already-backed-up per run, and the last
# generation written (gauge — SHOW storage reads it too).
BACKUP_OBJECTS_COPIED = GLOBAL_METRICS.counter(
    "backup_objects_copied_total")
BACKUP_OBJECTS_SKIPPED = GLOBAL_METRICS.counter(
    "backup_objects_skipped_total")
BACKUP_GENERATION = GLOBAL_METRICS.gauge("backup_last_generation")

# Compaction & retention plane (state/compactor.py): background merges
# off the commit path. Bytes rewritten + run count are the write-
# amplification record; the L0/read-amp gauges are the health line the
# soak gate asserts bounded; the per-source retention floor gauges show
# WHAT is holding GC back (-1 = source pins nothing).
COMPACTION_RUNS = GLOBAL_METRICS.counter("compaction_runs_total")
COMPACTION_BYTES_REWRITTEN = GLOBAL_METRICS.counter(
    "compaction_bytes_rewritten_total")
COMPACTION_SECONDS = GLOBAL_METRICS.histogram("compaction_seconds")
LSM_L0_RUNS = GLOBAL_METRICS.gauge("lsm_l0_runs")
LSM_READ_AMP = GLOBAL_METRICS.gauge("lsm_read_amp")
RETENTION_SEGMENTS_DROPPED = GLOBAL_METRICS.counter(
    "broker_retention_segments_dropped_total")


def retention_floor_gauge(source: str):
    """Per-pin-source floor gauge `retention_floor_epoch{source=...}` —
    labelled series ride the registry on demand (registry dedups by
    (name, labels), so this is idempotent)."""
    return GLOBAL_METRICS.gauge("retention_floor_epoch", source=source)


# Source split observability (stream/source.py): per-split labelled
# gauges `source_split_offset{source,split}` (rows consumed by the
# split, refreshed at barrier cadence) and `source_lag_rows{source,
# split}` (broker high watermark minus consumed offset, from the
# connector's CACHED watermark — external-ingress backlog). Labelled
# series ride the registry on demand; they die with the executor.
SINK_DELIVERED_EPOCHS = GLOBAL_METRICS.counter("sink_delivered_epochs_total")
SINK_DELIVERED_ROWS = GLOBAL_METRICS.counter("sink_delivered_rows_total")

"""Deterministic fault injection — the chaos harness's control surface.

Reference: the reference engine gates its recovery tests on failpoints
(`fail::fail_point!` sites compiled into meta/compute, armed per test by
name — e.g. the barrier-recovery suite in meta/src/barrier/recovery.rs
drives injected actor panics and storage errors). Same shape here: a
process-global `FAULTS` injector with NAMED fault points compiled into
the few places a real failure enters the system, armed from SQL
(`SET fault_injection = '...'`) and consumed by `scripts/chaos_profile.py`
plus the recovery tests.

Fault points (site → effect when the rule fires):

  actor_crash     stream/actor.py, at barrier receipt — the actor raises
                  before dispatching the barrier (an executor exception
                  at epoch N; filter `actor=`/`epoch=`)
  poison_chunk    stream/exchange.py ChannelInput — the CONSUMER raises
                  on the matching received chunk (a corrupt payload
                  kills the fragment that read it, not the producer)
  channel_stall   stream/exchange.py ChannelInput — the consumer parks
                  `ms=` milliseconds on the matching chunk (exercises
                  the stuck-barrier watchdog without a crash)
  upload_fail     meta/barrier_manager.py uploader — the checkpoint
                  upload raises (fail-stop parks, next injection
                  triggers full recovery from the committed epoch)
  upload_delay    same site, sleeps `ms=` before the upload
  recovery_crash  frontend/session.py — a crash DURING recovery itself
                  (mid DDL replay on the full path, mid rebuild on the
                  partial path; `phase=` filters full|partial)
  broker_fetch_fail   connectors/broker.py BrokerPartitionConnector —
                  the source's partition fetch raises (the consuming
                  actor dies -> fail-stop -> auto-recovery reseeks the
                  committed offset; filter `topic=`/`partition=`)
  broker_append_fail  connectors/broker.py BrokerSink — the sink's
                  topic append raises (delivery parks on the hub,
                  fail-stops the next injection exactly like an upload
                  failure; the re-delivered batch dedupes on the seq
                  persisted in the topic; filter `topic=`/`seq=`)
  compaction_merge    state/compactor.py _merge — the background
                  merge thread raises before rewriting (exercises the
                  orphan-at-worst invariant: the planned task abandons,
                  the trigger refires; filter `sst_id=`)
  object_put_fail state/object_store.py ResilientObjectStore — an
                  object PUT raises a TRANSIENT error below the retry
                  layer: with occurrence counts under the retry budget
                  the wrapper absorbs it (object_store_retries_total
                  bumps, ZERO recoveries); past the budget it surfaces
                  as ObjectStoreUnavailable and takes the existing
                  fail-stop path (filter `path=`/`kind=`sst|manifest|
                  catalog|dict|other/`attempt=`)
  object_get_fail same site, for object GETs (manifest loads, scrub
                  verifies, cluster commit reads)
  object_get_corrupt  same site — the GET succeeds but the returned
                  payload is corrupted AFTER the retry layer, so the
                  CALLER's checksum path runs: an SST/manifest reader
                  re-reads once (transient torn-cache/media model);
                  `times=` high enough makes the corruption durable —
                  quarantine + restore-from-backup (state/hummock.py)
  dcn_drop        stream/remote_exchange.py RemoteOutput.send (WORKER
                  process; the spec rides the cluster config push) —
                  severs one DCN output leg mid-epoch by closing its
                  socket: the producer parks on the dead leg, the
                  consumer dies on the disconnect and its worker
                  reports the failed actor ids, and per-worker partial
                  recovery rewinds the leg (filter `port=`)
  worker_crash_partial  cluster/compute_node.py _on_committed (WORKER
                  process) — hard-kills the worker (os._exit) at the
                  k-th sealed report (`at=k`; context `seals=` carries
                  the running count), so a real mid-epoch worker death
                  is deterministic: meta's connection loss marks the
                  handle dead and the worker radius re-places its
                  actors onto the survivors

Spec grammar (one statement, deterministic by construction — rules fire
on exact occurrence counts, never on wall clock):

    SET fault_injection = 'point[:k=v[,k=v ...]][;point ...]'
    SET fault_injection = ''                -- disarm

Per-rule keys: `at=N` fires on the Nth MATCHING hit (1-based, default 1);
`times=M` keeps firing for M consecutive matching hits (default 1);
`ms=N` the delay for stall/delay points; any other key is a context
filter — the rule matches only calls whose context carries that exact
value (e.g. `actor=3`, `epoch=42`, `phase=full`). A global `seed=N` rule
seeds the RNG used by the optional `prob=P` key (probabilistic faults
for soak runs; the CI gate uses exact counts only).

Hot-path contract: every site guards with `if FAULTS.active:` — one
attribute read when disarmed, no allocation, no call.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Optional


class FaultInjected(RuntimeError):
    """Raised at a fault point — deliberately a RuntimeError subclass so
    the failure takes the exact path a real actor/upload error takes."""


@dataclass
class FaultRule:
    point: str
    filters: dict = field(default_factory=dict)   # ctx key -> required value
    at: int = 1           # fire on the at-th matching hit (1-based)
    times: int = 1        # keep firing for this many matching hits
    prob: Optional[float] = None
    params: dict = field(default_factory=dict)    # ms=... etc.
    hits: int = 0         # matching hits seen
    fired: int = 0        # times actually fired

    def matches(self, ctx: dict) -> bool:
        return all(ctx.get(k) == v for k, v in self.filters.items())

    @property
    def exhausted(self) -> bool:
        return self.prob is None and self.fired >= self.times


def _parse_value(v: str):
    try:
        return int(v)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


class FaultInjector:
    """Process-global, armed per session via `SET fault_injection`."""

    # keys consumed by the injector itself; everything else is a filter
    _CONTROL = ("at", "times", "prob", "ms")

    def __init__(self):
        self.active = False
        self.rules: list[FaultRule] = []
        self.fired_log: list[tuple[str, dict]] = []
        self._rng = random.Random(0)

    # --------------------------------------------------------------- arm
    def arm(self, spec: str) -> None:
        """Parse and install the rule set ('' disarms). Raises ValueError
        on a malformed spec so `SET` rejects it at statement time."""
        rules: list[FaultRule] = []
        seed = 0
        for part in (spec or "").split(";"):
            part = part.strip()
            if not part:
                continue
            point, _, argstr = part.partition(":")
            point = point.strip()
            kv: dict = {}
            for item in filter(None,
                               (s.strip() for s in argstr.split(","))):
                k, eq, v = item.partition("=")
                if not eq:
                    raise ValueError(
                        f"fault_injection: expected k=v, got {item!r}")
                kv[k.strip()] = _parse_value(v.strip())
            if point == "seed":
                seed = int(kv.get("value", 0))
                continue
            rule = FaultRule(
                point,
                filters={k: v for k, v in kv.items()
                         if k not in self._CONTROL},
                at=int(kv.get("at", 1)),
                times=int(kv.get("times", 1)),
                prob=kv.get("prob"),
                params={k: kv[k] for k in ("ms",) if k in kv})
            if rule.at < 1 or rule.times < 1:
                raise ValueError("fault_injection: at/times must be >= 1")
            rules.append(rule)
        self._rng = random.Random(seed)
        self.rules = rules
        self.fired_log = []
        self.active = bool(rules)

    def disarm(self) -> None:
        self.rules = []
        self.active = False

    # --------------------------------------------------------------- hit
    def hit(self, point: str, **ctx) -> Optional[dict]:
        """A fault point reports one occurrence. Returns the firing
        rule's params (the site raises/sleeps as appropriate) or None.
        Counting is per rule over MATCHING occurrences, so `at=N` is
        deterministic for any deterministic call sequence."""
        if not self.active:
            return None
        for r in self.rules:
            if r.point != point or not r.matches(ctx):
                continue
            r.hits += 1
            if r.prob is not None:
                if self._rng.random() >= r.prob:
                    continue
            elif not (r.at <= r.hits < r.at + r.times):
                continue
            r.fired += 1
            self.fired_log.append((point, dict(ctx)))
            if all(x.exhausted for x in self.rules):
                # cheap steady state once every rule has fired out
                self.active = False
            return dict(r.params)
        return None


# the process-default injector (sites import this; Session arms it)
FAULTS = FaultInjector()

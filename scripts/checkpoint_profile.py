"""Checkpoint-pipeline micro-harness — q7-shaped durable run, no TPU.

Sibling of dispatch_profile.py: a canned tumble-window MAX(price) agg over
nexmark bids (the q7 window side) runs DURABLY against a Hummock store
whose object-store uploads are artificially slowed (the stand-in for the
tunneled link / remote object store), in two modes:

  inline     checkpoint_max_inflight=0 — store.sync() on the barrier
             path, every checkpoint stalls the stream for build+upload
  pipelined  checkpoint_max_inflight=2 — barriers complete at seal; the
             background uploader builds/uploads/commits behind the stream

Prints barrier p50 (inject -> collected) for both modes and exits
non-zero unless BOTH hold:

  * the pipelined barrier p50 is STRICTLY below the inline one (i.e. the
    SST build/upload cost left the barrier critical path), and
  * committed_epoch ordering was never violated (manifest swaps strictly
    in epoch order, store committed epoch == last committed).

CI usage (CPU backend):

    JAX_PLATFORMS=cpu python scripts/checkpoint_profile.py
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compilation cache (utils/compile_cache.py): the
# gate re-runs a canned shape every CI round — repeat runs skip the
# compile entirely
from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")


UPLOAD_DELAY_S = 0.04     # simulated object-store PUT latency per SST
WARMUP_ROUNDS = 2
MEASURE_ROUNDS = 10
WINDOW_US = 1_000_000


class SlowObjectStore:
    """In-memory object store with a fixed per-SST upload delay — the
    canned stand-in for a remote object store / tunneled device link.
    Manifest swaps stay fast (they are one small PUT in production too)."""

    def __init__(self, inner, delay_s: float):
        self._inner = inner
        self.delay_s = delay_s

    def upload(self, name, data):
        if name.startswith("ssts/"):
            time.sleep(self.delay_s)
        return self._inner.upload(name, data)

    def __getattr__(self, name):
        return getattr(self._inner, name)


def _build_pipeline(store):
    """q7's window side: bid -> project(window_end) -> MAX(price) by
    window_end -> materialize, all durable on `store`."""
    from risingwave_tpu.common import DataType, schema
    from risingwave_tpu.connectors import NexmarkGenerator
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.expr import call, col, lit
    from risingwave_tpu.expr.agg import agg_max
    from risingwave_tpu.state import StateTable
    from risingwave_tpu.stream import (
        HashAggExecutor, MaterializeExecutor, SourceExecutor,
    )
    from risingwave_tpu.stream.project import ProjectExecutor

    barrier_q = asyncio.Queue()
    gen = NexmarkGenerator("bid", chunk_size=256,
                           cfg=NexmarkConfig(inter_event_us=10_000))
    offsets = StateTable(
        store, table_id=1,
        schema=schema(("source_id", DataType.INT64),
                      ("offset", DataType.INT64)),
        pk_indices=[0])
    src = SourceExecutor(1, gen, barrier_q, state_table=offsets)
    # window_end = ts - ts % W + W (the TUMBLE the q7 planner emits)
    win = call("add", call("subtract", col(5),
                           call("modulus", col(5), lit(WINDOW_US))),
               lit(WINDOW_US))
    proj = ProjectExecutor(src, [col(0), col(2), win],
                           names=["auction", "price", "window_end"])
    agg_table = StateTable(
        store, table_id=2,
        schema=schema(("window_end", DataType.INT64),
                      ("maxprice", DataType.INT64),
                      ("_row_count", DataType.INT64)),
        pk_indices=[0])
    agg = HashAggExecutor(
        proj, group_key_indices=[2],
        agg_calls=[agg_max(1, append_only=True)],
        capacity=1 << 12, state_table=agg_table)
    mv = StateTable(store, table_id=3, schema=agg.schema,
                    pk_indices=list(agg.pk_indices))
    mat = MaterializeExecutor(agg, mv)
    return barrier_q, gen, mat


async def _run_mode(max_inflight: int) -> dict:
    from risingwave_tpu.meta import BarrierCoordinator
    from risingwave_tpu.state.hummock import HummockStateStore
    from risingwave_tpu.state.object_store import InMemObjectStore
    from risingwave_tpu.stream import Actor

    store = HummockStateStore(
        SlowObjectStore(InMemObjectStore(), UPLOAD_DELAY_S))
    barrier_q, gen, mat = _build_pipeline(store)
    coord = BarrierCoordinator(store, checkpoint_max_inflight=max_inflight)
    coord.register_source(barrier_q)
    coord.register_actor(1)
    task = Actor(1, mat, None, coord).spawn()

    await coord.run_rounds(WARMUP_ROUNDS)
    n_warm = len(coord.latencies_ns)
    for _ in range(MEASURE_ROUNDS):
        await asyncio.sleep(0.005)
        b = await coord.inject_barrier()
        await coord.wait_collected(b)
    measured = sorted(coord.latencies_ns[n_warm:])
    p50_s = measured[len(measured) // 2] / 1e9
    await coord.stop_all({1})
    await task

    # ---- ordering gates: manifest swaps strictly in epoch order ----
    commits = coord.committed_epochs
    ordered = all(a < b for a, b in zip(commits, commits[1:]))
    all_committed = (store.committed_epoch() == commits[-1]
                     if commits else False)
    no_leftover = not store._sealed
    return {
        "mode": "pipelined" if max_inflight else "inline",
        "checkpoint_max_inflight": max_inflight,
        "rounds": MEASURE_ROUNDS,
        "barrier_p50_s": round(p50_s, 4),
        "rows": gen.offset,
        "committed_epochs": len(commits),
        "commit_order_ok": bool(ordered and all_committed and no_leftover),
        "upload_overlap_pct": coord.upload_overlap_pct(),
    }


async def main() -> int:
    inline = await _run_mode(0)
    pipelined = await _run_mode(2)
    verdict = {
        "barrier_p50_speedup": round(
            inline["barrier_p50_s"]
            / max(pipelined["barrier_p50_s"], 1e-9), 2),
        "pipelined_strictly_below_inline":
            pipelined["barrier_p50_s"] < inline["barrier_p50_s"],
        "commit_order_ok": (inline["commit_order_ok"]
                            and pipelined["commit_order_ok"]),
        "upload_overlap_pct": pipelined["upload_overlap_pct"],
    }
    print(json.dumps(inline))
    print(json.dumps(pipelined))
    print(json.dumps({"verdict": verdict}))
    ok = (verdict["pipelined_strictly_below_inline"]
          and verdict["commit_order_ok"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

"""Log-store egress gate — exactly-once file sink on a q7-shaped run.

Canned tumble-window MAX(price) over nexmark bids (the q7 window side)
runs DURABLY against a Hummock store, twice:

  baseline   CREATE SINK ... WITH (connector='blackhole')   — the legacy
             direct at-barrier delivery of a free target (no file I/O)
  logstore   CREATE SINK ... WITH (connector='file')        — the
             exactly-once path: epoch batches persist WITH the
             checkpoint, a background task delivers (write + fsync per
             entry) AFTER the commit, cursor + truncation ride the next
             checkpoint

Exits non-zero unless ALL hold:

  * delivery off the critical path: the logstore run's barrier p50 is
    within 10% of the baseline's (an on-path fsync per epoch would blow
    far past that);
  * exactly-once across an injected crash: the run is killed mid-stream
    (session.crash()) and recovered; afterwards the delivered log-store
    sequence numbers are dense and duplicate-free, and REPLAYING the
    delivered changelog is self-consistent — every retraction matches a
    live row (a duplicated epoch double-inserts, a dropped epoch leaves
    later retractions dangling) and the final state holds exactly one
    row per window.

CI usage (CPU backend):

    JAX_PLATFORMS=cpu python scripts/logstore_profile.py
"""

import asyncio
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

WARMUP_ROUNDS = 4
MEASURE_ROUNDS = 40
WINDOW_US = 1_000_000
P50_HEADROOM = 1.10


def _sink_sql(connector_clause: str) -> list[str]:
    return [
        "SET streaming_watchdog = 0",
        ("CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
         "chunk_size=256, inter_event_us=2000, rate_limit=2048)"),
        ("CREATE SINK q7w AS "
         "SELECT window_end, max(price) AS maxprice "
         f"FROM TUMBLE(bid, date_time, {WINDOW_US}) GROUP BY window_end "
         f"WITH ({connector_clause})"),
    ]


async def _measure(session) -> float:
    coord = session.coord
    await session.tick(WARMUP_ROUNDS)
    n_warm = len(coord.latencies_ns)
    for _ in range(MEASURE_ROUNDS):
        await asyncio.sleep(0.002)
        b = await coord.inject_barrier()
        await coord.wait_collected(b)
    xs = sorted(coord.latencies_ns[n_warm:])
    return xs[len(xs) // 2] / 1e9


async def _run_baseline(tmp) -> dict:
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    s = Session(store=HummockStateStore(
        LocalFsObjectStore(os.path.join(tmp, "base"))))
    for sql in _sink_sql("connector='blackhole'"):
        await s.execute(sql)
    p50 = await _measure(s)
    await s.coord.drain_uploads()
    await s.drop_all()
    return {"mode": "baseline_blackhole_direct",
            "barrier_p50_s": round(p50, 5)}


async def _run_logstore(tmp) -> dict:
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    data = os.path.join(tmp, "log")
    out = os.path.join(tmp, "q7w.jsonl")
    s = Session(store=HummockStateStore(LocalFsObjectStore(data)))
    for sql in _sink_sql(f"connector='file', path='{out}'"):
        await s.execute(sql)
    p50 = await _measure(s)

    # ---- injected crash: kill everything mid-stream, recover, go on ----
    await s.crash()
    s2 = Session(store=HummockStateStore(LocalFsObjectStore(data)))
    await s2.recover()
    await s2.tick(6, max_recoveries=3)
    delivered = s2.coord.logstore.sinks["q7w"].delivered_epochs
    await s2.drop_all()

    # ---- exactly-once verification over the delivered changelog ----
    recs = [json.loads(ln) for ln in open(out) if ln.strip()]
    seqs = [r["seq"] for r in recs]
    seq_ok = seqs == list(range(1, len(seqs) + 1))
    live: Counter = Counter()
    dangling = 0
    for r in recs:
        for op, vals in r["rows"]:
            key = tuple(vals)
            if op in (1, 2):          # DELETE / UPDATE_DELETE
                if live[key] <= 0:
                    dangling += 1     # retraction of an absent row:
                    #                   a dropped or doubled epoch
                else:
                    live[key] -= 1
            else:
                live[key] += 1
    windows = [k[0] for k, n in live.items() for _ in range(n)]
    one_per_window = len(windows) == len(set(windows)) and len(windows) > 0
    return {
        "mode": "logstore_exactly_once_file",
        "barrier_p50_s": round(p50, 5),
        "entries_delivered": len(recs),
        "delivered_after_recovery": delivered,
        "seq_dense_unique": bool(seq_ok),
        "replay_consistent": dangling == 0,
        "one_row_per_window": bool(one_per_window),
        "windows": len(windows),
    }


async def main() -> int:
    import tempfile
    tmp = tempfile.mkdtemp(prefix="logstore_profile_")
    base = await _run_baseline(tmp)
    log = await _run_logstore(tmp)
    overhead = log["barrier_p50_s"] / max(base["barrier_p50_s"], 1e-9)
    verdict = {
        "p50_ratio_logstore_vs_baseline": round(overhead, 3),
        "delivery_off_critical_path": overhead <= P50_HEADROOM,
        "exactly_once_across_crash": bool(
            log["seq_dense_unique"] and log["replay_consistent"]
            and log["one_row_per_window"]
            and log["entries_delivered"] > 0),
    }
    print(json.dumps(base))
    print(json.dumps(log))
    print(json.dumps({"verdict": verdict}))
    ok = (verdict["delivery_off_critical_path"]
          and verdict["exactly_once_across_crash"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

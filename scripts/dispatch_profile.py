"""Dispatch/recompile micro-harness — q7-shaped pipeline, no TPU needed.

Prints device dispatches per barrier interval and recompiles after
warmup for a canned windowed-agg + join pipeline fed many SMALL chunks
per interval, in two modes:

  baseline   per-chunk applies (chunk batching off, no coalescing)
  optimized  ChunkCoalescer packs the runs + hash_agg/hash_join scan
             multiple chunks per dispatch

The counters come from ops/jit_state.py (every jitted step program in the
engine routes through it), so the numbers cover the WHOLE chain, not a
single executor. Future PRs run this on the CPU backend to spot dispatch
regressions without a TPU:

    JAX_PLATFORMS=cpu python scripts/dispatch_profile.py

Exit status is 0 iff the optimized mode both reduces dispatches per
interval and performs zero recompiles after warmup.
"""

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compilation cache (utils/compile_cache.py): the
# gate re-runs a canned shape every CI round — repeat runs skip the
# compile entirely
from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


N_INTERVALS = 8
WARMUP_INTERVALS = 3
CHUNKS_PER_INTERVAL = 6
CHUNK_CAP = 256          # deliberately small: the dispatch-bound regime
WINDOW = 1 << 10


def _metrics():
    from risingwave_tpu.utils.metrics import GLOBAL_METRICS
    snap = GLOBAL_METRICS.snapshot()

    def total(name):
        return sum(e["value"] for e in snap.get(name, [])
                   if not e["labels"])

    return total("device_dispatch_count"), total("jit_compile_count")


def _bid_schema():
    from risingwave_tpu.common import DataType, schema
    return schema(("auction", DataType.INT64), ("price", DataType.INT64),
                  ("ts", DataType.INT64))


def _chunks(epoch: int, rng) -> list:
    """One interval's worth of small bid-shaped chunks, varying
    cardinality (and therefore visibility masks) per chunk."""
    from risingwave_tpu.common.chunk import StreamChunk
    sch = _bid_schema()
    out = []
    base_ts = epoch * WINDOW * 4
    for i in range(CHUNKS_PER_INTERVAL):
        n = int(rng.randint(CHUNK_CAP // 4, CHUNK_CAP))
        auction = rng.randint(0, 50, size=n).astype(np.int64)
        price = rng.randint(1, 2_000, size=n).astype(np.int64)
        ts = (base_ts + rng.randint(0, WINDOW * 4, size=n)).astype(np.int64)
        out.append(StreamChunk.from_numpy(
            sch, [auction, price, ts], capacity=CHUNK_CAP))
    return out


class _Script:
    """Async source over a scripted message list."""

    def __init__(self, sch, messages):
        self.schema = sch
        self.messages = messages
        self.identity = "DispatchProfileSource"
        self.pk_indices = ()

    def fence_tokens(self):
        return []

    async def execute(self):
        for m in self.messages:
            yield m
            await asyncio.sleep(0)


def _script_messages(seed: int) -> list:
    from risingwave_tpu.common.epoch import EpochPair
    from risingwave_tpu.stream.message import Barrier, BarrierKind
    rng = np.random.RandomState(seed)
    msgs = [Barrier(EpochPair(1, 0), BarrierKind.INITIAL)]
    for e in range(2, 2 + N_INTERVALS):
        msgs.extend(_chunks(e, rng))
        msgs.append(Barrier(EpochPair(e, e - 1)))
    return msgs


def _coalesce_messages(msgs, max_capacity):
    """Receiver-side packing, exactly what ChannelInput/Merge do with
    SET streaming_chunk_coalesce (stream/exchange.py)."""
    from risingwave_tpu.common.chunk import ChunkCoalescer, StreamChunk
    co = ChunkCoalescer(max_capacity)
    out = []
    for m in msgs:
        if isinstance(m, StreamChunk):
            out.extend(co.push(m))
        else:
            out.extend(co.flush())
            out.append(m)
    return out


async def _run_pipeline(optimized: bool) -> dict:
    """q7 shape: bids -> window max agg; agg output JOINed back against
    the bid stream on price (hash join) -> counted sink."""
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.expr.agg import AggCall, AggKind
    from risingwave_tpu.stream import HashAggExecutor
    from risingwave_tpu.stream.hash_join import HashJoinExecutor
    from risingwave_tpu.stream.message import Barrier
    from risingwave_tpu.stream.project import ProjectExecutor
    from risingwave_tpu.expr import call, col, lit

    sch = _bid_schema()
    left_msgs = _script_messages(seed=7)
    right_msgs = _script_messages(seed=7)
    if optimized:
        left_msgs = _coalesce_messages(left_msgs, 8 * CHUNK_CAP)
        right_msgs = _coalesce_messages(right_msgs, 8 * CHUNK_CAP)

    # window_end = ts - ts % W + W, projected in front of the agg
    win = call("add", call("subtract", col(2),
                           call("modulus", col(2), lit(WINDOW))),
               lit(WINDOW))
    proj = ProjectExecutor(_Script(sch, right_msgs),
                           [col(0), col(1), win])
    agg = HashAggExecutor(
        proj, [2], [AggCall(AggKind.MAX, 1, sch[1].data_type,
                            append_only=True)],
        capacity=1 << 12)
    join = HashJoinExecutor(
        _Script(sch, left_msgs), agg,
        left_key_indices=[1], right_key_indices=[1],
        left_pk_indices=[0, 2], right_pk_indices=[0],
        key_capacity=1 << 12, row_capacity=1 << 14, match_factor=64)
    if not optimized:
        agg._use_chunk_batching = False
        join._use_chunk_batching = False

    d0, c0 = _metrics()
    warm_d = warm_c = None
    intervals = 0
    rows = 0
    async for msg in join.execute():
        if isinstance(msg, StreamChunk):
            rows += int(np.asarray(msg.vis).sum())
        elif isinstance(msg, Barrier):
            intervals += 1
            if intervals == WARMUP_INTERVALS + 1:   # +1 = INITIAL barrier
                warm_d, warm_c = _metrics()
    d1, c1 = _metrics()
    steady_intervals = intervals - (WARMUP_INTERVALS + 1)
    return {
        "mode": "optimized" if optimized else "baseline",
        "intervals": intervals - 1,
        "chunks_per_interval": CHUNKS_PER_INTERVAL,
        "join_rows": rows,
        "dispatches_total": d1 - d0,
        "dispatches_per_interval_steady": round(
            (d1 - warm_d) / max(steady_intervals, 1), 2),
        "recompiles_after_warmup": c1 - warm_c,
        "compiles_total": c1 - c0,
    }


async def main() -> int:
    base = await _run_pipeline(optimized=False)
    opt = await _run_pipeline(optimized=True)
    verdict = {
        "dispatch_reduction": round(
            base["dispatches_per_interval_steady"]
            / max(opt["dispatches_per_interval_steady"], 1e-9), 2),
        "zero_recompiles_after_warmup":
            opt["recompiles_after_warmup"] == 0,
        "rows_match": base["join_rows"] == opt["join_rows"],
    }
    print(json.dumps(base))
    print(json.dumps(opt))
    print(json.dumps({"verdict": verdict}))
    ok = (opt["dispatches_per_interval_steady"]
          < base["dispatches_per_interval_steady"]
          and verdict["zero_recompiles_after_warmup"]
          and verdict["rows_match"])
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

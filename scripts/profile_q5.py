"""Microbenchmark the q5 pipeline pieces on the current jax backend."""

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from risingwave_tpu.connectors import NexmarkGenerator
from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.stream import HashAggExecutor, HopWindowExecutor
from risingwave_tpu.stream.executor import Executor


class Dummy(Executor):
    def __init__(self, schema):
        self.schema = schema


def t(label, f, n=20):
    f()  # warmup/compile
    jax.block_until_ready(f())
    t0 = time.perf_counter()
    for _ in range(n):
        out = f()
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / n
    print(f"{label:35s} {dt*1e3:9.3f} ms")
    return dt


def main(chunk_size=16384):
    print("devices:", jax.devices())
    gen = NexmarkGenerator("bid", chunk_size=chunk_size,
                           cfg=NexmarkConfig(inter_event_us=1000))
    chunk = gen.next_chunk()
    t("source gen", lambda: gen.next_chunk())

    hop = HopWindowExecutor(Dummy(gen.schema), time_col=5,
                            window_slide_us=2_000_000, window_size_us=10_000_000)
    t("hop step (full expansion)", lambda: hop._step(chunk))
    hchunk = hop._step(chunk)

    agg = HashAggExecutor(Dummy(hop.schema), group_key_indices=[0, hop.window_start_idx],
                          agg_calls=[count_star(append_only=True)], capacity=1 << 16)
    d_apply = t("agg apply (16k rows)", lambda: agg._apply(agg.state, hchunk))
    st, n_un, occ = agg._apply(agg.state, hchunk)
    print("  unresolved:", int(n_un), " occupied:", int(occ))
    agg.state = st
    d_flush = t("agg flush", lambda: agg._flush(agg.state), n=5)
    d_lz = t("live/zombie check", lambda: agg._live_zombie(agg.state))

    total_per_chunk = 5 * (0 + d_apply) + 0  # 5 hop windows each applied
    print(f"\nestimated apply-only throughput: "
          f"{chunk_size / (5 * d_apply):,.0f} rows/s")
    print(f"flush per barrier: {d_flush*1e3:.1f} ms")


if __name__ == "__main__":
    main()

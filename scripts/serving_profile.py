"""Serving-layer gate — mixed workload, no TPU needed.

One in-memory session runs streaming ingest (CREATE TABLE + INSERT per
barrier round) under an agg MV, then measures the serving read path
three ways:

  scan        SET serving_cache = 0 — every point SELECT re-scans and
              re-decodes the whole MV from the LSM (the pre-serving
              behavior); its p50 is the O(table) reference point
  cached      SET serving_cache = 1 — the same point SELECTs hit the
              snapshot cache's pk index (O(result));
  concurrent  barrier rounds with identical ingest run idle, then again
              under continuous concurrent SELECT load through the
              serving pool; barrier p50 must not degrade materially

Exit status is 0 iff:
  * cached/indexed results are IDENTICAL to the scan path (point
    lookups AND order/limit scan queries),
  * cached point-lookup p50 is >= 10x below the full-scan p50,
  * barrier p50 under concurrent SELECT load stays within 1.5x of the
    idle-serving baseline (concurrent queries must not stall barrier
    injection).

    JAX_PLATFORMS=cpu python scripts/serving_profile.py
"""

import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compilation cache (utils/compile_cache.py): the
# gate re-runs a canned shape every CI round — repeat runs skip the
# compile entirely
from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

N_KEYS = 10_000            # distinct pk values in the MV
ROWS_PER_KEY = 2
INGEST_BATCHES = 10        # initial load, one INSERT+tick per batch
POINT_QUERIES = 40
BARRIER_ROUNDS = 12        # per idle/loaded phase
ROWS_PER_ROUND = 800       # streaming ingest during the barrier phases
LOAD_WORKERS = 4


def _p50(xs):
    xs = sorted(xs)
    return xs[len(xs) // 2] if xs else 0.0


async def main() -> int:
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.frontend import sql as ast

    s = Session()
    await s.execute("CREATE TABLE items (k int64, v int64)")
    await s.execute(
        "CREATE MATERIALIZED VIEW magg AS SELECT k, count(*) AS n, "
        "sum(v) AS sv FROM items GROUP BY k")

    total = N_KEYS * ROWS_PER_KEY
    per_batch = total // INGEST_BATCHES
    row = 0
    for _ in range(INGEST_BATCHES):
        vals = ", ".join(
            f"({(row + i) % N_KEYS}, {(row + i) * 7 % 1000})"
            for i in range(per_batch))
        await s.execute(f"INSERT INTO items VALUES {vals}")
        row += per_batch
        await s.tick(1)

    async def drain_ingest(expected):
        """The jsonl table source tails its file a bounded number of
        rows per barrier; tick until everything inserted is
        materialized, so the equivalence phases compare STABLE data."""
        from risingwave_tpu.frontend.batch import run_batch_select_full
        for _ in range(400):
            n = run_batch_select_full(
                s.catalog,
                ast.parse("SELECT count(*) AS c FROM items"))[2][0][0]
            if n >= expected:
                return
            await s.tick(1)
        raise RuntimeError(f"ingest never drained ({n} < {expected})")

    await drain_ingest(total)

    point_sqls = [f"SELECT k, n, sv FROM magg WHERE k = {(i * 97) % N_KEYS}"
                  for i in range(POINT_QUERIES)]
    scan_sqls = [
        "SELECT k, n, sv FROM magg ORDER BY sv DESC, k LIMIT 10",
        "SELECT k, n FROM magg WHERE n > 1 ORDER BY k LIMIT 20 OFFSET 5",
        "SELECT count(*) AS groups, sum(n) AS rows FROM magg",
    ]

    async def run_queries(sqls):
        out, lats = [], []
        for q in sqls:
            sel = ast.parse(q)
            t0 = time.monotonic()
            rows = (await s.run_serving_select(sel))[2]
            lats.append(time.monotonic() - t0)
            out.append(rows)
        return out, lats

    # ---- scan baseline (cache off) --------------------------------------
    await s.execute("SET serving_cache = 0")
    await run_queries(point_sqls[:4])                 # warmup
    scan_point, scan_lats = await run_queries(point_sqls)
    scan_scan_rows, _ = await run_queries(scan_sqls)

    # ---- cached (cache on) ----------------------------------------------
    await s.execute("SET serving_cache = 1")
    s.query(point_sqls[0])                            # first touch -> wanted
    await s.tick(1)                                   # cache builds here
    await run_queries(point_sqls[:4])                 # warmup
    cached_point, cached_lats = await run_queries(point_sqls)
    cached_scan_rows, _ = await run_queries(scan_sqls)

    from risingwave_tpu.utils.metrics import SERVING_POINT_LOOKUPS
    point_lookups = SERVING_POINT_LOOKUPS.value

    scan_p50 = _p50(scan_lats)
    cached_p50 = _p50(cached_lats)
    speedup = scan_p50 / cached_p50 if cached_p50 else float("inf")
    identical = (scan_point == cached_point
                 and scan_scan_rows == cached_scan_rows)

    # ---- barrier latency: idle vs under concurrent SELECT load ----------
    async def ingest_rounds(n):
        nonlocal row
        for _ in range(n):
            vals = ", ".join(
                f"({(row + i) % N_KEYS}, {(row + i) * 7 % 1000})"
                for i in range(ROWS_PER_ROUND))
            await s.execute(f"INSERT INTO items VALUES {vals}")
            row += ROWS_PER_ROUND
            await s.tick(1)

    mark = len(s.coord.latencies_ns)
    await ingest_rounds(BARRIER_ROUNDS)
    idle_lat = [x / 1e9 for x in s.coord.latencies_ns[mark:]]

    stop = asyncio.Event()

    async def load_worker(i):
        sels = [ast.parse(point_sqls[(i * 5 + j) % len(point_sqls)])
                for j in range(5)] + [ast.parse(scan_sqls[i % 2])]
        served = 0
        while not stop.is_set():
            for sel in sels:
                await s.run_serving_select(sel)
                served += 1
            await asyncio.sleep(0)
        return served

    workers = [asyncio.create_task(load_worker(i))
               for i in range(LOAD_WORKERS)]
    await asyncio.sleep(0.05)                 # load is flowing
    mark = len(s.coord.latencies_ns)
    await ingest_rounds(BARRIER_ROUNDS)
    loaded_lat = [x / 1e9 for x in s.coord.latencies_ns[mark:]]
    stop.set()
    served = sum(await asyncio.gather(*workers))

    idle_p50 = _p50(idle_lat)
    loaded_p50 = _p50(loaded_lat)
    barrier_ratio = loaded_p50 / idle_p50 if idle_p50 else float("inf")

    verdict = {
        "mv_rows": N_KEYS,
        "scan_point_p50_ms": round(scan_p50 * 1e3, 3),
        "cached_point_p50_ms": round(cached_p50 * 1e3, 3),
        "point_speedup": round(speedup, 1),
        "point_lookups_indexed": point_lookups,
        "results_identical": identical,
        "idle_barrier_p50_ms": round(idle_p50 * 1e3, 3),
        "loaded_barrier_p50_ms": round(loaded_p50 * 1e3, 3),
        "barrier_ratio": round(barrier_ratio, 2),
        "concurrent_queries_served": served,
        "serving_report": s.coord.serving.report(),
    }
    print(json.dumps({"verdict": verdict}, default=str))
    ok = (identical
          and speedup >= 10.0
          and point_lookups >= POINT_QUERIES
          and barrier_ratio <= 1.5
          and served > 0)
    await s.drop_all()
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

"""Broker gate — engine↔engine exactly-once pipelines through a real
broker process (ISSUE 10 capstone).

A standalone broker (`python -m risingwave_tpu.broker`, real socket)
carries a two-engine pipeline:

    engine A:  nexmark bid -> TUMBLE window MAX(price) -> BrokerSink
    engine B:  BrokerSource (primary_key=window_end) -> MV `out`

run four times: clean, kill engine A mid-stream (crash + catalog
recovery on its durable store), kill engine B the same way, and kill
the BROKER mid-stream (SIGKILL the process, restart on the same data
dir + port). After each run the pipeline quiesces and must satisfy:

  * BIT-IDENTITY: B's MV equals the numpy generator-prefix oracle
    (window_end -> max price) at A's COMMITTED source offset — the
    one-engine answer, end to end through the broker;
  * EXACTLY-ONCE EGRESS: the topic's batch metadata holds DENSE,
    duplicate-free delivery sequence numbers and no re-delivered epoch
    (a duplicated epoch would double a batch, a dropped one would break
    density);
  * the kill runs actually recovered (>= 1 recovery / restart).

Plus the ingest-latency bound: with identical per-barrier rate limits,
the broker-sourced ingest barrier p50 must stay within 3x of the
in-process generator (datagen) path — external ingress is a connector,
not a new bottleneck.

CI usage (CPU backend):

    JAX_PLATFORMS=cpu python scripts/broker_profile.py
"""

import asyncio
import json
import os
import signal
import subprocess
import sys
import tempfile
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

WINDOW_US = 1_000_000
RATE = 512
INGEST_RATE = 2048
INGEST_ROUNDS = 30
P50_RATIO_BOUND = 3.0


# ------------------------------------------------------------ broker proc
class BrokerProc:
    """The real thing: a subprocess serving the broker wire; kill() +
    start() on the same data dir is the broker-restart scenario."""

    def __init__(self, data: str, port: int = 0):
        self.data = data
        self.port = port
        self.proc = None
        self.addr = None

    def start(self) -> str:
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "risingwave_tpu.broker",
             "--data", self.data, "--port", str(self.port)],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            env=env, text=True)
        line = self.proc.stdout.readline()
        info = json.loads(line)
        self.addr = info["broker"]
        self.port = int(self.addr.rsplit(":", 1)[1])
        return self.addr

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)
            self.proc.wait()
        self.proc = None


# ---------------------------------------------------------------- oracle
def _oracle(offset: int) -> Counter:
    """Numpy recount of the bid generator prefix at `offset`:
    window_end -> max(price) — the one-engine answer."""
    import numpy as np
    from risingwave_tpu.connectors import NexmarkGenerator
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    gen = NexmarkGenerator("bid", chunk_size=max(256, offset),
                           cfg=NexmarkConfig(inter_event_us=2000))
    c = gen.next_chunk()
    price = np.asarray(c.columns[2].data)[:offset]
    dt = np.asarray(c.columns[5].data)[:offset]
    we = dt - dt % WINDOW_US + WINDOW_US
    out: Counter = Counter()
    for w in np.unique(we):
        out[(int(w), int(price[we == w].max()))] += 1
    return out


def _committed_offset(session) -> int:
    from risingwave_tpu.state.storage_table import StorageTable
    from risingwave_tpu.stream.source import SourceExecutor
    flows = (list(session.catalog.mvs.values())
             + list(session.catalog.sinks.values()))
    for flow in flows:
        for roots in flow.deployment.roots.values():
            for root in roots:
                node = root
                while node is not None:
                    if isinstance(node, SourceExecutor):
                        rows = list(StorageTable.for_state_table(
                            node.state_table).batch_iter())
                        return int(rows[0][1]) if rows else 0
                    node = getattr(node, "input", None)
    raise AssertionError("no source executor")


def _topic_seqs_epochs(data: str, topic: str):
    """Delivery (seq, epoch) pairs straight from the broker's durable
    batch metadata — read offline (the broker process may be dead)."""
    import struct
    from risingwave_tpu.broker.log import PartitionLog
    pairs = []
    tdir = os.path.join(data, topic)
    for p in sorted(os.listdir(tdir)):
        pl = PartitionLog(os.path.join(tdir, p), fsync=False)
        for _base, _n, seg, pos in pl._index:
            with open(seg, "rb") as f:
                f.seek(pos)
                ln, _crc = struct.unpack("!II", f.read(8))
                body = f.read(ln)
            _b, _nr, ml = struct.unpack_from("!QII", body)
            if ml:
                m = json.loads(body[16:16 + ml])
                pairs.append((m["seq"], m["epoch"]))
    return sorted(pairs)


# -------------------------------------------------------------- pipeline
def _session(path: str):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    return Session(store=HummockStateStore(LocalFsObjectStore(path)))


async def _engine_a(path: str, addr: str, topic: str):
    a = _session(path)
    await a.execute("SET streaming_watchdog = 0")
    if not a.catalog.sinks:
        await a.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
            f"chunk_size=128, inter_event_us=2000, rate_limit={RATE})")
        await a.execute(
            "CREATE SINK q7w AS SELECT window_end, max(price) AS mp "
            f"FROM TUMBLE(bid, date_time, {WINDOW_US}) "
            "GROUP BY window_end "
            f"WITH (connector='broker', topic='{topic}', "
            f"brokers='{addr}')")
    return a


async def _engine_b(path: str, addr: str, topic: str):
    b = _session(path)
    if not b.catalog.mvs:
        await b.execute(
            f"CREATE SOURCE q7 WITH (connector='broker', "
            f"topic='{topic}', brokers='{addr}', "
            "columns='window_end timestamp, mp int64', "
            "primary_key='window_end', chunk_size=64, "
            "discovery_interval_ms=0)")
        await b.execute("CREATE MATERIALIZED VIEW out AS "
                        "SELECT window_end, mp FROM q7")
    return b


async def _recover(path: str):
    s = _session(path)
    await s.recover()
    return s


async def _run_scenario(name: str, tmp: str, broker: BrokerProc) -> dict:
    topic = f"q7w_{name}"
    a_dir = os.path.join(tmp, f"a_{name}")
    b_dir = os.path.join(tmp, f"b_{name}")
    a = await _engine_a(a_dir, broker.addr, topic)
    b = await _engine_b(b_dir, broker.addr, topic)
    recoveries = 0

    await a.tick(3)
    await b.tick(2)

    if name == "kill_a":
        await a.crash()                 # process-kill simulation
        a = await _recover(a_dir)
        recoveries += 1
    elif name == "kill_b":
        await b.tick(1)
        await b.crash()
        b = await _recover(b_dir)
        recoveries += 1
    elif name == "kill_broker":
        broker.kill()                   # SIGKILL mid-stream
        # A's delivery fails against the dead broker -> parks ->
        # fail-stop; recovery cannot complete until the broker is back,
        # so this tick is EXPECTED to fail (that is the scenario)
        try:
            await a.tick(1, max_recoveries=1)
        except RuntimeError:
            pass
        await b.tick(1, max_recoveries=2)   # B just parks (exhausted)
        broker.start()                  # same data dir, same port
        recoveries += 1

    # more traffic THROUGH the recovered topology, then quiesce A
    # (ticks drain sink delivery), then B until its consumed offsets
    # reach the broker's TRUE high watermark (the connector's cached
    # watermark can lag freshly-delivered entries) + a settle tick so
    # the last fetch commits into the MV
    await a.tick(4, max_recoveries=4)
    await b.tick(2, max_recoveries=4)
    await a.tick(1, max_recoveries=4)
    from risingwave_tpu.broker.client import BrokerClient
    c = BrokerClient(broker.addr)
    for _ in range(20):
        await b.tick(1, max_recoveries=4)
        hwm = sum(c.high_watermark(topic=topic, partition=p)
                  for p in range(c.list_partitions(topic=topic)))
        consumed = sum(t[1] for aid in b.coord.source_execs
                       for t in b.coord.source_execs[aid].split_report())
        if consumed >= hwm:
            break
    c.close()
    await b.tick(2, max_recoveries=4)

    offset = _committed_offset(a)
    got = Counter(b.query("SELECT window_end, mp FROM out"))
    expected = _oracle(offset)
    pairs = _topic_seqs_epochs(broker.data, topic)
    seqs = [s for s, _e in pairs]
    epochs = [e for _s, e in pairs]
    out = {
        "scenario": name,
        "offset": offset,
        "mv_rows": sum(got.values()),
        "bit_identical": got == expected,
        "delivered_batches": len(pairs),
        "seqs_dense_unique": seqs == list(range(1, len(seqs) + 1))
        and len(seqs) > 0,
        "no_redelivered_epoch": len(epochs) == len(set(epochs)),
        "killed": bool(recoveries),
    }
    await a.drop_all()
    await b.drop_all()
    return out


# ------------------------------------------------------------- ingest p50
async def _ingest_p50_broker(tmp: str, addr: str) -> float:
    from risingwave_tpu.broker.client import BrokerClient
    c = BrokerClient(addr)
    c.create_topic(topic="ingest", partitions=1)
    rows = [json.dumps({"k": i, "v": i * 3}).encode()
            for i in range(INGEST_RATE * (INGEST_ROUNDS + 8))]
    for i in range(0, len(rows), 8192):
        c.append("ingest", 0, rows[i:i + 8192])
    c.close()
    s = _session(os.path.join(tmp, "ingest_broker"))
    await s.execute("SET streaming_watchdog = 0")
    await s.execute(
        f"CREATE SOURCE ev WITH (connector='broker', topic='ingest', "
        f"brokers='{addr}', columns='k int64, v int64', chunk_size=256, "
        f"rate_limit={INGEST_RATE}, discovery_interval_ms=0, "
        "append_only=1)")
    await s.execute("CREATE MATERIALIZED VIEW m AS SELECT k, v FROM ev")
    p50 = await _measure(s)
    assert len(s.query("SELECT k, v FROM m")) > INGEST_RATE
    await s.drop_all()
    return p50


async def _ingest_p50_datagen(tmp: str) -> float:
    s = _session(os.path.join(tmp, "ingest_datagen"))
    await s.execute("SET streaming_watchdog = 0")
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        f"chunk_size=256, rate_limit={INGEST_RATE})")
    await s.execute(
        "CREATE MATERIALIZED VIEW m AS SELECT auction, price FROM bid")
    p50 = await _measure(s)
    await s.drop_all()
    return p50


async def _measure(s) -> float:
    coord = s.coord
    await s.tick(4)                      # warmup (compiles)
    n_warm = len(coord.latencies_ns)
    for _ in range(INGEST_ROUNDS):
        await asyncio.sleep(0.002)
        bar = await coord.inject_barrier()
        await coord.wait_collected(bar)
    xs = sorted(coord.latencies_ns[n_warm:])
    return xs[len(xs) // 2] / 1e9


async def main() -> int:
    tmp = tempfile.mkdtemp(prefix="broker_profile_")
    broker = BrokerProc(os.path.join(tmp, "broker"))
    broker.start()
    results = []
    try:
        for name in ("clean", "kill_a", "kill_b", "kill_broker"):
            results.append(await _run_scenario(name, tmp, broker))
            print(json.dumps(results[-1]))
        p50_broker = await _ingest_p50_broker(tmp, broker.addr)
        p50_datagen = await _ingest_p50_datagen(tmp)
    finally:
        broker.kill()
    ratio = p50_broker / max(p50_datagen, 1e-9)
    verdict = {
        "all_bit_identical": all(r["bit_identical"] for r in results),
        "all_seqs_dense_unique": all(r["seqs_dense_unique"]
                                     for r in results),
        "no_redelivered_epochs": all(r["no_redelivered_epoch"]
                                     for r in results),
        "kills_injected": sum(1 for r in results if r["killed"]) == 3,
        "ingest_p50_broker_s": round(p50_broker, 5),
        "ingest_p50_datagen_s": round(p50_datagen, 5),
        "ingest_p50_ratio": round(ratio, 3),
        "ingest_within_bound": ratio <= P50_RATIO_BOUND,
    }
    print(json.dumps({"verdict": verdict}))
    return 0 if all(v for v in verdict.values()
                    if isinstance(v, bool)) else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

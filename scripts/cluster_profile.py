"""CI gate: 2-worker cluster runs the canned q7 shape correctly and
within a bounded barrier-latency multiple of single-process.

Deploys the q7 MV (shared bid source, tumble MAX agg, interval join)
over a meta session + 2 compute-node processes with vnode-partitioned
fragments, paces the same number of barrier rounds in cluster and
single-process mode, and REQUIRES:

  * identical committed source offsets and BIT-IDENTICAL MV contents
    (the cluster run must be indistinguishable from the single-process
    oracle);
  * a worker registry with 2 alive leases;
  * cluster barrier p50 within `MAX_P50_MULTIPLE` of single-process
    (the per-worker RPC injection/collection path and the DCN exchange
    add latency; they must not add an order of magnitude);
  * completion within the hard deadline (a stalled cluster — lost
    collection, deadlocked commit — fails loudly, rc != 0).

Prints one JSON line; exit code 0 iff every gate holds.
"""

import asyncio
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

ROUNDS = 8
MAX_P50_MULTIPLE = 10.0
HARD_DEADLINE_S = 420.0

W = 10_000_000
DDL = [
    ("CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
     "chunk_size=256, splits=2, rate_limit=512, inter_event_us=250, "
     f"emit_watermarks=1, watermark_lag_us={2 * W})"),
    ("CREATE MATERIALIZED VIEW q7 AS "
     "SELECT B.auction, B.price, B.bidder, B.date_time "
     "FROM bid B JOIN ("
     "  SELECT max(price) AS maxprice, window_end "
     f"  FROM TUMBLE(bid, date_time, {W}) GROUP BY window_end) B1 "
     "ON B.price = B1.maxprice "
     f"AND B.date_time > B1.window_end - {W} "
     "AND B.date_time <= B1.window_end"),
]


def free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def spawn_worker(port: int) -> subprocess.Popen:
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS",
                                                   "cpu"))
    p = subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.worker", str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=1).close()
            return p
        except OSError:
            time.sleep(0.2)
    p.terminate()
    raise RuntimeError("worker never started listening")


async def run_once(ports):
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import (HummockStateStore,
                                      LocalFsObjectStore)
    root = tempfile.mkdtemp(prefix="cluster_profile_")
    s = Session(store=HummockStateStore(LocalFsObjectStore(root)))
    workers = 0
    if ports:
        addr = ",".join(f"127.0.0.1:{p}" for p in ports)
        await s.execute(f"SET cluster = '{addr}'")
        workers = len(await s.execute("SHOW cluster"))
    for d in DDL:
        await s.execute(d)
    for _ in range(ROUNDS):
        await s.tick()
    rows = sorted(s.query(
        "SELECT auction, price, bidder, date_time FROM q7"))
    p50 = s.coord.barrier_latency_percentile(0.5)
    # committed split offsets (source state table over the meta handle)
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.storage_table import StorageTable
    sch = Schema((Field("split_id", DataType.INT64),
                  Field("offset", DataType.INT64)))
    offsets = {}
    for tid in range(1, 40):
        st = StateTable(s.store, table_id=tid, schema=sch,
                        pk_indices=(0,))
        try:
            rws = list(StorageTable.for_state_table(st).batch_iter())
        except Exception:  # noqa: BLE001
            continue
        if rws and all(len(r) == 2 for r in rws) \
                and {r[0] for r in rws} <= {0, 1}:
            offsets = {int(k): int(v) for k, v in rws}
            break
    await s.shutdown()
    return dict(rows=rows, p50=p50, offsets=offsets, workers=workers)


async def main_async() -> dict:
    ports = [free_port(), free_port()]
    procs = [spawn_worker(p) for p in ports]
    try:
        cluster = await asyncio.wait_for(run_once(ports),
                                         HARD_DEADLINE_S * 0.6)
        single = await asyncio.wait_for(run_once([]),
                                        HARD_DEADLINE_S * 0.35)
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
    identical = cluster["rows"] == single["rows"]
    same_offsets = (cluster["offsets"] == single["offsets"]
                    and bool(cluster["offsets"]))
    ratio = (cluster["p50"] / single["p50"]
             if single["p50"] > 0 else float("inf"))
    ok = (identical and same_offsets and bool(cluster["rows"])
          and cluster["workers"] == 2 and ratio <= MAX_P50_MULTIPLE)
    return {
        "metric": "cluster_q7_gate",
        "ok": ok,
        "workers": cluster["workers"],
        "rows": len(cluster["rows"]),
        "bit_identical": identical,
        "offsets_match": same_offsets,
        "cluster_barrier_p50_s": round(cluster["p50"], 4),
        "single_barrier_p50_s": round(single["p50"], 4),
        "p50_multiple": round(ratio, 2),
        "max_p50_multiple": MAX_P50_MULTIPLE,
    }


def main() -> int:
    t0 = time.time()
    try:
        out = asyncio.run(asyncio.wait_for(main_async(),
                                           HARD_DEADLINE_S))
    except Exception as e:  # noqa: BLE001 — a stall IS a failure
        print(json.dumps({"metric": "cluster_q7_gate", "ok": False,
                          "error": f"{type(e).__name__}: {e}",
                          "seconds": round(time.time() - t0, 1)}),
              flush=True)
        return 1
    out["seconds"] = round(time.time() - t0, 1)
    print(json.dumps(out), flush=True)
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())

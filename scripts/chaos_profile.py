"""Chaos gate — deterministic fault injection over a q7-shaped durable run.

Every fault class the FaultInjector models (utils/faults.py) is injected
into its own fresh durable session running the q7 window aggregation
(source -> project -> tumble project -> hash_agg -> materialize: four
fragments, four actors — the same shape the recovery tests and
logstore gate use):

  mv_actor_crash   actor exception at the TERMINAL (materialize)
                   fragment -> blast radius is one fragment: partial
                   recovery rebuilds ONLY that actor; the agg fragment
                   keeps its device state and the exchange channels
                   replay the in-flight interval
  poison_chunk     corrupt payload kills the CONSUMING (materialize)
                   actor -> same partial scope
  interior_crash   actor exception at an INTERIOR fragment (hash_agg,
                   which has a downstream consumer) -> scope=CONE: the
                   agg AND its downstream materialize rebuild together,
                   the upstream source/project chain keeps its device
                   state, the cone's inbound frontier replays
  mesh_crash       the FUSED MESH fragment (streaming_parallelism_
                   devices=2 on the virtual mesh) crashes -> scope=MESH:
                   the fused program re-runs from the committed epoch
                   over the replayed ingest instead of tearing the
                   deployment down
  mesh_topn_crash  the q5 lowering: the SHARDED TOP-N actor (ORDER BY n
                   DESC LIMIT k over the retracting agg changelog,
                   streaming_parallelism_devices=2) crashes ->
                   scope=MESH; recovery re-plans it sharded and the
                   rows re-characterize against the upstream recount
  dcn_drop         2-WORKER cluster run: one DCN output leg severed
                   mid-epoch -> scope=WORKER: the dead leg's consumer
                   closure rebuilds in place, the surviving producer
                   rewinds its replay buffer into the rebuilt consumer,
                   survivors' stores stay open
  upload_fail      checkpoint upload raises -> fail-stop -> full
                   recovery from the committed epoch
  kill_during_recovery  interior crash + crashes injected inside BOTH
                   recovery paths (mid cone rebuild, then mid
                   DDL-replay) -> the retry converges (re-entrancy)
  channel_stall    the consumer parks 400ms on one chunk -> NO recovery,
                   the barrier just completes late
  upload_delay     the checkpoint upload sleeps 400ms -> NO recovery,
                   the pipelined commit just lands late (delivery and
                   replay-buffer trims follow it)

plus the STORAGE-PLANE classes (state/object_store.py retry layer,
state/hummock.py read-path integrity, state/backup.py verified
backup/restore — transient faults absorb BELOW the recovery radius
engine, durable faults repair from backup):

  object_put_flake    two consecutive transient PUT failures during
                   checkpoint upload -> absorbed by the bounded-retry
                   wrapper: ZERO recoveries, retries counted, MV
                   bit-identical to the oracle
  object_get_flake    a transient GET failure on the scrub read path ->
                   absorbed the same way, zero recoveries
  object_get_corrupt_transient  one corrupted GET payload -> the crc
                   retry re-reads clean: zero recoveries, nothing
                   quarantined
  sst_corrupt_durable  an on-disk SST bit-rotted AFTER a backup -> the
                   scrubber detects it, quarantines the bad bytes,
                   restores the object from its checksum-verified
                   backup copy, /healthz flips degraded — zero
                   recoveries, the engine never serves the corruption
  backup_restore_coldstart  BACKUP TO twice (the second run must copy
                   only the new generation's objects), then a REAL
                   FRESH PROCESS runs RESTORE FROM into an empty
                   primary and converges bit-identical to the
                   generator-prefix oracle at the restored committed
                   offset; a deliberately corrupted backup object is
                   REFUSED loudly at restore time

plus the external-ingress/egress classes over an in-process broker
(connectors/broker.py — the fail-stop -> auto-recovery path, never a
hang):

  broker_fetch_fail   the source's partition fetch raises -> the
                   consuming actor dies -> recovery reseeks the
                   committed offsets; the MV converges to exactly the
                   produced rows (no loss, no duplication)
  broker_append_fail  the sink's topic append raises -> delivery parks
                   and fail-stops the next injection; after recovery
                   the topic holds dense, duplicate-free delivery
                   sequence numbers and exactly the MV's changelog

Exits non-zero unless ALL hold:

  * every run converges BIT-IDENTICAL to the generator-prefix oracle:
    the MV's rows equal a numpy recount of the bid generator prefix at
    the committed source offset (window_end -> max(price));
  * every CONTAINED fault recovers at its named scope — fragment, cone,
    mesh, worker — with the matching recovery_total{scope=...} label in
    /metrics, and rebuilds a STRICT SUBSET of the topology's actors
    (asserted on the actor-id sets reported in last_recovery);
  * fragment/cone/worker-scope recovery p50s beat the full-recovery p50
    AND fragment stays under the absolute budget (0.5s on CPU — a
    partial rebuild is host-side re-wiring plus state reload, not a
    DDL replay);
  * recovery_total{scope=...,cause=...} and recovery_duration_seconds
    render in /metrics, and /healthz carries the last-recovery fields
    (scope/cause/duration) — recovery is observable end to end.

CI usage (CPU backend):

    JAX_PLATFORMS=cpu python scripts/chaos_profile.py
"""

import asyncio
import json
import os
import socket
import subprocess
import sys
import time
import urllib.request
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# the mesh_crash class needs a multi-device mesh on the CPU backend
# (same virtual-device trick as tests/conftest.py) — must precede any
# jax import
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

WINDOW_US = 1_000_000
FRAGMENT_P50_BUDGET_S = 0.5


def _ddl() -> list:
    return [
        "SET streaming_watchdog = 0",
        ("CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
         "chunk_size=128, inter_event_us=2000, rate_limit=512)"),
        ("CREATE MATERIALIZED VIEW q7w AS "
         "SELECT window_end, max(price) AS maxprice "
         f"FROM TUMBLE(bid, date_time, {WINDOW_US}) GROUP BY window_end"),
    ]


def _oracle(offset: int) -> Counter:
    """Numpy recount of the generator prefix: window_end -> max(price),
    the exactly-once convergence target."""
    import numpy as np
    from risingwave_tpu.connectors import NexmarkGenerator
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    gen = NexmarkGenerator("bid", chunk_size=max(256, offset),
                           cfg=NexmarkConfig(inter_event_us=2000))
    c = gen.next_chunk()
    price = np.asarray(c.columns[2].data)[:offset]
    dt = np.asarray(c.columns[5].data)[:offset]
    we = dt - dt % WINDOW_US + WINDOW_US
    out: Counter = Counter()
    for w in np.unique(we):
        out[(int(w), int(price[we == w].max()))] += 1
    return out


def _committed_offset(session, mv: str = "q7w") -> int:
    from risingwave_tpu.state.storage_table import StorageTable
    from risingwave_tpu.stream.source import SourceExecutor
    dep = session.catalog.mvs[mv].deployment
    for roots in dep.roots.values():
        for root in roots:
            node = root
            while node is not None:
                if isinstance(node, SourceExecutor):
                    rows = list(StorageTable.for_state_table(
                        node.state_table).batch_iter())
                    return int(rows[0][1]) if rows else 0
                node = getattr(node, "input", None)
    raise AssertionError("no source executor")


async def _run_fault(name: str, tmp: str, arm, pre_ddl=()) -> dict:
    """One fresh durable session, one injected fault class: warm up,
    arm the injector, tick through the fault and its recovery, then
    verify convergence against the oracle. `arm(session) -> spec`."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    store = HummockStateStore(
        LocalFsObjectStore(os.path.join(tmp, name)))
    s = Session(store=store)
    for sql in pre_ddl:
        await s.execute(sql)
    for sql in _ddl():
        await s.execute(sql)
    await s.tick(3)
    spec = arm(s)
    await s.execute(f"SET fault_injection = '{spec}'")
    await s.tick(5, max_recoveries=4)
    await s.execute("SET fault_injection = ''")
    await s.tick(2)

    offset = _committed_offset(s)
    got = Counter(s.query("SELECT window_end, maxprice FROM q7w"))
    expected = _oracle(offset)
    total_actors = sorted(
        a.actor_id
        for f in list(s.catalog.mvs.values()) + list(s.catalog.sinks.values())
        for a in f.deployment.actors)

    # observability surfaces, scraped over a real socket
    await s.start_monitor(0)
    port = s.monitor.port

    def _get(path: str) -> str:
        # off the loop: the monitor serves ON this loop, so a blocking
        # urlopen here would deadlock the scrape
        return urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=5).read().decode()

    metrics = await asyncio.to_thread(_get, "/metrics")
    healthz = json.loads(await asyncio.to_thread(_get, "/healthz"))
    await s.stop_monitor()
    out = {
        "fault": name,
        "converged": got == expected,
        "offset": offset,
        "mv_rows": sum(got.values()),
        "recoveries": s.recoveries,
        "last_recovery": s.last_recovery,
        "total_actors": total_actors,
        "metrics_recovery_total": "recovery_total" in metrics,
        "metrics_recovery_duration":
            "recovery_duration_seconds" in metrics,
        "healthz_last_recovery": healthz.get("last_recovery"),
    }
    await s.drop_all()
    return out


def _mv_actor(session) -> int:
    mv = session.catalog.mvs["q7w"]
    return mv.deployment.frag_actor_ids[mv.mv_fragment][0]


def _agg_actor(session) -> int:
    """The hash_agg fragment's actor — upstream of the terminal one."""
    from risingwave_tpu.plan.build import _iter_executor_chain
    mv = session.catalog.mvs["q7w"]
    dep = mv.deployment
    for fid, roots in dep.roots.items():
        if fid == mv.mv_fragment:
            continue
        for root in roots:
            for ex in _iter_executor_chain(root):
                if "HashAgg" in getattr(ex, "identity", ""):
                    return dep.frag_actor_ids[fid][0]
    raise AssertionError("no hash_agg fragment")


async def _run_broker_faults(tmp: str) -> list:
    """The ingress/egress fault classes need a broker in the loop: a
    fresh session per class over an in-process broker (tests cover the
    socket transport; the fault path is transport-independent)."""
    import json as _json
    from risingwave_tpu.broker import Broker, register_inproc
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore

    out = []

    # ---- broker_fetch_fail: source fetch dies mid-ingest ----
    broker = Broker(os.path.join(tmp, "broker_in"), fsync=False)
    register_inproc("chaos_in", broker)
    broker.create_topic("ev", 1)
    rows = [_json.dumps({"k": i, "v": i * 7}).encode() for i in range(400)]
    broker.append("ev", 0, rows[:250])
    s = Session(store=HummockStateStore(
        LocalFsObjectStore(os.path.join(tmp, "broker_fetch_fail"))))
    await s.execute("SET streaming_watchdog = 0")
    await s.execute(
        "CREATE SOURCE ev WITH (connector='broker', topic='ev', "
        "brokers='inproc://chaos_in', columns='k int64, v int64', "
        "chunk_size=64, discovery_interval_ms=0, append_only=1)")
    await s.execute("CREATE MATERIALIZED VIEW bm AS SELECT k, v FROM ev")
    await s.tick(2)
    await s.execute("SET fault_injection = 'broker_fetch_fail:at=2'")
    broker.append("ev", 0, rows[250:])
    await s.tick(5, max_recoveries=4)
    await s.execute("SET fault_injection = ''")
    await s.tick(2)
    got = Counter(s.query("SELECT k, v FROM bm"))
    expected = Counter((i, i * 7) for i in range(400))
    out.append({"fault": "broker_fetch_fail",
                "converged": got == expected,
                "mv_rows": sum(got.values()),
                "recoveries": s.recoveries,
                "last_recovery": s.last_recovery})
    await s.drop_all()

    # ---- broker_append_fail: sink delivery dies mid-append ----
    s = Session(store=HummockStateStore(
        LocalFsObjectStore(os.path.join(tmp, "broker_append_fail"))))
    await s.execute("SET streaming_watchdog = 0")
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        "chunk_size=128, inter_event_us=2000, rate_limit=512)")
    await s.execute("SET fault_injection = 'broker_append_fail:at=2'")
    await s.execute(
        "CREATE SINK q7b AS SELECT window_end, max(price) AS maxprice "
        f"FROM TUMBLE(bid, date_time, {WINDOW_US}) GROUP BY window_end "
        "WITH (connector='broker', topic='q7b', "
        "brokers='inproc://chaos_in')")
    await s.tick(5, max_recoveries=4)
    await s.execute("SET fault_injection = ''")
    await s.tick(3)
    # topic-side exactly-once: dense unique seqs, replay-consistent rows
    seqs = []
    live: Counter = Counter()
    dangling = 0
    from risingwave_tpu.broker.log import PartitionLog
    for p in range(broker.list_partitions("q7b")):
        pl = PartitionLog(os.path.join(tmp, "broker_in", "q7b",
                                       f"p{p:05d}"), fsync=False)
        for rec in pl.fetch(0, 1_000_000):
            o = _json.loads(rec)
            key = (o.get("window_end"), o.get("maxprice"))
            if o.get("__op") == 1:
                if live[key] <= 0:
                    dangling += 1
                else:
                    live[key] -= 1
            else:
                live[key] += 1
    # batch metas carry the delivery seqs — walk them via the log index
    for p in range(broker.list_partitions("q7b")):
        pl = broker._parts[("q7b", p)]
        for base, _n, seg, pos in pl._index:
            import struct as _struct
            import zlib as _zlib
            with open(seg, "rb") as f:
                f.seek(pos)
                ln, _crc = _struct.unpack("!II", f.read(8))
                body = f.read(ln)
            _b, _nr, ml = _struct.unpack_from("!QII", body)
            if ml:
                seqs.append(_json.loads(body[16:16 + ml])["seq"])
    seqs.sort()
    windows = [k[0] for k, c in live.items() for _ in range(c)]
    out.append({"fault": "broker_append_fail",
                "converged": (seqs == list(range(1, len(seqs) + 1))
                              and len(seqs) > 0 and dangling == 0
                              and len(windows) == len(set(windows))),
                "delivered_seqs": len(seqs),
                "recoveries": s.recoveries,
                "last_recovery": s.last_recovery})
    await s.drop_all()
    return out


CHILD_RESTORE_SRC = r"""
import asyncio, json, os, sys
os.environ.setdefault("JAX_PLATFORMS", "cpu")

async def main():
    bak, primary = sys.argv[1], sys.argv[2]
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    from risingwave_tpu.state.storage_table import StorageTable
    from risingwave_tpu.stream.source import SourceExecutor
    s = Session(store=HummockStateStore(LocalFsObjectStore(primary)))
    meta = await s.execute("RESTORE FROM '%s'" % bak)
    offset = 0
    dep = s.catalog.mvs["q7w"].deployment
    for roots in dep.roots.values():
        for root in roots:
            node = root
            while node is not None:
                if isinstance(node, SourceExecutor):
                    rows = list(StorageTable.for_state_table(
                        node.state_table).batch_iter())
                    offset = int(rows[0][1]) if rows else 0
                node = getattr(node, "input", None)
    rows = sorted(s.query("SELECT window_end, maxprice FROM q7w"))
    print(json.dumps({"restore": meta, "offset": offset, "rows": rows}))
    await s.crash()

asyncio.run(main())
"""


async def _run_storage_faults(tmp: str) -> tuple[list, dict]:
    """The storage-plane classes: transient object faults absorb BELOW
    the recovery machinery (zero recoveries, retries counted), durable
    corruption repairs from backup, and the incremental backup restores
    bit-identical over a REAL fresh process."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    from risingwave_tpu.utils.metrics import (OBJECT_RETRIES,
                                              RECOVERY_TOTAL,
                                              STORAGE_CRC_RETRIES)
    out = []

    async def _q7(name, pre=()):
        store = HummockStateStore(
            LocalFsObjectStore(os.path.join(tmp, name)))
        s = Session(store=store)
        for sql in pre:
            await s.execute(sql)
        for sql in _ddl():
            await s.execute(sql)
        await s.tick(3)
        return s, store

    def _conv(s):
        offset = _committed_offset(s)
        got = Counter(s.query("SELECT window_end, maxprice FROM q7w"))
        return got == _oracle(offset), offset, sum(got.values())

    async def _transient(name, spec, pre=()):
        s, store = await _q7(name, pre=pre)
        r0 = OBJECT_RETRIES.value
        c0 = STORAGE_CRC_RETRIES.value
        t0 = RECOVERY_TOTAL.value
        await s.execute(f"SET fault_injection = '{spec}'")
        await s.tick(4)
        await s.execute("SET fault_injection = ''")
        await s.tick(1)
        conv, offset, nrows = _conv(s)
        res = {"fault": name, "converged": conv, "offset": offset,
               "mv_rows": nrows, "recoveries": s.recoveries,
               "retries_delta": OBJECT_RETRIES.value - r0,
               "crc_retries_delta": STORAGE_CRC_RETRIES.value - c0,
               "recovery_total_delta": RECOVERY_TOTAL.value - t0,
               "quarantined": list(store.quarantined)}
        await s.drop_all()
        return res

    scrub_on = ("SET storage_scrub_interval = 1",
                "SET storage_scrub_batch = 4")
    out.append(await _transient(
        "object_put_flake", "object_put_fail:at=1,times=2"))
    out.append(await _transient(
        "object_get_flake", "object_get_fail:at=1,kind=sst",
        pre=scrub_on))
    out.append(await _transient(
        "object_get_corrupt_transient", "object_get_corrupt:at=1,kind=sst",
        pre=scrub_on))

    # ---- durable SST corruption -> quarantine + restore-from-backup ----
    s, store = await _q7("sst_corrupt_durable",
                         pre=("SET storage_scrub_interval = 1",
                              "SET storage_scrub_batch = 8"))
    bak_repair = os.path.join(tmp, "sst_corrupt_durable_bak")
    await s.execute(f"BACKUP TO '{bak_repair}'")
    t0 = RECOVERY_TOTAL.value
    sst = store._l0[0] if store._l0 else store._l1
    sst_path = os.path.join(tmp, "sst_corrupt_durable", "ssts",
                            f"{sst.sst_id:010d}.sst")
    with open(sst_path, "r+b") as f:     # bit-rot AFTER the backup
        f.seek(24)
        f.write(b"\xde\xad\xbe\xef")
    await s.tick(4)                      # scrub pulse finds + repairs it
    from risingwave_tpu.state.sstable import SsTable
    healed = True
    try:
        SsTable.parse(sst.sst_id, open(sst_path, "rb").read())
    except Exception:  # noqa: BLE001
        healed = False
    await s.start_monitor(0)
    port = s.monitor.port
    healthz = json.loads(await asyncio.to_thread(
        lambda: urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=5)
        .read().decode()))
    await s.stop_monitor()
    conv, offset, nrows = _conv(s)
    out.append({"fault": "sst_corrupt_durable", "converged": conv,
                "offset": offset, "mv_rows": nrows,
                "recoveries": s.recoveries,
                "recovery_total_delta": RECOVERY_TOTAL.value - t0,
                "quarantined": list(store.quarantined),
                "restored": list(store.restored_objects),
                "healed_on_disk": healed,
                "healthz_degraded": bool(healthz.get("degraded"))})
    await s.drop_all()

    # ---- incremental backup + cold-start restore in a FRESH process ----
    s, store = await _q7("coldstart_primary")
    bak = os.path.join(tmp, "coldstart_bak")
    meta1 = await s.execute(f"BACKUP TO '{bak}'")
    await s.tick(3)
    meta2 = await s.execute(f"BACKUP TO '{bak}'")
    final_offset = _committed_offset(s)
    final_rows = sorted(s.query("SELECT window_end, maxprice FROM q7w"))
    await s.crash()

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PYTHONPATH=repo)

    def _restore_child(primary):
        return subprocess.run(
            [sys.executable, "-c", CHILD_RESTORE_SRC, bak, primary],
            capture_output=True, timeout=300, env=env, cwd=repo)

    child = _restore_child(os.path.join(tmp, "coldstart_fresh"))
    restored = {}
    if child.returncode == 0:
        restored = json.loads(child.stdout.decode().strip().split("\n")[-1])
    conv = (bool(restored)
            and Counter(map(tuple, restored["rows"]))
            == _oracle(restored["offset"])
            and restored["offset"] == final_offset
            and [list(r) for r in final_rows] == restored["rows"])
    # a corrupted backup object must REFUSE loudly at restore time
    from risingwave_tpu.state.backup import load_backup_manifest
    ledger = load_backup_manifest(LocalFsObjectStore(bak))
    sst_name = sorted(n for n in ledger["objects"] if n.startswith("ssts/"))[0]
    with open(os.path.join(bak, *sst_name.split("/")), "r+b") as f:
        f.seek(16)
        f.write(b"\x66\x6f\x6f\x21")
    child2 = _restore_child(os.path.join(tmp, "coldstart_fresh2"))
    refused = (child2.returncode != 0
               and b"BackupCorruption" in child2.stderr)
    out.append({"fault": "backup_restore_coldstart",
                "converged": conv,
                "recoveries": 0,
                "backup_gen1": meta1, "backup_gen2": meta2,
                "child_rc": child.returncode,
                "corrupt_backup_refused": refused,
                "child2_rc": child2.returncode})
    verdict_bits = {
        "storage_transient_zero_recoveries": all(
            r["recoveries"] == 0 and r["recovery_total_delta"] == 0
            for r in out if r["fault"] != "backup_restore_coldstart"),
        "storage_retries_counted": (
            out[0]["retries_delta"] > 0 and out[1]["retries_delta"] > 0
            and out[2]["crc_retries_delta"] > 0),
        "storage_transient_nothing_quarantined": all(
            not r["quarantined"] for r in out[:3]),
        "storage_all_converged": all(
            r["converged"] for r in out),
        "sst_corrupt_durable_repaired": (
            bool(out[3]["quarantined"]) and bool(out[3]["restored"])
            and out[3]["healed_on_disk"] and out[3]["healthz_degraded"]),
        "backup_incremental_copy_only_new": (
            meta2["generation"] == meta1["generation"] + 1
            and meta2["skipped"] > 0
            and meta2["copied"] < meta2["objects"]),
        "coldstart_restore_converged": conv,
        "corrupt_backup_refused": refused,
    }
    return out, verdict_bits


def _mesh_actor(session) -> int:
    """The fused mesh fragment's actor (the agg lowered onto the
    virtual device mesh under streaming_parallelism_devices=2)."""
    dep = session.catalog.mvs["q7w"].deployment
    assert dep.mesh_actor_ids, "no mesh fragment deployed"
    return dep.mesh_actor_ids[0]


async def _run_mesh_topn_crash(tmp: str) -> dict:
    """scope=MESH for the q5 lowering: crash the SHARDED TOP-N actor
    (ORDER BY n DESC LIMIT k over a retracting agg changelog, lowered
    onto the device mesh). Recovery must rebuild only the mesh radius,
    re-plan the executor SHARDED (durable full-input store + ingest
    replay), and converge: the top-N rows must characterize exactly
    against the batch recount of the upstream MV, which itself must
    match the generator-prefix recount at the committed offset."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    from risingwave_tpu.stream.sharded_top_n import ShardedTopNExecutor
    k = 5
    store = HummockStateStore(
        LocalFsObjectStore(os.path.join(tmp, "mesh_topn_crash")))
    s = Session(store=store)
    await s.execute("SET streaming_parallelism_devices = 2")
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        "chunk_size=128, rate_limit=512)")
    await s.execute("CREATE MATERIALIZED VIEW counts AS SELECT auction "
                    "AS a, count(*) AS n FROM bid GROUP BY auction")
    await s.execute("CREATE MATERIALIZED VIEW t5 AS SELECT a, n FROM "
                    f"counts ORDER BY n DESC LIMIT {k}")
    await s.tick(3)
    dep = s.catalog.mvs["t5"].deployment
    assert dep.mesh_actor_ids, "top-N did not deploy on the mesh"
    victim = dep.mesh_actor_ids[0]
    await s.execute(
        f"SET fault_injection = 'actor_crash:actor={victim},at=2'")
    await s.tick(5, max_recoveries=4)
    await s.execute("SET fault_injection = ''")
    await s.tick(2)

    replanned = []
    for roots in s.catalog.mvs["t5"].deployment.roots.values():
        for root in roots:
            node = root
            while node is not None:
                if isinstance(node, ShardedTopNExecutor):
                    replanned.append(node)
                node = getattr(node, "input", None)

    # characterization: order-key vector vs the batch engine's recount
    # of the upstream MV (ties at the k-boundary may pick either key),
    # every (a, n) pair present upstream, and the upstream MV anchored
    # to the generator prefix at its committed offset
    got = s.query("SELECT a, n FROM t5 ORDER BY 2 DESC, 1")
    want = s.query(f"SELECT a, n FROM counts ORDER BY 2 DESC, 1 LIMIT {k}")
    base = dict(s.query("SELECT a, n FROM counts"))
    import numpy as np
    from risingwave_tpu.connectors import NexmarkGenerator
    offset = _committed_offset(s, mv="counts")
    gen = NexmarkGenerator("bid", chunk_size=max(256, offset))
    auction = np.asarray(gen.next_chunk().columns[0].data)[:offset]
    recount = Counter(auction.tolist())
    converged = (
        [n for _, n in got] == [n for _, n in want]
        and len(got) == min(k, len(base))
        and all(base.get(a) == n for a, n in got)
        and base == {int(a): int(n) for a, n in recount.items()})
    total_actors = sorted(
        a.actor_id
        for f in list(s.catalog.mvs.values()) + list(s.catalog.sinks.values())
        for a in f.deployment.actors)
    out = {
        "fault": "mesh_topn_crash",
        "converged": converged,
        "offset": offset,
        "mv_rows": len(got),
        "recoveries": s.recoveries,
        "last_recovery": s.last_recovery,
        "total_actors": total_actors,
        "replanned_sharded": bool(replanned)
        and all(t.mesh_shuffle for t in replanned),
    }
    await s.drop_all()
    return out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_worker(port: int) -> subprocess.Popen:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.worker", str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=1).close()
            return p
        except OSError:
            time.sleep(0.2)
    p.terminate()
    raise RuntimeError("worker never started listening")


async def _run_cluster_dcn(tmp: str) -> dict:
    """The WORKER radius over a real 2-worker cluster: sever one DCN
    output leg mid-epoch (dcn_drop, armed on the workers through the
    cluster config push) — the consumer's downstream closure rebuilds
    in place at scope=worker, the surviving producer rewinds its
    replay buffer into the rebuilt consumer, survivors keep their
    store objects, and the MV converges bit-identical to the
    generator-prefix oracle at the committed per-split offsets."""
    import numpy as np
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    ports = [_free_port(), _free_port()]
    procs = [_spawn_worker(p) for p in ports]
    try:
        s = Session(store=HummockStateStore(
            LocalFsObjectStore(os.path.join(tmp, "dcn"))))
        addr = ",".join(f"127.0.0.1:{p}" for p in ports)
        await s.execute(f"SET cluster = '{addr}'")
        await s.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
            "chunk_size=256, splits=2, rate_limit=512)")
        await s.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS n, max(price) AS mx FROM bid GROUP BY auction")
        for _ in range(4):
            await asyncio.wait_for(s.tick(), 60)
        all_actors = sorted(
            a for dep in s.cluster.deployments.values()
            for ids in dep.rebuild_info["actors"].values() for a in ids)
        await s.execute("SET fault_injection = 'dcn_drop:at=3'")
        for _ in range(6):
            await asyncio.wait_for(s.tick(max_recoveries=4), 60)
        await s.execute("SET fault_injection = ''")
        await asyncio.wait_for(s.tick(2), 60)

        got = sorted(s.query("SELECT auction, n, mx FROM agg"))
        # generator-prefix oracle at the committed per-split offsets
        from risingwave_tpu.common.types import (DataType, Field,
                                                 Schema)
        from risingwave_tpu.connectors import NexmarkGenerator
        from risingwave_tpu.state.state_table import StateTable
        from risingwave_tpu.state.storage_table import StorageTable
        sch = Schema((Field("split_id", DataType.INT64),
                      Field("offset", DataType.INT64)))
        offsets = {}
        for tid in range(1, 40):
            st = StateTable(s.store, table_id=tid, schema=sch,
                            pk_indices=(0,))
            try:
                rows = list(StorageTable.for_state_table(st).batch_iter())
            except Exception:  # noqa: BLE001 — not this table's layout
                continue
            if rows and all(len(r) == 2 for r in rows) \
                    and {r[0] for r in rows} <= {0, 1}:
                offsets = {int(k): int(v) for k, v in rows}
                break
        gen = NexmarkGenerator("bid", chunk_size=1 << 16)
        c = gen.next_chunk()
        auction = np.asarray(c.columns[0].data)
        price = np.asarray(c.columns[2].data)
        idx = []
        for k, off in offsets.items():
            for j in range(off // 256):
                b = j * 2 + k
                idx.extend(range(b * 256, (b + 1) * 256))
        idx = np.asarray(sorted(idx), dtype=np.int64)
        a, p = auction[idx], price[idx]
        cnt = Counter(a.tolist())
        mx: dict = {}
        for ai, pi in zip(a.tolist(), p.tolist()):
            mx[ai] = max(mx.get(ai, 0), pi)
        oracle = sorted((k, cnt[k], mx[k]) for k in cnt)
        out = {
            "fault": "dcn_drop",
            "converged": got == oracle and bool(offsets),
            "mv_rows": sum(g[1] for g in got),
            "recoveries": s.recoveries,
            "last_recovery": s.last_recovery,
            "total_actors": all_actors,
        }
        await s.shutdown()
        return out
    finally:
        for p_ in procs:
            if p_.poll() is None:
                p_.terminate()


async def main() -> int:
    import tempfile
    tmp = tempfile.mkdtemp(prefix="chaos_profile_")
    results = []

    results.append(await _run_fault(
        "mv_actor_crash", tmp,
        lambda s: f"actor_crash:actor={_mv_actor(s)},at=2"))
    results.append(await _run_fault(
        "poison_chunk", tmp,
        lambda s: f"poison_chunk:actor={_mv_actor(s)},at=3"))
    results.append(await _run_fault(
        "interior_crash", tmp,
        lambda s: f"actor_crash:actor={_agg_actor(s)},at=2"))
    results.append(await _run_fault(
        "mesh_crash", tmp,
        lambda s: f"actor_crash:actor={_mesh_actor(s)},at=2",
        pre_ddl=("SET streaming_parallelism_devices = 2",)))
    mesh_topn = await _run_mesh_topn_crash(tmp)
    results.append(await _run_fault(
        "upload_fail", tmp, lambda s: "upload_fail:at=1"))
    results.append(await _run_fault(
        "kill_during_recovery", tmp,
        lambda s: (f"actor_crash:actor={_agg_actor(s)},at=2;"
                   "recovery_crash:phase=partial,at=1;"
                   "recovery_crash:phase=full,at=1")))
    results.append(await _run_fault(
        "channel_stall", tmp,
        lambda s: f"channel_stall:actor={_mv_actor(s)},at=2,ms=400"))
    results.append(await _run_fault(
        "upload_delay", tmp, lambda s: "upload_delay:at=1,ms=400"))
    dcn = await _run_cluster_dcn(tmp)
    results_cluster = [dcn, mesh_topn]
    broker_results = await _run_broker_faults(tmp)
    storage_results, storage_verdict = await _run_storage_faults(tmp)
    for r in (results + results_cluster + broker_results
              + storage_results):
        print(json.dumps(r))

    by_name = {r["fault"]: r for r in results}
    frag_runs = [by_name["mv_actor_crash"], by_name["poison_chunk"]]
    cone_runs = [by_name["interior_crash"]]
    mesh_runs = [by_name["mesh_crash"], mesh_topn]
    full_runs = [by_name["upload_fail"], by_name["kill_during_recovery"]]
    contained = frag_runs + cone_runs + mesh_runs + [dcn]

    def _p50(runs):
        xs = sorted(r["last_recovery"]["duration_s"] for r in runs)
        return xs[len(xs) // 2]

    frag_p50 = _p50(frag_runs)
    cone_p50 = _p50(cone_runs)
    mesh_p50 = _p50(mesh_runs)
    worker_p50 = _p50([dcn])
    full_p50 = _p50(full_runs)
    stall = by_name["channel_stall"]
    delay = by_name["upload_delay"]
    # scope labels land in the process-global registry as the runs go
    from risingwave_tpu.utils.metrics import GLOBAL_METRICS
    final_metrics = GLOBAL_METRICS.render_prometheus()
    verdict = {
        "all_converged": all(r["converged"]
                             for r in results + results_cluster),
        "delay_no_recovery": delay["recoveries"] == 0,
        "fragment_scope": all(
            r["last_recovery"]["scope"] == "fragment" for r in frag_runs),
        "cone_scope": all(
            r["last_recovery"]["scope"] == "cone" for r in cone_runs),
        "mesh_scope": all(
            r["last_recovery"]["scope"] == "mesh" for r in mesh_runs),
        "worker_scope": dcn["last_recovery"]["scope"] == "worker",
        # every contained radius rebuilds a STRICT subset of the actors
        "contained_rebuild_strictly_fewer": all(
            set(r["last_recovery"]["actors"]) < set(r["total_actors"])
            for r in contained),
        "full_scope": all(
            r["last_recovery"]["scope"] == "full"
            and set(r["last_recovery"]["actors"]) == set(r["total_actors"])
            for r in full_runs),
        "stall_no_recovery": stall["recoveries"] == 0,
        "fragment_recovery_p50_s": round(frag_p50, 5),
        "cone_recovery_p50_s": round(cone_p50, 5),
        "mesh_recovery_p50_s": round(mesh_p50, 5),
        "worker_recovery_p50_s": round(worker_p50, 5),
        "full_recovery_p50_s": round(full_p50, 5),
        "fragment_beats_full": frag_p50 < full_p50,
        "cone_beats_full": cone_p50 < full_p50,
        # channel-free mesh replay: the rebuilt fused executor preloads
        # the MeshIngestLog suffix (one fused scan, no per-chunk channel
        # re-delivery), so the mesh radius must stay cheaper than full
        "mesh_beats_full": mesh_p50 < full_p50,
        "worker_beats_full": worker_p50 < full_p50,
        "fragment_under_budget": frag_p50 < FRAGMENT_P50_BUDGET_S,
        "scope_labels_in_metrics": all(
            f'scope="{sc}"' in final_metrics
            for sc in ("fragment", "cone", "mesh", "worker", "full")),
        "recovery_metrics_visible": all(
            r["metrics_recovery_total"] and r["metrics_recovery_duration"]
            for r in results),
        "healthz_last_recovery": all(
            r["healthz_last_recovery"] is not None
            and "scope" in r["healthz_last_recovery"]
            for r in frag_runs + cone_runs + [by_name["mesh_crash"]]
            + full_runs),
        # the q5 lowering's crash run must come back SHARDED
        "mesh_topn_replanned_sharded": mesh_topn["replanned_sharded"],
        # external ingress/egress faults take the fail-stop -> recovery
        # path (never a hang) and converge exactly-once
        "broker_faults_converged": all(
            r["converged"] and r["recoveries"] >= 1
            for r in broker_results),
    }
    # storage plane: transient classes absorb below the radius engine,
    # durable corruption repairs from backup, cold-start restore over a
    # real fresh process converges (bits computed in _run_storage_faults)
    verdict.update(storage_verdict)
    print(json.dumps({"verdict": verdict}))
    ok = all(v for k, v in verdict.items()
             if isinstance(v, bool))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

"""Instrument one q5 bench round to find where wall time goes."""

import asyncio
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import numpy as np

from risingwave_tpu.connectors import NexmarkGenerator
from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.expr.agg import count_star
from risingwave_tpu.meta import BarrierCoordinator
from risingwave_tpu.state import MemoryStateStore
from risingwave_tpu.stream import (
    Actor, HashAggExecutor, HopWindowExecutor, SourceExecutor,
)
from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.stream.executor import Executor

T0 = time.perf_counter()


def log(msg):
    print(f"[{time.perf_counter()-T0:8.3f}] {msg}", flush=True)


async def main():
    store = MemoryStateStore()
    barrier_q = asyncio.Queue()
    gen = NexmarkGenerator("bid", chunk_size=16384,
                           cfg=NexmarkConfig(inter_event_us=1000))
    src = SourceExecutor(1, gen, barrier_q)
    hop = HopWindowExecutor(src, time_col=5, window_slide_us=2_000_000,
                            window_size_us=10_000_000)
    agg = HashAggExecutor(hop, group_key_indices=[0, hop.window_start_idx],
                          agg_calls=[count_star(append_only=True)],
                          capacity=1 << 16)

    class Sink(Executor):
        def __init__(self, input):
            self.input = input
            self.schema = input.schema
            self.n_chunks = 0

        async def execute(self):
            async for msg in self.input.execute():
                if isinstance(msg, StreamChunk):
                    self.n_chunks += 1
                    log(f"  sink chunk #{self.n_chunks} cap={msg.capacity}")
                yield msg

    sink = Sink(agg)
    coord = BarrierCoordinator(store)
    coord.register_source(barrier_q)
    coord.register_actor(1)
    task = Actor(1, sink, None, coord).spawn()

    for i in range(6):
        log(f"round {i} inject")
        b = await coord.inject_barrier() if i else await coord.inject_barrier(
            kind=__import__("risingwave_tpu.stream.message", fromlist=["BarrierKind"]).BarrierKind.INITIAL)
        await coord.wait_collected(b)
        log(f"round {i} collected")
    await coord.stop_all({1})
    await task
    log(f"done offset={gen.offset}")


asyncio.run(main())

"""Observability-plane gate — canned q7 shape, no TPU needed.

Eight checks, rc=0 iff all pass:

  1. OVERHEAD — the q7-shaped pipeline (broadcast source -> window-max
     agg -> join back) runs under real actors + a real coordinator at
     `metric_level=off` and `metric_level=debug`; the debug barrier p50
     must stay within the SAME-MACHINE calibrated limit of off: the
     spread the off-mode passes show against each other (identical
     work, so pure scheduler noise) sets the allowance, floored at 10%
     — a fixed ratio on a noisy box fails runs a null comparison would
     also fail. Each mode runs several passes and takes the best
     per-mode median to damp scheduler noise.
  2. EXPOSITION — the monitor endpoint's /metrics body (served over a
     real socket) must parse as valid Prometheus text exposition:
     families grouped under one `# TYPE`, histogram `le` ascending with
     a trailing +Inf, labels quoted/escaped.
  3. WATCHDOG — a synthetically parked actor (registered, never
     collects) must trip the stuck-barrier watchdog within the
     threshold: barrier_stalls_total increments and the report names the
     remaining actor.
  4. PROFILE PERTURBATION — a 2s on-demand cpu profile sampled while
     the q7 shape keeps pacing barriers must keep the barrier p50
     within 15% of the unprofiled run (and yield parseable stacks).
  5. METRICS HISTORY — the barrier-paced sampler on (interval=1, full
     default allowlist) must keep the barrier p50 within the calibrated
     limit of sampling-off, leave >= 2 samples per tracked series, and
     answer through SQL: GROUP BY / filtered aggregates over
     `rw_metrics` via the normal batch pipeline.
  6. CROSS-ENGINE STITCH — two in-process engines chained through one
     broker topic export their chrome traces; the stitcher must merge
     them into one Perfetto-loadable timeline with >= 1 sink-delivery
     -> source-ingest flow link.
  7. CLUSTER TRACE OVERHEAD — a real 2-worker deployment runs the q7
     DDL with distributed span recording at `debug`; barrier p50 must
     stay within the same-machine calibrated limit of `off` (off runs
     twice, bracketing debug, to supply the null spread; span bundles
     ride every sealed report).
  8. CLUSTER STALL REPORT — a worker-side `channel_stall` fault wedges
     an epoch past the watchdog threshold; the merged report must name
     the stalled WORKER (one `== worker wN ==` section per live worker)
     and the remaining ACTORS.

    JAX_PLATFORMS=cpu python scripts/observability_profile.py
"""

import asyncio
import contextlib
import io
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compilation cache (utils/compile_cache.py): the
# gate re-runs a canned shape every CI round — repeat runs skip the
# compile entirely
from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


N_INTERVALS = 30
WARMUP_INTERVALS = 8
PASSES = 3
CHUNKS_PER_INTERVAL = 4
CHUNK_CAP = 256
WINDOW = 1 << 10
OVERHEAD_FLOOR = 1.10


def _calibrated_limit(null_p50s) -> float:
    """Same-machine overhead allowance: the off-mode passes run
    IDENTICAL work, so the spread they show against each other is pure
    scheduler noise on this box. Gating debug against that observed
    null ratio (floored at the nominal 10%) keeps the check meaningful
    on a quiet machine without failing noisy CI runners on jitter a
    null comparison would also fail."""
    spread = max(null_p50s) / max(min(null_p50s), 1e-9)
    return round(max(OVERHEAD_FLOOR, spread), 3)


def _bid_schema():
    from risingwave_tpu.common import DataType, schema
    return schema(("auction", DataType.INT64), ("price", DataType.INT64),
                  ("ts", DataType.INT64))


class IntervalSource:
    """Barrier-driven scripted source: emits a fixed batch of canned
    chunks per interval, then parks on the coordinator's barrier queue
    (the same shape a rate-limited connector source has)."""

    def __init__(self, sch, barrier_q, all_chunks):
        self.schema = sch
        self.pk_indices = ()
        self.identity = "IntervalSource"
        self.barrier_q = barrier_q
        self.chunks = all_chunks          # list of per-interval lists
        self.obs = None

    def fence_tokens(self):
        return []

    async def execute(self):
        barrier = await self.barrier_q.get()       # INITIAL
        yield barrier
        i = 0
        while True:
            for ch in self.chunks[i % len(self.chunks)]:
                yield ch
            i += 1
            barrier = await self.barrier_q.get()
            yield barrier
            if barrier.is_stop(0):
                return


def _canned_chunks(seed: int):
    from risingwave_tpu.common.chunk import StreamChunk
    sch = _bid_schema()
    rng = np.random.RandomState(seed)
    intervals = []
    for e in range(N_INTERVALS):
        batch = []
        base_ts = e * WINDOW * 4
        for _ in range(CHUNKS_PER_INTERVAL):
            n = int(rng.randint(CHUNK_CAP // 4, CHUNK_CAP))
            auction = rng.randint(0, 50, size=n).astype(np.int64)
            price = rng.randint(1, 2_000, size=n).astype(np.int64)
            ts = (base_ts
                  + rng.randint(0, WINDOW * 4, size=n)).astype(np.int64)
            batch.append(StreamChunk.from_numpy(
                sch, [auction, price, ts], capacity=CHUNK_CAP))
        intervals.append(batch)
    return intervals


async def _run_q7(metric_level: str, profile_seconds: float = 0.0,
                  history_interval=None) -> dict:
    """q7 shape under real actors: one source actor broadcasting to a
    join actor whose right side is project -> window-max agg.

    With `profile_seconds` > 0, a cpu profile samples from a helper
    thread WHILE barriers keep pacing (the perturbation check): the
    interval loop keeps injecting until the profile window closes, and
    only the latencies that overlap it are measured.

    `history_interval` (0 = sampling off, N = every N barriers)
    configures the coordinator's metrics-history sampler for the
    HISTORY overhead check; None leaves the default."""
    from risingwave_tpu.expr import call, col, lit
    from risingwave_tpu.expr.agg import AggCall, AggKind
    from risingwave_tpu.meta.barrier_manager import BarrierCoordinator
    from risingwave_tpu.state import MemoryStateStore
    from risingwave_tpu.stream import (
        Actor, BroadcastDispatcher, Channel, ChannelInput,
        HashAggExecutor, StopMutation)
    from risingwave_tpu.stream.hash_join import HashJoinExecutor
    from risingwave_tpu.stream.project import ProjectExecutor

    sch = _bid_schema()
    coord = BarrierCoordinator(MemoryStateStore(),
                               checkpoint_max_inflight=0)
    coord.stats.configure(metric_level)
    if history_interval is not None:
        coord.metrics_history.configure(interval=history_interval)
    barrier_q: asyncio.Queue = asyncio.Queue()
    coord.register_source(barrier_q)

    src = IntervalSource(sch, barrier_q, _canned_chunks(seed=7))
    ch_l, ch_r = Channel(64), Channel(64)
    src_actor = Actor(1, src, BroadcastDispatcher([ch_l, ch_r]), coord)

    win = call("add", call("subtract", col(2),
                           call("modulus", col(2), lit(WINDOW))),
               lit(WINDOW))
    proj = ProjectExecutor(ChannelInput(ch_r, sch), [col(0), col(1), win])
    agg = HashAggExecutor(
        proj, [2], [AggCall(AggKind.MAX, 1, sch[1].data_type,
                            append_only=True)],
        capacity=1 << 12)
    join = HashJoinExecutor(
        ChannelInput(ch_l, sch), agg,
        left_key_indices=[1], right_key_indices=[1],
        left_pk_indices=[0, 2], right_pk_indices=[0],
        key_capacity=1 << 12, row_capacity=1 << 14, match_factor=64)
    join_actor = Actor(2, join, None, coord)

    for actor, root in ((src_actor, src), (join_actor, join)):
        coord.register_actor(actor.actor_id)
        coord.stats.register("q7", actor, root)
    tasks = [src_actor.spawn(), join_actor.spawn()]

    from risingwave_tpu.stream.message import BarrierKind
    b = await coord.inject_barrier(kind=BarrierKind.INITIAL)
    await coord.wait_collected(b)
    lat = []
    prof_task = None
    prof_text = None
    i = 0
    while True:
        b = await coord.inject_barrier()
        await coord.wait_collected(b)
        if i >= WARMUP_INTERVALS:
            if profile_seconds and prof_task is None:
                from risingwave_tpu.utils.profiler import profile_cpu
                prof_task = asyncio.ensure_future(
                    asyncio.to_thread(profile_cpu, profile_seconds))
            lat.append(coord.latencies_ns[-1] / 1e6)
        i += 1
        if prof_task is not None:
            if prof_task.done():
                prof_text = prof_task.result()
                break
        elif i >= N_INTERVALS - 1:
            break
    b = await coord.inject_barrier(mutation=StopMutation(frozenset({1, 2})))
    await coord.wait_collected(b)
    for t in tasks:
        await t
    lat.sort()
    out = {"metric_level": metric_level,
           "p50_ms": round(lat[len(lat) // 2], 3),
           "p90_ms": round(lat[int(len(lat) * 0.9)], 3),
           "intervals": len(lat)}
    if prof_text is not None:
        from risingwave_tpu.utils.profiler import parse_collapsed
        stacks = parse_collapsed(prof_text)
        out["profile_samples"] = sum(c for _, c in stacks)
    if history_interval:
        per_series: dict = {}
        for r in coord.metrics_history.rows():
            key = (r["name"], tuple(sorted(r["labels"].items())))
            per_series[key] = per_series.get(key, 0) + 1
        out["history_series"] = len(per_series)
        out["history_min_samples"] = min(per_series.values(), default=0)
    return out


# ---------------------------------------------------------- exposition check

def parse_exposition(text: str) -> dict:
    """Minimal Prometheus text-format validator: returns
    family -> [(labels_str, value)], raising on malformed lines,
    ungrouped families, or mis-ordered histogram `le` buckets."""
    line_re = re.compile(
        r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})? ([0-9eE.+-]+|NaN|[+-]Inf)$")
    families: dict = {}
    seen_types: dict = {}
    current = None
    for ln in text.splitlines():
        if not ln.strip():
            continue
        if ln.startswith("# TYPE "):
            _, _, name, typ = ln.split(" ", 3)
            if name in seen_types:
                raise ValueError(f"family {name} declared twice")
            seen_types[name] = typ
            current = name
            continue
        if ln.startswith("#"):
            continue
        m = line_re.match(ln)
        if m is None:
            raise ValueError(f"malformed exposition line: {ln!r}")
        name = m.group(1)
        base = re.sub(r"_(bucket|sum|count)$", "", name)
        fam = name if name in seen_types else base
        if fam != current:
            raise ValueError(
                f"series {name} outside its family block ({current})")
        families.setdefault(fam, []).append(
            (m.group(2) or "", float(m.group(3))
             if m.group(3) not in ("+Inf", "-Inf", "NaN") else m.group(3)))
    # histogram le ordering per labelset
    for fam, typ in seen_types.items():
        if typ != "histogram":
            continue
        by_rest: dict = {}
        for labels, _v in families.get(fam, []):
            if '_le_sentinel' in labels:
                continue
            mle = re.search(r'le="([^"]+)"', labels)
            if mle is None:
                continue
            rest = re.sub(r'le="[^"]+",?', "", labels)
            by_rest.setdefault(rest, []).append(mle.group(1))
        for rest, les in by_rest.items():
            vals = [float("inf") if x == "+Inf" else float(x) for x in les]
            if vals != sorted(vals) or vals[-1] != float("inf"):
                raise ValueError(
                    f"histogram {fam}{rest}: le not ascending to +Inf: "
                    f"{les}")
    return families


async def _check_exposition() -> dict:
    """Serve /metrics from a LIVE session over a real socket and parse."""
    from risingwave_tpu.frontend import Session
    s = Session()
    await s.execute("SET metric_level = debug")
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        "chunk_size=128, rate_limit=128)")
    await s.execute(
        "CREATE MATERIALIZED VIEW obs_gate AS SELECT auction, price "
        "FROM bid")
    await s.tick(3)
    mon = await s.start_monitor(0)
    reader, writer = await asyncio.open_connection("127.0.0.1", mon.port)
    writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
    raw = await reader.read()
    writer.close()
    head, _, body = raw.decode().partition("\r\n\r\n")
    assert head.startswith("HTTP/1.0 200"), head
    families = parse_exposition(body)
    per_actor = [f for f in families if f.startswith("stream_actor_")]
    await s.stop_monitor()
    await s.drop_all()
    return {"families": len(families),
            "per_actor_families": sorted(per_actor),
            "row_series": len(families.get("stream_actor_row_count", []))}


# ------------------------------------------------------------ watchdog check

async def _check_watchdog() -> dict:
    """A registered actor that never collects must trip the watchdog."""
    from risingwave_tpu.meta.barrier_manager import BarrierCoordinator
    from risingwave_tpu.state import MemoryStateStore
    from risingwave_tpu.utils.metrics import GLOBAL_METRICS

    coord = BarrierCoordinator(MemoryStateStore())
    coord.stall_threshold_ms = 150.0
    coord.register_actor(999)                 # parked forever
    q: asyncio.Queue = asyncio.Queue()
    coord.register_source(q)
    stalls0 = GLOBAL_METRICS.counter("barrier_stalls_total").value
    buf = io.StringIO()
    # the report lands on STDERR (stdout is the JSON result channel)
    with contextlib.redirect_stderr(buf):
        b = await coord.inject_barrier()
        waiter = asyncio.ensure_future(coord.wait_collected(b))
        await asyncio.sleep(0.6)
        coord.collect(999, b)                 # un-park; epoch completes
        await waiter
    report = buf.getvalue()
    stalls = GLOBAL_METRICS.counter("barrier_stalls_total").value - stalls0
    return {"stalls_fired": stalls,
            "report_names_actor": "999" in report,
            "report_has_await_tree": "await tree" in report}


# ------------------------------------------------------------- cluster checks

CLUSTER_WARMUP = 4
CLUSTER_MEASURE = 12
PROFILE_PERTURB_LIMIT = 1.15

W = 10_000_000
CLUSTER_Q7_DDL = [
    ("CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
     "chunk_size=256, splits=2, rate_limit=512, inter_event_us=250, "
     f"emit_watermarks=1, watermark_lag_us={2 * W})"),
    ("CREATE MATERIALIZED VIEW q7 AS "
     "SELECT B.auction, B.price, B.bidder, B.date_time "
     "FROM bid B JOIN ("
     "  SELECT max(price) AS maxprice, window_end "
     f"  FROM TUMBLE(bid, date_time, {W}) GROUP BY window_end) B1 "
     "ON B.price = B1.maxprice "
     f"AND B.date_time > B1.window_end - {W} "
     "AND B.date_time <= B1.window_end"),
]


def _free_port() -> int:
    import socket
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_worker(port: int):
    import socket
    import subprocess
    import time
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "risingwave_tpu.worker", str(port)],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            socket.create_connection(("127.0.0.1", port),
                                     timeout=1).close()
            return p
        except OSError:
            time.sleep(0.2)
    p.terminate()
    raise RuntimeError("worker never started listening")


def _p50(xs):
    xs = sorted(xs)
    return round(xs[len(xs) // 2], 3) if xs else 0.0


async def _check_cluster() -> dict:
    """One 2-worker deployment, two checks:

    TRACE OVERHEAD — the q7 pipeline runs paced rounds with span
    recording at `metric_level=off` and again at `debug` (per-actor
    series + span shipping on every sealed report); the debug barrier
    p50 must stay within 10% of off.

    STALL REPORT — a worker-side `channel_stall` (the spec rides the
    cluster config push and fires inside the WORKER process) wedges an
    epoch past the watchdog threshold; the merged report meta prints
    must carry every live worker's section so it names the stalled
    worker AND its remaining actors."""
    import tempfile

    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore

    root = tempfile.mkdtemp(prefix="obsgate-cluster-")
    ports = [_free_port(), _free_port()]
    procs = [_spawn_worker(p) for p in ports]
    out: dict = {}
    try:
        s = Session(store=HummockStateStore(LocalFsObjectStore(
            os.path.join(root, "store"))))
        addr = ",".join(f"127.0.0.1:{p}" for p in ports)
        await s.execute(f"SET cluster = '{addr}'")
        for d in CLUSTER_Q7_DDL:
            await s.execute(d)

        # off runs twice (bracketing debug) so the cluster gate also
        # carries its own same-machine null baseline
        p50 = {"off": [], "debug": []}
        for mode in ("off", "debug", "off"):
            await s.execute(f"SET metric_level = {mode}")
            await s.tick(CLUSTER_WARMUP)
            n0 = len(s.coord.latencies_ns)
            await s.tick(CLUSTER_MEASURE)
            p50[mode].append(_p50([x / 1e6
                                   for x in s.coord.latencies_ns[n0:]]))
        off_best = min(p50["off"])
        out["trace_off_p50_ms"] = off_best
        out["trace_debug_p50_ms"] = p50["debug"][0]
        out["trace_ratio"] = round(
            p50["debug"][0] / max(off_best, 1e-9), 3)
        out["trace_limit"] = _calibrated_limit(p50["off"])

        await s.execute("SET barrier_stall_threshold_ms = 500")
        await s.execute(
            "SET fault_injection = 'channel_stall:ms=4000'")
        buf = io.StringIO()
        with contextlib.redirect_stderr(buf):
            await s.tick(3)
        report = buf.getvalue()
        stalls = s.event_log.records(kind="barrier_stall")
        out["stall_report_fired"] = "[stuck barrier]" in report
        out["stall_report_names_worker"] = (
            "== worker w1 ==" in report and "== worker w2 ==" in report)
        out["stall_report_names_actor"] = bool(
            stalls and stalls[-1].get("remaining"))
        out["stalled_actors"] = (stalls[-1]["remaining"]
                                 if stalls else [])
        await s.execute("SET fault_injection = ''")
        await s.shutdown()
    finally:
        for p in procs:
            if p.poll() is None:
                p.terminate()
                p.wait(timeout=10)
    return out


async def _check_profile_perturbation(baseline_p50: float) -> dict:
    """A 2s on-demand cpu profile sampled WHILE the q7 shape keeps
    pacing barriers must not move the barrier p50 by more than 15% —
    /debug/profile/cpu has to be safe to point at a live cluster."""
    runs = [await _run_q7("debug", profile_seconds=2.0)
            for _ in range(2)]
    prof_p50 = min(r["p50_ms"] for r in runs)
    return {"baseline_p50_ms": baseline_p50,
            "profiled_p50_ms": prof_p50,
            "ratio": round(prof_p50 / max(baseline_p50, 1e-9), 3),
            "profile_samples": max(r.get("profile_samples", 0)
                                   for r in runs)}


# ------------------------------------------------------ metrics history check

async def _check_history() -> dict:
    """METRICS HISTORY — two halves:

    OVERHEAD — the q7 shape runs with the barrier-paced sampler off
    (interval=0) and on (interval=1, full default allowlist at
    metric_level=debug); the sampling-on barrier p50 must stay within
    the same-machine calibrated limit of off, and every sampled series
    must hold >= 2 samples after the run.

    SQL SURFACE — a live Session ticks a real pipeline, then the
    history must answer through the batch pipeline: a GROUP BY over
    rw_metrics returns >= 2 samples per name, and a filtered aggregate
    (max of one series) returns a finite value."""
    import math

    p50 = {"off": [], "on": []}
    on_runs = []
    for _ in range(PASSES):
        for mode, interval in (("off", 0), ("on", 1)):
            r = await _run_q7("debug", history_interval=interval)
            p50[mode].append(r["p50_ms"])
            if mode == "on":
                on_runs.append(r)
    off_best, on_best = min(p50["off"]), min(p50["on"])
    out = {"off_p50_ms": off_best, "on_p50_ms": on_best,
           "ratio": round(on_best / max(off_best, 1e-9), 3),
           "limit": _calibrated_limit(p50["off"]),
           "series": max(r["history_series"] for r in on_runs),
           "min_samples": max(r["history_min_samples"] for r in on_runs)}

    from risingwave_tpu.frontend import Session
    s = Session()
    await s.execute("SET metric_level = debug")
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        "chunk_size=128, rate_limit=256)")
    await s.execute(
        "CREATE MATERIALIZED VIEW hist_gate AS SELECT auction, price "
        "FROM bid")
    await s.tick(8)
    counts = dict(s.query(
        "SELECT name, count(*) FROM rw_metrics GROUP BY name"))
    agg = s.query(
        "SELECT max(value) FROM rw_metrics "
        "WHERE name = 'meta_barrier_latency_seconds_p50'")
    await s.drop_all()
    out["sql_names"] = len(counts)
    out["sql_min_samples"] = int(min(counts.values(), default=0))
    out["sql_max_latency_p50"] = (float(agg[0][0])
                                  if agg and agg[0][0] is not None
                                  else None)
    out["sql_agg_finite"] = bool(
        agg and agg[0][0] is not None and math.isfinite(float(agg[0][0])))
    return out


# --------------------------------------------------- cross-engine trace check

async def _check_xengine_stitch() -> dict:
    """CROSS-ENGINE STITCH — two in-process engines chained through one
    broker topic (A: nexmark -> windowed-agg broker sink; B: broker
    source -> MV). Each engine's tracer exports its own chrome trace;
    `stitch_chrome_traces` must merge them into ONE Perfetto-loadable
    timeline with >= 1 sink-delivery -> source-ingest flow link."""
    import tempfile

    from risingwave_tpu.broker import (Broker, register_inproc,
                                       unregister_inproc)
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.utils.trace import (stitch_chrome_traces,
                                            traces_to_chrome)

    root = tempfile.mkdtemp(prefix="obsgate-xengine-")
    b = Broker(os.path.join(root, "broker"), fsync=False)
    register_inproc("obs_gate_x", b)
    try:
        a = Session()
        await a.execute("SET streaming_watchdog = 0")
        await a.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
            "chunk_size=128, inter_event_us=2000, rate_limit=512)")
        await a.execute(
            "CREATE SINK q7x AS SELECT window_end, max(price) AS mp "
            "FROM TUMBLE(bid, date_time, 1000000) GROUP BY window_end "
            "WITH (connector='broker', topic='q7x', "
            "brokers='inproc://obs_gate_x')")
        await a.tick(5)
        bs = Session()
        await bs.execute("SET streaming_watchdog = 0")
        await bs.execute(
            "CREATE SOURCE q7 WITH (connector='broker', topic='q7x', "
            "brokers='inproc://obs_gate_x', "
            "columns='window_end timestamp, mp int64', "
            "primary_key='window_end', chunk_size=64, "
            "discovery_interval_ms=0)")
        await bs.execute(
            "CREATE MATERIALIZED VIEW xout AS "
            "SELECT window_end, mp FROM q7")
        await bs.tick(5)
        ev_a = traces_to_chrome(a.coord.tracer.open_traces()
                                + a.coord.tracer.recent())
        ev_b = traces_to_chrome(bs.coord.tracer.open_traces()
                                + bs.coord.tracer.recent())
        merged, n_links = stitch_chrome_traces(
            ev_a, ev_b, a.engine_id, bs.engine_id)
        # Perfetto loads a flat chrome-format event array: every event
        # needs numeric ts and a ph; the stitched ids must still pair
        json.dumps(merged)
        bad = [e for e in merged
               if "ph" not in e
               or not isinstance(e.get("ts", 0), (int, float))]
        rows = bs.query("SELECT window_end, mp FROM xout")
        await a.drop_all()
        await bs.drop_all()
        return {"events_a": len(ev_a), "events_b": len(ev_b),
                "merged_events": len(merged), "links": n_links,
                "malformed_events": len(bad), "rows_through": len(rows)}
    finally:
        unregister_inproc("obs_gate_x")


async def main() -> int:
    # overhead: alternate modes, best median per mode
    p50 = {"off": [], "debug": []}
    for _ in range(PASSES):
        for mode in ("off", "debug"):
            r = await _run_q7(mode)
            p50[mode].append(r["p50_ms"])
    off_p50, dbg_p50 = min(p50["off"]), min(p50["debug"])
    limit = _calibrated_limit(p50["off"])
    overhead = {"off_p50_ms": off_p50, "debug_p50_ms": dbg_p50,
                "ratio": round(dbg_p50 / max(off_p50, 1e-9), 3),
                "limit": limit,
                "passes": p50}
    expo = await _check_exposition()
    wd = await _check_watchdog()
    perturb = await _check_profile_perturbation(dbg_p50)
    # cluster keeps its original slot (same process state as ever for
    # its timing comparison); the new checks run after it
    cluster = await _check_cluster()
    hist = await _check_history()
    xeng = await _check_xengine_stitch()
    verdict = {
        "overhead_within_calibrated_limit": dbg_p50 <= off_p50 * limit,
        "exposition_valid": expo["row_series"] > 0,
        "watchdog_fired": (wd["stalls_fired"] >= 1
                           and wd["report_names_actor"]
                           and wd["report_has_await_tree"]),
        "cluster_trace_overhead_within_calibrated_limit":
            cluster["trace_ratio"] <= cluster["trace_limit"],
        "cluster_stall_report_names_worker_actor": (
            cluster["stall_report_fired"]
            and cluster["stall_report_names_worker"]
            and cluster["stall_report_names_actor"]),
        "cpu_profile_perturbation_within_15pct": (
            perturb["ratio"] <= PROFILE_PERTURB_LIMIT
            and perturb["profile_samples"] > 10),
        "history_overhead_within_calibrated_limit":
            hist["ratio"] <= hist["limit"],
        "history_queryable_via_sql": (
            hist["min_samples"] >= 2 and hist["sql_names"] > 0
            and hist["sql_min_samples"] >= 2 and hist["sql_agg_finite"]),
        "xengine_stitched_with_links": (
            xeng["links"] >= 1 and xeng["malformed_events"] == 0
            and xeng["rows_through"] > 0),
    }
    print(json.dumps({"overhead": overhead}))
    print(json.dumps({"exposition": expo}))
    print(json.dumps({"watchdog": wd}))
    print(json.dumps({"profile_perturbation": perturb}))
    print(json.dumps({"history": hist}))
    print(json.dumps({"xengine": xeng}))
    print(json.dumps({"cluster": cluster}))
    print(json.dumps({"verdict": verdict}))
    return 0 if all(verdict.values()) else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

"""Compaction & retention soak gate — a compressed 7-day churn run.

Two identical durable churn runs (nexmark bid -> filtered MV, with the
scrubber on and two mid-run backup generations), one with the
background compactor ENABLED (the default) and one with it DISABLED
(SET compaction_interval = 0, the inline commit-path fallback). The
enabled run must show a compacted LSM with no loop-side cost:

  * the commit path never runs a full-state merge — the store's
    inline_compaction flag stays off for the whole enabled run, every
    merge lands through the background install path
    (compactor.runs_total > 0);
  * L0 depth and read amplification stay BOUNDED at every soak
    checkpoint (depth <= trigger + in-flight slack, read amp <=
    depth + 1) while the disabled run's L0 saws up to the inline
    threshold;
  * barrier p50 with the compactor is no worse than with compaction
    disabled (tolerance 1.5x for CPU timing noise) — merging off the
    loop must not slow the loop;
  * the scrubber is CLEAN at every checkpoint: zero corruptions, and
    no object referenced by the manifest, a pinned snapshot, or a
    backup generation was deleted (verify_backup passes over BOTH
    retained generations at the end, point-in-time restore intact);
  * a NEW MV created mid-churn (after merges have rewritten history)
    backfills to exactly the same rows as the original — compaction
    never changes what a backfill reads;
  * the final MV is BIT-IDENTICAL to a numpy recount of the generator
    prefix at the committed source offset (exactly-once under churn).

Prints one JSON report; exits non-zero if any bound fails.

CI usage (CPU backend):

    JAX_PLATFORMS=cpu python scripts/compaction_profile.py
"""

import asyncio
import json
import os
import sys
import time
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

BARRIERS = 48
CHECK_EVERY = 8
PRICE_FLOOR = 5_000_000
P50_TOLERANCE = 1.5
L0_TRIGGER = 4


def _ddl() -> list:
    return [
        "SET streaming_watchdog = 0",
        "SET storage_scrub_interval = 4",
        "SET storage_scrub_batch = 8",
        f"SET compaction_l0_trigger = {L0_TRIGGER}",
        ("CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
         "chunk_size=128, rate_limit=512)"),
        ("CREATE MATERIALIZED VIEW mv AS SELECT auction, price FROM bid "
         f"WHERE price > {PRICE_FLOOR}"),
    ]


def _oracle(offset: int) -> Counter:
    """Numpy recount of the bid generator prefix at the committed
    offset — the exactly-once convergence target."""
    import numpy as np
    from risingwave_tpu.connectors import NexmarkGenerator
    gen = NexmarkGenerator("bid", chunk_size=max(256, offset))
    c = gen.next_chunk()
    auction = np.asarray(c.columns[0].data)[:offset]
    price = np.asarray(c.columns[2].data)[:offset]
    keep = price > PRICE_FLOOR
    return Counter(zip(auction[keep].tolist(), price[keep].tolist()))


def _committed_offset(session, mv: str = "mv") -> int:
    from risingwave_tpu.state.storage_table import StorageTable
    from risingwave_tpu.stream.source import SourceExecutor
    dep = session.catalog.mvs[mv].deployment
    for roots in dep.roots.values():
        for root in roots:
            node = root
            while node is not None:
                if isinstance(node, SourceExecutor):
                    rows = list(StorageTable.for_state_table(
                        node.state_table).batch_iter())
                    return int(rows[0][1]) if rows else 0
                node = getattr(node, "input", None)
    raise AssertionError("no source executor")


async def _churn(tmp: str, enabled: bool) -> dict:
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.state import HummockStateStore, LocalFsObjectStore
    from risingwave_tpu.state.backup import verify_backup

    root = os.path.join(tmp, "enabled" if enabled else "disabled")
    bak_dir = os.path.join(root, "bak")
    s = Session(store=HummockStateStore(
        LocalFsObjectStore(os.path.join(root, "live"))))
    for sql in _ddl():
        await s.execute(sql)
    if not enabled:
        await s.execute("SET compaction_interval = 0")

    barrier_s: list = []
    checkpoints: list = []
    failures: list = []
    backfill_ok = None
    for i in range(1, BARRIERS + 1):
        t0 = time.monotonic()
        await s.tick(1)
        barrier_s.append(time.monotonic() - t0)
        if enabled and s.store.inline_compaction:
            failures.append(f"inline merge re-enabled at barrier {i}")
        if i == BARRIERS // 3:
            await s.execute(f"BACKUP TO '{bak_dir}'")      # generation 1
        if i == 2 * BARRIERS // 3:
            await s.execute(f"BACKUP TO '{bak_dir}'")      # generation 2
            # stable backfill: a NEW MV over history the compactor has
            # already rewritten must read the same world
            await s.execute(
                "CREATE MATERIALIZED VIEW mv2 AS SELECT auction, price "
                f"FROM bid WHERE price > {PRICE_FLOOR}")
        if i % CHECK_EVERY == 0:
            scrub = s.coord.scrubber.report()
            cp = {
                "barrier": i,
                "l0_runs": s.store.l0_run_count(),
                "read_amp": s.store.read_amp(),
                "scrub_corruptions": scrub["corruptions"],
            }
            checkpoints.append(cp)
            if scrub["corruptions"]:
                failures.append(f"scrub corruption at barrier {i}: {scrub}")
            if enabled:
                # bounded depth: the trigger plus one landing run and
                # one in-flight merge output of slack
                if cp["l0_runs"] > L0_TRIGGER + 3:
                    failures.append(
                        f"L0 depth {cp['l0_runs']} exceeds bound "
                        f"at barrier {i}")
                if cp["read_amp"] > L0_TRIGGER + 4:
                    failures.append(
                        f"read amp {cp['read_amp']} exceeds bound "
                        f"at barrier {i}")

    # the new MV's backfill reads the same world: bit-identical to the
    # generator oracle at ITS committed offset (it may still be
    # catching up to mv under the rate limit — correctness, not lag)
    got_mv = Counter(s.query("SELECT auction, price FROM mv"))
    got_mv2 = Counter(s.query("SELECT auction, price FROM mv2"))
    offset2 = _committed_offset(s, "mv2")
    backfill_ok = got_mv2 == _oracle(offset2)
    if not backfill_ok:
        failures.append(
            f"backfilled mv2 diverged from the oracle at offset "
            f"{offset2} ({sum(got_mv2.values())} rows)")

    # bit-identical to the generator-prefix oracle
    offset = _committed_offset(s)
    expected = _oracle(offset)
    converged = got_mv == expected
    if not converged:
        failures.append(
            f"final MV diverged from the oracle at offset {offset}")

    # no object any backup generation references was deleted: both
    # retained generations still verify end to end
    from risingwave_tpu.state import LocalFsObjectStore as _Fs
    ledger = verify_backup(_Fs(bak_dir))
    generations = sorted(int(g) for g in (ledger.get("generations") or {}))

    comp = s.coord.compactor
    srt = sorted(barrier_s)
    out = {
        "enabled": enabled,
        "barriers": BARRIERS,
        "barrier_p50_ms": round(srt[len(srt) // 2] * 1e3, 2),
        "barrier_p90_ms": round(srt[int(len(srt) * 0.9)] * 1e3, 2),
        "final_l0_runs": s.store.l0_run_count(),
        "final_read_amp": s.store.read_amp(),
        "compaction_runs": comp.runs_total,
        "bytes_rewritten": comp.bytes_rewritten_total,
        "merge_failures": comp.merge_failures,
        "installs_abandoned": comp.installs_abandoned,
        "mv_rows": sum(got_mv.values()),
        "offset": offset,
        "converged": converged,
        "backfill_ok": backfill_ok,
        "backup_generations": generations,
        "checkpoints": checkpoints,
        "failures": failures,
    }
    if enabled and comp.runs_total == 0:
        failures.append("compactor never ran a background merge")
    if enabled and len(generations) < 2:
        failures.append(f"expected 2 retained generations, got "
                        f"{generations}")
    await s.drop_all()
    return out


async def main() -> int:
    import tempfile
    with tempfile.TemporaryDirectory(prefix="compaction_gate_") as tmp:
        enabled = await _churn(tmp, enabled=True)
        disabled = await _churn(tmp, enabled=False)
    failures = list(enabled["failures"]) + [
        f"[disabled] {f}" for f in disabled["failures"]]
    # the loop-cost acceptance bound: background merging must not slow
    # the barrier path relative to no compaction at all
    if enabled["barrier_p50_ms"] > disabled["barrier_p50_ms"] * P50_TOLERANCE:
        failures.append(
            f"barrier p50 regressed: {enabled['barrier_p50_ms']}ms with "
            f"compactor vs {disabled['barrier_p50_ms']}ms without")
    report = {
        "enabled": enabled,
        "disabled": disabled,
        "p50_ratio": round(enabled["barrier_p50_ms"]
                           / max(disabled["barrier_p50_ms"], 1e-6), 3),
        "failures": failures,
        "ok": not failures,
    }
    print(json.dumps(report, indent=2))
    return 0 if not failures else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

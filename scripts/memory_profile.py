"""HBM memory-manager gate — growing-keyspace q7 shape, no TPU needed.

A windowed agg + join pipeline whose keyspace GROWS every interval (new
windows arrive, old ones go cold, the occasional late row touches an old
window again) runs twice:

  unbounded   hbm_budget_bytes = 0 — today's grow-forever behavior;
              the run's peak accounted bytes is the reference point
  budgeted    hbm_budget_bytes = ~half the unbounded peak — the
              MemoryManager evicts cold slots to host at barriers and
              late rows reload through the read-through path

Exit status is 0 iff, after warmup:
  * the budgeted run's accounted device state stays under budget at
    every barrier,
  * eviction and at least one read-through reload actually happened,
  * the materialized results (changelog applied to a dict) and the join
    match multiset are IDENTICAL to the unbounded run.

    JAX_PLATFORMS=cpu python scripts/memory_profile.py
"""

import asyncio
import json
import os
import sys
from collections import Counter

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# persistent XLA compilation cache (utils/compile_cache.py): the
# gate re-runs a canned shape every CI round — repeat runs skip the
# compile entirely
from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


N_INTERVALS = 24
WARMUP_INTERVALS = 10
ROWS_PER_INTERVAL = 192
CHUNK_CAP = 256
WINDOW = 1 << 10


def _bid_schema():
    from risingwave_tpu.common import DataType, schema
    return schema(("auction", DataType.INT64), ("price", DataType.INT64),
                  ("window_end", DataType.INT64))


class _Script:
    def __init__(self, sch, messages):
        self.schema = sch
        self.messages = messages
        self.identity = "MemoryProfileSource"
        self.pk_indices = ()

    def fence_tokens(self):
        return []

    async def execute(self):
        for m in self.messages:
            yield m
            await asyncio.sleep(0)


def _script_messages(seed: int) -> list:
    """Growing keyspace: each interval's rows land in a FRESH window
    (plus a sprinkle of late rows into windows several intervals old —
    the read-through reload workload)."""
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.common.epoch import EpochPair
    from risingwave_tpu.stream.message import Barrier, BarrierKind
    rng = np.random.RandomState(seed)
    sch = _bid_schema()
    msgs = [Barrier(EpochPair(1, 0), BarrierKind.INITIAL)]
    for e in range(N_INTERVALS):
        w_end = (e + 1) * WINDOW
        n = ROWS_PER_INTERVAL
        auction = rng.randint(0, 40, size=n).astype(np.int64)
        price = rng.randint(1, 2_000, size=n).astype(np.int64)
        wend = np.full(n, w_end, dtype=np.int64)
        if e >= 6:
            # late rows re-open a long-cold window
            k = 4
            wend[:k] = (e - 5) * WINDOW
        msgs.append(StreamChunk.from_numpy(
            sch, [auction, price, wend], capacity=CHUNK_CAP))
        msgs.append(Barrier(EpochPair(e + 2, e + 1)))
    return msgs


async def _run(budget_bytes: int) -> dict:
    """agg: max(price) per (window_end, auction); join: bids back against
    the agg output on window_end — both stateful stages grow with the
    keyspace unless the manager evicts."""
    from risingwave_tpu.common import DataType, schema
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.expr.agg import AggCall, AggKind
    from risingwave_tpu.memory import MemoryManager
    from risingwave_tpu.state import MemoryStateStore, StateTable
    from risingwave_tpu.stream import HashAggExecutor
    from risingwave_tpu.stream.hash_join import HashJoinExecutor
    from risingwave_tpu.stream.message import Barrier

    sch = _bid_schema()
    store = MemoryStateStore()
    agg_state = StateTable(
        store, 1, schema(("window_end", DataType.INT64),
                         ("auction", DataType.INT64),
                         ("state0", DataType.INT64),
                         ("_row_count", DataType.INT64)), (0, 1))
    join_states = (
        StateTable(store, 2, sch, (0, 1, 2)),
        StateTable(store, 3, schema(("window_end", DataType.INT64),
                                    ("auction", DataType.INT64),
                                    ("maxp", DataType.INT64)), (0, 1)),
    )
    agg = HashAggExecutor(
        _Script(sch, _script_messages(seed=7)), [2, 0],
        [AggCall(AggKind.MAX, 1, sch[1].data_type, append_only=True)],
        capacity=1 << 12, state_table=agg_state)
    join = HashJoinExecutor(
        _Script(sch, _script_messages(seed=7)), agg,
        left_key_indices=[2], right_key_indices=[0],
        left_pk_indices=[0, 1, 2], right_pk_indices=[0, 1],
        key_capacity=1 << 12, row_capacity=1 << 13, match_factor=64,
        state_tables=join_states)
    mgr = MemoryManager()
    mgr.register("agg", agg)
    mgr.register("join", join)
    mgr.configure(budget_bytes=budget_bytes)

    from risingwave_tpu.common.chunk import OP_INSERT, OP_UPDATE_INSERT
    mat: dict = {}
    # NET multiset of joined rows (insert +1 / delete -1): the join's
    # transient changelog interleaving is alignment-dependent (two-input
    # polling order), but the net materialized result must be exact
    matches = Counter()
    peak = peak_after_warmup = 0
    barriers = 0
    over_budget_barriers = 0
    async for msg in join.execute():
        if isinstance(msg, StreamChunk):
            for op, row in msg.to_rows():
                if op in (OP_INSERT, OP_UPDATE_INSERT):
                    matches[row] += 1
                else:
                    matches[row] -= 1
                    if matches[row] == 0:
                        del matches[row]
        elif isinstance(msg, Barrier):
            barriers += 1
            mgr.on_barrier(msg.epoch.curr)
            total = mgr.total_bytes()
            peak = max(peak, total)
            if barriers > WARMUP_INTERVALS:
                peak_after_warmup = max(peak_after_warmup, total)
                if budget_bytes and total > budget_bytes:
                    over_budget_barriers += 1
    # the materialized agg result via a second pass over its state table
    for _, row in agg_state.iter_all():
        mat[row[:2]] = row
    return {
        "budget_bytes": budget_bytes,
        "peak_bytes": peak,
        "peak_after_warmup": peak_after_warmup,
        "over_budget_barriers": over_budget_barriers,
        "evicted_bytes": agg.mem_evicted_bytes + join.mem_evicted_bytes,
        "reloads": agg.mem_reload_count + join.mem_reload_count,
        "spilled_rows": agg.mem_spilled_rows + join.mem_spilled_rows,
        "mat": mat,
        "matches": matches,
    }


async def main() -> int:
    base = await _run(0)
    budget = base["peak_bytes"] // 2
    bud = await _run(budget)
    verdict = {
        "budget_bytes": budget,
        "unbounded_peak": base["peak_bytes"],
        "budgeted_peak_after_warmup": bud["peak_after_warmup"],
        "under_budget_after_warmup": bud["over_budget_barriers"] == 0,
        "evicted_bytes": bud["evicted_bytes"],
        "reloads": bud["reloads"],
        "spilled_rows_final": bud["spilled_rows"],
        "mat_rows": len(base["mat"]),
        "results_identical": (base["mat"] == bud["mat"]
                              and base["matches"] == bud["matches"]),
    }
    print(json.dumps({k: v for k, v in base.items()
                      if k not in ("mat", "matches")}))
    print(json.dumps({k: v for k, v in bud.items()
                      if k not in ("mat", "matches")}))
    print(json.dumps({"verdict": verdict}))
    ok = (verdict["under_budget_after_warmup"]
          and verdict["evicted_bytes"] > 0
          and verdict["reloads"] > 0
          and verdict["results_identical"]
          and verdict["mat_rows"] > 0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

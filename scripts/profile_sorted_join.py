"""Microbenchmark SortedJoinExecutor's apply path in q7/q8 shapes.

Flat-out device throughput of the per-chunk program (probe + evict +
merge), no barriers, no host pipeline — the ceiling the bench configs
are sized against. No d2h transfers inside the timed loop (tunneled-TPU
contract); one block_until_ready at the end.
"""

import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

from risingwave_tpu.common import DataType
from risingwave_tpu.common.types import schema
from risingwave_tpu.connectors import NexmarkGenerator
from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.expr import call, col, lit
from risingwave_tpu.stream.executor import Executor
from risingwave_tpu.stream.sorted_join import SortedJoinExecutor


class Dummy(Executor):
    def __init__(self, sch):
        self.schema = sch


def bench_q8_shape(chunk_size=131072, capacity=1 << 16, n_iter=60):
    cfg = NexmarkConfig(inter_event_us=100)
    W = 10_000_000
    gen_p = NexmarkGenerator("person", chunk_size=chunk_size, cfg=cfg)
    gen_a = NexmarkGenerator("auction", chunk_size=chunk_size, cfg=cfg)
    P2 = schema(("id", DataType.INT64), ("window_start", DataType.TIMESTAMP))
    A2 = schema(("seller", DataType.INT64), ("window_start", DataType.TIMESTAMP))
    join = SortedJoinExecutor(
        Dummy(P2), Dummy(A2),
        left_key_indices=[0, 1], right_key_indices=[0, 1],
        left_pk_indices=[0, 1], right_pk_indices=[0, 1],
        capacity=capacity, match_factor=2, output_indices=[0, 1],
        append_only=(True, True), clean_watermark_cols=(1, 1),
        watchdog_interval=None)

    proj_p = [col(0), call("tumble_start", col(6, DataType.TIMESTAMP), lit(W))]
    proj_a = [col(7), call("tumble_start", col(5, DataType.TIMESTAMP), lit(W))]

    def next2(gen, exprs, sch):
        c = gen.next_chunk()
        cols = tuple(e.eval(c.columns) for e in exprs)
        from risingwave_tpu.common.chunk import StreamChunk
        return StreamChunk(cols, c.ops, c.vis, sch)

    # warmup / compile
    cp = next2(gen_p, proj_p, P2)
    ca = next2(gen_a, proj_a, A2)
    wm = jnp.int64(0)
    out = join._apply(join.sides[0], join.sides[1], join._errs_dev, cp, wm, side=0)
    join.sides[0] = out[0]
    out = join._apply(join.sides[1], join.sides[0], join._errs_dev, ca, wm, side=1)
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    rows = 0
    for i in range(n_iter):
        cp = next2(gen_p, proj_p, P2)
        wm_p = jnp.int64(gen_p.current_watermark() - W)
        (join.sides[0], od, _, _, vis0, join._errs_dev, _) = join._apply(
            join.sides[0], join.sides[1], join._errs_dev, cp, wm_p, side=0)
        ca = next2(gen_a, proj_a, A2)
        wm_a = jnp.int64(gen_a.current_watermark() - W)
        (join.sides[1], od, _, _, vis1, join._errs_dev, _) = join._apply(
            join.sides[1], join.sides[0], join._errs_dev, ca, wm_a, side=1)
        rows += 2 * chunk_size
    jax.block_until_ready(join.sides[1].n)
    dt = time.perf_counter() - t0
    errs = np.asarray(join._errs_dev)
    print(f"q8-shape: chunk={chunk_size} cap={capacity} "
          f"{rows/dt/1e6:8.1f}M rows/s   ({dt/ (2*n_iter) *1e3:.2f} ms/apply)  "
          f"errs={errs.tolist()}  n=({int(join.sides[0].n)},{int(join.sides[1].n)})")
    return rows / dt


def bench_q7_shape(chunk_size=131072, capacity=1 << 18, n_iter=60):
    cfg = NexmarkConfig(inter_event_us=250)
    W = 10_000_000
    gen = NexmarkGenerator("bid", chunk_size=chunk_size, cfg=cfg)
    BID4 = schema(("auction", DataType.INT64), ("bidder", DataType.INT64),
                  ("price", DataType.INT64), ("date_time", DataType.TIMESTAMP))
    AGG = schema(("window_end", DataType.TIMESTAMP), ("maxprice", DataType.INT64))
    join = SortedJoinExecutor(
        Dummy(BID4), Dummy(AGG),
        left_key_indices=[2], right_key_indices=[1],
        left_pk_indices=[0, 1, 2, 3], right_pk_indices=[0],
        capacity=capacity, match_factor=2,
        append_only=(True, False), clean_watermark_cols=(3, None),
        watchdog_interval=None)
    proj = [col(0), col(1), col(2), col(5, DataType.TIMESTAMP)]

    def next4():
        c = gen.next_chunk()
        cols = tuple(e.eval(c.columns) for e in proj)
        from risingwave_tpu.common.chunk import StreamChunk
        return StreamChunk(cols, c.ops, c.vis, BID4)

    cb = next4()
    wm = jnp.int64(0)
    out = join._apply(join.sides[0], join.sides[1], join._errs_dev, cb, wm, side=0)
    join.sides[0] = out[0]
    jax.block_until_ready(out)

    t0 = time.perf_counter()
    rows = 0
    for i in range(n_iter):
        cb = next4()
        wm_b = jnp.int64(gen.current_watermark() - 2 * W)
        (join.sides[0], od, _, _, vis0, join._errs_dev, _) = join._apply(
            join.sides[0], join.sides[1], join._errs_dev, cb, wm_b, side=0)
        rows += chunk_size
    jax.block_until_ready(join.sides[0].n)
    dt = time.perf_counter() - t0
    errs = np.asarray(join._errs_dev)
    print(f"q7-shape: chunk={chunk_size} cap={capacity} "
          f"{rows/dt/1e6:8.1f}M rows/s   ({dt/n_iter*1e3:.2f} ms/apply)  "
          f"errs={errs.tolist()}  n_left={int(join.sides[0].n)}")
    return rows / dt


if __name__ == "__main__":
    print("devices:", jax.devices())
    for cs in (65536, 131072, 262144):
        bench_q8_shape(chunk_size=cs)
    for cs in (65536, 131072, 262144):
        bench_q7_shape(chunk_size=cs)

"""Mesh-execution gate — fused mesh-resident CHAIN vs host paths.

Runs the same q7-shaped windowed-agg SQL three ways on an 8-device
VIRTUAL CPU mesh (`--xla_force_host_platform_device_count=8` — no TPU
needed):

  host         SET streaming_parallelism = 8    8 actors, HashDispatcher
                                                + host channels + Merge
  mesh_unfused SET streaming_parallelism_devices = 8
               SET streaming_mesh_chain = 0     the PR 8 per-fragment
                                                plane: producer stages
                                                run on the host per
                                                chunk, the sharded agg
                                                re-ingests each interval
  mesh         SET streaming_parallelism_devices = 8
                                                the producer -> shuffle
                                                -> consumer chain fused
                                                into one shard_map
                                                program per barrier
                                                interval — hollow
                                                producer stages run as
                                                preludes INSIDE it,
                                                zero per-chunk host hops

Exit status is 0 iff ALL hold:
  * ALL paths' materialized results equal the host recount of the
    generator prefix at their exact source offsets (sources free-run
    between paced barriers, so offsets are load-dependent; exact
    content equality at the observed offset is the deterministic form
    of "identical results" — any common prefix agrees transitively)
  * fused device dispatches per interval strictly below the host path's
    (the fused program count must not scale with shard count)
  * the fused plane actually engaged: mesh_shuffle_applies > 0, the
    fragment registered with the coordinator as ONE actor x 8 shards,
    and zero mesh_shuffle_dropped_rows_total
  * the CHAIN fused: a mesh chain registered in both mesh modes,
    mesh_host_round_trips_total stays ZERO per fused steady interval,
    and the unfused plane pays >= 2x the fused plane's per-interval
    host transfers (>= 2 per interval vs 0 — the two hollowed producer
    stages' worth)
  * the q5-shaped top-N (ORDER BY n DESC LIMIT 10 over the retracting
    agg changelog) mesh-lowers: exactly ONE fused top-N dispatch per
    barrier interval, >= 3x fewer dispatches/interval than the
    single-device plan (topn_host: 8 actors, one chip), zero shuffle
    drops, and both planes match the characterization oracle at their
    exact offsets

    JAX_PLATFORMS=cpu python scripts/mesh_profile.py
"""

import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# 8 virtual devices BEFORE jax initializes (tests/conftest.py discipline)
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

from risingwave_tpu.utils.compile_cache import enable_persistent_cache  # noqa: E402

enable_persistent_cache()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

N_DEVICES = 8
WARMUP_ROUNDS = 3
MEASURE_ROUNDS = 6
W = 10_000_000          # 10s tumble window, microseconds

# q7-shaped windowed agg, with `auction` added to the group key so the
# vnode routing actually spreads over all 8 shards (window_end alone is
# one vnode per interval — maximal skew, which the zero-drop sizing
# handles but which exercises only one shard's table)
SQL = ("SELECT auction, window_end, max(price) AS maxprice, "
       "count(*) AS n "
       f"FROM TUMBLE(bid, date_time, {W}) GROUP BY auction, window_end")


def _oracle(n: int) -> list:
    """Host recount of the first n bid rows: per tumble window
    (max(price), count(*)) — the single-device semantics of SQL above."""
    import numpy as np
    from risingwave_tpu.connectors import NexmarkGenerator
    gen = NexmarkGenerator("bid", chunk_size=max(256, n))
    c = gen.next_chunk()
    cols = [np.asarray(col.data)[:n] for col in c.columns]
    auction, price, ts = cols[0], cols[2], cols[5]
    we = ts - ts % W + W
    agg: dict = {}
    for a, w, p in zip(auction, we, price):
        k = (int(a), int(w))
        m, cnt = agg.get(k, (0, 0))
        agg[k] = (max(m, int(p)), cnt + 1)
    return sorted((a, w, m, cnt) for (a, w), (m, cnt) in agg.items())


TOPN_K = 10
# q5-shaped top-N: ORDER BY n DESC LIMIT k over a retracting agg
# changelog. Small source chunks mean many chunks per interval: the
# single-device plane pays per-chunk dispatches the fused mesh plane
# collapses into scan-batched programs per interval.
TOPN_AGG_SQL = ("SELECT auction AS a, count(*) AS n FROM bid "
                "GROUP BY auction")
TOPN_SQL = f"SELECT a, n FROM counts ORDER BY n DESC LIMIT {TOPN_K}"


def _topn_check(rows, offset: int) -> bool:
    """Characterization oracle for the q5 top-N at an exact offset:
    every materialized (a, n) matches the host recount, the order-key
    multiset equals the recount's top-k (ties at the boundary may pick
    either key — all executors share the same hash tie-break, so any
    one run is bit-identical to a single-device run over the same
    chunks), and the row count is exactly min(k, groups)."""
    import numpy as np
    from risingwave_tpu.connectors import NexmarkGenerator
    gen = NexmarkGenerator("bid", chunk_size=max(256, offset))
    c = gen.next_chunk()
    auction = np.asarray(c.columns[0].data)[:offset]
    cnt: dict = {}
    for a in auction:
        cnt[int(a)] = cnt.get(int(a), 0) + 1
    want_ns = sorted(cnt.values(), reverse=True)[:TOPN_K]
    got_ns = sorted((int(n) for _, n in rows), reverse=True)
    return (got_ns == want_ns
            and all(cnt.get(int(a)) == int(n) for a, n in rows)
            and len(rows) == min(TOPN_K, len(cnt)))


def _dispatches() -> int:
    from risingwave_tpu.utils.metrics import GLOBAL_METRICS
    snap = GLOBAL_METRICS.snapshot()
    return int(sum(e["value"] for e in snap.get("device_dispatch_count", [])
                   if not e["labels"]))


def _sources(session):
    from risingwave_tpu.stream.source import SourceExecutor
    out = []
    for mv in session.catalog.mvs.values():
        for roots in mv.deployment.roots.values():
            for root in roots:
                node = root
                while node is not None:
                    if isinstance(node, SourceExecutor):
                        out.append(node)
                    node = getattr(node, "input", None)
    return out


def _sharded_aggs(session):
    from risingwave_tpu.stream.sharded_agg import ShardedHashAggExecutor
    out = []
    for mv in session.catalog.mvs.values():
        for roots in mv.deployment.roots.values():
            for root in roots:
                node = root
                while node is not None:
                    if isinstance(node, ShardedHashAggExecutor):
                        out.append(node)
                    node = getattr(node, "input", None)
    return out


async def _run(mode: str) -> dict:
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.stream.monitor import mesh_host_round_trips
    from risingwave_tpu.utils.metrics import MESH_SHUFFLE_DROPPED
    s = Session()
    await s.execute("SET streaming_durability = 0")
    if mode.startswith("mesh"):
        await s.execute(f"SET streaming_parallelism_devices = {N_DEVICES}")
    else:
        await s.execute(f"SET streaming_parallelism = {N_DEVICES}")
    if mode == "mesh_unfused":
        # PR 8 comparison plane: the chain still registers (so the
        # host-hop counter runs) but the producer stages stay host-side
        await s.execute("SET streaming_mesh_chain = 0")
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        "chunk_size=256, rate_limit=1024)")
    await s.execute(f"CREATE MATERIALIZED VIEW m AS {SQL}")
    aggs = _sharded_aggs(s)
    n_actors = len(s.coord.actor_ids)
    mesh_frags = dict(s.coord.mesh_fragments)
    mesh_chains = {c: dict(info) for c, info in s.coord.mesh_chains.items()}
    await s.tick(WARMUP_ROUNDS)
    drop0 = MESH_SHUFFLE_DROPPED.value
    d0 = _dispatches()
    h0 = mesh_host_round_trips()
    await s.tick(MEASURE_ROUNDS)
    d1 = _dispatches()
    h1 = mesh_host_round_trips()
    # quiesce BEFORE reading: sources free-run between barriers, so
    # without a Pause the connector offset runs ahead of the last
    # materialized interval and the oracle comparison races (bench.py's
    # quiesce phase, same reason)
    from risingwave_tpu.stream.message import PauseMutation
    b = await s.coord.inject_barrier(mutation=PauseMutation())
    await s.coord.wait_collected(b)
    rows = sorted(s.query(
        "SELECT auction, window_end, maxprice, n FROM m"))
    offset = max(g.connector.offset for g in _sources(s))
    out = {
        "mode": mode,
        "actors": n_actors,
        "mesh_fragments": {str(a): n for a, (n, _) in mesh_frags.items()},
        "mesh_chains": mesh_chains,
        "dispatches_per_interval": round((d1 - d0) / MEASURE_ROUNDS, 2),
        "host_hops_per_interval": round((h1 - h0) / MEASURE_ROUNDS, 2),
        "rows": len(rows),
        "offset": offset,
        "matches_oracle": rows == _oracle(offset),
        "fused_applies": sum(a.mesh_shuffle_applies for a in aggs),
        "sharded_aggs": len(aggs),
        "shuffle_dropped": int(MESH_SHUFFLE_DROPPED.value - drop0),
    }
    await s.drop_all()
    return out


def _sharded_topns(session):
    from risingwave_tpu.stream.sharded_top_n import ShardedTopNExecutor
    out = []
    for mv in session.catalog.mvs.values():
        for roots in mv.deployment.roots.values():
            for root in roots:
                node = root
                while node is not None:
                    if isinstance(node, ShardedTopNExecutor):
                        out.append(node)
                    node = getattr(node, "input", None)
    return out


async def _run_topn(mode: str) -> dict:
    """q5-shaped top-N over the retracting agg changelog: `topn_host`
    deploys the single-DEVICE plan (8 host actors, every dispatch lands
    on one chip — the same baseline the q7 gate uses), `topn_mesh` the
    fused mesh fragments over 8 devices."""
    from risingwave_tpu.frontend import Session
    from risingwave_tpu.stream.message import PauseMutation
    from risingwave_tpu.utils.metrics import MESH_SHUFFLE_DROPPED
    s = Session()
    await s.execute("SET streaming_durability = 0")
    if mode == "topn_mesh":
        await s.execute(f"SET streaming_parallelism_devices = {N_DEVICES}")
    else:
        await s.execute(f"SET streaming_parallelism = {N_DEVICES}")
    # the top-N store retains the FULL agg changelog input (retraction
    # support), i.e. one row per distinct auction — size it above the
    # distinct-key count at the offsets this run reaches
    await s.execute("SET streaming_top_n_capacity = 65536")
    # small chunks: the generator is throughput-bound, so chunk_size
    # sets the per-interval CHUNK count — the axis the fused plane
    # collapses (scan-batched ingest) and the single-device plane pays
    # per chunk
    await s.execute(
        "CREATE SOURCE bid WITH (connector='nexmark', table='bid', "
        "chunk_size=64, rate_limit=4096)")
    await s.execute(f"CREATE MATERIALIZED VIEW counts AS {TOPN_AGG_SQL}")
    await s.execute(f"CREATE MATERIALIZED VIEW t10 AS {TOPN_SQL}")
    tops = _sharded_topns(s)
    await s.tick(WARMUP_ROUNDS)
    drop0 = MESH_SHUFFLE_DROPPED.value
    d0 = _dispatches()
    a0 = sum(t.mesh_shuffle_applies for t in tops)
    await s.tick(MEASURE_ROUNDS)
    d1 = _dispatches()
    a1 = sum(t.mesh_shuffle_applies for t in tops)
    b = await s.coord.inject_barrier(mutation=PauseMutation())
    await s.coord.wait_collected(b)
    rows = s.query("SELECT a, n FROM t10")
    offset = max(g.connector.offset for g in _sources(s))
    out = {
        "mode": mode,
        "actors": len(s.coord.actor_ids),
        "dispatches_per_interval": round((d1 - d0) / MEASURE_ROUNDS, 2),
        "topn_fused_dispatches_per_interval": round(
            (a1 - a0) / MEASURE_ROUNDS, 2),
        "rows": len(rows),
        "offset": offset,
        "matches_oracle": _topn_check(rows, offset),
        "sharded_topns": len(tops),
        "shuffle_dropped": int(MESH_SHUFFLE_DROPPED.value - drop0),
    }
    await s.drop_all()
    return out


async def main() -> int:
    host = await _run("host")
    unfused = await _run("mesh_unfused")
    mesh = await _run("mesh")
    # "host transfers per interval" for the >=2x gate: the counted
    # per-chunk host-plane crossings; a zero fused count compares
    # against an >= 2 unfused count (ratio floor of 2 with the 1-hop
    # denominator clamp)
    hop_reduction = (unfused["host_hops_per_interval"]
                     / max(mesh["host_hops_per_interval"], 1.0))
    t_host = await _run_topn("topn_host")
    t_mesh = await _run_topn("topn_mesh")
    topn_reduction = (t_host["dispatches_per_interval"]
                      / max(t_mesh["dispatches_per_interval"], 1e-9))
    verdict = {
        "results_identical_to_oracle": (host["matches_oracle"]
                                        and unfused["matches_oracle"]
                                        and mesh["matches_oracle"]),
        "dispatch_reduction": round(
            host["dispatches_per_interval"]
            / max(mesh["dispatches_per_interval"], 1e-9), 2),
        "one_actor_covers_8_shards": (
            mesh["sharded_aggs"] == 1
            and mesh["mesh_fragments"]
            and all(n == N_DEVICES
                    for n in mesh["mesh_fragments"].values())),
        "fused_plane_engaged": mesh["fused_applies"] > 0,
        "zero_shuffle_drops": mesh["shuffle_dropped"] == 0,
        "chain_registered": (
            any(i["hollow"] for i in mesh["mesh_chains"].values())
            and any(not i["hollow"]
                    for i in unfused["mesh_chains"].values())),
        "zero_host_hops_fused": mesh["host_hops_per_interval"] == 0,
        "host_hop_reduction": round(hop_reduction, 2),
        "topn_matches_oracle": (t_host["matches_oracle"]
                                and t_mesh["matches_oracle"]),
        "topn_dispatch_reduction": round(topn_reduction, 2),
        "topn_one_fused_dispatch_per_interval": (
            t_mesh["sharded_topns"] == 1
            and t_mesh["topn_fused_dispatches_per_interval"] == 1.0),
        "topn_zero_shuffle_drops": t_mesh["shuffle_dropped"] == 0,
    }
    print(json.dumps(host))
    print(json.dumps(unfused))
    print(json.dumps(mesh))
    print(json.dumps(t_host))
    print(json.dumps(t_mesh))
    print(json.dumps({"verdict": verdict}))
    ok = (verdict["results_identical_to_oracle"]
          and mesh["dispatches_per_interval"]
          < host["dispatches_per_interval"]
          and verdict["one_actor_covers_8_shards"]
          and verdict["fused_plane_engaged"]
          and verdict["zero_shuffle_drops"]
          and verdict["chain_registered"]
          and verdict["zero_host_hops_fused"]
          and hop_reduction >= 2.0
          and mesh["rows"] > 0 and host["offset"] > 0
          and unfused["offset"] > 0 and mesh["offset"] > 0
          and verdict["topn_matches_oracle"]
          and topn_reduction >= 3.0
          and verdict["topn_one_fused_dispatch_per_interval"]
          and verdict["topn_zero_shuffle_drops"]
          and t_mesh["rows"] > 0 and t_host["offset"] > 0
          and t_mesh["offset"] > 0)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))

"""Nexmark q1 through the public API: the minimum end-to-end slice.

  SELECT auction, bidder, 0.908 * price, date_time FROM bid

Builds source -> jitted project -> row-id gen -> materialize, runs N barrier
epochs with checkpoints, prints MV stats + barrier latency.

Run: python examples/nexmark_q1.py [num_barriers] [chunk_size]
"""

import asyncio
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))

import jax

from risingwave_tpu.common import DataType, schema
from risingwave_tpu.connectors import NexmarkGenerator
from risingwave_tpu.expr import call, col, lit
from risingwave_tpu.meta import BarrierCoordinator
from risingwave_tpu.state import MemoryStateStore, StateTable
from risingwave_tpu.stream import (
    Actor, MaterializeExecutor, ProjectExecutor, RowIdGenExecutor, SourceExecutor,
)


async def main(rounds: int = 5, chunk_size: int = 4096) -> None:
    print(f"devices: {jax.devices()}")
    store = MemoryStateStore()
    barrier_q = asyncio.Queue()
    gen = NexmarkGenerator("bid", chunk_size=chunk_size)

    offsets = StateTable(store, 1, schema(("source_id", DataType.INT64),
                                          ("offset", DataType.INT64)), pk_indices=[0])
    src = SourceExecutor(1, gen, barrier_q, state_table=offsets)
    proj = ProjectExecutor(
        src,
        [col(0), col(1), call("multiply", col(2), lit(0.908)), col(5, DataType.TIMESTAMP)],
        names=["auction", "bidder", "price", "date_time"])
    rid = RowIdGenExecutor(proj)
    mv = StateTable(store, 2, rid.schema, pk_indices=rid.pk_indices)
    mat = MaterializeExecutor(rid, mv)

    coord = BarrierCoordinator(store)
    coord.register_source(barrier_q)
    coord.register_actor(1)
    task = Actor(1, mat, None, coord).spawn()

    t0 = time.perf_counter()
    await coord.run_rounds(rounds, interval_s=0.05)
    await coord.stop_all({1})
    await task
    dt = time.perf_counter() - t0

    n = sum(1 for _ in mv.iter_all())
    some = [r for _, r in zip(range(3), mv.iter_all())]
    print(f"rows materialized: {n} (source offset {gen.offset}) in {dt:.2f}s "
          f"-> {gen.offset / dt:,.0f} rows/s wall")
    print(f"sample rows (auction, bidder, price, date_time, _row_id):")
    for _, row in some:
        print("  ", row)
    print(f"barrier p50 latency: {coord.barrier_latency_percentile(0.5)*1e3:.2f} ms; "
          f"committed epochs: {len(coord.committed_epochs)}")
    off = offsets.get_row((1,))
    print(f"committed source offset: {off[1] if off else None}")
    assert n == gen.offset, "MV row count must equal generated events"


if __name__ == "__main__":
    rounds = int(sys.argv[1]) if len(sys.argv) > 1 else 5
    chunk = int(sys.argv[2]) if len(sys.argv) > 2 else 4096
    asyncio.run(main(rounds, chunk))
